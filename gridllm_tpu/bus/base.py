"""Message-bus interface: the DCN-plane control/data bus contract.

Reference analogue: server/src/services/RedisService.ts:110-247 and
client/src/services/RedisConnectionManager.ts:257-358 — Redis KV + hash +
pub/sub with a `GridLLM:` key prefix. Design fixes baked in (SURVEY.md §2.8):

- ``subscribe`` returns a ``Subscription`` handle whose ``unsubscribe()``
  removes exactly that handler — the reference leaked one `message` listener
  per subscribe call (RedisService.ts:207-227).
- Channel names are NOT key-prefixed (matches reference behavior: ioredis
  keyPrefix does not apply to pub/sub), keys ARE.

The protocol carried over this interface (channels `worker:*`, `job:*`,
keys `workers`, `heartbeat:{id}`, `active_jobs`, `job_queue`) is inventoried
in SURVEY.md §2.6 and implemented by scheduler/ and worker/.
"""

from __future__ import annotations

import abc
import asyncio
import time
from typing import Any, Awaitable, Callable

from gridllm_tpu import faults
from gridllm_tpu.obs import metrics as obs

# handler(channel, message) — message is the raw string payload
Handler = Callable[[str, str], Awaitable[None]]

# Bus-plane instruments (process-global registry): publish/deliver volumes
# and delivery latency (publish → handler start), labeled by channel CLASS
# (per-job/per-worker ids collapsed) so cardinality stays bounded.
_PUBLISHED = obs.default_registry().counter(
    "gridllm_bus_messages_published_total",
    "Messages published to the bus, by channel class.",
    ("channel",),
)
_DELIVERED = obs.default_registry().counter(
    "gridllm_bus_messages_delivered_total",
    "Messages delivered to subscribed handlers, by channel class.",
    ("channel",),
)
_DELIVERY_LATENCY = obs.default_registry().histogram(
    "gridllm_bus_delivery_latency_seconds",
    "Latency from subscriber-side enqueue to handler start, by channel class.",
    ("channel",),
)

_CHANNEL_CLASS_PREFIXES = (
    ("job:stream:", "job:stream"),
    ("job:result:", "job:result"),
    ("admin:result:", "admin:result"),
    ("worker:reregister:", "worker:reregister"),
    ("trace:", "trace"),
    # multi-host SPMD plan replay: slice:{worker_id}:plan and
    # slice:{worker_id}:ready:{pid} — collapse both under one class
    ("slice:", "slice"),
    # KV-page migration chunk streams (ISSUE 7): kvx:{request_id}
    ("kvx:", "kvx"),
)


def channel_class(channel: str) -> str:
    """Collapse per-id channels (``job:stream:{id}``, ``worker:{id}:job``)
    into their fixed class name for metric labels."""
    for prefix, cls in _CHANNEL_CLASS_PREFIXES:
        if channel.startswith(prefix):
            return cls
    if channel.startswith("worker:") and channel.endswith(":job"):
        return "worker:job"
    return channel


# -- durable channel classes (ISSUE 10) -------------------------------------
#
# Channels whose loss mid-outage is NOT recoverable by the at-least-once
# sweeps alone: result/stream frames feed live client streams, snapshots
# are the crash-resume watermarks, handoff/drain move live assignments,
# kvx:* carries KV-page migration chunks, and worker:{id}:job carries
# assignments/cancellations (an assignment published while the worker's
# subscriber is mid-reconnect would otherwise vanish until the job
# timeout). The broker assigns these a per-channel monotonic sequence
# number and keeps a bounded replay ring; a reconnecting RespBus
# subscriber issues RESUME to replay the gap and dedupes by seq, so
# consumer-observed delivery is exactly-once across a broker bounce.
# Everything else (heartbeats, registration, traces) is periodic or
# best-effort and stays plain fire-and-forget pub/sub.
_DURABLE_PREFIXES = ("job:result:", "job:stream:", "admin:result:", "kvx:")
_DURABLE_CHANNELS = frozenset((
    "job:completed", "job:failed", "job:timeout",
    "job:snapshot", "job:handoff", "job:drain", "job:preempted",
))


def durable_channel(channel: str) -> bool:
    """True when the broker sequences + ring-buffers this channel."""
    if channel in _DURABLE_CHANNELS or channel.startswith(_DURABLE_PREFIXES):
        return True
    return channel.startswith("worker:") and channel.endswith(":job")


# Sequence framing on durable channels: the broker prefixes the payload
# with an out-of-band marker + seq so subscribers can dedupe replays.
# Payloads are JSON in this protocol, so the NUL-framed marker can never
# collide with organic content; a broker that doesn't sequence (real
# Redis) simply yields seq=None and the client skips dedupe/resume.
_SEQ_MARK = "\x00q\x00"


def encode_seq(seq: int, payload: str) -> str:
    return f"{_SEQ_MARK}{seq}\x00{payload}"


def split_seq(payload: str) -> tuple[int | None, str]:
    """(seq, body) for a seq-framed payload; (None, payload) otherwise."""
    if not payload.startswith(_SEQ_MARK):
        return None, payload
    rest = payload[len(_SEQ_MARK):]
    num, sep, body = rest.partition("\x00")
    if not sep or not num.isdigit():
        return None, payload
    return int(num), body


def liveness_suspended(bus: "MessageBus", grace_ms: float) -> bool:
    """Partition-aware liveness (ISSUE 10): True while the bus session is
    degraded OR within the rejoin grace window after it recovered. The
    registry suspends worker-death verdicts and the scheduler defers
    orphan sweeps while this holds — a broker bounce must not be read as
    a fleet-wide worker die-off (every heartbeat went missing because WE
    were deaf, not because the workers died)."""
    st = bus.partition_state()
    if st.get("degraded"):
        return True
    rejoined = st.get("lastRejoin")
    if rejoined is None:
        return False
    return (time.monotonic() - float(rejoined)) * 1000.0 < grace_ms


def record_publish(channel: str) -> None:
    """Called by bus implementations on every publish. The bus.publish
    fault site lives here — BEFORE the accounting and the actual send, so
    an injected publish failure looks exactly like a dead bus to the
    caller (the message never leaves the process)."""
    faults.inject("bus.publish")
    _PUBLISHED.inc(channel=channel_class(channel))


class HandlerPump:
    """Per-handler FIFO delivery: a queue plus one pump task, so a handler
    always finishes message N before seeing N+1 (token-stream frames on
    `job:stream:{id}` rely on in-order delivery), while publishers never
    block. Handler exceptions are logged and do not kill the pump."""

    def __init__(self, handler: Handler):
        self.handler = handler
        self.queue: asyncio.Queue[tuple[str, str, float]] = asyncio.Queue()
        self.task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            channel, message, t_push = await self.queue.get()
            if faults.check("bus.deliver"):
                # injected delivery loss: the handler never sees the
                # message — exactly what an at-least-once consumer must
                # survive via sweeps/retries/heartbeat timeouts
                self.queue.task_done()
                continue
            cls = channel_class(channel)
            _DELIVERED.inc(channel=cls)
            _DELIVERY_LATENCY.observe(
                max(0.0, time.monotonic() - t_push), channel=cls
            )
            try:
                await self.handler(channel, message)
            except asyncio.CancelledError:
                raise
            except Exception:
                import traceback

                traceback.print_exc()
            finally:
                self.queue.task_done()

    def push(self, channel: str, message: str) -> None:
        self.queue.put_nowait((channel, message, time.monotonic()))

    async def drain(self) -> None:
        await self.queue.join()

    def stop(self) -> None:
        self.task.cancel()


class Subscription:
    """Handle for one (pattern|channel, handler) registration."""

    def __init__(self, unsubscribe: Callable[[], Awaitable[None]], target: str):
        self._unsubscribe = unsubscribe
        self.target = target
        self.active = True

    async def unsubscribe(self) -> None:
        if self.active:
            self.active = False
            await self._unsubscribe()


class MessageBus(abc.ABC):
    """KV + hash + pub/sub bus. All ``key`` args get the configured prefix."""

    def __init__(self, key_prefix: str = "GridLLM:"):
        self.key_prefix = key_prefix

    def _k(self, key: str) -> str:
        return f"{self.key_prefix}{key}"

    # -- lifecycle ----------------------------------------------------------
    @abc.abstractmethod
    async def connect(self) -> None: ...

    @abc.abstractmethod
    async def disconnect(self) -> None: ...

    @abc.abstractmethod
    async def is_healthy(self) -> bool:
        """reference: RedisService.isHealthy (ping), RedisService.ts:270-277."""

    def partition_state(self) -> dict[str, Any]:
        """Point-in-time session health for partition-aware liveness
        (ISSUE 10): ``degraded`` while this process's subscriber session
        is down (its view of heartbeats/events is stale, not the fleet),
        ``since`` the monotonic start of the current partition, and
        ``lastRejoin`` the monotonic time the session last recovered.
        In-process buses are never partitioned — only RespBus overrides."""
        return {"degraded": False, "since": None, "lastRejoin": None}

    # -- KV -----------------------------------------------------------------
    @abc.abstractmethod
    async def get(self, key: str) -> str | None: ...

    @abc.abstractmethod
    async def set(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    async def set_with_expiry(self, key: str, value: str, ttl_s: float) -> None:
        """reference: setWithExpiry — heartbeat TTL keys
        (RedisConnectionManager.ts:299-309)."""

    @abc.abstractmethod
    async def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    async def ttl(self, key: str) -> int:
        """Seconds to live; -1 no expiry; -2 missing (Redis TTL semantics —
        the liveness probe reads this, WorkerRegistry.ts:161-180)."""

    # -- hash ---------------------------------------------------------------
    @abc.abstractmethod
    async def hget(self, key: str, field: str) -> str | None: ...

    @abc.abstractmethod
    async def hset(self, key: str, field: str, value: str) -> None: ...

    @abc.abstractmethod
    async def hgetall(self, key: str) -> dict[str, str]: ...

    @abc.abstractmethod
    async def hdel(self, key: str, field: str) -> None: ...

    # -- pub/sub ------------------------------------------------------------
    @abc.abstractmethod
    async def publish(self, channel: str, message: str) -> int:
        """Returns receiver count when known (0 otherwise)."""

    @abc.abstractmethod
    async def subscribe(self, channel: str, handler: Handler) -> Subscription: ...

    @abc.abstractmethod
    async def psubscribe(self, pattern: str, handler: Handler) -> Subscription:
        """Glob-style pattern subscribe (reference: RedisService.ts:230-247)."""
