"""Message-bus interface: the DCN-plane control/data bus contract.

Reference analogue: server/src/services/RedisService.ts:110-247 and
client/src/services/RedisConnectionManager.ts:257-358 — Redis KV + hash +
pub/sub with a `GridLLM:` key prefix. Design fixes baked in (SURVEY.md §2.8):

- ``subscribe`` returns a ``Subscription`` handle whose ``unsubscribe()``
  removes exactly that handler — the reference leaked one `message` listener
  per subscribe call (RedisService.ts:207-227).
- Channel names are NOT key-prefixed (matches reference behavior: ioredis
  keyPrefix does not apply to pub/sub), keys ARE.

The protocol carried over this interface (channels `worker:*`, `job:*`,
keys `workers`, `heartbeat:{id}`, `active_jobs`, `job_queue`) is inventoried
in SURVEY.md §2.6 and implemented by scheduler/ and worker/. Every channel
family is declared in the typed CHANNELS registry below (ISSUE 13) — call
sites use the CH_* constants / *_channel helpers, never raw name strings;
the channel-discipline analyzer rule enforces it.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import re
import time
from typing import Any, Awaitable, Callable

from gridllm_tpu import faults
from gridllm_tpu.obs import metrics as obs

# Fleet timeline (ISSUE 17): every publish is stamped with the process
# HLC (inside the broker's seq framing) and every delivery merges the
# stamp back, so cross-member event order is provable without clock
# sync. Importing obs.timeline here is safe ONLY because the line above
# already loaded the whole obs package — timeline.py itself must never
# import bus code at module level (see its module docstring).
from gridllm_tpu.obs.timeline import (
    EDGE_FAMILIES,
    default_clock,
    edge_request_id,
    emit_event,
    encode_hlc,
    split_hlc,
    timeline_armed,
)

# handler(channel, message) — message is the raw string payload
Handler = Callable[[str, str], Awaitable[None]]

# Bus-plane instruments (process-global registry): publish/deliver volumes
# and delivery latency (publish → handler start), labeled by channel CLASS
# (per-job/per-worker ids collapsed) so cardinality stays bounded.
_PUBLISHED = obs.default_registry().counter(
    "gridllm_bus_messages_published_total",
    "Messages published to the bus, by channel class.",
    ("channel",),
)
_DELIVERED = obs.default_registry().counter(
    "gridllm_bus_messages_delivered_total",
    "Messages delivered to subscribed handlers, by channel class.",
    ("channel",),
)
_DELIVERY_LATENCY = obs.default_registry().histogram(
    "gridllm_bus_delivery_latency_seconds",
    "Latency from subscriber-side enqueue to handler start, by channel class.",
    ("channel",),
)

# -- typed channel registry (ISSUE 13) --------------------------------------
#
# Every channel family the protocol carries is declared here ONCE —
# mirroring the ENV_VARS registry in utils/config.py — with its name
# pattern, payload contract, durability class, and intended publisher/
# subscriber modules. Call sites never spell a channel name as a raw
# string: fixed channels use the CH_* constants below, parameterized
# channels go through the *_channel helpers. The channel-discipline rule
# (gridllm_tpu/analysis/) enforces all of it statically: raw literals at
# publish/subscribe call sites are findings, publish/subscribe direction
# must match the declared modules, publisher-side payload keys must
# agree with the declared model both ways, and ``durable_channel`` /
# ``channel_class`` below DERIVE from this registry so a channel can't
# be durable-in-docs but fire-and-forget-in-code. The README "Bus
# channels" table is cross-checked against this registry by the same
# rule, so docs cannot drift from the protocol.


@dataclasses.dataclass(frozen=True)
class ChannelSpec:
    """One channel family: the single source of truth for its wire name,
    payload shape, durability class, and who talks on it."""

    family: str                   # metric-label class (collapses per-id names)
    pattern: str                  # "job:result:{job_id}" / fixed literal
    payload: str                  # pydantic model name, "keys", or "opaque"
    keys: tuple[str, ...]         # declared payload keys ("keys" payloads)
    durable: bool                 # broker sequences + ring-buffers it
    publishers: tuple[str, ...]   # repo-relative modules that may publish
    subscribers: tuple[str, ...]  # repo-relative modules that may subscribe
    helper: str                   # the constant / helper call sites must use
    description: str


CHANNELS: dict[str, ChannelSpec] = {}


def register_channel(family: str, *, pattern: str, payload: str = "keys",
                     keys: tuple[str, ...] = (), durable: bool = False,
                     publishers: tuple[str, ...] = (),
                     subscribers: tuple[str, ...] = (),
                     helper: str = "", description: str = "") -> None:
    if family in CHANNELS:
        # same contract as register_env: silent last-writer-wins would
        # let two registrations disagree with no signal anywhere
        raise ValueError(f"duplicate register_channel({family!r})")
    CHANNELS[family] = ChannelSpec(family, pattern, payload, tuple(keys),
                                   durable, tuple(publishers),
                                   tuple(subscribers), helper, description)


# Durability rationale (ISSUE 10): durable=True marks channels whose loss
# mid-outage is NOT recoverable by the at-least-once sweeps alone —
# result/stream frames feed live client streams, snapshots are the
# crash-resume watermarks, handoff/drain/preempted move live assignments,
# kvx:* carries KV-page migration chunks, and worker:{id}:job carries
# assignments/cancellations (an assignment published while the worker's
# subscriber is mid-reconnect must not vanish until the job timeout).
# Everything else (heartbeats, registration, traces, plan replay) is
# periodic or best-effort and stays plain fire-and-forget pub/sub.

register_channel(
    "worker:job", pattern="worker:{worker_id}:job", payload="keys",
    keys=("type", "job", "jobId", "reason", "xfer", "fromWorker", "header"),
    durable=True,
    publishers=("gridllm_tpu/scheduler/scheduler.py",
                "gridllm_tpu/transfer/migrate.py",
                "gridllm_tpu/obs/health.py"),
    subscribers=("gridllm_tpu/worker/service.py",),
    helper="worker_job_channel",
    description="Per-worker control: job_assignment/job_cancellation/"
                "job_preempt/kv_import/kv_release/drain messages, "
                "demuxed by the 'type' key.")
register_channel(
    "worker:reregister", pattern="worker:reregister:{worker_id}",
    payload="keys", keys=("type", "timestamp"),
    publishers=("gridllm_tpu/scheduler/registry.py",),
    subscribers=("gridllm_tpu/worker/service.py",),
    helper="worker_reregister_channel",
    description="Registry asks one silent-but-alive worker to re-publish "
                "its registration.")
register_channel(
    "worker:admin", pattern="worker:admin", payload="keys",
    keys=("op", "id", "model", "source", "destination", "if_idle",
          "workerId"),
    publishers=("gridllm_tpu/gateway/admin.py",
                "gridllm_tpu/scheduler/placement.py"),
    subscribers=("gridllm_tpu/worker/service.py",),
    helper="CH_WORKER_ADMIN",
    description="Model-management ops (load/unload/copy), broadcast by "
                "the gateway or targeted at one worker (workerId key) by "
                "the placement controller; workers answer on "
                "admin:result.")
register_channel(
    "admin:result", pattern="admin:result:{op_id}", payload="keys",
    keys=("workerId", "op", "ack", "ok", "detail"), durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/gateway/admin.py",
                 "gridllm_tpu/scheduler/placement.py"),
    helper="admin_result_channel",
    description="Per-op admin answers: immediate ack, then ok/detail "
                "when the op resolves.")
register_channel(
    "worker:registered", pattern="worker:registered", payload="WorkerInfo",
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_WORKER_REGISTERED",
    description="Worker self-registration (full WorkerInfo).")
register_channel(
    "worker:unregistered", pattern="worker:unregistered", payload="keys",
    keys=("workerId",),
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_WORKER_UNREGISTERED",
    description="Graceful worker shutdown announcement.")
register_channel(
    "worker:heartbeat", pattern="worker:heartbeat", payload="keys",
    keys=("workerId", "status", "currentJobs", "prefixKeys", "role",
          "decodeSlotsFree", "httpAddr", "modelCapacity"),
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_WORKER_HEARTBEAT",
    description="Periodic liveness + load + prefix-affinity keys + "
                "disagg role/headroom/transfer address + per-model "
                "slot/KV-page headroom (ISSUE 16 capacity signals).")
register_channel(
    "worker:status_update", pattern="worker:status_update", payload="keys",
    keys=("workerId", "status", "currentJobs"),
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_WORKER_STATUS_UPDATE",
    description="Change-deduped online/busy/draining transitions.")
register_channel(
    "worker:disconnected", pattern="worker:disconnected", payload="keys",
    keys=("workerId", "reason"),
    publishers=("gridllm_tpu/worker/group.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_WORKER_DISCONNECTED",
    description="Fast-path worker death announcement (multi-host slice "
                "failure) — beats the heartbeat TTL by ~10 s.")
register_channel(
    "job:completed", pattern="job:completed", payload="JobResult",
    durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_COMPLETED",
    description="Global job-success lifecycle event.")
register_channel(
    "job:failed", pattern="job:failed", payload="JobResult", durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_FAILED",
    description="Global job-failure / NACK lifecycle event (nack=True "
                "requeues without burning the retry ladder).")
register_channel(
    "job:result", pattern="job:result:{job_id}", payload="JobResult",
    durable=True,
    publishers=("gridllm_tpu/worker/service.py",
                "gridllm_tpu/scheduler/scheduler.py"),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="job_result_channel",
    description="Per-job final result delivered to the submit waiter.")
register_channel(
    "job:stream", pattern="job:stream:{job_id}", payload="StreamChunk",
    durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="job_stream_channel",
    description="Per-job token stream frames (absolute char offsets; "
                "the gateway trims resume overlap).")
register_channel(
    "job:snapshot", pattern="job:snapshot", payload="keys",
    keys=("jobId", "workerId", "tokens", "seed"), durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_SNAPSHOT",
    description="Decode-resume watermarks (generated ids + resolved "
                "sampler seed) at the snapshot cadence.")
register_channel(
    "job:handoff", pattern="job:handoff", payload="keys",
    keys=("jobId", "fromWorker", "toWorker", "ok", "reason", "tokens",
          "bytes", "seconds", "path"), durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_HANDOFF",
    description="Disagg prefill→decode handoff report (ok=False counts "
                "the local-serve fallback).")
register_channel(
    "job:drain", pattern="job:drain", payload="keys",
    keys=("jobId", "fromWorker", "toWorker", "migrated", "snapshot",
          "tokens", "bytes"), durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_DRAIN",
    description="Graceful-drain handoff: suspended decode moved to a "
                "peer (or requeued) with its resume snapshot.")
register_channel(
    "job:preempted", pattern="job:preempted", payload="keys",
    keys=("jobId", "fromWorker", "snapshot", "tokens", "parkedTokens"),
    durable=True,
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="CH_JOB_PREEMPTED",
    description="Suspend-to-host preemption report; the victim requeues "
                "behind the higher-priority work.")
register_channel(
    "ctrl:submit", pattern="ctrl:submit", payload="keys",
    keys=("request", "submitter"), durable=True,
    publishers=("gridllm_tpu/controlplane/client.py",),
    subscribers=("gridllm_tpu/controlplane/shard.py",),
    helper="CH_CTRL_SUBMIT",
    description="Gateway-replica job submission fan-out (ISSUE 15): "
                "every scheduler shard consumes it and the one owning "
                "shard_of(job id) enqueues; durable so a submission "
                "published while a shard's subscriber reconnects "
                "replays instead of vanishing.")
register_channel(
    "ctrl:cancel", pattern="ctrl:cancel", payload="keys",
    keys=("jobId", "reason", "submitter"), durable=True,
    publishers=("gridllm_tpu/controlplane/client.py",),
    subscribers=("gridllm_tpu/controlplane/shard.py",),
    helper="CH_CTRL_CANCEL",
    description="Gateway-replica cancellation relay: the owning shard "
                "runs its local cancel path (queued, retrying, or "
                "active).")
register_channel(
    "ctrl:status", pattern="ctrl:status", payload="keys",
    keys=("member", "role", "ts", "shards", "leases", "stats", "slo",
          "queued", "active", "hangs"),
    publishers=("gridllm_tpu/controlplane/status.py",),
    subscribers=("gridllm_tpu/controlplane/status.py",),
    helper="CH_CTRL_STATUS",
    description="Periodic control-plane member status envelopes; the "
                "gateway replicas' FleetView aggregates them into one "
                "fleet-wide /metrics + /admin/slo + /health view "
                "(best-effort, re-published every interval).")
register_channel(
    "trace", pattern="trace:{request_id}", payload="keys",
    keys=("requestId", "workerId", "spans"),
    publishers=("gridllm_tpu/worker/service.py",),
    subscribers=("gridllm_tpu/scheduler/scheduler.py",),
    helper="trace_channel",
    description="Worker-side span timelines, stitched into one trace by "
                "the gateway (helper lives in obs/tracer.py; the "
                "scheduler psubscribes trace_pattern()).")
register_channel(
    "kvx", pattern="kvx:{xfer_id}", payload="opaque", durable=True,
    publishers=("gridllm_tpu/transfer/migrate.py",),
    subscribers=("gridllm_tpu/transfer/migrate.py",),
    helper="kvx_channel",
    description="KV-page migration chunk streams (versioned wire frames, "
                "per-attempt transfer id — transfer/wire.py).")
register_channel(
    "slice", pattern="slice:{worker_id}:plan", payload="keys",
    keys=("seq", "rec"),
    publishers=("gridllm_tpu/worker/plan.py",),
    subscribers=("gridllm_tpu/worker/plan.py",),
    helper="plan_channel",
    description="Multi-host SPMD plan replay: liaison publishes ordered "
                "engine plan ops, followers apply in lockstep.")
register_channel(
    "obs:event", pattern="obs:event", payload="keys",
    keys=("member", "events"), durable=True,
    publishers=("gridllm_tpu/obs/timeline.py",),
    subscribers=("gridllm_tpu/obs/timeline.py",),
    helper="CH_OBS_EVENT",
    description="Fleet timeline event batches (ISSUE 17): every member's "
                "TimelinePublisher flushes HLC-stamped lifecycle events "
                "here; TimelineStore instances on gateway replicas and "
                "shards merge them into the causal fleet log behind "
                "/admin/timeline and /admin/incidents. Durable: a "
                "subscriber mid-reconnect replays the ring instead of "
                "losing the incident window it exists to capture.")
register_channel(
    "obs:dump", pattern="obs:dump", payload="keys",
    keys=("opId", "requester"),
    publishers=("gridllm_tpu/gateway/obs_routes.py",),
    subscribers=("gridllm_tpu/controlplane/status.py",),
    helper="CH_OBS_DUMP",
    description="Fleet-merged dump fan-out (ISSUE 17): a gateway replica "
                "serving /admin/dump?fleet=1 broadcasts a collection op; "
                "every control-plane member's StatusPublisher answers "
                "with its local dump artifact on the per-op reply "
                "channel. Best-effort — a silent member is reported "
                "missing, never silently merged.")
register_channel(
    "obs:dump:reply", pattern="obs:dump:reply:{op_id}", payload="keys",
    keys=("opId", "member", "dump"), durable=True,
    publishers=("gridllm_tpu/controlplane/status.py",),
    subscribers=("gridllm_tpu/gateway/obs_routes.py",),
    helper="obs_dump_reply_channel",
    description="Per-op replies to a fleet dump collection: one message "
                "per live member, keyed by member identity. Durable so a "
                "reply published while the requester's subscriber is "
                "still settling replays instead of vanishing.")
register_channel(
    "health:state", pattern="health:state", payload="keys",
    keys=("worker", "state", "reason", "member", "ts"), durable=True,
    publishers=("gridllm_tpu/obs/health.py",),
    subscribers=("gridllm_tpu/scheduler/registry.py",),
    helper="CH_HEALTH_STATE",
    description="Worker health-state transitions (ISSUE 19): the shard's "
                "health monitor announces online/degraded/quarantined/"
                "probation verdicts; every registry (shards AND observer "
                "replicas) applies them to its worker table so placement "
                "and /health/workers agree fleet-wide. Durable: a missed "
                "quarantine verdict would leave a replica routing at a "
                "bad worker.")


# -- registry constants & helpers (the only sanctioned channel spellings) ----

CH_WORKER_ADMIN = "worker:admin"
CH_WORKER_REGISTERED = "worker:registered"
CH_WORKER_UNREGISTERED = "worker:unregistered"
CH_WORKER_HEARTBEAT = "worker:heartbeat"
CH_WORKER_STATUS_UPDATE = "worker:status_update"
CH_WORKER_DISCONNECTED = "worker:disconnected"
CH_JOB_COMPLETED = "job:completed"
CH_JOB_FAILED = "job:failed"
CH_JOB_SNAPSHOT = "job:snapshot"
CH_JOB_HANDOFF = "job:handoff"
CH_JOB_DRAIN = "job:drain"
CH_JOB_PREEMPTED = "job:preempted"
CH_CTRL_SUBMIT = "ctrl:submit"
CH_CTRL_CANCEL = "ctrl:cancel"
CH_CTRL_STATUS = "ctrl:status"
CH_OBS_EVENT = "obs:event"
CH_OBS_DUMP = "obs:dump"
CH_HEALTH_STATE = "health:state"


def worker_job_channel(worker_id: str) -> str:
    return f"worker:{worker_id}:job"


def worker_reregister_channel(worker_id: str) -> str:
    return f"worker:reregister:{worker_id}"


def admin_result_channel(op_id: str) -> str:
    return f"admin:result:{op_id}"


def job_result_channel(job_id: str) -> str:
    return f"job:result:{job_id}"


def job_stream_channel(job_id: str) -> str:
    return f"job:stream:{job_id}"


def kvx_channel(xfer_id: str) -> str:
    return f"kvx:{xfer_id}"


def plan_channel(worker_id: str) -> str:
    return f"slice:{worker_id}:plan"


def obs_dump_reply_channel(op_id: str) -> str:
    return f"obs:dump:reply:{op_id}"


# -- derived classification (pattern matchers over the registry) -------------

def _compile_pattern(pattern: str) -> Callable[[str], bool]:
    """Matcher for one registered pattern: literal segments must appear in
    order, ``{placeholder}`` segments match one-or-more characters."""
    parts = re.split(r"\{[^{}]+\}", pattern)
    if len(parts) == 1:
        lit = parts[0]
        return lambda ch: ch == lit
    first, *mid, last = parts

    def match(ch: str) -> bool:
        if not ch.startswith(first):
            return False
        pos = len(first)
        for seg in mid:
            idx = ch.find(seg, pos + 1)  # placeholder is ≥ 1 char
            if idx < 0:
                return False
            pos = idx + len(seg)
        if last:
            return ch.endswith(last) and len(ch) >= pos + 1 + len(last)
        return len(ch) > pos

    return match


# fixed channels resolve by dict lookup; parameterized ones walk matchers.
# Compiled lazily and invalidated by registry size so a register_channel()
# call after import (tests, future plugins) is never silently ignored by
# durable_channel()/channel_class().
_MATCHERS: tuple[int, dict[str, ChannelSpec],
                 tuple[tuple[Callable[[str], bool], ChannelSpec], ...]] \
    = (-1, {}, ())


def _matchers() -> tuple[dict[str, ChannelSpec],
                         tuple[tuple[Callable[[str], bool],
                                     ChannelSpec], ...]]:
    global _MATCHERS
    version, fixed, param = _MATCHERS
    if version != len(CHANNELS):
        fixed = {s.pattern: s for s in CHANNELS.values()
                 if "{" not in s.pattern}
        param = tuple((_compile_pattern(s.pattern), s)
                      for s in CHANNELS.values() if "{" in s.pattern)
        _MATCHERS = (len(CHANNELS), fixed, param)
    return fixed, param


def channel_spec(channel: str) -> ChannelSpec | None:
    """The registered spec a concrete channel name belongs to, or None."""
    fixed, param = _matchers()
    spec = fixed.get(channel)
    if spec is not None:
        return spec
    for match, s in param:
        if match(channel):
            return s
    return None


def channel_class(channel: str) -> str:
    """Collapse per-id channels (``job:stream:{id}``, ``worker:{id}:job``)
    into their registered family name for metric labels. Derived from the
    channel registry; unregistered channels pass through unchanged."""
    spec = channel_spec(channel)
    return channel if spec is None else spec.family


def durable_channel(channel: str) -> bool:
    """True when the broker sequences + ring-buffers this channel.
    Derived from the channel registry — durability is declared exactly
    once, on the ChannelSpec (ISSUE 10 semantics unchanged)."""
    spec = channel_spec(channel)
    return spec is not None and spec.durable


# Sequence framing on durable channels: the broker prefixes the payload
# with an out-of-band marker + seq so subscribers can dedupe replays.
# Payloads are JSON in this protocol, so the NUL-framed marker can never
# collide with organic content; a broker that doesn't sequence (real
# Redis) simply yields seq=None and the client skips dedupe/resume.
_SEQ_MARK = "\x00q\x00"


def encode_seq(seq: int, payload: str) -> str:
    return f"{_SEQ_MARK}{seq}\x00{payload}"


def split_seq(payload: str) -> tuple[int | None, str]:
    """(seq, body) for a seq-framed payload; (None, payload) otherwise."""
    if not payload.startswith(_SEQ_MARK):
        return None, payload
    rest = payload[len(_SEQ_MARK):]
    num, sep, body = rest.partition("\x00")
    if not sep or not num.isdigit():
        return None, payload
    return int(num), body


def liveness_suspended(bus: "MessageBus", grace_ms: float) -> bool:
    """Partition-aware liveness (ISSUE 10): True while the bus session is
    degraded OR within the rejoin grace window after it recovered. The
    registry suspends worker-death verdicts and the scheduler defers
    orphan sweeps while this holds — a broker bounce must not be read as
    a fleet-wide worker die-off (every heartbeat went missing because WE
    were deaf, not because the workers died)."""
    st = bus.partition_state()
    if st.get("degraded"):
        return True
    rejoined = st.get("lastRejoin")
    if rejoined is None:
        return False
    return (time.monotonic() - float(rejoined)) * 1000.0 < grace_ms


def record_publish(channel: str, message: str | None = None) -> str | None:
    """Called by bus implementations on every publish. The bus.publish
    fault site lives here — BEFORE the accounting and the actual send, so
    an injected publish failure looks exactly like a dead bus to the
    caller (the message never leaves the process).

    Fleet timeline (ISSUE 17): when ``message`` is given, it comes back
    HLC-framed (stamped with the process clock's ``tick()``) and the bus
    implementation sends the RETURNED string; lifecycle families in
    ``EDGE_FAMILIES`` additionally leave a ``bus.send`` edge event
    carrying the same stamp, so a receiver's merge provably orders the
    matching ``bus.recv`` after it."""
    faults.inject("bus.publish")
    cls = channel_class(channel)
    _PUBLISHED.inc(channel=cls)
    if message is None:
        return None
    stamp = default_clock().tick()
    if timeline_armed() and cls in EDGE_FAMILIES:
        emit_event("bus.send", request_id=edge_request_id(message),
                   stamp=stamp, channel=cls)
    return encode_hlc(stamp, message)


class HandlerPump:
    """Per-handler FIFO delivery: a queue plus one pump task, so a handler
    always finishes message N before seeing N+1 (token-stream frames on
    `job:stream:{id}` rely on in-order delivery), while publishers never
    block. Handler exceptions are logged and do not kill the pump."""

    def __init__(self, handler: Handler):
        self.handler = handler
        self.queue: asyncio.Queue[tuple[str, str, float]] = asyncio.Queue()
        self.task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            channel, message, t_push = await self.queue.get()
            if faults.check("bus.deliver"):
                # injected delivery loss: the handler never sees the
                # message — exactly what an at-least-once consumer must
                # survive via sweeps/retries/heartbeat timeouts (and no
                # HLC merge: a dropped message established no order)
                self.queue.task_done()
                continue
            cls = channel_class(channel)
            stamp, message = split_hlc(message)
            if stamp is not None:
                # HLC merge hook (ISSUE 17): the local clock advances
                # past the sender's stamp, so every event this process
                # emits from here on is provably after the send
                merged = default_clock().update(stamp)
                if timeline_armed() and cls in EDGE_FAMILIES:
                    emit_event("bus.recv",
                               request_id=edge_request_id(message),
                               stamp=merged, channel=cls)
            _DELIVERED.inc(channel=cls)
            _DELIVERY_LATENCY.observe(
                max(0.0, time.monotonic() - t_push), channel=cls
            )
            try:
                await self.handler(channel, message)
            except asyncio.CancelledError:
                raise
            except Exception:
                import traceback

                traceback.print_exc()
            finally:
                self.queue.task_done()

    def push(self, channel: str, message: str) -> None:
        self.queue.put_nowait((channel, message, time.monotonic()))

    async def drain(self) -> None:
        await self.queue.join()

    def stop(self) -> None:
        self.task.cancel()


class Subscription:
    """Handle for one (pattern|channel, handler) registration."""

    def __init__(self, unsubscribe: Callable[[], Awaitable[None]], target: str):
        self._unsubscribe = unsubscribe
        self.target = target
        self.active = True

    async def unsubscribe(self) -> None:
        if self.active:
            self.active = False
            await self._unsubscribe()


class MessageBus(abc.ABC):
    """KV + hash + pub/sub bus. All ``key`` args get the configured prefix."""

    def __init__(self, key_prefix: str = "GridLLM:"):
        self.key_prefix = key_prefix

    def _k(self, key: str) -> str:
        return f"{self.key_prefix}{key}"

    # -- lifecycle ----------------------------------------------------------
    @abc.abstractmethod
    async def connect(self) -> None: ...

    @abc.abstractmethod
    async def disconnect(self) -> None: ...

    @abc.abstractmethod
    async def is_healthy(self) -> bool:
        """reference: RedisService.isHealthy (ping), RedisService.ts:270-277."""

    def partition_state(self) -> dict[str, Any]:
        """Point-in-time session health for partition-aware liveness
        (ISSUE 10): ``degraded`` while this process's subscriber session
        is down (its view of heartbeats/events is stale, not the fleet),
        ``since`` the monotonic start of the current partition, and
        ``lastRejoin`` the monotonic time the session last recovered.
        In-process buses are never partitioned — only RespBus overrides."""
        return {"degraded": False, "since": None, "lastRejoin": None}

    # -- KV -----------------------------------------------------------------
    @abc.abstractmethod
    async def get(self, key: str) -> str | None: ...

    @abc.abstractmethod
    async def set(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    async def set_with_expiry(self, key: str, value: str, ttl_s: float) -> None:
        """reference: setWithExpiry — heartbeat TTL keys
        (RedisConnectionManager.ts:299-309)."""

    @abc.abstractmethod
    async def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    async def ttl(self, key: str) -> int:
        """Seconds to live; -1 no expiry; -2 missing (Redis TTL semantics —
        the liveness probe reads this, WorkerRegistry.ts:161-180)."""

    # -- hash ---------------------------------------------------------------
    @abc.abstractmethod
    async def hget(self, key: str, field: str) -> str | None: ...

    @abc.abstractmethod
    async def hset(self, key: str, field: str, value: str) -> None: ...

    @abc.abstractmethod
    async def hgetall(self, key: str) -> dict[str, str]: ...

    @abc.abstractmethod
    async def hdel(self, key: str, field: str) -> None: ...

    # -- pub/sub ------------------------------------------------------------
    @abc.abstractmethod
    async def publish(self, channel: str, message: str) -> int:
        """Returns receiver count when known (0 otherwise)."""

    @abc.abstractmethod
    async def subscribe(self, channel: str, handler: Handler) -> Subscription: ...

    @abc.abstractmethod
    async def psubscribe(self, pattern: str, handler: Handler) -> Subscription:
        """Glob-style pattern subscribe (reference: RedisService.ts:230-247)."""
