from gridllm_tpu.bus.base import MessageBus, Subscription
from gridllm_tpu.bus.memory import InMemoryBus


def create_bus(url: str = "", key_prefix: str = "GridLLM:",
               password: str | None = None, db: int = 0) -> MessageBus:
    """Bus factory. "" → process-local in-memory bus; "resp://host:port" or a
    standard "redis://[:pass@]host:port[/db]" URL → RESP wire protocol (real
    Redis or the bundled gridbus broker). Explicit password/db args are
    fallbacks for URL forms that omit them."""
    if not url or url == "memory://":
        return InMemoryBus(key_prefix=key_prefix)
    if url.startswith(("resp://", "redis://", "rediss://")):
        from urllib.parse import urlparse

        from gridllm_tpu.bus.resp import RespBus

        parsed = urlparse(url)
        url_db = parsed.path.lstrip("/")
        return RespBus(
            host=parsed.hostname or "localhost",
            port=parsed.port or 6379,
            key_prefix=key_prefix,
            password=parsed.password or password,
            db=int(url_db) if url_db.isdigit() else db,
        )
    raise ValueError(f"Unknown bus url: {url!r}")


__all__ = ["MessageBus", "Subscription", "InMemoryBus", "create_bus"]
