from gridllm_tpu.bus.base import MessageBus, Subscription
from gridllm_tpu.bus.memory import InMemoryBus


def _parse_endpoint(ep: str) -> tuple[str, int]:
    """``resp://host:port`` / ``redis://…`` / bare ``host:port`` → (host,
    port). Bare entries keep GRIDLLM_BUS_ENDPOINTS copy-pasteable."""
    from urllib.parse import urlparse

    if "//" not in ep:
        ep = "resp://" + ep
    parsed = urlparse(ep)
    return parsed.hostname or "localhost", parsed.port or 6379


def create_bus(url: str = "", key_prefix: str = "GridLLM:",
               password: str | None = None, db: int = 0,
               endpoints: list[str] | None = None) -> MessageBus:
    """Bus factory. "" → process-local in-memory bus; "resp://host:port" or a
    standard "redis://[:pass@]host:port[/db]" URL → RESP wire protocol (real
    Redis or the bundled gridbus broker). Explicit password/db args are
    fallbacks for URL forms that omit them.

    ``endpoints`` (ISSUE 10, from GRIDLLM_BUS_ENDPOINTS) is the ordered
    broker list for warm-standby failover — primary FIRST; when set it
    defines where the RespBus connects (url still picks the protocol and
    supplies credentials). The url itself may also carry a comma list:
    ``resp://h1:p1,h2:p2``.
    """
    if not url and endpoints:
        url = "resp://" + endpoints[0].split("//")[-1]
    if not url or url == "memory://":
        return InMemoryBus(key_prefix=key_prefix)
    if url.startswith(("resp://", "redis://", "rediss://")):
        from urllib.parse import urlparse

        from gridllm_tpu.bus.resp import RespBus

        scheme, _, rest = url.partition("//")
        url_eps = [e for e in rest.split(",") if e]
        parsed = urlparse(scheme + "//" + url_eps[0])
        url_db = parsed.path.lstrip("/")
        eps = [_parse_endpoint(e) for e in (endpoints or url_eps)]
        return RespBus(
            host=eps[0][0],
            port=eps[0][1],
            key_prefix=key_prefix,
            password=parsed.password or password,
            db=int(url_db) if url_db.isdigit() else db,
            endpoints=eps,
        )
    raise ValueError(f"Unknown bus url: {url!r}")


__all__ = ["MessageBus", "Subscription", "InMemoryBus", "create_bus"]
