"""Model architecture configs + the name registry.

Maps Ollama-style model names (the scheduler routes on these —
reference: server/src/services/JobScheduler.ts:317-360 selects workers by
model name string) to architecture configs. Dimensions follow the public
HF configs for each family; `hf_config()` round-trips to a transformers
config so golden tests can instantiate the torch twin locally.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from gridllm_tpu.ops.layers import RopeScaling


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    """CLIP-style vision tower (llava family). Defaults = CLIP-ViT-L/14-336,
    the tower every llava-1.5 checkpoint ships."""
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    image_size: int = 336
    patch_size: int = 14
    layer_norm_eps: float = 1e-5
    # HF semantics: hidden_states index fed to the projector (-2 = output
    # of the penultimate encoder layer; llava-1.5 default)
    feature_layer: int = -2
    # id of the per-image placeholder token in the TEXT vocab; the engine
    # expands each to num_patches copies and the prefill splices projected
    # patch embeddings over them
    image_token: int = 32_000

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    family: str = "llama"            # llama | mixtral | bert_embed
    vocab_size: int = 128_256
    hidden_size: int = 4096
    intermediate_size: int = 14_336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None      # None → hidden_size // num_heads
    rope_theta: float = 500_000.0
    rope_scaling: RopeScaling | None = None
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    max_seq_len: int = 8192
    # MoE (mixtral family)
    num_experts: int = 0
    experts_per_token: int = 2
    # attention variants
    attn_logit_softcap: float = 0.0
    sliding_window: int = 0          # 0 → full attention
    attn_bias: bool = False          # qwen2: bias on q/k/v projections
    qk_norm: bool = False            # qwen3: per-head RMSNorm on q/k pre-rope
    # gemma2: logits scale by qpas**-0.5 (None → head_dim), lm-head
    # logits tanh-capped
    query_pre_attn_scalar: float | None = None
    final_logit_softcap: float = 0.0
    # embeddings (bert_embed family)
    pooling: str = "mean"            # "mean" | "cls"
    # multimodal: accepts image inputs (the per-model capability gate the
    # engine rejects on); llava family carries the tower config here
    vision: bool = False
    vision_cfg: VisionConfig | None = None
    # kernel dispatch: None = env/auto policy (ops.attention); the engine
    # sets False on its config copy when serving under a device mesh
    use_pallas: bool | None = None

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def hf_config(self) -> Any:
        """Equivalent transformers config (for golden tests, local only)."""
        common = dict(
            vocab_size=self.vocab_size,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_layers,
            num_attention_heads=self.num_heads,
            num_key_value_heads=self.num_kv_heads,
            rope_theta=self.rope_theta,
            rms_norm_eps=self.rms_eps,
            tie_word_embeddings=self.tie_embeddings,
            max_position_embeddings=self.max_seq_len,
            attention_bias=False,
        )
        if self.family == "mixtral":
            from transformers import MixtralConfig

            return MixtralConfig(
                num_local_experts=self.num_experts,
                num_experts_per_tok=self.experts_per_token,
                sliding_window=self.sliding_window or None,
                **common,
            )
        if self.family == "bert_embed":
            from transformers import BertConfig

            return BertConfig(
                vocab_size=self.vocab_size,
                hidden_size=self.hidden_size,
                num_hidden_layers=self.num_layers,
                num_attention_heads=self.num_heads,
                intermediate_size=self.intermediate_size,
                max_position_embeddings=self.max_seq_len,
                layer_norm_eps=self.rms_eps,
            )
        if self.family == "llava":
            from transformers import CLIPVisionConfig, LlamaConfig, LlavaConfig

            vc = self.vision_cfg or VisionConfig()
            return LlavaConfig(
                vision_config=CLIPVisionConfig(
                    hidden_size=vc.hidden_size,
                    intermediate_size=vc.intermediate_size,
                    num_hidden_layers=vc.num_layers,
                    num_attention_heads=vc.num_heads,
                    image_size=vc.image_size,
                    patch_size=vc.patch_size,
                    layer_norm_eps=vc.layer_norm_eps,
                ),
                text_config=LlamaConfig(**common),
                image_token_index=vc.image_token,
                vision_feature_layer=vc.feature_layer,
                vision_feature_select_strategy="default",
                projector_hidden_act="gelu",
            )
        if self.family == "gemma2":
            from transformers import Gemma2Config

            return Gemma2Config(
                head_dim=self.head_dim_,
                sliding_window=self.sliding_window,
                attn_logit_softcapping=self.attn_logit_softcap,
                final_logit_softcapping=self.final_logit_softcap,
                query_pre_attn_scalar=self.query_pre_attn_scalar
                or self.head_dim_,
                **common,
            )
        if self.family == "qwen2":
            from transformers import Qwen2Config

            common.pop("attention_bias")  # qwen2 hardcodes qkv bias
            return Qwen2Config(**common)
        if self.family == "qwen3":
            from transformers import Qwen3Config

            return Qwen3Config(head_dim=self.head_dim_, **common)
        if self.sliding_window:  # windowed llama skeleton = mistral v0.1
            from transformers import MistralConfig

            common.pop("attention_bias")
            return MistralConfig(
                sliding_window=self.sliding_window,
                head_dim=self.head_dim_,
                **common,
            )
        from transformers import LlamaConfig

        if self.rope_scaling is not None:
            common["rope_scaling"] = {
                "rope_type": "llama3",
                "factor": self.rope_scaling.factor,
                "low_freq_factor": self.rope_scaling.low_freq_factor,
                "high_freq_factor": self.rope_scaling.high_freq_factor,
                "original_max_position_embeddings": self.rope_scaling.original_max_position_embeddings,
            }
        # explicit head_dim: models like mistral-nemo:12b have
        # head_dim != hidden_size // num_heads
        return LlamaConfig(head_dim=self.head_dim_, **common)


_LLAMA3_SCALING = RopeScaling(
    factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
    original_max_position_embeddings=8192,
)

# Registry keyed by Ollama model names (BASELINE.md configs 1-5) plus
# tiny/debug configs used by tests and the synthetic bench path.
REGISTRY: dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    REGISTRY[cfg.name] = cfg
    return cfg


register(ModelConfig(
    name="llama3.2:1b", vocab_size=128_256, hidden_size=2048,
    intermediate_size=8192, num_layers=16, num_heads=32, num_kv_heads=8,
    head_dim=64, rope_theta=500_000.0, rope_scaling=_LLAMA3_SCALING,
    tie_embeddings=True, max_seq_len=131_072,
))
register(ModelConfig(
    name="llama3.2:3b", vocab_size=128_256, hidden_size=3072,
    intermediate_size=8192, num_layers=28, num_heads=24, num_kv_heads=8,
    head_dim=128, rope_theta=500_000.0, rope_scaling=_LLAMA3_SCALING,
    tie_embeddings=True, max_seq_len=131_072,
))
register(ModelConfig(
    name="llama3:8b", vocab_size=128_256, hidden_size=4096,
    intermediate_size=14_336, num_layers=32, num_heads=32, num_kv_heads=8,
    rope_theta=500_000.0, max_seq_len=8192,
))
register(ModelConfig(
    name="llama3.1:8b", vocab_size=128_256, hidden_size=4096,
    intermediate_size=14_336, num_layers=32, num_heads=32, num_kv_heads=8,
    rope_theta=500_000.0, rope_scaling=_LLAMA3_SCALING, max_seq_len=131_072,
))
register(ModelConfig(
    name="llama3:70b", vocab_size=128_256, hidden_size=8192,
    intermediate_size=28_672, num_layers=80, num_heads=64, num_kv_heads=8,
    rope_theta=500_000.0, max_seq_len=8192,
))
register(ModelConfig(
    name="qwen2.5:0.5b", family="qwen2", vocab_size=151_936, hidden_size=896,
    intermediate_size=4864, num_layers=24, num_heads=14, num_kv_heads=2,
    head_dim=64, rope_theta=1_000_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=32_768, attn_bias=True,
))
register(ModelConfig(
    name="qwen2.5:7b", family="qwen2", vocab_size=152_064, hidden_size=3584,
    intermediate_size=18_944, num_layers=28, num_heads=28, num_kv_heads=4,
    head_dim=128, rope_theta=1_000_000.0, rms_eps=1e-6,
    max_seq_len=32_768, attn_bias=True,
))
register(ModelConfig(
    name="qwen3:0.6b", family="qwen3", vocab_size=151_936, hidden_size=1024,
    intermediate_size=3072, num_layers=28, num_heads=16, num_kv_heads=8,
    head_dim=128, rope_theta=1_000_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=40_960, qk_norm=True,
))
register(ModelConfig(
    name="qwen3:8b", family="qwen3", vocab_size=151_936, hidden_size=4096,
    intermediate_size=12_288, num_layers=36, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=1_000_000.0, rms_eps=1e-6,
    max_seq_len=40_960, qk_norm=True,
))
# llava-1.5 (BASELINE vision parity): vicuna/llama2 text stack + CLIP-L/14
# tower. vocab 32064 = llama2's 32000 padded with the <image>/<pad> extras
# the llava-hf checkpoints ship.
register(ModelConfig(
    name="llava:7b", family="llava", vocab_size=32_064, hidden_size=4096,
    intermediate_size=11_008, num_layers=32, num_heads=32, num_kv_heads=32,
    rope_theta=10_000.0, max_seq_len=4096, rms_eps=1e-5,
    vision=True, vision_cfg=VisionConfig(),
))
register(ModelConfig(
    name="llava:13b", family="llava", vocab_size=32_064, hidden_size=5120,
    intermediate_size=13_824, num_layers=40, num_heads=40, num_kv_heads=40,
    rope_theta=10_000.0, max_seq_len=4096, rms_eps=1e-5,
    vision=True, vision_cfg=VisionConfig(),
))

# mistral (llama skeleton; v0.3 dropped the sliding window, v0.1-class
# checkpoints with one are supported via ModelConfig.sliding_window)
register(ModelConfig(
    name="mistral:7b", vocab_size=32_768, hidden_size=4096,
    intermediate_size=14_336, num_layers=32, num_heads=32, num_kv_heads=8,
    rope_theta=1_000_000.0, max_seq_len=32_768, rms_eps=1e-5,
))
register(ModelConfig(
    name="mistral-nemo:12b", vocab_size=131_072, hidden_size=5120,
    intermediate_size=14_336, num_layers=40, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=1_000_000.0, max_seq_len=131_072, rms_eps=1e-5,
))

# gemma2 (public HF configs; Ollama's gemma2 tags)
register(ModelConfig(
    name="gemma2:2b", family="gemma2", vocab_size=256_000, hidden_size=2304,
    intermediate_size=9216, num_layers=26, num_heads=8, num_kv_heads=4,
    head_dim=256, rope_theta=10_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=8192, sliding_window=4096, attn_logit_softcap=50.0,
    final_logit_softcap=30.0, query_pre_attn_scalar=256,
))
register(ModelConfig(
    name="gemma2:9b", family="gemma2", vocab_size=256_000, hidden_size=3584,
    intermediate_size=14_336, num_layers=42, num_heads=16, num_kv_heads=8,
    head_dim=256, rope_theta=10_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=8192, sliding_window=4096, attn_logit_softcap=50.0,
    final_logit_softcap=30.0, query_pre_attn_scalar=256,
))
register(ModelConfig(
    name="gemma2:27b", family="gemma2", vocab_size=256_000, hidden_size=4608,
    intermediate_size=36_864, num_layers=46, num_heads=32, num_kv_heads=16,
    head_dim=128, rope_theta=10_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=8192, sliding_window=4096, attn_logit_softcap=50.0,
    final_logit_softcap=30.0, query_pre_attn_scalar=144,
))

register(ModelConfig(
    name="mixtral:8x7b", family="mixtral", vocab_size=32_000,
    hidden_size=4096, intermediate_size=14_336, num_layers=32,
    num_heads=32, num_kv_heads=8, rope_theta=1_000_000.0,
    num_experts=8, experts_per_token=2, max_seq_len=32_768, rms_eps=1e-5,
))

register(ModelConfig(
    name="all-minilm", family="bert_embed", vocab_size=30_522,
    hidden_size=384, intermediate_size=1536, num_layers=6, num_heads=12,
    num_kv_heads=12, rms_eps=1e-12, max_seq_len=512, pooling="mean",
))
register(ModelConfig(
    name="mxbai-embed-large", family="bert_embed", vocab_size=30_522,
    hidden_size=1024, intermediate_size=4096, num_layers=24, num_heads=16,
    num_kv_heads=16, rms_eps=1e-12, max_seq_len=512, pooling="cls",
))

# Tiny configs: architecture-faithful, test/bench-sized.
register(ModelConfig(
    name="tiny-llama", vocab_size=256, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
    rope_theta=10_000.0, max_seq_len=256, tie_embeddings=False,
))
register(ModelConfig(
    name="tiny-mixtral", family="mixtral", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, max_seq_len=256,
    num_experts=4, experts_per_token=2,
))
register(ModelConfig(
    name="tiny-qwen2", family="qwen2", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, rms_eps=1e-6, max_seq_len=256,
    attn_bias=True,
))
register(ModelConfig(
    name="tiny-qwen3", family="qwen3", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, rms_eps=1e-6, max_seq_len=256,
    qk_norm=True,
))
register(ModelConfig(
    name="tiny-bert", family="bert_embed", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=4,
    rms_eps=1e-12, max_seq_len=128,
))
register(ModelConfig(
    name="tiny-mistral", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, max_seq_len=256, sliding_window=8,
))
register(ModelConfig(
    name="tiny-gemma2", family="gemma2", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, rms_eps=1e-6, tie_embeddings=True,
    max_seq_len=256, sliding_window=8, attn_logit_softcap=50.0,
    final_logit_softcap=30.0, query_pre_attn_scalar=24,
))
register(ModelConfig(
    name="tiny-llava", family="llava", vocab_size=256, hidden_size=64,
    intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=16, rope_theta=10_000.0, max_seq_len=512,
    vision=True, vision_cfg=VisionConfig(
        hidden_size=32, intermediate_size=64, num_layers=3, num_heads=2,
        image_size=28, patch_size=14, image_token=250,
    ),
))


def get_config(name: str) -> ModelConfig:
    if name in REGISTRY:
        return REGISTRY[name]
    # Ollama-style tag normalization: suffixes live in the TAG, after the
    # colon — "llama3.2:3b-instruct-fp16" → "llama3.2:3b". Splitting the
    # whole name at '-' would break hyphenated model names
    # ("mistral-nemo:12b-instruct" must not become "mistral").
    if ":" in name:
        model, tag = name.split(":", 1)
        base = f"{model}:{tag.split('-')[0]}"
        if base in REGISTRY:
            return REGISTRY[base]
    raise KeyError(f"unknown model: {name!r} (known: {sorted(REGISTRY)})")


_HF_FAMILY = {
    "llama": "llama",
    "mistral": "llama",  # llama skeleton (+ optional sliding window)
    "qwen2": "qwen2",
    "qwen3": "qwen3",
    "gemma2": "gemma2",
    "mixtral": "mixtral",
    "bert": "bert_embed",
}


def config_from_hf_dir(name: str, path: str) -> ModelConfig:
    """Build a ModelConfig from a local HF checkpoint's config.json, so any
    HF-layout directory can be served without a registry entry (the engine
    falls back to this when `model` is not a registered name but a
    checkpoint_path is set). Inverse of `hf_config()` for the supported
    families."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    return _config_from_hf_dict(name, hf, path)


def _config_from_hf_dict(name: str, hf: dict, path: str) -> ModelConfig:
    mt = hf.get("model_type", "llama")
    if mt == "llava":
        vc = hf.get("vision_config") or {}
        text = dict(hf.get("text_config") or {})
        text.setdefault("model_type", "llama")
        # llava text_configs may be sparse (LlamaConfig defaults implied)
        for k, v in (("vocab_size", 32_064), ("hidden_size", 4096),
                     ("intermediate_size", 11_008), ("num_hidden_layers", 32),
                     ("num_attention_heads", 32),
                     ("max_position_embeddings", 4096)):
            text.setdefault(k, v)
        # only keys the HF config actually carries — VisionConfig's field
        # defaults (the single source of truth) fill the rest
        vkeys = {
            "hidden_size": vc.get("hidden_size"),
            "intermediate_size": vc.get("intermediate_size"),
            "num_layers": vc.get("num_hidden_layers"),
            "num_heads": vc.get("num_attention_heads"),
            "image_size": vc.get("image_size"),
            "patch_size": vc.get("patch_size"),
            "layer_norm_eps": vc.get("layer_norm_eps"),
            "feature_layer": hf.get("vision_feature_layer"),
            "image_token": hf.get("image_token_index"),
        }
        return dataclasses.replace(
            _config_from_hf_dict(name, text, path),
            family="llava", vision=True,
            vision_cfg=VisionConfig(
                **{k: v for k, v in vkeys.items() if v is not None}
            ),
        )
    if mt not in _HF_FAMILY:
        raise ValueError(
            f"unsupported HF model_type {mt!r} in {path} "
            f"(supported: {sorted(_HF_FAMILY)} + llava)"
        )
    family = _HF_FAMILY[mt]
    if family == "bert_embed":
        return ModelConfig(
            name=name, family=family,
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf["num_attention_heads"],
            rms_eps=hf.get("layer_norm_eps", 1e-12),
            max_seq_len=hf.get("max_position_embeddings", 512),
        )
    scaling = None
    rs = hf.get("rope_scaling") or None
    if rs and rs.get("rope_type", rs.get("type")) == "llama3":
        scaling = RopeScaling(
            factor=rs["factor"],
            low_freq_factor=rs["low_freq_factor"],
            high_freq_factor=rs["high_freq_factor"],
            original_max_position_embeddings=rs["original_max_position_embeddings"],
        )
    return ModelConfig(
        name=name, family=family,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf.get("head_dim"),
        rope_theta=hf.get("rope_theta", 10_000.0),
        rope_scaling=scaling,
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        # gemma2 checkpoints tie embeddings without always saying so
        tie_embeddings=hf.get("tie_word_embeddings", family == "gemma2"),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        num_experts=hf.get("num_local_experts", 0),
        experts_per_token=hf.get("num_experts_per_tok", 2),
        # qwen2-style configs carry sliding_window with
        # use_sliding_window=false — honoring it would break the family's
        # full-attention contract (and trip _check_supported)
        sliding_window=(
            (hf.get("sliding_window") or 0)
            if hf.get("use_sliding_window", True) else 0
        ),
        attn_bias=family == "qwen2" or bool(hf.get("attention_bias")),
        qk_norm=family == "qwen3",
        attn_logit_softcap=hf.get("attn_logit_softcapping") or 0.0,
        final_logit_softcap=hf.get("final_logit_softcapping") or 0.0,
        query_pre_attn_scalar=hf.get("query_pre_attn_scalar"),
    )
