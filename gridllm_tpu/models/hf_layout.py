"""HF checkpoint layout: the ONE place that assembles/flattens our
stacked-layer pytree from/to HF tensor names.

Consumers: llama/mixtral `convert_hf_state_dict` (torch state dicts) and
engine/loader.py (safetensors files + sharded placement). Each family owns
its name map (`llama.HF_MAP` / `mixtral.HF_MAP`: our leaf name → (HF name
template, transpose?)); this module owns the stacking mechanics so the
three call sites cannot drift (templates with an expert slot — two `{}`
placeholders — expand over cfg.num_experts into a [L, X, ...] leaf).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models.configs import ModelConfig

# get(hf_name) -> host array; place(pytree_path, host_array) -> device leaf
Get = Callable[[str], np.ndarray]
Place = Callable[[tuple[str, ...], np.ndarray], Any]


def is_expert_leaf(tmpl: str) -> bool:
    """Templates with two {} slots (layer, expert) stack an extra X axis."""
    return tmpl.count("{}") == 2


def default_place(dtype) -> Place:
    return lambda path, arr: jnp.asarray(arr, dtype)


def stack_layer_leaves(
    cfg: ModelConfig,
    get: Get,
    name_map: dict[str, tuple[str, bool]],
    place: Place,
) -> dict[str, Any]:
    """The shared stacking mechanics: per-layer (and per-expert) HF
    tensors onto leading [L] (and [X]) axes, transposing matmul weights
    to [in, out]. Used by every family's assembly path."""
    L = cfg.num_layers

    def stacked(tmpl: str, transpose: bool) -> np.ndarray:
        if is_expert_leaf(tmpl):
            def one(i):
                es = [get(tmpl.format(i, x)) for x in range(cfg.num_experts)]
                return np.stack([e.T if transpose else e for e in es])
        else:
            def one(i):
                w = get(tmpl.format(i))
                return w.T if transpose else w
        return np.stack([np.asarray(one(i)) for i in range(L)])

    return {
        n: place(("layers", n), stacked(t, tr)) for n, (t, tr) in name_map.items()
    }


def flatten_layer_leaves(
    layers: dict[str, Any],
    cfg: ModelConfig,
    name_map: dict[str, tuple[str, bool]],
) -> dict[str, np.ndarray]:
    """Inverse of stack_layer_leaves → HF-named fp32 tensors."""
    out: dict[str, np.ndarray] = {}
    for name, (tmpl, transpose) in name_map.items():
        stacked = np.asarray(layers[name], np.float32)
        for i in range(cfg.num_layers):
            if is_expert_leaf(tmpl):
                for x in range(cfg.num_experts):
                    w = stacked[i, x]
                    out[tmpl.format(i, x)] = w.T.copy() if transpose else w.copy()
            else:
                w = stacked[i]
                out[tmpl.format(i)] = w.T.copy() if transpose else w.copy()
    return out


def to_pytree(
    cfg: ModelConfig,
    get: Get,
    name_map: dict[str, tuple[str, bool]],
    dtype=jnp.bfloat16,
    place: Place | None = None,
) -> dict[str, Any]:
    """Assemble the DECODER-family params pytree (embed/layers/final_norm
    [+ lm_head]); bert_embed composes its own top level over
    stack_layer_leaves."""
    if place is None:
        place = default_place(dtype)
    params: dict[str, Any] = {
        "embed": place(("embed",), np.asarray(get("model.embed_tokens.weight"))),
        "layers": stack_layer_leaves(cfg, get, name_map, place),
        "final_norm": place(("final_norm",), np.asarray(get("model.norm.weight"))),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = place(
            ("lm_head",), np.asarray(get("lm_head.weight")).T
        )
    return params


def to_hf_tensors(
    params: dict[str, Any],
    cfg: ModelConfig,
    name_map: dict[str, tuple[str, bool]],
) -> dict[str, np.ndarray]:
    """Inverse of to_pytree: flatten our pytree into HF-named fp32 tensors
    (checkpoint save + round-trip tests)."""
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    out.update(flatten_layer_leaves(params["layers"], cfg, name_map))
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T.copy()
    return out
