"""BERT-family encoder for the embeddings path (BASELINE.md config #5).

Covers the standard Ollama embedding models that are vanilla BERT
architecture (all-minilm, mxbai-embed-large). The reference served these
by proxying `/api/embed` to Ollama (client/src/services/OllamaService.ts:601);
here they are a first-class model family with an HF `BertModel` golden
twin (tests/test_bert_embed.py).

TPU-first notes: same stacked-[L]-axis + lax.scan scheme as the decoder
families; attention is bidirectional with padding-key masking (seq_lens),
one fused pass per batch — no KV cache, no incremental state. Embedding
models are small; sharding is replicated by default (dp-scale via more
workers, the reference's own model).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.models.llama import _precision
from gridllm_tpu.ops.layers import layer_norm

Params = dict[str, Any]

_NEG_INF = -1e30


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    e, f, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_layers
    ks = iter(jax.random.split(key, 12))

    def w(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    return {
        "word_embed": w(next(ks), v, e),
        "pos_embed": w(next(ks), cfg.max_seq_len, e),
        "type_embed": w(next(ks), 2, e),
        "embed_ln_w": jnp.ones((e,), dtype),
        "embed_ln_b": jnp.zeros((e,), dtype),
        "layers": {
            "wq": w(next(ks), L, e, e), "bq": jnp.zeros((L, e), dtype),
            "wk": w(next(ks), L, e, e), "bk": jnp.zeros((L, e), dtype),
            "wv": w(next(ks), L, e, e), "bv": jnp.zeros((L, e), dtype),
            "wo": w(next(ks), L, e, e), "bo": jnp.zeros((L, e), dtype),
            "attn_ln_w": jnp.ones((L, e), dtype),
            "attn_ln_b": jnp.zeros((L, e), dtype),
            "w_in": w(next(ks), L, e, f), "b_in": jnp.zeros((L, f), dtype),
            "w_out": w(next(ks), L, f, e), "b_out": jnp.zeros((L, e), dtype),
            "mlp_ln_w": jnp.ones((L, e), dtype),
            "mlp_ln_b": jnp.zeros((L, e), dtype),
        },
    }


def hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray | None = None,
    mesh=None,  # family-API uniformity; jnp attention is GSPMD-safe
) -> jnp.ndarray:
    """tokens [B, T] → final hidden states [B, T, E]. Bidirectional
    attention; key positions >= seq_lens are masked (padding must not leak
    into valid tokens' attention, unlike the causal families)."""
    b, t = tokens.shape
    h = cfg.num_heads
    d = cfg.hidden_size // h
    eps = cfg.rms_eps
    if seq_lens is None:
        seq_lens = jnp.full((b,), t, jnp.int32)

    x = (
        params["word_embed"][tokens]
        + params["pos_embed"][jnp.arange(t)][None]
        + params["type_embed"][0][None, None]
    )
    x = layer_norm(x, params["embed_ln_w"], params["embed_ln_b"], eps)
    key_valid = jnp.arange(t)[None] < seq_lens[:, None]  # [B, T]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    def layer(x, lp):
        p = _precision(x)

        def proj(wn, bn):
            return (jnp.dot(x, lp[wn], precision=p) + lp[bn]).reshape(b, t, h, d)

        q, k, v = proj("wq", "bq"), proj("wk", "bk"), proj("wv", "bv")
        logits = jnp.einsum(
            "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ) * scale
        logits = jnp.where(key_valid[:, None, None, :], logits, _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum(
            "bhts,bshd->bthd", probs, v.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(x.dtype).reshape(b, t, -1)
        attn = jnp.dot(attn, lp["wo"], precision=p) + lp["bo"]
        x = layer_norm(x + attn, lp["attn_ln_w"], lp["attn_ln_b"], eps)
        ff = jax.nn.gelu(jnp.dot(x, lp["w_in"], precision=p) + lp["b_in"],
                         approximate=False)
        ff = jnp.dot(ff, lp["w_out"], precision=p) + lp["b_out"]
        return layer_norm(x + ff, lp["mlp_ln_w"], lp["mlp_ln_b"], eps), None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def pool(
    hidden: jnp.ndarray, seq_lens: jnp.ndarray, mode: str
) -> jnp.ndarray:
    """[B, T, E] → [B, E], L2-normalized. mode: "mean" (all-minilm /
    sentence-transformers default) or "cls" (mxbai)."""
    if mode == "cls":
        pooled = hidden[:, 0]
    else:
        t = hidden.shape[1]
        mask = (jnp.arange(t)[None] < seq_lens[:, None])[..., None]
        pooled = (hidden * mask).sum(1) / jnp.maximum(mask.sum(1), 1)
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )


# ---------------------------------------------------------------------------
# HF weight conversion (layout contract with transformers BertModel)
# ---------------------------------------------------------------------------

# our layer leaf → (BertModel tensor template, transpose?)
HF_MAP: dict[str, tuple[str, bool]] = {
    "wq": ("encoder.layer.{}.attention.self.query.weight", True),
    "bq": ("encoder.layer.{}.attention.self.query.bias", False),
    "wk": ("encoder.layer.{}.attention.self.key.weight", True),
    "bk": ("encoder.layer.{}.attention.self.key.bias", False),
    "wv": ("encoder.layer.{}.attention.self.value.weight", True),
    "bv": ("encoder.layer.{}.attention.self.value.bias", False),
    "wo": ("encoder.layer.{}.attention.output.dense.weight", True),
    "bo": ("encoder.layer.{}.attention.output.dense.bias", False),
    "attn_ln_w": ("encoder.layer.{}.attention.output.LayerNorm.weight", False),
    "attn_ln_b": ("encoder.layer.{}.attention.output.LayerNorm.bias", False),
    "w_in": ("encoder.layer.{}.intermediate.dense.weight", True),
    "b_in": ("encoder.layer.{}.intermediate.dense.bias", False),
    "w_out": ("encoder.layer.{}.output.dense.weight", True),
    "b_out": ("encoder.layer.{}.output.dense.bias", False),
    "mlp_ln_w": ("encoder.layer.{}.output.LayerNorm.weight", False),
    "mlp_ln_b": ("encoder.layer.{}.output.LayerNorm.bias", False),
}
_TOP_MAP: dict[str, str] = {
    "word_embed": "embeddings.word_embeddings.weight",
    "pos_embed": "embeddings.position_embeddings.weight",
    "type_embed": "embeddings.token_type_embeddings.weight",
    "embed_ln_w": "embeddings.LayerNorm.weight",
    "embed_ln_b": "embeddings.LayerNorm.bias",
}


def from_getter(
    cfg: ModelConfig,
    get: Callable[[str], np.ndarray],
    dtype=jnp.bfloat16,
    place=None,
) -> Params:
    """Assemble params from an HF-name tensor getter (state dict or
    safetensors). BertModel checkpoints may prefix names with "bert." —
    both spellings accepted; the pooler head is ignored. Stacking
    mechanics come from hf_layout (the one owner of that logic)."""
    from gridllm_tpu.models import hf_layout

    if place is None:
        place = hf_layout.default_place(dtype)

    def get_any(name):
        try:
            return np.asarray(get(name))
        except KeyError:
            return np.asarray(get("bert." + name))

    params: Params = {
        k: place((k,), get_any(v)) for k, v in _TOP_MAP.items()
    }
    params["layers"] = hf_layout.stack_layer_leaves(cfg, get_any, HF_MAP, place)
    return params


def convert_hf_state_dict(cfg: ModelConfig, sd: dict[str, Any], dtype=jnp.bfloat16) -> Params:
    """HF `BertModel.state_dict()` → our pytree."""
    def get(name):
        t = sd[name]  # KeyError propagates to from_getter's fallback
        if hasattr(t, "detach"):
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)

    return from_getter(cfg, get, dtype)


def to_hf_tensors(params: Params, cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Inverse of from_getter (checkpoint save + round-trip tests)."""
    from gridllm_tpu.models import hf_layout

    out: dict[str, np.ndarray] = {
        v: np.asarray(params[k], np.float32) for k, v in _TOP_MAP.items()
    }
    out.update(hf_layout.flatten_layer_leaves(params["layers"], cfg, HF_MAP))
    return out
