"""Llama-family decoder (Llama 3/3.1/3.2, and the dense core Mixtral shares).

TPU-first design choices (SURVEY.md §7 step 4):
- Params are a plain pytree with per-layer weights STACKED on a leading [L]
  axis and the layer loop is `lax.scan` — one traced layer body, O(1)
  compile time in depth, and XLA donates the KV pool buffers through the
  scan so cache updates are in-place in HBM.
- Three entry points, all static-shape: `forward` (full logits, golden
  tests / graft entry), `prefill` (one slot, bucketed T, writes the paged
  cache), `decode_step` (all slots, one token each).
- No data-dependent Python control flow anywhere; active/inactive slots are
  masked, not branched.

The reference has no model code to mirror (compute delegated to Ollama,
client/src/services/OllamaService.ts:17-27); HF Llama is the weight-layout
contract (see convert_hf_state_dict).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.ops.attention import (
    attention_prefill,
    attention_prefix_chunk,
    paged_attention_decode,
    paged_attention_verify,
    ragged_attention_enabled,
    ragged_paged_attention,
)
from gridllm_tpu.ops.kvcache import (
    PagedKVCache,
    write_decode_all,
    write_multi_all,
    write_prefill_all,
)
from gridllm_tpu.ops.quant import qdot
from gridllm_tpu.ops.layers import apply_rope, precompute_rope, rms_norm

Params = dict[str, Any]

# Per-layer FFN body: (layer params, normed activations) -> FFN output.
# llama uses the dense SwiGLU `_mlp`; models/mixtral.py routes its sparse
# MoE body through the same decoder skeleton (attention/norm/paged-cache
# structure is identical across both families).
MlpFn = Callable[["Params", jnp.ndarray], jnp.ndarray]

# Prefill attention body: (q, k, v, seq_lens) -> attended values. Default
# is the ops.attention dispatch (jnp ref / Pallas flash); the engine
# passes ops.ring_attention for sp-sharded long-context prefill.
AttnFn = Callable[
    [jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray
]


def _default_attn(cfg: ModelConfig, mesh=None) -> AttnFn:
    def attn(q, k, v, seq_lens):
        return attention_prefill(
            q, k, v, seq_lens, use_pallas=cfg.use_pallas,
            window=cfg.sliding_window, mesh=mesh,
        )

    return attn


def _precision(x: jnp.ndarray):
    # fp32 runs (goldens) need exact matmuls; bf16 uses the MXU default.
    return jax.lax.Precision.HIGHEST if x.dtype == jnp.float32 else None


def _check_supported(cfg: ModelConfig) -> None:
    # Loud failure beats silently-wrong attention for knobs this skeleton
    # doesn't route (gemma2 owns softcapping in models/gemma.py; uniform
    # sliding windows — mistral-v0.1-class — thread through the attention
    # calls here).
    if cfg.attn_logit_softcap:
        raise NotImplementedError(f"{cfg.name}: attn_logit_softcap")


def validate_mesh(cfg: ModelConfig, mesh) -> None:
    """Engine-init mesh check: ring-attention (sp) prefill has no
    sliding-window variant."""
    if cfg.sliding_window and mesh is not None and mesh.shape.get("sp", 1) > 1:
        raise ValueError(
            f"{cfg.name}: sliding-window attention cannot combine with sp "
            "(ring-attention prefill) yet — shape the mesh without sp"
        )


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16, dense_ffn: bool = True
) -> Params:
    """Random-init params (tests + synthetic bench; real loads go through
    engine/loader.py). `dense_ffn=False` skips the SwiGLU leaves — the MoE
    family reuses the attention skeleton and supplies its own expert leaves
    (materializing dense FFNs only to delete them would transiently cost
    ~11 GB at 8x7b scale)."""
    e, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, kvh, d, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_, cfg.num_layers
    ks = iter(jax.random.split(key, 16))

    def w(k, *shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params: Params = {
        "embed": w(next(ks), v, e, scale=0.02),
        "layers": {
            "attn_norm": jnp.ones((L, e), dtype),
            "wq": w(next(ks), L, e, h * d),
            "wk": w(next(ks), L, e, kvh * d),
            "wv": w(next(ks), L, e, kvh * d),
            "wo": w(next(ks), L, h * d, e),
            "mlp_norm": jnp.ones((L, e), dtype),
        },
        "final_norm": jnp.ones((e,), dtype),
    }
    if dense_ffn:
        params["layers"]["w_gate"] = w(next(ks), L, e, f)
        params["layers"]["w_up"] = w(next(ks), L, e, f)
        params["layers"]["w_down"] = w(next(ks), L, f, e)
    if cfg.attn_bias:
        params["layers"]["bq"] = w(next(ks), L, h * d, scale=0.02)
        params["layers"]["bk"] = w(next(ks), L, kvh * d, scale=0.02)
        params["layers"]["bv"] = w(next(ks), L, kvh * d, scale=0.02)
    if cfg.qk_norm:
        params["layers"]["q_norm"] = jnp.ones((L, d), dtype)
        params["layers"]["k_norm"] = jnp.ones((L, d), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = w(next(ks), e, v, scale=0.02)
    return params


def _mlp(lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    p = _precision(x)
    gate = qdot(x, lp["w_gate"], precision=p)
    up = qdot(x, lp["w_up"], precision=p)
    return qdot(jax.nn.silu(gate) * up, lp["w_down"], precision=p)


def _qkv(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """x: [..., T, E] → q [..., T, H, D], k/v [..., T, KVH, D].

    Family knobs: qwen2 adds bias on the q/k/v projections (never on wo);
    qwen3 RMS-normalizes q/k per head over head_dim before rope (HF
    Qwen3Attention order: project → view heads → q_norm/k_norm → rope).
    """
    p = _precision(x)
    d = cfg.head_dim_
    q = qdot(x, lp["wq"], precision=p)
    k = qdot(x, lp["wk"], precision=p)
    v = qdot(x, lp["wv"], precision=p)
    if cfg.attn_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(*x.shape[:-1], cfg.num_heads, d)
    k = k.reshape(*x.shape[:-1], cfg.num_kv_heads, d)
    v = v.reshape(*x.shape[:-1], cfg.num_kv_heads, d)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
    return q, k, v


def _unembed(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return qdot(
        x, head, precision=_precision(x), preferred_element_type=jnp.float32
    )


def hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    mlp: MlpFn = _mlp,
    seq_lens: jnp.ndarray | None = None,
    attn: AttnFn | None = None,
    embeds: jnp.ndarray | None = None,
    mesh=None,
) -> jnp.ndarray:
    """Final-norm hidden states [B, T, E] (embeddings path; no unembed).
    seq_lens masks padding keys out of attention (None → all valid).
    `embeds` ([B, T, E]) overrides the embedding lookup (vision splice)."""
    _check_supported(cfg)
    if attn is None:
        attn = _default_attn(cfg, mesh)
    b, t = tokens.shape
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    x = params["embed"][tokens] if embeds is None else embeds.astype(
        params["embed"].dtype
    )
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    if seq_lens is None:
        seq_lens = jnp.full((b,), t, jnp.int32)

    def layer(x, lp):
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        att = attn(q, k, v, seq_lens).reshape(b, t, -1)
        x = x + qdot(att, lp["wo"], precision=_precision(x))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        return x + mlp(lp, hx), None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_eps)


def forward(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray, mlp: MlpFn = _mlp,
    embeds: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Cache-free full forward: tokens [B, T] → logits [B, T, V] (fp32).

    The oracle path — golden tests compare this against HF; prefill/decode
    must agree with it (tested in tests/test_models.py).
    """
    return _unembed(
        cfg, params, hidden_states(params, cfg, tokens, mlp, embeds=embeds)
    )


def _seq_constraint(mesh) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """T-axis activation constraint for sp-sharded prefill.

    Round-1 VERDICT #9: without pinning the [1, T, E] residual stream to
    P(None, "sp", None), whether q/k/v projections and MLP activations
    outside ring_attention's shard_map are actually O(T/sp) per device
    depends on GSPMD propagation luck. This turns the memory claim into an
    annotated property (asserted structurally by tests/test_parallel.py).
    """
    if mesh is None or mesh.shape.get("sp", 1) <= 1:
        return lambda x: x
    from jax.sharding import NamedSharding, PartitionSpec

    s = NamedSharding(mesh, PartitionSpec(None, "sp", None))
    return lambda x: jax.lax.with_sharding_constraint(x, s)


def prefill_layers(
    layers: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    seq_lens: jnp.ndarray,
    mlp: MlpFn = _mlp,
    attn: AttnFn | None = None,
    seq_c: Callable[[jnp.ndarray], jnp.ndarray] = lambda x: x,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Self-contained prefill layer scan over an arbitrary stacked block of
    layers (full [L] stack from `prefill`; per-stage blocks from
    parallel/pipeline.py). x: [1, T, E] in; returns (x out,
    k_new [N, T, KVH, D], v_new) — pool writes are the caller's.
    """
    if attn is None:
        attn = _default_attn(cfg)
    t = x.shape[1]
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    pos = jnp.arange(t, dtype=jnp.int32)[None]

    def layer(x, lp):
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        att = attn(q, k, v, seq_lens).reshape(1, t, -1)
        x = seq_c(x + qdot(att, lp["wo"], precision=_precision(x)))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        # K/V ride out as scan ys; the pool is written ONCE after the scan
        # (per-layer writes inside the scan defeat XLA's in-place aliasing
        # and cost full-pool copies — round-4 profiling)
        return seq_c(x + mlp(lp, hx)), (k[0], v[0])

    x, (k_new, v_new) = jax.lax.scan(layer, x, layers)
    return x, k_new, v_new


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp: MlpFn = _mlp,
    attn: AttnFn | None = None,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Prefill ONE slot. tokens: [T] (padded bucket), length: scalar valid
    count, table_row: [max_pages] this slot's pages. Returns (last-token
    logits [V] fp32, updated cache). Sets cache.lengths[slot] = length.
    `mesh` (with sp > 1) pins the residual stream's T axis to the sp mesh
    axis so prefill activations really are O(T/sp) per device.
    `embeds` ([T, E]) overrides the token-embedding lookup — the vision
    path (models/llava.py splice_embeds) feeds image-spliced embeddings;
    tokens are still used for lengths/window bookkeeping by the caller.
    """
    _check_supported(cfg)
    if attn is None:
        attn = _default_attn(cfg, mesh)
    seq_c = _seq_constraint(mesh)
    t = tokens.shape[0]
    x = params["embed"][tokens] if embeds is None else embeds
    x = seq_c(x.astype(params["embed"].dtype)[None])  # [1, T, E]
    x, k_new, v_new = prefill_layers(
        params["layers"], cfg, x, length[None], mlp, attn, seq_c
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    # last *valid* token's logits
    last = x[0, jnp.maximum(length - 1, 0)]
    logits = _unembed(cfg, params, last)

    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new, v_new, table_row,
        jnp.int32(0), length, cache.page_size, use_pallas=cfg.use_pallas,
        mesh=mesh,
    )
    cache = PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(length),
        page_size=cache.page_size,
    )
    return logits, cache


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp: MlpFn = _mlp,
    mesh=None,  # accepted for family-API uniformity (MoE uses it)
    embeds: jnp.ndarray | None = None,  # [C, E] override (vision splice)
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Prefill ONE CHUNK of one slot against its cached prefix.

    tokens: [C] (padded chunk bucket), start: scalar absolute position of
    tokens[0] (0 for the first chunk), length: scalar valid tokens in THIS
    chunk. Attention reads prefix K/V from the page pool (the chunk's K/V
    are written first), so a long prompt runs as ceil(T/C) invocations of
    ONE compiled program instead of a per-length trace (VERDICT.md #4).
    Returns (last-valid-token logits [V] fp32, cache with lengths[slot] =
    start + length).
    """
    _check_supported(cfg)
    x = params["embed"][tokens] if embeds is None else embeds
    x = x.astype(params["embed"].dtype)[None]  # [1, C, E]
    x, k_new, v_new = prefill_chunk_layers(
        params["layers"], cfg, x, cache.k, cache.v, table_row, start,
        length, cache.page_size, mlp, mesh=mesh,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    last = x[0, jnp.maximum(length - 1, 0)]
    logits = _unembed(cfg, params, last)

    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new, v_new, table_row, start, length,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    cache = PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(start + length),
        page_size=cache.page_size,
    )
    return logits, cache


def prefill_chunk_layers(
    layers: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    page_size: int,
    mlp: MlpFn = _mlp,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Chunked-prefill layer scan over an arbitrary stacked block of
    layers against the slot's cached prefix (full stack from
    `prefill_chunk`; per-stage blocks from parallel/pipeline.py).
    x: [1, C, E] in; returns (x out, k_new [N, C, KVH, D], v_new).
    Attention dispatches to pallas_kernels.prefix_chunk (paged-prefix
    streaming flash) when kernels are on — `mesh` threads through for the
    meshed shard_map wrapper."""
    t = x.shape[1]
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    pos = (start + jnp.arange(t, dtype=jnp.int32))[None]
    total = start + length
    n = jax.tree.leaves(layers)[0].shape[0]

    def layer(x, xs):
        lp, li = xs
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        # pool holds the PREFIX only (writes deferred past the scan); the
        # fresh chunk's K/V are overlaid inside the attention. Full pool as
        # closure + layer index — see decode_layers. Ragged mode routes
        # through the unified kernel's chunk region (ISSUE 6).
        if ragged_attention_enabled():
            att, _ = ragged_paged_attention(
                k_pool, v_pool, page_size,
                q_chunk=q, chunk_row=table_row, chunk_start=start,
                chunk_total=total, k_chunk=k[0], v_chunk=v[0], layer=li,
                use_pallas=cfg.use_pallas, window=cfg.sliding_window,
                mesh=mesh,
            )
            att = att.reshape(1, t, -1)
        else:
            att = attention_prefix_chunk(
                q, k_pool, v_pool, table_row, start, total, page_size,
                k_cur=k[0], v_cur=v[0], layer=li, use_pallas=cfg.use_pallas,
                window=cfg.sliding_window, mesh=mesh,
            ).reshape(1, t, -1)
        x = x + qdot(att, lp["wo"], precision=_precision(x))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        return x + mlp(lp, hx), (k[0], v[0])

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (layers, jnp.arange(n, dtype=jnp.int32))
    )
    return x, k_new, v_new


def decode_layers(
    layers: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    page_size: int,
    mlp: MlpFn = _mlp,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The decode layer scan over an arbitrary stacked block of layers.

    `layers` leaves are stacked [N, ...]; `k_pool`/`v_pool` is the
    matching [N, P, ps, KVH, D] pool block. decode_step runs this over the
    full [L] stack; parallel/pipeline.py runs it per pp stage with the
    stage's local block. x: [S, E] residual stream in; returns
    (x out, k_new [N, S, KVH, D], v_new) — pool writes are the caller's
    (deferred one-shot write after the scan).
    """
    s = x.shape[0]
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    n = jax.tree.leaves(layers)[0].shape[0]

    def layer(x, xs):
        lp, li = xs
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)  # q: [S, H, D] (T-less), k/v: [S, KVH, D]
        q = apply_rope(q[:, None], positions[:, None], inv_freq)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], inv_freq)[:, 0]
        # pool holds the prefix only (lengths = positions); the current
        # token's K/V are merged in-register by the attention and written
        # to the pool ONCE after the scan (in-place DMA kernel). The FULL
        # pool rides in as a scan closure with `li` selecting the layer —
        # per-layer xs slices would materialize 2×pool-slice copies/iter.
        # Ragged mode: a decode step is the unified kernel's group region
        # with query_len = 1 per slot (ISSUE 6).
        if ragged_attention_enabled():
            _, attn = ragged_paged_attention(
                k_pool, v_pool, page_size,
                q_group=q[:, None], page_table=page_table,
                group_lengths=positions, k_group=k[:, None],
                v_group=v[:, None], layer=li, use_pallas=cfg.use_pallas,
                window=cfg.sliding_window, mesh=mesh,
            )
            attn = attn[:, 0].reshape(s, -1)
        else:
            attn = paged_attention_decode(
                q, k_pool, v_pool, page_table, positions,
                page_size, k_cur=k, v_cur=v, layer=li,
                use_pallas=cfg.use_pallas, window=cfg.sliding_window,
                mesh=mesh,
            ).reshape(s, -1)
        x = x + qdot(attn, lp["wo"], precision=_precision(x))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        return x + mlp(lp, hx), (k, v)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (layers, jnp.arange(n, dtype=jnp.int32))
    )
    return x, k_new, v_new


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp: MlpFn = _mlp,
    mesh=None,  # meshed-kernel dispatch (ops) + MoE EP routing
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step for ALL slots. tokens: [S] (last sampled token per
    slot), active: [S] bool. Returns (logits [S, V] fp32, updated cache
    with lengths advanced for active slots).
    """
    _check_supported(cfg)
    x = params["embed"][tokens]  # [S, E]
    positions = cache.lengths  # new token's position per slot
    # clamp at pool-wide capacity: finished slots stay device-active for up
    # to decode_block × pipeline_depth in-flight steps after the host
    # finishes them (engine.py); unbounded growth would walk the length
    # past the page table (reads) even though writes are sentinel-dropped
    new_lengths = jnp.minimum(
        cache.lengths + active.astype(jnp.int32), cache.max_context
    )

    x, k_new, v_new = decode_layers(
        params["layers"], cfg, x, cache.k, cache.v, cache.page_table,
        positions, cache.page_size, mlp, mesh=mesh,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x)

    k_pool, v_pool = write_decode_all(
        cache.k, cache.v, k_new, v_new, cache.page_table, positions, active,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    cache = PagedKVCache(
        k=k_pool, v=v_pool, page_table=cache.page_table,
        lengths=new_lengths, page_size=cache.page_size,
    )
    return logits, cache


def verify_layers(
    layers: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    base_lengths: jnp.ndarray,
    page_size: int,
    mlp: MlpFn = _mlp,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative-verify layer scan: T candidate tokens for ALL slots at
    once against each slot's paged prefix (ISSUE 5). x: [S, T, E];
    base_lengths: [S] cached-prefix length per slot (candidate i sits at
    absolute position base_lengths[s] + i). Returns (x out, k_new
    [L, S, T, KVH, D], v_new) — pool writes are the caller's, same
    deferred-write discipline as decode_layers.

    Tree verify (ISSUE 18): with `tree_pos` ([T] node depths) and
    `tree_mask` ([T, T] ancestor-or-self, both static host constants) the
    T candidates form a token tree — node i takes rope at LOGICAL
    position base_lengths[s] + tree_pos[i] and its query attends the
    prefix plus exactly its tree ancestors (see
    ops.attention.paged_attention_verify_ref)."""
    s, t = x.shape[:2]
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    rel = (jnp.asarray(tree_pos, jnp.int32) if tree_pos is not None
           else jnp.arange(t, dtype=jnp.int32))
    pos = base_lengths[:, None] + rel[None]
    n = jax.tree.leaves(layers)[0].shape[0]

    def layer(x, xs):
        lp, li = xs
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)  # q: [S, T, H, D], k/v: [S, T, KVH, D]
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        # pool holds each slot's prefix only; the candidates' K/V are
        # overlaid in-register and written ONCE after the scan (full pool
        # as closure + layer index — see decode_layers). Ragged mode: ONE
        # launch over all slots (group region, query_len = K+1) instead
        # of paged_attention_verify's per-slot kernel loop (ISSUE 6).
        if ragged_attention_enabled():
            _, att = ragged_paged_attention(
                k_pool, v_pool, page_size,
                q_group=q, page_table=page_table,
                group_lengths=base_lengths, k_group=k, v_group=v,
                layer=li, use_pallas=cfg.use_pallas,
                window=cfg.sliding_window, mesh=mesh,
                tree_pos=tree_pos, tree_mask=tree_mask,
            )
            att = att.reshape(s, t, -1)
        else:
            att = paged_attention_verify(
                q, k_pool, v_pool, page_table, base_lengths, page_size,
                k_cur=k, v_cur=v, layer=li, use_pallas=cfg.use_pallas,
                window=cfg.sliding_window, mesh=mesh,
                tree_pos=tree_pos, tree_mask=tree_mask,
            ).reshape(s, t, -1)
        x = x + qdot(att, lp["wo"], precision=_precision(x))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        return x + mlp(lp, hx), (k, v)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (layers, jnp.arange(n, dtype=jnp.int32))
    )
    return x, k_new, v_new


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp: MlpFn = _mlp,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One speculative-verify forward for ALL slots (ISSUE 5). tokens:
    [S, T] candidate blocks (col 0 = each slot's committed last token,
    cols 1..T-1 = drafted candidates), active: [S] bool. Returns (logits
    [S, T, V] fp32 — row j is the distribution after consuming candidates
    0..j — and the cache with the candidates' KV written OPTIMISTICALLY at
    positions lengths[s]..lengths[s]+T-1 but lengths UNCHANGED: the engine
    commits the accepted length afterwards via
    ops.kvcache.rollback_to_length, which drops rejected rows).

    Tree verify (ISSUE 18): `tree_pos`/`tree_mask` (static topology, see
    verify_layers) make cols 1..T-1 a token TREE — node i still lands at
    STORAGE position lengths[s] + i (the engine compacts the accepted
    path with ops.kvcache.commit_tree_path before rolling lengths
    forward), logits row i is the distribution after consuming node i's
    root path."""
    _check_supported(cfg)
    s, t = tokens.shape
    x = params["embed"][tokens]  # [S, T, E]
    base = cache.lengths
    positions = base[:, None] + jnp.arange(t, dtype=jnp.int32)[None]

    x, k_new, v_new = verify_layers(
        params["layers"], cfg, x, cache.k, cache.v, cache.page_table,
        base, cache.page_size, mlp, mesh=mesh,
        tree_pos=tree_pos, tree_mask=tree_mask,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x)  # [S, T, V]

    k_pool, v_pool = write_multi_all(
        cache.k, cache.v, k_new, v_new, cache.page_table, positions, active,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    cache = PagedKVCache(
        k=k_pool, v=v_pool, page_table=cache.page_table,
        lengths=base, page_size=cache.page_size,
    )
    return logits, cache


def mixed_layers(
    layers: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    chunk_width: int,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    chunk_row: jnp.ndarray,
    chunk_start: jnp.ndarray,
    chunk_total: jnp.ndarray,
    group_lengths: jnp.ndarray,
    page_size: int,
    mlp: MlpFn = _mlp,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Mixed chunked-prefill + decode layer scan (ISSUE 6): the ragged
    token batch [1, C+S, E] — rows [0, C) one admitting slot's prefill
    chunk at absolute positions chunk_start + i, rows [C, C+S) one decode
    token per slot at positions group_lengths[s] — runs the whole layer
    stack with ONE ragged attention launch per layer. Pointwise sublayers
    (norms, projections, MLP) are row-independent, so each region's rows
    compute exactly what the separate legacy programs would. Returns
    (x out, k_new [L, C+S, KVH, D], v_new) — pool writes are the
    caller's, split per region."""
    c = chunk_width
    t = x.shape[1]
    s = t - c
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    pos = jnp.concatenate([
        chunk_start + jnp.arange(c, dtype=jnp.int32), group_lengths
    ])[None]
    n = jax.tree.leaves(layers)[0].shape[0]

    def layer(x, xs):
        lp, li = xs
        hx = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)
        q = apply_rope(q, pos, inv_freq)
        k = apply_rope(k, pos, inv_freq)
        oc, og = ragged_paged_attention(
            k_pool, v_pool, page_size,
            q_chunk=q[:, :c], chunk_row=chunk_row, chunk_start=chunk_start,
            chunk_total=chunk_total, k_chunk=k[0, :c], v_chunk=v[0, :c],
            q_group=q[0, c:][:, None], page_table=page_table,
            group_lengths=group_lengths, k_group=k[0, c:][:, None],
            v_group=v[0, c:][:, None], layer=li, use_pallas=cfg.use_pallas,
            window=cfg.sliding_window, mesh=mesh,
        )
        att = jnp.concatenate([oc[0], og[:, 0]]).reshape(1, t, -1)
        x = x + qdot(att, lp["wo"], precision=_precision(x))
        hx = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        return x + mlp(lp, hx), (k[0], v[0])

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (layers, jnp.arange(n, dtype=jnp.int32))
    )
    return x, k_new, v_new


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    chunk_tokens: jnp.ndarray,
    chunk_start: jnp.ndarray,
    chunk_len: jnp.ndarray,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp: MlpFn = _mlp,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, PagedKVCache]:
    """One fused chunked-prefill + decode step (ISSUE 6): the prefill
    chunk for ONE admitting slot PLUS one decode token for every active
    slot, batched into one ragged descriptor — a single attention launch
    per layer instead of the legacy per-phase (and per-slot) dispatches.
    Long prefills stop stalling running streams: the batch keeps decoding
    while the chunk prefills alongside it (the DeepServe mixed-step
    shape).

    chunk_tokens: [C] (padded chunk), chunk_start/chunk_len: scalars,
    table_row: [max_pages] the admitting slot's pages, tokens: [S] each
    slot's last token, active: [S]. Returns (chunk last-valid-token
    logits [V], decode logits [S, V], updated cache with the chunk
    written at [chunk_start, chunk_start+chunk_len) and active slots
    advanced by one)."""
    _check_supported(cfg)
    c = chunk_tokens.shape[0]
    xc = params["embed"][chunk_tokens] if embeds is None else embeds
    xg = params["embed"][tokens]
    x = jnp.concatenate([
        xc.astype(params["embed"].dtype), xg.astype(params["embed"].dtype)
    ])[None]                                        # [1, C+S, E]
    positions = cache.lengths
    total = chunk_start + chunk_len

    x, k_new, v_new = mixed_layers(
        params["layers"], cfg, x, c, cache.k, cache.v, cache.page_table,
        table_row, chunk_start, total, positions, cache.page_size, mlp,
        mesh=mesh,
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    chunk_logits = _unembed(
        cfg, params, x[0, jnp.maximum(chunk_len - 1, 0)]
    )
    dec_logits = _unembed(cfg, params, x[0, c:])

    # region writes target disjoint pages (the admitting slot is not yet
    # active), so the order is immaterial
    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new[:, :c], v_new[:, :c], table_row,
        chunk_start, chunk_len, cache.page_size, use_pallas=cfg.use_pallas,
        mesh=mesh,
    )
    k_pool, v_pool = write_decode_all(
        k_pool, v_pool, k_new[:, c:], v_new[:, c:], cache.page_table,
        positions, active, cache.page_size, use_pallas=cfg.use_pallas,
        mesh=mesh,
    )
    new_lengths = jnp.minimum(
        cache.lengths + active.astype(jnp.int32), cache.max_context
    ).at[slot].set(total)
    cache = PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=new_lengths, page_size=cache.page_size,
    )
    return chunk_logits, dec_logits, cache


# ---------------------------------------------------------------------------
# HF weight conversion (layout contract with transformers LlamaForCausalLM)
# ---------------------------------------------------------------------------

# Single source of truth for the HF<->ours layout contract: our layer-leaf
# name → (HF tensor name template, transpose?). {} is the layer index (an
# extra {} is the expert index for MoE leaves). engine/loader.py drives the
# safetensors path off this same table. HF stores projections [out, in];
# we keep [in, out] so forward is x @ W — hence transpose=True on matmuls.
HF_MAP: dict[str, tuple[str, bool]] = {
    "attn_norm": ("model.layers.{}.input_layernorm.weight", False),
    "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
    "mlp_norm": ("model.layers.{}.post_attention_layernorm.weight", False),
    "w_gate": ("model.layers.{}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{}.mlp.down_proj.weight", True),
}


def hf_map(cfg: ModelConfig) -> dict[str, tuple[str, bool]]:
    """HF_MAP extended with the config's family knobs (qwen2 qkv bias,
    qwen3 qk norms) — the full layout contract for llama-skeleton models."""
    m = dict(HF_MAP)
    if cfg.attn_bias:
        m["bq"] = ("model.layers.{}.self_attn.q_proj.bias", False)
        m["bk"] = ("model.layers.{}.self_attn.k_proj.bias", False)
        m["bv"] = ("model.layers.{}.self_attn.v_proj.bias", False)
    if cfg.qk_norm:
        m["q_norm"] = ("model.layers.{}.self_attn.q_norm.weight", False)
        m["k_norm"] = ("model.layers.{}.self_attn.k_norm.weight", False)
    return m


def convert_state_dict(
    cfg: ModelConfig,
    sd: dict[str, Any],
    name_map: dict[str, tuple[str, bool]],
    dtype=jnp.bfloat16,
) -> Params:
    """Generic HF state_dict → stacked-layer pytree, driven by a name map
    (llama's HF_MAP or mixtral's). Accepts numpy/torch tensors."""
    import numpy as np

    from gridllm_tpu.models import hf_layout

    def get(name):
        t = sd[name]
        if hasattr(t, "detach"):
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)

    return hf_layout.to_pytree(cfg, get, name_map, dtype)


def convert_hf_state_dict(cfg: ModelConfig, sd: dict[str, Any], dtype=jnp.bfloat16) -> Params:
    """HF `LlamaForCausalLM.state_dict()`-style mapping → our pytree
    (also Qwen2/Qwen3ForCausalLM — same skeleton, knobs via hf_map)."""
    return convert_state_dict(cfg, sd, hf_map(cfg), dtype)
