"""Llava-family vision-language model: CLIP-ViT tower + MLP projector +
the shared llama decoder skeleton.

The reference served llava-class models by passing base64 `images`
through to Ollama (client/src/services/OllamaService.ts:197-226 `images`
option); this is the rebuild's native implementation (VERDICT r03
missing #5). TPU-first choices:

- The patch "convolution" is a reshape + one [N, 3*ps*ps] x [3*ps*ps, D]
  matmul — a conv with stride == kernel size IS a patch matmul, and the
  matmul form lands on the MXU without any conv lowering.
- The tower is scan-stacked like every other family; the HF
  `vision_feature_layer=-2` semantics (stop before the last encoder
  layer) become a STATIC slice of the stacked layer params — no
  per-layer Python loop, no dead compute for the unused tail layers.
- Image-token splice is a gather-select inside the jitted prefill: the
  engine expands each image placeholder to `num_patches` copies of
  `vision_cfg.image_token` host-side, and `splice_embeds` overlays the
  j-th image-token position with projected patch row j. Same scatter
  semantics as HF's masked_scatter fill, but as a dense where() —
  shape-static and trivially shardable.

Weight layout contract: HF `LlavaForConditionalGeneration`. Both HF
namings are accepted — the 4.52+ "model.vision_tower.* / lm_head" flat
layout and the original "vision_tower.* / language_model.model.*"
checkpoint layout that llava-hf publishes (tests/test_llava.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import ModelConfig, VisionConfig
from gridllm_tpu.ops.layers import layer_norm

Params = dict[str, Any]

# the decoder skeleton is llama's — prefill/decode/forward are shared
# verbatim (the text stack of llava-1.5 is a vanilla llama/vicuna)
prefill = llama.prefill
prefill_chunk = llama.prefill_chunk
decode_step = llama.decode_step
verify_step = llama.verify_step
mixed_step = llama.mixed_step
forward = llama.forward
hidden_states = llama.hidden_states
hf_map = llama.hf_map


def _quick_gelu(x: jnp.ndarray) -> jnp.ndarray:
    # CLIP's activation (HF ACT2FN["quick_gelu"])
    return x * jax.nn.sigmoid(1.702 * x)


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16
) -> Params:
    """llama text params at the top level (so the engine's decode path is
    family-agnostic) + `vision` / `projector` subtrees."""
    vc = cfg.vision_cfg or VisionConfig()
    kt, kv = jax.random.split(key)
    params = llama.init_params(cfg, kt, dtype)
    dv, fv, lv = vc.hidden_size, vc.intermediate_size, vc.num_layers
    e = cfg.hidden_size
    pdim = 3 * vc.patch_size * vc.patch_size
    ks = iter(jax.random.split(kv, 12))

    def w(k, *shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params["vision"] = {
        "cls": w(next(ks), dv),
        "patch_embed": w(next(ks), pdim, dv),
        "pos_embed": w(next(ks), vc.num_patches + 1, dv),
        "pre_ln_w": jnp.ones((dv,), dtype),
        "pre_ln_b": jnp.zeros((dv,), dtype),
        "layers": {
            "ln1_w": jnp.ones((lv, dv), dtype),
            "ln1_b": jnp.zeros((lv, dv), dtype),
            "wq": w(next(ks), lv, dv, dv),
            "bq": jnp.zeros((lv, dv), dtype),
            "wk": w(next(ks), lv, dv, dv),
            "bk": jnp.zeros((lv, dv), dtype),
            "wv": w(next(ks), lv, dv, dv),
            "bv": jnp.zeros((lv, dv), dtype),
            "wo": w(next(ks), lv, dv, dv),
            "bo": jnp.zeros((lv, dv), dtype),
            "ln2_w": jnp.ones((lv, dv), dtype),
            "ln2_b": jnp.zeros((lv, dv), dtype),
            "fc1": w(next(ks), lv, dv, fv),
            "b1": jnp.zeros((lv, fv), dtype),
            "fc2": w(next(ks), lv, fv, dv),
            "b2": jnp.zeros((lv, dv), dtype),
        },
    }
    params["projector"] = {
        "w1": w(next(ks), dv, e),
        "b1": jnp.zeros((e,), dtype),
        "w2": w(next(ks), e, e),
        "b2": jnp.zeros((e,), dtype),
    }
    return params


def _patchify(vc: VisionConfig, pixel_values: jnp.ndarray) -> jnp.ndarray:
    """[B, 3, S, S] → [B, N, 3*ps*ps] with per-patch dims flattened in the
    HF conv kernel's (channel, row, col) order."""
    b = pixel_values.shape[0]
    ps = vc.patch_size
    n = vc.image_size // ps
    x = pixel_values.reshape(b, 3, n, ps, n, ps)
    x = x.transpose(0, 2, 4, 1, 3, 5)          # [B, nh, nw, 3, ps, ps]
    return x.reshape(b, n * n, 3 * ps * ps)


def vision_tower(
    params: Params, vc: VisionConfig, pixel_values: jnp.ndarray
) -> jnp.ndarray:
    """CLIP vision encoder → feature-layer patch embeddings.

    pixel_values: [B, 3, S, S] (CLIP-normalized). Returns [B, N, Dv]: the
    hidden states at `vc.feature_layer` (HF hidden_states indexing), CLS
    dropped ("default" select strategy — llava-1.5's).
    """
    vp = params["vision"]
    b = pixel_values.shape[0]
    dv, heads, dh = vc.hidden_size, vc.num_heads, vc.head_dim
    eps = vc.layer_norm_eps

    patches = _patchify(vc, pixel_values.astype(vp["patch_embed"].dtype))
    x = patches @ vp["patch_embed"]                       # [B, N, Dv]
    cls = jnp.broadcast_to(vp["cls"], (b, 1, dv)).astype(x.dtype)
    x = jnp.concatenate([cls, x], axis=1) + vp["pos_embed"]
    x = layer_norm(x, vp["pre_ln_w"], vp["pre_ln_b"], eps)

    # HF hidden_states[i] = input of layer i (hidden_states[0] = post-
    # pre-LN embeddings, [-1] = final layer's output); feature_layer=-2
    # therefore runs all but the last encoder layer. Static slice of the
    # stacked params — the unused tail layers cost nothing.
    fl = vc.feature_layer
    n_run = vc.num_layers + 1 + fl if fl < 0 else fl
    if not 0 <= n_run <= vc.num_layers:
        raise ValueError(f"vision feature_layer {fl} out of range")
    lp_run = jax.tree.map(lambda a: a[:n_run], vp["layers"])

    def layer(x, lp):
        # pre-LN transformer block, bidirectional MHA with biases
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], eps)
        t = h.shape[1]
        q = (h @ lp["wq"] + lp["bq"]).reshape(b, t, heads, dh)
        k = (h @ lp["wk"] + lp["bk"]).reshape(b, t, heads, dh)
        v = (h @ lp["wv"] + lp["bv"]).reshape(b, t, heads, dh)
        logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
        probs = jax.nn.softmax(logits / np.sqrt(dh), axis=-1).astype(v.dtype)
        att = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(b, t, dv)
        x = x + (att @ lp["wo"] + lp["bo"])
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], eps)
        h = _quick_gelu(h @ lp["fc1"] + lp["b1"]) @ lp["fc2"] + lp["b2"]
        return x + h, None

    if n_run > 0:
        x, _ = jax.lax.scan(layer, x, lp_run)
    return x[:, 1:]  # drop CLS


def encode_images(
    params: Params, cfg: ModelConfig, pixel_values: jnp.ndarray
) -> jnp.ndarray:
    """[B, 3, S, S] → projected image embeddings [B, N, E_text]."""
    vc = cfg.vision_cfg or VisionConfig()
    feats = vision_tower(params, vc, pixel_values)
    pj = params["projector"]
    h = jax.nn.gelu(feats @ pj["w1"] + pj["b1"], approximate=False)
    return h @ pj["w2"] + pj["b2"]


def splice_embeds(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    img_embeds: jnp.ndarray,
    offset: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Token embeddings with image positions overlaid.

    tokens: [T] (image placeholders already EXPANDED to num_patches copies
    of vision_cfg.image_token per image, engine-side); img_embeds: [M, E]
    flattened projected patches (M = n_images * num_patches; rows in
    prompt order). The j-th image-token position takes img_embeds[offset+j]
    — HF's masked-fill semantics as a dense select. `offset` is the count
    of image tokens BEFORE this span (chunked prefill passes per-chunk
    offsets so one fixed-shape program serves every chunk). Returns [T, E].
    """
    vc = cfg.vision_cfg or VisionConfig()
    base = params["embed"][tokens]                       # [T, E]
    is_img = tokens == vc.image_token
    j = offset + jnp.cumsum(is_img.astype(jnp.int32)) - 1  # [T]
    j = jnp.clip(j, 0, img_embeds.shape[0] - 1)
    return jnp.where(is_img[:, None], img_embeds[j].astype(base.dtype), base)


# ---------------------------------------------------------------------------
# HF weight layout
# ---------------------------------------------------------------------------

# our vision leaf → (HF suffix template under the vision tower, transpose?)
_VISION_LAYER_MAP: dict[str, tuple[str, bool]] = {
    "ln1_w": ("encoder.layers.{}.layer_norm1.weight", False),
    "ln1_b": ("encoder.layers.{}.layer_norm1.bias", False),
    "wq": ("encoder.layers.{}.self_attn.q_proj.weight", True),
    "bq": ("encoder.layers.{}.self_attn.q_proj.bias", False),
    "wk": ("encoder.layers.{}.self_attn.k_proj.weight", True),
    "bk": ("encoder.layers.{}.self_attn.k_proj.bias", False),
    "wv": ("encoder.layers.{}.self_attn.v_proj.weight", True),
    "bv": ("encoder.layers.{}.self_attn.v_proj.bias", False),
    "wo": ("encoder.layers.{}.self_attn.out_proj.weight", True),
    "bo": ("encoder.layers.{}.self_attn.out_proj.bias", False),
    "ln2_w": ("encoder.layers.{}.layer_norm2.weight", False),
    "ln2_b": ("encoder.layers.{}.layer_norm2.bias", False),
    "fc1": ("encoder.layers.{}.mlp.fc1.weight", True),
    "b1": ("encoder.layers.{}.mlp.fc1.bias", False),
    "fc2": ("encoder.layers.{}.mlp.fc2.weight", True),
    "b2": ("encoder.layers.{}.mlp.fc2.bias", False),
}

Get = Callable[[str], np.ndarray]


def _resolving_get(get: Get) -> Callable[[str], np.ndarray]:
    """Accept both HF llava namings: transformers ≥4.52's flat
    "model.language_model.* / model.vision_tower.* / lm_head.*" and the
    published checkpoints' "language_model.model.* / vision_tower.* /
    language_model.lm_head.*"."""
    alts = {
        "model.": ("model.language_model.", "language_model.model."),
        "lm_head.": ("lm_head.", "language_model.lm_head."),
        "VIS.": ("model.vision_tower.vision_model.",
                 "vision_tower.vision_model."),
        "PROJ.": ("model.multi_modal_projector.",
                  "multi_modal_projector."),
    }

    def resolve(name: str) -> np.ndarray:
        for pfx, subs in alts.items():
            if name.startswith(pfx):
                last = None
                for sub in subs:
                    try:
                        return get(sub + name[len(pfx):])
                    except KeyError as e:
                        last = e
                raise last
        return get(name)

    return resolve


def from_getter(
    cfg: ModelConfig, get: Get, dtype, place
) -> Params:
    """Assemble the llava pytree from HF-named tensors (engine/loader)."""
    from gridllm_tpu.models import hf_layout

    vc = cfg.vision_cfg or VisionConfig()
    rget = _resolving_get(get)
    params = hf_layout.to_pytree(cfg, rget, hf_map(cfg), dtype, place)

    ps = vc.patch_size
    patch = np.asarray(rget("VIS.embeddings.patch_embedding.weight"))
    vision: Params = {
        "cls": place(("vision", "cls"),
                     np.asarray(rget("VIS.embeddings.class_embedding"))),
        # conv [Dv, 3, ps, ps] → matmul [3*ps*ps, Dv]
        "patch_embed": place(("vision", "patch_embed"),
                             patch.reshape(patch.shape[0], 3 * ps * ps).T),
        "pos_embed": place(("vision", "pos_embed"),
                           np.asarray(rget("VIS.embeddings.position_embedding.weight"))),
        # (sic) "pre_layrnorm" is HF's own spelling
        "pre_ln_w": place(("vision", "pre_ln_w"),
                          np.asarray(rget("VIS.pre_layrnorm.weight"))),
        "pre_ln_b": place(("vision", "pre_ln_b"),
                          np.asarray(rget("VIS.pre_layrnorm.bias"))),
    }
    layers: Params = {}
    for leaf, (tmpl, tr) in _VISION_LAYER_MAP.items():
        rows = []
        for i in range(vc.num_layers):
            w = np.asarray(rget("VIS." + tmpl.format(i)))
            rows.append(w.T if tr else w)
        layers[leaf] = place(("vision", "layers", leaf), np.stack(rows))
    vision["layers"] = layers
    params["vision"] = vision
    params["projector"] = {
        "w1": place(("projector", "w1"),
                    np.asarray(rget("PROJ.linear_1.weight")).T),
        "b1": place(("projector", "b1"),
                    np.asarray(rget("PROJ.linear_1.bias"))),
        "w2": place(("projector", "w2"),
                    np.asarray(rget("PROJ.linear_2.weight")).T),
        "b2": place(("projector", "b2"),
                    np.asarray(rget("PROJ.linear_2.bias"))),
    }
    return params


def convert_hf_state_dict(
    cfg: ModelConfig, sd: dict[str, Any], dtype=jnp.bfloat16
) -> Params:
    """torch state dict (LlavaForConditionalGeneration) → our pytree
    (golden tests)."""
    from gridllm_tpu.models import hf_layout

    def get(name: str) -> np.ndarray:
        if name not in sd:
            raise KeyError(name)
        return sd[name].to("cpu").float().numpy()

    return from_getter(cfg, get, dtype, hf_layout.default_place(dtype))
