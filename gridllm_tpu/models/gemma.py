"""Gemma-2 family decoder (gemma2:2b/9b/27b).

Same scan-stacked/paged-cache skeleton as models/llama.py, but the gemma2
block differs in every place that matters for numerics, so the family owns
its layer body instead of parameterizing llama's:

- RMSNorm multiplies by (1 + w), in fp32 (HF Gemma2RMSNorm);
- FOUR norms per layer: pre/post attention and pre/post feed-forward,
  with the post-norms applied to the sublayer OUTPUT before the residual;
- GeGLU with tanh-approximated gelu (hidden_activation
  "gelu_pytorch_tanh");
- embeddings scaled by sqrt(hidden_size) (cast to the activation dtype
  first, matching HF's normalizer rounding);
- attention logits tanh-softcapped (attn_logit_softcapping) and scaled by
  query_pre_attn_scalar**-0.5 instead of head_dim**-0.5 — implemented by
  pre-scaling q with sqrt(d / qpas) so the shared attention ops keep
  their 1/sqrt(d) convention;
- sliding-window attention on EVEN layers (HF: layer_idx % 2 == 0),
  threaded through the scan as a per-layer window scalar — handled by
  both the ops/attention.py jnp paths and the Pallas kernels (softcap +
  window as traced per-layer scalars, tests/test_pallas.py); dispatch
  follows cfg.use_pallas;
- final logits tanh-softcapped (final_logit_softcapping).

Weight layout contract: HF Gemma2ForCausalLM (tied embeddings; the four
per-layer norms under their HF names). The reference served gemma via
Ollama passthrough (client/src/services/OllamaService.ts); no model code
to mirror.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from gridllm_tpu.models.configs import ModelConfig
# _precision: the families' shared dtype→matmul-precision policy;
# validate_mesh: gemma2 always has sliding windows, so llama's window×sp
# engine-init guard is exactly the needed rule — one copy, no drift
from gridllm_tpu.models.llama import _precision, validate_mesh  # noqa: F401
from gridllm_tpu.ops.attention import (
    attention_prefill,
    attention_prefix_chunk,
    paged_attention_decode,
    paged_attention_verify,
    ragged_attention_enabled,
    ragged_paged_attention,
)
from gridllm_tpu.ops.kvcache import (
    PagedKVCache,
    write_decode_all,
    write_multi_all,
    write_prefill_all,
)
from gridllm_tpu.ops.layers import apply_rope, precompute_rope
from gridllm_tpu.ops.quant import qdot

Params = dict[str, Any]


def _gnorm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Gemma RMSNorm: fp32, multiplies by (1 + w)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * (1.0 + w.astype(jnp.float32))).astype(dtype)




def _geglu(lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    p = _precision(x)
    gate = qdot(x, lp["w_gate"], precision=p)
    up = qdot(x, lp["w_up"], precision=p)
    return qdot(
        jax.nn.gelu(gate, approximate=True) * up, lp["w_down"], precision=p
    )


def _embed_in(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
              embeds: jnp.ndarray | None = None) -> jnp.ndarray:
    x = params["embed"][tokens] if embeds is None else embeds
    x = x.astype(params["embed"].dtype)
    # HF casts the sqrt(E) normalizer to the hidden dtype BEFORE the
    # multiply — mirroring that rounding keeps bf16 goldens bit-tight
    return x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)


def _q_prescale(cfg: ModelConfig, q: jnp.ndarray) -> jnp.ndarray:
    """Make the ops' 1/sqrt(d) scale equal gemma's 1/sqrt(qpas)."""
    d = cfg.head_dim_
    qpas = cfg.query_pre_attn_scalar or d
    if qpas == d:
        return q
    return q * jnp.asarray(math.sqrt(d / qpas), q.dtype)


def _qkv(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    p = _precision(x)
    d = cfg.head_dim_
    q = qdot(x, lp["wq"], precision=p).reshape(*x.shape[:-1], cfg.num_heads, d)
    k = qdot(x, lp["wk"], precision=p).reshape(*x.shape[:-1], cfg.num_kv_heads, d)
    v = qdot(x, lp["wv"], precision=p).reshape(*x.shape[:-1], cfg.num_kv_heads, d)
    return q, k, v


def _layer_windows(cfg: ModelConfig) -> jnp.ndarray:
    """Per-layer sliding window (0 = global): EVEN layers slide."""
    return jnp.asarray(
        [cfg.sliding_window if i % 2 == 0 else 0
         for i in range(cfg.num_layers)],
        jnp.int32,
    )


def _unembed(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    logits = qdot(
        x, params["embed"].T, precision=_precision(x),
        preferred_element_type=jnp.float32,
    )
    cap = cfg.final_logit_softcap
    return cap * jnp.tanh(logits / cap) if cap else logits


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    e, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, kvh, d, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_, cfg.num_layers
    ks = iter(jax.random.split(key, 10))

    def w(k, *shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    return {
        "embed": w(next(ks), v, e, scale=0.02),
        "layers": {
            "attn_norm": jnp.zeros((L, e), dtype),      # (1+w) convention
            "wq": w(next(ks), L, e, h * d),
            "wk": w(next(ks), L, e, kvh * d),
            "wv": w(next(ks), L, e, kvh * d),
            "wo": w(next(ks), L, h * d, e),
            "post_attn_norm": jnp.zeros((L, e), dtype),
            "pre_ffn_norm": jnp.zeros((L, e), dtype),
            "w_gate": w(next(ks), L, e, f),
            "w_up": w(next(ks), L, e, f),
            "w_down": w(next(ks), L, f, e),
            "post_ffn_norm": jnp.zeros((L, e), dtype),
        },
        "final_norm": jnp.zeros((e,), dtype),
    }


def _block(cfg: ModelConfig, lp: Params, x: jnp.ndarray, attn_out: jnp.ndarray,
           ) -> jnp.ndarray:
    """Post-attention half of the gemma2 block: post-norm the attention
    output, add residual, then the normed GeGLU with its own post-norm."""
    eps = cfg.rms_eps
    x = x + _gnorm(attn_out, lp["post_attn_norm"], eps)
    h = _gnorm(x, lp["pre_ffn_norm"], eps)
    h = _geglu(lp, h)
    return x + _gnorm(h, lp["post_ffn_norm"], eps)


def _scan_layers(params: Params, cfg: ModelConfig, x: jnp.ndarray,
                 pos: jnp.ndarray, attn_fn):
    """The ONE gemma2 layer scan all four entry points share.

    x: [B, T, E]; pos: [B, T] absolute positions;
    attn_fn(q, k, v, win, li) -> attended [B, T, H*D] (q/k post-rope,
    q pre-scaled; win = this layer's sliding window, li = layer index).
    Returns (x, k_ys [L, B, T, KVH, D], v_ys) — pool writes are the
    caller's.
    """
    inv_freq = precompute_rope(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)
    windows = _layer_windows(cfg)

    def layer(x, xs):
        lp, win, li = xs
        hx = _gnorm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(cfg, lp, hx)
        q = _q_prescale(cfg, apply_rope(q, pos, inv_freq))
        k = apply_rope(k, pos, inv_freq)
        att = qdot(attn_fn(q, k, v, win, li), lp["wo"],
                   precision=_precision(x))
        return _block(cfg, lp, x, att), (k, v)

    x, (k_ys, v_ys) = jax.lax.scan(
        layer, x,
        (params["layers"], windows,
         jnp.arange(cfg.num_layers, dtype=jnp.int32)),
    )
    return x, k_ys, v_ys


def hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    mlp=None,  # family-API uniformity (gemma owns its GeGLU)
    seq_lens: jnp.ndarray | None = None,
    attn=None,
    embeds: jnp.ndarray | None = None,
    mesh=None,
) -> jnp.ndarray:
    del mlp, attn
    b, t = tokens.shape
    x = _embed_in(params, cfg, tokens, embeds)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    if seq_lens is None:
        seq_lens = jnp.full((b,), t, jnp.int32)

    def attn_fn(q, k, v, win, li):
        return attention_prefill(
            q, k, v, seq_lens, use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
        ).reshape(b, t, -1)

    x, _, _ = _scan_layers(params, cfg, x, pos, attn_fn)
    return _gnorm(x, params["final_norm"], cfg.rms_eps)


def forward(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray, mlp=None,
    embeds: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Cache-free full forward: tokens [B, T] → logits [B, T, V] fp32
    (the golden-test oracle vs HF Gemma2ForCausalLM)."""
    return _unembed(
        cfg, params, hidden_states(params, cfg, tokens, embeds=embeds)
    )


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp=None,
    attn=None,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Prefill ONE slot (same contract as llama.prefill)."""
    del mlp
    if attn is not None:
        raise NotImplementedError(
            f"{cfg.name}: custom prefill attention (sp ring) is not "
            "supported — validate_mesh rejects such meshes at engine init"
        )
    t = tokens.shape[0]
    x = _embed_in(params, cfg, tokens, embeds)[None]  # [1, T, E]
    pos = jnp.arange(t, dtype=jnp.int32)[None]
    seq_lens = length[None]

    def attn_fn(q, k, v, win, li):
        return attention_prefill(
            q, k, v, seq_lens, use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
        ).reshape(1, t, -1)

    x, k_ys, v_ys = _scan_layers(params, cfg, x, pos, attn_fn)
    k_new, v_new = k_ys[:, 0], v_ys[:, 0]  # [L, T, KVH, D]
    x = _gnorm(x, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x[0, jnp.maximum(length - 1, 0)])

    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new, v_new, table_row, jnp.int32(0), length,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(length),
        page_size=cache.page_size,
    )


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp=None,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Chunked prefill against the cached prefix (llama.prefill_chunk
    contract)."""
    del mlp
    t = tokens.shape[0]
    x = _embed_in(params, cfg, tokens, embeds)[None]  # [1, C, E]
    pos = (start + jnp.arange(t, dtype=jnp.int32))[None]
    total = start + length

    def attn_fn(q, k, v, win, li):
        if ragged_attention_enabled():
            att, _ = ragged_paged_attention(
                cache.k, cache.v, cache.page_size,
                q_chunk=q, chunk_row=table_row, chunk_start=start,
                chunk_total=total, k_chunk=k[0], v_chunk=v[0], layer=li,
                use_pallas=cfg.use_pallas,
                logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
            )
            return att.reshape(1, t, -1)
        return attention_prefix_chunk(
            q, cache.k, cache.v, table_row, start, total, cache.page_size,
            k_cur=k[0], v_cur=v[0], layer=li, use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
        ).reshape(1, t, -1)

    x, k_ys, v_ys = _scan_layers(params, cfg, x, pos, attn_fn)
    k_new, v_new = k_ys[:, 0], v_ys[:, 0]  # [L, C, KVH, D]
    x = _gnorm(x, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x[0, jnp.maximum(length - 1, 0)])

    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new, v_new, table_row, start, length,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(total),
        page_size=cache.page_size,
    )


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp=None,
    mesh=None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step for ALL slots (llama.decode_step contract)."""
    del mlp
    s = tokens.shape[0]
    # the decode token is a length-1 "sequence" per slot: [S, 1, E] with
    # per-slot positions, so the shared scan body applies unchanged
    x = _embed_in(params, cfg, tokens)[:, None]  # [S, 1, E]
    positions = cache.lengths
    new_lengths = jnp.minimum(
        cache.lengths + active.astype(jnp.int32), cache.max_context
    )

    def attn_fn(q, k, v, win, li):
        if ragged_attention_enabled():
            _, att = ragged_paged_attention(
                cache.k, cache.v, cache.page_size,
                q_group=q, page_table=cache.page_table,
                group_lengths=positions, k_group=k, v_group=v, layer=li,
                use_pallas=cfg.use_pallas,
                logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
            )
            return att.reshape(s, 1, -1)
        return paged_attention_decode(
            q[:, 0], cache.k, cache.v, cache.page_table, positions,
            cache.page_size, k_cur=k[:, 0], v_cur=v[:, 0], layer=li,
            use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
        ).reshape(s, 1, -1)

    x, k_ys, v_ys = _scan_layers(
        params, cfg, x, positions[:, None], attn_fn
    )
    k_new, v_new = k_ys[:, :, 0], v_ys[:, :, 0]  # [L, S, KVH, D]
    x = _gnorm(x[:, 0], params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x)

    k_pool, v_pool = write_decode_all(
        cache.k, cache.v, k_new, v_new, cache.page_table, positions, active,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool, page_table=cache.page_table,
        lengths=new_lengths, page_size=cache.page_size,
    )


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp=None,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Speculative-verify forward (llama.verify_step contract): T candidate
    tokens per slot in one pass, KV written optimistically, lengths left
    for the engine's rollback_to_length commit. Softcap and the per-layer
    sliding windows thread through paged_attention_verify exactly as they
    do through the decode path. Tree verify (`tree_pos`/`tree_mask`,
    ISSUE 18): rope at logical positions base + depth, KV still stored at
    base + i — same contract as llama.verify_step."""
    del mlp
    s, t = tokens.shape
    x = _embed_in(params, cfg, tokens)  # [S, T, E]
    base = cache.lengths
    store_pos = base[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
    pos = (base[:, None] + jnp.asarray(tree_pos, jnp.int32)[None]
           if tree_pos is not None else store_pos)

    def attn_fn(q, k, v, win, li):
        if ragged_attention_enabled():
            _, att = ragged_paged_attention(
                cache.k, cache.v, cache.page_size,
                q_group=q, page_table=cache.page_table, group_lengths=base,
                k_group=k, v_group=v, layer=li, use_pallas=cfg.use_pallas,
                logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
                tree_pos=tree_pos, tree_mask=tree_mask,
            )
            return att.reshape(s, t, -1)
        return paged_attention_verify(
            q, cache.k, cache.v, cache.page_table, base, cache.page_size,
            k_cur=k, v_cur=v, layer=li, use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
            tree_pos=tree_pos, tree_mask=tree_mask,
        ).reshape(s, t, -1)

    x, k_new, v_new = _scan_layers(params, cfg, x, pos, attn_fn)
    x = _gnorm(x, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, x)  # [S, T, V]

    k_pool, v_pool = write_multi_all(
        cache.k, cache.v, k_new, v_new, cache.page_table, store_pos, active,
        cache.page_size, use_pallas=cfg.use_pallas, mesh=mesh,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool, page_table=cache.page_table,
        lengths=base, page_size=cache.page_size,
    )


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    chunk_tokens: jnp.ndarray,
    chunk_start: jnp.ndarray,
    chunk_len: jnp.ndarray,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp=None,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, PagedKVCache]:
    """Fused chunked-prefill + decode step (llama.mixed_step contract):
    one ragged attention launch per layer serves the admitting slot's
    chunk AND a decode token for every active slot. Softcap and the
    per-layer sliding windows thread through exactly as in decode/verify.
    Only called with ragged attention enabled (engine gates on it)."""
    del mlp
    c = chunk_tokens.shape[0]
    s = tokens.shape[0]
    xc = _embed_in(params, cfg, chunk_tokens, embeds)   # [C, E]
    xg = _embed_in(params, cfg, tokens)                 # [S, E]
    x = jnp.concatenate([xc, xg])[None]                 # [1, C+S, E]
    positions = cache.lengths
    total = chunk_start + chunk_len
    pos = jnp.concatenate([
        chunk_start + jnp.arange(c, dtype=jnp.int32), positions
    ])[None]

    def attn_fn(q, k, v, win, li):
        oc, og = ragged_paged_attention(
            cache.k, cache.v, cache.page_size,
            q_chunk=q[:, :c], chunk_row=table_row, chunk_start=chunk_start,
            chunk_total=total, k_chunk=k[0, :c], v_chunk=v[0, :c],
            q_group=q[0, c:][:, None], page_table=cache.page_table,
            group_lengths=positions, k_group=k[0, c:][:, None],
            v_group=v[0, c:][:, None], layer=li, use_pallas=cfg.use_pallas,
            logit_softcap=cfg.attn_logit_softcap, window=win, mesh=mesh,
        )
        return jnp.concatenate([oc[0], og[:, 0]]).reshape(1, c + s, -1)

    x, k_ys, v_ys = _scan_layers(params, cfg, x, pos, attn_fn)
    k_new, v_new = k_ys[:, 0], v_ys[:, 0]               # [L, C+S, KVH, D]
    x = _gnorm(x, params["final_norm"], cfg.rms_eps)
    chunk_logits = _unembed(cfg, params, x[0, jnp.maximum(chunk_len - 1, 0)])
    dec_logits = _unembed(cfg, params, x[0, c:])

    k_pool, v_pool = write_prefill_all(
        cache.k, cache.v, k_new[:, :c], v_new[:, :c], table_row,
        chunk_start, chunk_len, cache.page_size, use_pallas=cfg.use_pallas,
        mesh=mesh,
    )
    k_pool, v_pool = write_decode_all(
        k_pool, v_pool, k_new[:, c:], v_new[:, c:], cache.page_table,
        positions, active, cache.page_size, use_pallas=cfg.use_pallas,
        mesh=mesh,
    )
    new_lengths = jnp.minimum(
        cache.lengths + active.astype(jnp.int32), cache.max_context
    ).at[slot].set(total)
    return chunk_logits, dec_logits, PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=new_lengths, page_size=cache.page_size,
    )


# ---------------------------------------------------------------------------
# HF layout (Gemma2ForCausalLM)
# ---------------------------------------------------------------------------

HF_MAP: dict[str, tuple[str, bool]] = {
    "attn_norm": ("model.layers.{}.input_layernorm.weight", False),
    "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
    "post_attn_norm": ("model.layers.{}.post_attention_layernorm.weight", False),
    "pre_ffn_norm": ("model.layers.{}.pre_feedforward_layernorm.weight", False),
    "w_gate": ("model.layers.{}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{}.mlp.down_proj.weight", True),
    "post_ffn_norm": ("model.layers.{}.post_feedforward_layernorm.weight", False),
}


def hf_map(cfg: ModelConfig) -> dict[str, tuple[str, bool]]:
    return dict(HF_MAP)


def convert_hf_state_dict(
    cfg: ModelConfig, sd: dict[str, Any], dtype=jnp.bfloat16
) -> Params:
    from gridllm_tpu.models import llama

    return llama.convert_state_dict(cfg, sd, HF_MAP, dtype)
