"""Mixtral sparse-MoE decoder (BASELINE.md config #4: mixtral:8x7b EP).

Shares llama's decoder skeleton (attention, norms, paged KV cache) and
swaps the FFN for a top-k routed mixture of experts. The reference has no
MoE (or any model) code — SURVEY.md §2.5 marks expert parallelism "No …
north star names Mixtral 8×7B EP as a target config".

TPU-first routing design: every expert computes every token, with
non-selected (token, expert) pairs zero-weighted — the einsum over the
stacked expert axis X keeps the MXU fed with one big batched matmul and,
under GSPMD, shards cleanly on the "ep" mesh axis (each shard computes
only its X/ep experts for all tokens, then the weighted combine is the
all-reduce XLA inserts; see parallel/sharding.py `we_*` specs). This
trades X/top_k extra FLOPs for zero dynamic shapes, no token dropping,
and no host-visible dispatch — the right trade at decode batch sizes,
where the expert matmuls are bandwidth-bound on the weights either way.
A ragged/sorted dispatch Pallas kernel is the future optimization for
long-prompt prefill (PAPERS.md MoE dispatch patterns).

Routing numerics follow HF `MixtralSparseMoeBlock`: softmax over ALL
expert logits in fp32 → top-k → renormalize the selected weights.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.ops.kvcache import PagedKVCache
from gridllm_tpu.utils.config import env_str

Params = dict[str, Any]


# tokens-at-or-above this (per MoE call) take the sorted ragged-dispatch
# path during single-device prefill; below it (decode steps, tiny batches)
# the dense all-experts form wins on dispatch overhead
_RAGGED_MIN_TOKENS = 16


def _route(cfg: ModelConfig, lp: Params, x: jnp.ndarray):
    """Router math (HF MixtralSparseMoeBlock order): softmax over ALL
    expert logits in fp32 → top-k → renormalize. Returns (top_w, top_i)."""
    probs = jax.nn.softmax(
        jnp.dot(x.astype(jnp.float32), lp["router"].astype(jnp.float32)), axis=-1
    )  # [..., X] fp32 — router math stays fp32 (tiny; routing flips are costly)
    top_w, top_i = jax.lax.top_k(probs, cfg.experts_per_token)
    top_w = top_w / top_w.sum(axis=-1, keepdims=True)
    return top_w, top_i


def _moe_mlp_dense(cfg: ModelConfig, lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Dense form: every expert computes every token, non-selected pairs
    zero-weighted. One big batched einsum over the stacked expert axis —
    MXU-friendly, EP-shardable (each "ep" shard computes its X/ep experts
    for all tokens; the combine is the all-reduce XLA inserts). The right
    trade at decode batch sizes, where expert matmuls are bandwidth-bound
    on the weights either way."""
    p = llama._precision(x)
    top_w, top_i = _route(cfg, lp, x)
    one_hot = jax.nn.one_hot(top_i, cfg.num_experts, dtype=jnp.float32)
    gates = jnp.einsum("...k,...kx->...x", top_w, one_hot).astype(x.dtype)

    g = jnp.einsum("...e,xef->...xf", x, lp["we_gate"], precision=p)
    u = jnp.einsum("...e,xef->...xf", x, lp["we_up"], precision=p)
    y = jax.nn.silu(g) * u * gates[..., None]
    return jnp.einsum("...xf,xfe->...e", y, lp["we_down"], precision=p)


def _moe_mlp_ragged(cfg: ModelConfig, lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Sorted ragged dispatch (VERDICT #7): tokens sorted by expert, then
    ONE grouped matmul per projection via jax.lax.ragged_dot — T·top_k row
    FLOPs instead of the dense form's T·X (4× for 8×7b prefill), exact
    (no capacity factor, no token dropping), static shapes throughout
    (argsort/bincount are fixed-size; raggedness lives in group_sizes
    values, not array shapes)."""
    k, X = cfg.experts_per_token, cfg.num_experts
    lead = x.shape[:-1]
    e = x.shape[-1]
    xf = x.reshape(-1, e)                       # [T, E]
    t = xf.shape[0]
    top_w, top_i = _route(cfg, lp, xf)          # [T, k]

    flat_expert = top_i.reshape(-1)             # [T*k]
    token_idx = jnp.repeat(jnp.arange(t), k)    # [T*k]
    order = jnp.argsort(flat_expert)            # stable → token order kept
    rows = token_idx[order]                     # [T*k] source token per row
    xs = xf[rows]                               # [T*k, E] sorted operand
    group_sizes = jnp.bincount(flat_expert, length=X).astype(jnp.int32)

    g = jax.lax.ragged_dot(xs, lp["we_gate"], group_sizes)
    u = jax.lax.ragged_dot(xs, lp["we_up"], group_sizes)
    y = (jax.nn.silu(g) * u).astype(x.dtype)
    down = jax.lax.ragged_dot(y, lp["we_down"], group_sizes)  # [T*k, E]

    w = top_w.reshape(-1)[order].astype(x.dtype)              # [T*k]
    out = jnp.zeros((t, e), x.dtype).at[rows].add(down * w[:, None])
    return out.reshape(*lead, e)


def _moe_mlp_ragged_ep(
    cfg: ModelConfig, lp: Params, x: jnp.ndarray, mesh
) -> jnp.ndarray:
    """EP ragged dispatch under a mesh (VERDICT r03 next-round #7: the
    meshed dense form paid X/top_k = 4× redundant expert FLOPs exactly
    where EP matters — sharded prefill).

    shard_map over ("ep", "tp"): each shard holds X/ep experts (their
    gate/up/down slabs further split F-wise over tp), runs the SAME sorted
    ragged_dot dispatch as the single-device path but over its LOCAL
    expert range (assignments outside the range sort to the tail, get
    group_sizes 0, and are zero-weighted — NaN-proofed before the
    combine), then one psum over (ep, tp) merges expert contributions and
    the tp partial sums in a single collective. Tokens are replicated into
    the shard (activations are bytes; expert weights are the GBs), so the
    only cross-device traffic is the output psum — an all-to-all token
    exchange buys nothing at these activation sizes on ICI.

    Per-shard row FLOPs: T·top_k/ep on average vs the dense form's T·X/ep
    — the same 4× saving (8×7b, top_k=2) the single-device ragged path
    gets, now under the mesh.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    k = cfg.experts_per_token
    lead = x.shape[:-1]
    e = x.shape[-1]
    xf = x.reshape(-1, e)
    t = xf.shape[0]
    # routing inputs are replicated — run the canonical _route ONCE
    # outside the shard_map (keeps the HF routing numerics single-sourced)
    top_w, top_i = _route(cfg, lp, xf)

    def shard_fn(xf, top_w, top_i, wg, wu, wd):
        xl = wg.shape[0]                       # local experts
        lo = jax.lax.axis_index("ep") * xl
        flat = top_i.reshape(-1)               # [T*k] global expert ids
        tok = jnp.repeat(jnp.arange(t), k)
        el = flat - lo
        valid = (el >= 0) & (el < xl)
        order = jnp.argsort(jnp.where(valid, el, xl))  # invalid → tail
        rows = tok[order]
        xs = xf[rows]
        gs = jnp.bincount(
            jnp.where(valid, el, xl), length=xl + 1
        )[:xl].astype(jnp.int32)

        g = jax.lax.ragged_dot(xs, wg, gs)
        u = jax.lax.ragged_dot(xs, wu, gs)
        y = (jax.nn.silu(g) * u).astype(xf.dtype)
        d = jax.lax.ragged_dot(y, wd, gs)

        vs = valid[order]
        w = jnp.where(vs, top_w.reshape(-1)[order], 0.0).astype(xf.dtype)
        d = jnp.where(vs[:, None], d, 0)       # rows past all groups
        out = jnp.zeros((t, e), xf.dtype).at[rows].add(d * w[:, None])
        return jax.lax.psum(out, ("ep", "tp"))

    out = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(), P("ep", None, "tp"), P("ep", None, "tp"),
                  P("ep", "tp", None)),
        out_specs=P(),
    )(xf, top_w, top_i, lp["we_gate"], lp["we_up"], lp["we_down"])
    return out.reshape(*lead, e)


def _ragged_enabled() -> bool:
    raw = env_str("GRIDLLM_MOE_RAGGED").lower()
    if raw == "auto":
        # CPU's ragged_dot lowering is a serial group loop, measured ~25%
        # SLOWER than dense even at X=8 — the grouped matmul win is a
        # TPU/Mosaic property. Env override lets tests force it on CPU.
        return jax.default_backend() == "tpu"
    return raw in ("1", "on", "true")


def _moe_mlp(
    cfg: ModelConfig, mesh, lp: Params, x: jnp.ndarray
) -> jnp.ndarray:
    """Sparse-MoE FFN: x [..., E] → [..., E].

    lp carries router [E, X] and stacked experts we_gate/we_up [X, E, F],
    we_down [X, F, E] (the per-layer slice of the [L, X, ...] leaves).

    Form selection (trace-time, static):
    - meshed + prefill-sized tokens + divisible layout → shard_map EP
      ragged dispatch (top_k-proportional FLOPs per shard);
    - meshed otherwise (decode-sized batches, indivisible X/F) → dense
      all-experts einsum (EP-shardable via GSPMD, no dynamic shapes);
    - single device → sorted ragged_dot for prefill-sized counts on TPU,
      dense for decode-sized counts and CPU.
    """
    n_tokens = 1
    for s in x.shape[:-1]:
        n_tokens *= s
    if mesh is not None:
        ep = mesh.shape.get("ep", 1)
        tp = mesh.shape.get("tp", 1)
        divisible = (
            cfg.num_experts % ep == 0
            and cfg.intermediate_size % tp == 0
        )
        if (n_tokens >= _RAGGED_MIN_TOKENS and divisible
                and _ragged_enabled()):
            return _moe_mlp_ragged_ep(cfg, lp, x, mesh)
        return _moe_mlp_dense(cfg, lp, x)
    if cfg.use_pallas is False or n_tokens < _RAGGED_MIN_TOKENS:
        return _moe_mlp_dense(cfg, lp, x)
    if _ragged_enabled():
        return _moe_mlp_ragged(cfg, lp, x)
    return _moe_mlp_dense(cfg, lp, x)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init params: llama attention skeleton + MoE expert leaves."""
    e, f = cfg.hidden_size, cfg.intermediate_size
    X, L = cfg.num_experts, cfg.num_layers
    base_key, k_r, k_g, k_u, k_d = jax.random.split(key, 5)
    params = llama.init_params(cfg, base_key, dtype, dense_ffn=False)
    lp = params["layers"]

    def w(k, *shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    lp["router"] = w(k_r, L, e, X, scale=0.02)
    lp["we_gate"] = w(k_g, L, X, e, f)
    lp["we_up"] = w(k_u, L, X, e, f)
    lp["we_down"] = w(k_d, L, X, f, e)
    return params


def _mlp_for(cfg: ModelConfig, mesh=None):
    return partial(_moe_mlp, cfg, mesh)


def hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray | None = None,
    mesh=None,
) -> jnp.ndarray:
    return llama.hidden_states(
        params, cfg, tokens, mlp=_mlp_for(cfg, mesh), seq_lens=seq_lens,
        mesh=mesh,
    )


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            mesh=None) -> jnp.ndarray:
    return llama.forward(params, cfg, tokens, mlp=_mlp_for(cfg, mesh))


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    attn: llama.AttnFn | None = None,
    mesh=None,
    embeds: jnp.ndarray | None = None,  # family-API uniformity (vision)
) -> tuple[jnp.ndarray, PagedKVCache]:
    return llama.prefill(
        params, cfg, tokens, length, cache, slot, table_row,
        mlp=_mlp_for(cfg, mesh), attn=attn, mesh=mesh, embeds=embeds,
    )


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mesh=None,
    embeds: jnp.ndarray | None = None,  # family-API uniformity (vision)
) -> tuple[jnp.ndarray, PagedKVCache]:
    return llama.prefill_chunk(
        params, cfg, tokens, start, length, cache, slot, table_row,
        mlp=_mlp_for(cfg, mesh), mesh=mesh, embeds=embeds,
    )


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mesh=None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    return llama.decode_step(
        params, cfg, tokens, cache, active, mlp=_mlp_for(cfg, mesh),
        mesh=mesh,
    )


def verify_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Speculative-verify forward (llama.verify_step contract) with the
    MoE feed-forward routed per candidate token — _moe_mlp is leading-dim
    agnostic, so the [S, T, E] verify stream routes like prefill's (and
    the tree-verify args pass straight through)."""
    return llama.verify_step(
        params, cfg, tokens, cache, active, mlp=_mlp_for(cfg, mesh),
        mesh=mesh, tree_pos=tree_pos, tree_mask=tree_mask,
    )


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    chunk_tokens: jnp.ndarray,
    chunk_start: jnp.ndarray,
    chunk_len: jnp.ndarray,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, PagedKVCache]:
    """Fused chunked-prefill + decode step (llama.mixed_step contract);
    the flat [C+S, E] ragged token batch routes through the MoE exactly
    like any other leading-dim layout."""
    return llama.mixed_step(
        params, cfg, chunk_tokens, chunk_start, chunk_len, slot, table_row,
        tokens, cache, active, mlp=_mlp_for(cfg, mesh), mesh=mesh,
        embeds=embeds,
    )


# ---------------------------------------------------------------------------
# HF weight conversion (layout contract with transformers MixtralForCausalLM)
# ---------------------------------------------------------------------------

# Same single-source-of-truth scheme as llama.HF_MAP (w1=gate, w2=down,
# w3=up per HF MixtralBlockSparseTop2MLP); engine/loader.py reads this.
HF_MAP: dict[str, tuple[str, bool]] = {
    **{k: v for k, v in llama.HF_MAP.items()
       if k not in ("w_gate", "w_up", "w_down")},
    "router": ("model.layers.{}.block_sparse_moe.gate.weight", True),
    "we_gate": ("model.layers.{}.block_sparse_moe.experts.{}.w1.weight", True),
    "we_down": ("model.layers.{}.block_sparse_moe.experts.{}.w2.weight", True),
    "we_up": ("model.layers.{}.block_sparse_moe.experts.{}.w3.weight", True),
}


def convert_hf_state_dict(cfg: ModelConfig, sd: dict[str, Any], dtype=jnp.bfloat16) -> Params:
    """HF `MixtralForCausalLM.state_dict()` → our pytree."""
    return llama.convert_state_dict(cfg, sd, HF_MAP, dtype)
