"""Model families (Llama, Mixtral, embeddings) — the capability the
reference delegates to Ollama's model store (SURVEY.md §0)."""

from gridllm_tpu.models.configs import ModelConfig, get_config, REGISTRY

__all__ = ["ModelConfig", "get_config", "REGISTRY"]
