"""GridLLM-TPU: a TPU-native distributed LLM inference framework.

A ground-up rebuild of the GridLLM orchestrator (reference: GridLLM/GridLLM,
a TypeScript server/worker system proxying to Ollama — see SURVEY.md) with the
inference engine implemented natively in JAX/XLA/Pallas for TPU:

- ``gateway``   — Ollama- and OpenAI-compatible HTTP API server
                  (reference: server/src/routes/*)
- ``scheduler`` — job queue, worker registry, failure machinery
                  (reference: server/src/services/JobScheduler.ts, WorkerRegistry.ts)
- ``bus``       — pub/sub + KV message bus (in-memory and RESP/Redis wire)
                  (reference: server/src/services/RedisService.ts)
- ``worker``    — TPU worker host: registration, heartbeat, job execution
                  (reference: client/src/services/WorkerClientService.ts)
- ``engine``    — JAX inference engine: continuous batching, streaming decode
                  (replaces the reference's external Ollama dependency,
                  client/src/services/OllamaService.ts)
- ``models``    — Llama / Mixtral / embedding model definitions (pure JAX)
- ``ops``       — attention, KV-cache, sampling, norms; Pallas TPU kernels
- ``parallel``  — device mesh, sharding plans (TP/EP/DP/SP), collectives
"""

__version__ = "0.1.0"
