"""Paged KV cache.

The reference has no KV cache (Ollama owns it externally; SURVEY.md §0). This
is the TPU-native replacement per SURVEY.md §5.7/§7 step 5: a single static
page pool shared by all batch slots, so HBM is sized by total live tokens
rather than slots × max_seq_len, and shapes stay static under jit.

Layout (per model):
  k/v: [num_layers, num_pages, page_size, num_kv_heads, head_dim]
  page_table: [max_slots, max_pages_per_slot] int32 page ids (-1 = unmapped)
  lengths: [max_slots] int32 tokens stored per slot

Page *allocation* is host-side Python (engine/scheduling concern, cheap,
O(pages)); device ops only read/scatter through the tables. Page 0 is a real,
usable page — unmapped entries are -1; the write paths remap them (and
inactive slots) to index `num_pages`, which is out of bounds so scatter
mode="drop" actually drops them (a raw -1 would WRAP to the last page —
jax negative indexing applies in scatter too).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
from collections import OrderedDict
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from gridllm_tpu.analysis import numcheck
from gridllm_tpu.obs import default_registry
from gridllm_tpu.utils.config import env_str

# Which implementation each traced program took: "pallas" (kernel) or
# "jnp" (fallback scatter/reference). Incremented at TRACE time — once per
# compiled program, not per step — so a nonzero jnp count for an op that
# should run the kernel path is the silent-fallback tripwire (the
# pre-fb61f50 d=64 fallback would have been one dashboard cell, not a
# bisect). ops/attention.py records through this too.
_KERNEL_DISPATCH = default_registry().counter(
    "gridllm_kernel_dispatch_total",
    "Compiled programs by op and implementation path (pallas kernel vs "
    "jnp fallback). Counted per trace/compile, not per step.",
    ("op", "path"),
)


def record_kernel_path(op: str, kernel: bool) -> None:
    _KERNEL_DISPATCH.inc(op=op, path="pallas" if kernel else "jnp")


# Automatic prefix caching (ISSUE 3): page-granular reuse accounting.
# hit/miss are counted in PAGES of the prompt at admission time (a hit page
# is prefill compute skipped, a miss page is prefill compute paid), so
# hits / (hits + misses) is the prompt-page hit rate the engine exports as
# gridllm_prefix_cache_hit_rate. evictions = cached pages reclaimed for
# fresh allocations; cow_copies = tail pages that WERE cached but had to be
# privately rebuilt because the request writes into them (the last-token /
# partial-tail copy-on-write, realized as recompute-into-a-fresh-page).
_PREFIX_HITS = default_registry().counter(
    "gridllm_prefix_cache_hits_total",
    "Prompt pages served from the prefix cache (prefill skipped), by model.",
    ("model",),
)
_PREFIX_MISSES = default_registry().counter(
    "gridllm_prefix_cache_misses_total",
    "Prompt pages not found in the prefix cache (prefill paid), by model.",
    ("model",),
)
_PREFIX_EVICTIONS = default_registry().counter(
    "gridllm_prefix_cache_evictions_total",
    "Cached prefix pages evicted (LRU) to satisfy fresh allocations, "
    "by model.",
    ("model",),
)
_PREFIX_COW = default_registry().counter(
    "gridllm_prefix_cache_cow_copies_total",
    "Cached tail pages privately rebuilt because the request writes into "
    "them (copy-on-write of the partial tail page), by model.",
    ("model",),
)


@functools.cache
def _env_mode() -> tuple[bool, bool]:
    """(use_kernels, interpret) from the environment, resolved once.
    Shared policy for the attention kernels (ops/attention.py imports it)
    and the KV-write kernels below: env `GRIDLLM_PALLAS` = "auto"
    (default: kernels on TPU backends only), "1" (force on), "0" (force
    off), "interpret" (kernels in interpreter mode — CPU testing)."""
    raw = env_str("GRIDLLM_PALLAS").lower()
    if raw in ("0", "off", "false"):
        return False, False
    if raw in ("1", "on", "true"):
        return True, False
    if raw == "interpret":
        return True, True
    return jax.default_backend() == "tpu", False


def _pallas_mode(use_pallas: bool | None) -> tuple[bool, bool]:
    """`use_pallas` is the per-call override (threaded from
    ModelConfig.use_pallas by the model code); None defers to the env
    policy. pallas_call has no GSPMD partitioning rule, so under a mesh
    the dispatch layers wrap the kernel in a full-manual shard_map
    (`kernel_mesh_axis` below) instead of letting GSPMD see it."""
    use, interpret = _env_mode()
    if use_pallas is not None:
        use = use_pallas
    return use, interpret


def kernel_mesh_axis(mesh, kvh: int, h: int | None = None):
    """(mode, axis) for running Pallas kernels under `mesh`.

    pallas_call has no GSPMD partitioning rule: inside an auto-partitioned
    jit it either fails to partition or forces full replication. The fix
    (VERDICT r04 #2) is a FULL-manual shard_map at the kernel boundary —
    attention and KV-writes are embarrassingly parallel over kv-heads, so
    each tp shard runs the existing kernel on its head slice with no
    collectives. This helper decides the layout:

    - ("direct", None): no mesh — call the kernel directly.
    - ("wrap", "tp"): kv-heads (and q-heads) divide by the tp axis —
      shard head dims over "tp", matching parallel/sharding.py's Megatron
      specs, so the shard_map boundary is a no-op resharding.
    - ("wrap", None): mesh present but heads don't divide (tiny test
      configs) — the wrapper still isolates the kernel from GSPMD, with
      head dims replicated (matches sharding._fit's fallback).
    - ("ref", None): the wrapper can't express the operands' sharding —
      pp > 1 shards the pool's layer axis, and a spec that doesn't
      mention pp would silently all-gather the whole pool. Callers must
      take their jnp reference path (GSPMD-safe). The pipeline module
      pins use_pallas=False anyway; this is the belt to that suspender.

    Unmentioned mesh axes (dp/ep/sp) mean "replicated" in a full-manual
    shard_map — exactly how those axes see attention operands.
    """
    if mesh is None:
        return "direct", None
    if mesh.shape.get("pp", 1) > 1:
        return "ref", None
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and kvh % tp == 0 and (h is None or h % tp == 0):
        return "wrap", "tp"
    return "wrap", None


def _shard_map_kernel(mesh, body, in_specs, out_specs):
    """jax.shard_map for a kernel body: full-manual (all axes), with vma
    checking off — pallas_call can't annotate how outputs vary across
    mesh axes, and the bodies here have no collectives to get wrong.
    Resolves whichever spelling this jax ships: the stable ``jax
    .shard_map`` (``check_vma``) or the older experimental one
    (``check_rep``)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def flat_lanes_ok(kvh: int, d: int) -> bool:
    """True when a page's rows are lane-aligned VIEWED FLAT ([ps, KVH*D])
    even though d alone is not — the ragged layout's trick (ISSUE 6):
    pages are contiguous, so the page DMA moves tile-aligned flat rows
    without padding D itself (the ragged kernel lane-pads the LOADED
    values in-register before its dots). d=64 models with KVH >= 2 per
    shard keep the kernel write/attention paths on an UNPADDED pool
    (half the KV bytes of the lane-padded layout).

    `kvh` must be the PER-SHARD head count: under tp the kernels run
    inside a full-manual shard_map with kv heads split over "tp"
    (kernel_mesh_axis), so each shard's page rows are (kvh/tp)*D lanes —
    callers divide before asking (see local_kv_heads)."""
    return (kvh * d) % 128 == 0


def local_kv_heads(kvh: int, mesh) -> int:
    """KV heads per kernel shard: kvh/tp when the tp axis will split the
    head dim (the same divisibility rule kernel_mesh_axis applies),
    otherwise the full count (no mesh, or indivisible heads replicate)."""
    if mesh is None:
        return kvh
    tp = mesh.shape.get("tp", 1)
    return kvh // tp if tp > 1 and kvh % tp == 0 else kvh


def _write_lane_gate(k_pages, ax, mesh, interpret: bool) -> bool:
    """Mosaic lane-alignment gate for the pool-write kernels: classic
    128-lane head dim, or the ragged flat-lane row view — checked at the
    PER-SHARD head count when `ax` says tp will split heads."""
    d = k_pages.shape[-1]
    kvh = k_pages.shape[-2]
    if ax == "tp":
        kvh //= mesh.shape["tp"]
    return interpret or d % 128 == 0 or flat_lanes_ok(kvh, d)


def lane_pad_dim(d: int) -> int:
    """Head dim rounded up to the 128-lane tile. The engine allocates the
    page pool at this width when kernels are on (d=64 models: qwen2.5
    class) so Mosaic's alignment constraint is met and decode/writes keep
    the kernel path; the attention/write dispatchers pad q/K/V to the
    pool's width and slice outputs back (exact — see
    ops.attention.paged_attention_decode). Costs 2x KV memory on d=64
    models, which are the smallest ones served."""
    return -(-d // 128) * 128


def _pad_new_lanes(k_pages, k_new, v_new):
    """Zero-pad fresh K/V rows to a lane-padded pool's head dim."""
    dpool, d = k_pages.shape[-1], k_new.shape[-1]
    if dpool == d:
        return k_new, v_new
    pad = [(0, 0)] * (k_new.ndim - 1) + [(0, dpool - d)]
    return jnp.pad(k_new, pad), jnp.pad(v_new, pad)


def _wrap_write_kernel(mesh, ax, kernel, scalar_specs):
    """Shared meshed wrapper for the two pool-write kernels: pools + new
    rows split on `ax` over kv-heads, trailing host-computed operands
    (page_idx/offset or table_row/start/length) per `scalar_specs`."""
    from jax.sharding import PartitionSpec as P

    pool = P(None, None, None, ax, None)
    new = P(None, None, ax, None)
    return _shard_map_kernel(
        mesh, kernel,
        in_specs=(pool, pool, new, new, *scalar_specs),
        out_specs=(pool, pool),
    )


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["k", "v", "page_table", "lengths"],
    meta_fields=["page_size"],
)
@dataclasses.dataclass
class PagedKVCache:
    k: jnp.ndarray           # [L, P, page_size, KVH, D]
    v: jnp.ndarray           # [L, P, page_size, KVH, D]
    page_table: jnp.ndarray  # [S, max_pages] int32
    lengths: jnp.ndarray     # [S] int32
    page_size: int = 128

    @staticmethod
    def create(
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
        max_slots: int,
        max_pages_per_slot: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (num_layers, num_pages, page_size, num_kv_heads, head_dim)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            page_table=jnp.full((max_slots, max_pages_per_slot), -1, jnp.int32),
            lengths=jnp.zeros((max_slots,), jnp.int32),
            page_size=page_size,
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def max_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def max_context(self) -> int:
        return self.page_table.shape[1] * self.page_size


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["data", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class QuantPages:
    """int8 KV page pool + per-row dequant scales (ISSUE 11,
    ``GRIDLLM_KV_INT8``): ``data`` holds the quantized values, ``scale``
    one float32 symmetric scale per (layer, page, row) — a token row
    [KVH, D] is the quantization granule, so incremental decode/verify
    writes quantize independently without ever re-scaling a page. The
    engine stores a QuantPages where ``PagedKVCache.k``/``.v`` would hold
    a raw array; model code passes it through opaquely (same pytree
    flow/donation), and the ops dispatchers here and in ops/attention.py
    unwrap it: writes quantize at the boundary, reads dequantize — the
    ragged Pallas kernel in its flat-row page load (dequant epilogue),
    every jnp fallback via :func:`gather_kv`/``take``. Halves resident
    KV HBM at a bounded accuracy cost (per-row worst case scale/2 ≈
    amax/254 absolute error per element)."""

    data: jnp.ndarray   # int8 [L, P, ps, KVH, D] (or one layer: 4-dim)
    scale: jnp.ndarray  # f32  [L, P, ps]         (or one layer: [P, ps])

    @property
    def shape(self):
        return self.data.shape

    @property
    def ndim(self) -> int:
        return self.data.ndim

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.scale.nbytes

    def layer(self, li) -> "QuantPages":
        """One layer's pool slice (dynamic index — from inside a scan)."""
        return QuantPages(
            jax.lax.dynamic_index_in_dim(self.data, li, keepdims=False),
            jax.lax.dynamic_index_in_dim(self.scale, li, keepdims=False),
        )

    def take(self, rows: jnp.ndarray) -> jnp.ndarray:
        """Dequantized float32 pages gathered along the page axis of a
        single-layer (4-dim) pool: data[rows] * scale[rows] broadcast
        over each row's [KVH, D]."""
        return (self.data[rows].astype(jnp.float32)
                * self.scale[rows][..., None, None])


def quantize_kv_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row symmetric int8 quantization of fresh K/V:
    x [..., KVH, D] float → (int8 values, float32 scales [...]). A row's
    scale is amax/127 (all-zero rows keep 1.0 so dequant is exact)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None, None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _safe_page_idx(
    lookup,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
    page_size: int,
    max_pages: int,
    num_pages: int,
) -> jnp.ndarray:
    """Page index for each write position, with every hazard masked to the
    out-of-bounds sentinel `num_pages` so scatter mode="drop" really drops:

    - invalid positions (padding / inactive slot) — caller's `valid` mask
    - past-capacity positions: jax gather CLAMPS out-of-range lookups to
      the row's last entry (a real page), so mask before looking up
    - unmapped table entries (-1): negative indices WRAP in jax scatter

    `lookup(page_no)` maps in-range page numbers to page ids.
    """
    in_cap = positions < max_pages * page_size
    mapped = lookup(jnp.minimum(positions // page_size, max_pages - 1))
    return jnp.where(valid & in_cap & (mapped >= 0), mapped, num_pages)


def write_prefill(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    page_size: int,
    use_pallas: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefill chunk for ONE slot into the (single-layer) page pool.

    k_pages/v_pages: [P, page_size, KVH, D] — one layer's pool.
    k_new/v_new: [T, KVH, D] (T = padded bucket length).
    table_row: [max_pages] page ids for this slot.
    start: scalar — absolute position of k_new[0] (0 for fresh prompts,
    cached length for chunked prefill). length: scalar — valid tokens in
    k_new; positions >= length are dropped.

    Single-layer scatter form (CPU/mesh fallback and tests); the hot path
    writes all layers at once AFTER the layer scan via write_prefill_all —
    per-layer writes inside a scan defeat XLA's in-place buffer aliasing.
    """
    del use_pallas  # single-layer form is always scatter; see _all variant
    if isinstance(k_pages, QuantPages):
        # only pp routes through the single-layer forms, and the engine
        # pins int8 off under any mesh — reaching here is a wiring bug
        raise TypeError("int8 KV pools are not supported on the "
                        "single-layer write path")
    t = jnp.arange(k_new.shape[0], dtype=jnp.int32)
    pos = start + t
    page_idx = _safe_page_idx(
        lambda p: table_row[p], pos, t < length, page_size,
        table_row.shape[0], k_pages.shape[0],
    )
    offset = pos % page_size
    k_pages = k_pages.at[page_idx, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[page_idx, offset].set(v_new, mode="drop")
    return k_pages, v_pages


def write_decode(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    active: jnp.ndarray,
    page_size: int,
    use_pallas: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one new token per slot into the (single-layer) page pool.

    k_new/v_new: [S, KVH, D]; positions: [S] absolute write position per
    slot; active: [S] bool — inactive slots are dropped.

    Single-layer scatter form (CPU/mesh fallback and tests); the hot path
    is write_decode_all (all layers, once per step, after the layer scan).
    """
    del use_pallas
    if isinstance(k_pages, QuantPages):
        raise TypeError("int8 KV pools are not supported on the "
                        "single-layer write path")
    s = jnp.arange(page_table.shape[0], dtype=jnp.int32)
    page_idx = _safe_page_idx(
        lambda p: page_table[s, p], positions, active, page_size,
        page_table.shape[1], k_pages.shape[0],
    )
    offset = positions % page_size
    k_pages = k_pages.at[page_idx, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[page_idx, offset].set(v_new, mode="drop")
    return k_pages, v_pages


def write_decode_all(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    active: jnp.ndarray,
    page_size: int,
    use_pallas: bool | None = None,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write one token per slot across ALL layers at once.

    k_pages/v_pages: [L, P, ps, KVH, D] (the full pool); k_new/v_new:
    [L, S, KVH, D]. Runs once per decode step at jit top level, where
    donation makes the update truly in place (TPU: DMA kernel; otherwise
    one batched scatter). Under `mesh` the kernel runs inside a
    full-manual shard_map with kv-heads split over tp (writes are
    shard-local — no collectives; see kernel_mesh_axis).

    int8 pools (QuantPages, ISSUE 11) quantize the fresh rows per row at
    this boundary and scatter values + scales; the write KERNEL path is
    deliberately skipped there (the int8 scatter is O(S) rows — tiny
    next to attention — and Mosaic's int8 sublane tiling on sub-lane-row
    DMA destinations is unproven on real hardware).
    """
    # numerics sanitizer: a NaN/Inf KV row poisons every later read of
    # its page — trip at the write boundary, not in a garbled stream
    numcheck.check_finite("kv.write", k_new, v_new)
    if isinstance(k_pages, QuantPages):
        k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
        s = jnp.arange(page_table.shape[0], dtype=jnp.int32)
        page_idx = _safe_page_idx(
            lambda p: page_table[s, p], positions, active, page_size,
            page_table.shape[1], k_pages.data.shape[1],
        )
        offset = positions % page_size
        record_kernel_path("write_decode", False)
        kq, ksc = quantize_kv_rows(k_new)   # [L, S, KVH, D] / [L, S]
        vq, vsc = quantize_kv_rows(v_new)
        return (
            QuantPages(
                k_pages.data.at[:, page_idx, offset].set(kq, mode="drop"),
                k_pages.scale.at[:, page_idx, offset].set(ksc, mode="drop"),
            ),
            QuantPages(
                v_pages.data.at[:, page_idx, offset].set(vq, mode="drop"),
                v_pages.scale.at[:, page_idx, offset].set(vsc, mode="drop"),
            ),
        )
    k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
    s = jnp.arange(page_table.shape[0], dtype=jnp.int32)
    page_idx = _safe_page_idx(
        lambda p: page_table[s, p], positions, active, page_size,
        page_table.shape[1], k_pages.shape[1],
    )
    offset = positions % page_size
    use, interpret = _pallas_mode(use_pallas)
    # same Mosaic constraint as the attention kernels: page slices need a
    # 128-lane-aligned minor dim on real TPU — met either by a (padded)
    # d % 128 pool or by the ragged layout's flat [ps, KVH*D] row view
    mode, ax = kernel_mesh_axis(mesh, k_new.shape[2])
    if use and mode != "ref" and _write_lane_gate(k_pages, ax, mesh,
                                                  interpret):
        from gridllm_tpu.ops.pallas_kernels import paged_write_decode

        record_kernel_path("write_decode", True)
        kernel = partial(paged_write_decode, interpret=interpret)
        if mode == "wrap":
            from jax.sharding import PartitionSpec as P

            kernel = _wrap_write_kernel(mesh, ax, kernel,
                                        (P(None), P(None)))
        return kernel(k_pages, v_pages, k_new, v_new, page_idx, offset)
    record_kernel_path("write_decode", False)
    # one scatter over (page, row) applied to every layer: index arrays are
    # adjacent advanced indices after the leading ':' so the result keeps
    # [L, S, KVH, D] — matching k_new's layout
    k_pages = k_pages.at[:, page_idx, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[:, page_idx, offset].set(v_new, mode="drop")
    return k_pages, v_pages


def write_multi_all(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    active: jnp.ndarray,
    page_size: int,
    use_pallas: bool | None = None,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Multi-token append: write T consecutive tokens per slot across ALL
    layers at once (the speculative-verify KV write, ISSUE 5).

    k_pages/v_pages: [L, P, ps, KVH, D]; k_new/v_new: [L, S, T, KVH, D];
    positions: [S, T] absolute write position per (slot, candidate);
    active: [S] bool — inactive slots are dropped entirely. Every hazard
    (inactive slot, past-capacity position, unmapped page) masks to the
    out-of-bounds sentinel exactly like write_decode_all.

    The write is OPTIMISTIC: all T candidate rows land in the pool before
    accept/reject is known. Rejected rows are dropped afterwards by
    rollback_to_length — pure length bookkeeping, no data movement.

    Kernel path: the (slot, candidate) pairs flatten to S*T independent
    rows, which is exactly paged_write_decode's contract (one [KVH, D]
    row per destination, destinations never colliding — positions within
    a slot are consecutive and distinct, pages are slot-exclusive).

    int8 pools (QuantPages): the flattened rows quantize per row and the
    scales scatter alongside, exactly like write_decode_all.
    """
    numcheck.check_finite("kv.write", k_new, v_new)
    if isinstance(k_pages, QuantPages):
        k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
        n_layers, s, t = k_new.shape[:3]
        pos = positions.reshape(-1)
        slot_of = jnp.repeat(
            jnp.arange(page_table.shape[0], dtype=jnp.int32), t)
        page_idx = _safe_page_idx(
            lambda p: page_table[slot_of, p], pos, jnp.repeat(active, t),
            page_size, page_table.shape[1], k_pages.data.shape[1],
        )
        offset = pos % page_size
        record_kernel_path("write_multi", False)
        kq, ksc = quantize_kv_rows(
            k_new.reshape(n_layers, s * t, *k_new.shape[3:]))
        vq, vsc = quantize_kv_rows(
            v_new.reshape(n_layers, s * t, *v_new.shape[3:]))
        return (
            QuantPages(
                k_pages.data.at[:, page_idx, offset].set(kq, mode="drop"),
                k_pages.scale.at[:, page_idx, offset].set(ksc, mode="drop"),
            ),
            QuantPages(
                v_pages.data.at[:, page_idx, offset].set(vq, mode="drop"),
                v_pages.scale.at[:, page_idx, offset].set(vsc, mode="drop"),
            ),
        )
    k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
    n_layers, s, t = k_new.shape[:3]
    pos = positions.reshape(-1)
    slot_of = jnp.repeat(jnp.arange(page_table.shape[0], dtype=jnp.int32), t)
    page_idx = _safe_page_idx(
        lambda p: page_table[slot_of, p], pos, jnp.repeat(active, t),
        page_size, page_table.shape[1], k_pages.shape[1],
    )
    offset = pos % page_size
    k_flat = k_new.reshape(n_layers, s * t, *k_new.shape[3:])
    v_flat = v_new.reshape(n_layers, s * t, *v_new.shape[3:])
    use, interpret = _pallas_mode(use_pallas)
    mode, ax = kernel_mesh_axis(mesh, k_new.shape[3])
    if use and mode != "ref" and _write_lane_gate(k_pages, ax, mesh,
                                                  interpret):
        from gridllm_tpu.ops.pallas_kernels import paged_write_decode

        record_kernel_path("write_multi", True)
        kernel = partial(paged_write_decode, interpret=interpret)
        if mode == "wrap":
            from jax.sharding import PartitionSpec as P

            kernel = _wrap_write_kernel(mesh, ax, kernel,
                                        (P(None), P(None)))
        return kernel(k_pages, v_pages, k_flat, v_flat, page_idx, offset)
    record_kernel_path("write_multi", False)
    k_pages = k_pages.at[:, page_idx, offset].set(k_flat, mode="drop")
    v_pages = v_pages.at[:, page_idx, offset].set(v_flat, mode="drop")
    return k_pages, v_pages


def rollback_to_length(cache: PagedKVCache,
                       new_lengths: jnp.ndarray) -> PagedKVCache:
    """Truncate each slot's valid KV to `new_lengths` — the speculative
    ROLLBACK (ISSUE 5): after a verify step optimistically wrote K+1
    candidate rows (write_multi_all), the accepted length is committed
    here and every rejected row is dropped.

    Dropping is pure bookkeeping, exact by the pool's own invariants:

    - reads: every attention path masks keys at k_pos >= lengths[slot]
      (plus the in-register overlay), so rolled-back rows are invisible —
      the same mechanism that guards stale data in owned-but-unwritten
      page tails;
    - writes: the next decode/verify step writes at the committed
      lengths, overwriting the junk rows in place;
    - prefix cache (PR 3): verify writes only touch positions >= the
      slot's prompt length, strictly past any refcount-shared prefix page
      (shared pages are fully covered by prompt-minus-last-token), so a
      rollback can never corrupt — or expose junk through — a page another
      request shares. Host-side page ownership is untouched: pages are
      allocated to slot capacity at admission and registered for reuse
      only from the final HOST-visible context (engine._finish), which
      never includes rolled-back tokens.
    """
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=cache.page_table,
        lengths=new_lengths, page_size=cache.page_size,
    )


def commit_tree_path(cache: PagedKVCache,
                     path: jnp.ndarray,
                     active: jnp.ndarray) -> PagedKVCache:
    """Compact the ACCEPTED root-to-leaf path of a tree-verify step into
    contiguous KV rows (ISSUE 18).

    Tree verify writes node i's K/V optimistically at storage position
    ``lengths + i`` (write_multi_all), but node i's LOGICAL position is
    ``lengths + depth[i]`` — a rejected sibling leaves a hole between
    accepted chain rows. ``path[s, j]`` names the tree node whose row
    backs committed position ``lengths[s] + 1 + j`` (0 = no KV: the
    final corrected/bonus token, or beyond n_emit — spec_accept_tree's
    contract). This copies row ``lengths + path[s, j]`` over row
    ``lengths + 1 + j`` for every ``path[s, j] > 0`` and leaves lengths
    untouched (the caller rolls forward with rollback_to_length, same as
    the chain path).

    Safety invariants:

    - all gathers read the ORIGINAL pool and all scatters land via the
      out-of-bounds sentinel (mode="drop"), so overlapping src/dst rows
      and inactive/unmapped hazards are both safe;
    - topological node order (parents[i] < i) gives src >= dst for every
      copy, so the accepted path only ever moves data DOWN toward its
      committed position, never over a row another slot could read —
      pages are slot-exclusive past the prompt, and tree rows start at
      position ``lengths`` >= prompt length, strictly past any
      refcount-shared prefix page (same argument as rollback_to_length);
    - int8 pools (QuantPages) move the quantized data AND the per-row
      scale verbatim — a dequantize/requantize round trip is NOT exact
      (the scale would be recomputed from the row's int8 absmax), so the
      committed row must be bit-identical to the optimistic write.
    """
    s, n = path.shape
    ps = cache.page_size
    table = cache.page_table
    max_pages = table.shape[1]
    pool = cache.k.data if isinstance(cache.k, QuantPages) else cache.k
    num_pages = pool.shape[1]

    j = jnp.arange(n, dtype=jnp.int32)[None, :]
    do = active[:, None] & (path > 0) & (path != j + 1)
    src_pos = (cache.lengths[:, None] + path).reshape(-1)
    dst_pos = (cache.lengths[:, None] + 1 + j).reshape(-1)
    dv = do.reshape(-1)
    slot_of = jnp.repeat(jnp.arange(s, dtype=jnp.int32), n)

    # src: gather clamps out-of-range and wraps -1 entries to a real page,
    # so a hazardous read returns junk — harmless, the matching scatter
    # row is masked to the sentinel below and dropped.
    src_page = table[slot_of, jnp.clip(src_pos // ps, 0, max_pages - 1)]
    src_off = src_pos % ps
    dst_page = _safe_page_idx(
        lambda p: table[slot_of, p], dst_pos, dv, ps, max_pages, num_pages,
    )
    dst_off = dst_pos % ps

    def move(pages):
        if isinstance(pages, QuantPages):
            return QuantPages(
                pages.data.at[:, dst_page, dst_off].set(
                    pages.data[:, src_page, src_off], mode="drop"),
                pages.scale.at[:, dst_page, dst_off].set(
                    pages.scale[:, src_page, src_off], mode="drop"),
            )
        return pages.at[:, dst_page, dst_off].set(
            pages[:, src_page, src_off], mode="drop")

    return PagedKVCache(
        k=move(cache.k), v=move(cache.v), page_table=cache.page_table,
        lengths=cache.lengths, page_size=cache.page_size,
    )


def write_prefill_all(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    page_size: int,
    use_pallas: bool | None = None,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a prefill chunk for ONE slot across ALL layers at once.

    k_pages/v_pages: [L, P, ps, KVH, D]; k_new/v_new: [L, T, KVH, D].
    Kernel path (TPU) requires T % page_size == 0 (static check) and
    page-aligned `start` (engine-guaranteed; see paged_write_chunk).
    Under `mesh`: full-manual shard_map, kv-heads split over tp.

    int8 pools (QuantPages): per-row quantize + scale scatter, like
    write_decode_all (scatter path — see the rationale there).
    """
    numcheck.check_finite("kv.write", k_new, v_new)
    if isinstance(k_pages, QuantPages):
        k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
        t = jnp.arange(k_new.shape[1], dtype=jnp.int32)
        pos = start + t
        page_idx = _safe_page_idx(
            lambda p: table_row[p], pos, t < length, page_size,
            table_row.shape[0], k_pages.data.shape[1],
        )
        offset = pos % page_size
        record_kernel_path("write_prefill", False)
        kq, ksc = quantize_kv_rows(k_new)   # [L, T, KVH, D] / [L, T]
        vq, vsc = quantize_kv_rows(v_new)
        return (
            QuantPages(
                k_pages.data.at[:, page_idx, offset].set(kq, mode="drop"),
                k_pages.scale.at[:, page_idx, offset].set(ksc, mode="drop"),
            ),
            QuantPages(
                v_pages.data.at[:, page_idx, offset].set(vq, mode="drop"),
                v_pages.scale.at[:, page_idx, offset].set(vsc, mode="drop"),
            ),
        )
    k_new, v_new = _pad_new_lanes(k_pages, k_new, v_new)
    use, interpret = _pallas_mode(use_pallas)
    mode, ax = kernel_mesh_axis(mesh, k_new.shape[2])
    if use and mode != "ref" and k_new.shape[1] % page_size == 0 and (
        _write_lane_gate(k_pages, ax, mesh, interpret)
    ):
        from gridllm_tpu.ops.pallas_kernels import paged_write_chunk

        record_kernel_path("write_prefill", True)
        kernel = partial(
            paged_write_chunk, page_size=page_size, interpret=interpret
        )
        if mode == "wrap":
            from jax.sharding import PartitionSpec as P

            kernel = _wrap_write_kernel(mesh, ax, kernel,
                                        (P(None), P(), P()))
        return kernel(k_pages, v_pages, k_new, v_new, table_row, start,
                      length)
    record_kernel_path("write_prefill", False)
    t = jnp.arange(k_new.shape[1], dtype=jnp.int32)
    pos = start + t
    page_idx = _safe_page_idx(
        lambda p: table_row[p], pos, t < length, page_size,
        table_row.shape[0], k_pages.shape[1],
    )
    offset = pos % page_size
    k_pages = k_pages.at[:, page_idx, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[:, page_idx, offset].set(v_new, mode="drop")
    return k_pages, v_pages


def gather_kv(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    table_row: jnp.ndarray,
    page_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize one slot's K/V [max_pages*page_size, KVH, D] from the pool.

    Reference implementation (CPU-testable); the Pallas paged-attention
    kernel reads pages in place instead of materializing. int8 pools
    (QuantPages) dequantize here — float32 out, which the refs cast to
    anyway — so every jnp fallback reads correct values for free.
    """
    rows = jnp.maximum(table_row, 0)
    if isinstance(k_pages, QuantPages):
        pages_k = k_pages.take(rows)              # [maxp, ps, KVH, D] f32
        pages_v = v_pages.take(rows)
    else:
        pages_k = k_pages[rows]                   # [maxp, ps, KVH, D]
        pages_v = v_pages[rows]
    kvh, d = k_pages.shape[-2], k_pages.shape[-1]
    n = table_row.shape[0] * page_size
    return pages_k.reshape(n, kvh, d), pages_v.reshape(n, kvh, d)


def _page_chain_key(parent: bytes, tokens: list[int]) -> bytes:
    """Content-address of one FULL page given its prefix: the hash chain
    hash(parent_hash, page_token_ids). blake2b so collisions are
    cryptographically negligible — a collision here would silently serve
    another prompt's KV."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(b" ".join(b"%d" % t for t in tokens))
    return h.digest()


class PageAllocator:
    """Host-side ref-counted page allocator (plain Python, not traced).

    Owns which pages back which slot; the device only sees the resulting
    int32 tables. O(1) alloc/free per page.

    Automatic prefix caching (ISSUE 3): pages holding FULL pages of a
    completed request's context are content-addressed by a hash chain
    (key_i = hash(key_{i-1}, page_i_token_ids)) and, once their refcount
    drops to zero, parked in an LRU of reusable blocks instead of the free
    list. A new request matches its longest cached prefix page-by-page,
    bumps refcounts, and shares those pages copy-free; fresh allocations
    evict from the LRU only when the free list is empty. `cache_pages`
    bounds the LRU (0 disables caching entirely — byte-identical to the
    pre-cache allocator; a negative value means unbounded).

    Sharing is page-aligned and read-only by construction: a matched page
    is fully covered by the new request's prompt minus its last token (the
    last token must run through the model to produce logits), prefill
    starts writing at the page boundary after the match, and decode writes
    land past the prompt — so a shared page is never written while shared,
    and a refcount pins it against eviction for as long as any request
    reads it.
    """

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_slot: int, cache_pages: int = 0,
                 model: str = ""):
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        self.cache_pages = cache_pages
        self.model = model or "unknown"
        # Tiered KV cache (ISSUE 11): optional host-tier hooks the engine
        # installs. spill_sink(page, chain_key) fires right before a
        # REGISTERED page is evicted from the reuse LRU (the engine copies
        # the page to host RAM); restore_source(chain_key) is consulted by
        # match_prefix on a chain miss and returns a freshly installed,
        # registered, refcount-0 page id (or None). Both run under the
        # engine's _alloc_lock — the same lock every allocator mutation
        # holds — so the callback may call back into claim_page /
        # register_claimed / unpin_pages safely (RLock).
        self.spill_sink: Any = None
        self.restore_source: Any = None
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._owned: dict[int, list[int]] = {}
        self._refs: dict[int, int] = {}          # page → owners (≥ 1)
        self._key_of: dict[int, bytes] = {}      # page → registered chain key
        self._page_by_key: dict[bytes, int] = {}  # chain key → page
        self._lru: OrderedDict[int, None] = OrderedDict()  # ref-0 cached pages
        # match accounting staged per slot by match_prefix and committed by
        # the matching alloc() — a pool-exhausted admission retry re-runs
        # match_prefix, and counting there would tally the same prompt's
        # pages once per retry
        self._staged_stats: dict[int, tuple[int, int, bool]] = {}
        # cumulative counters (mirrored into the obs registry); kept as
        # plain ints so the engine can compute a hit rate without reading
        # the registry back
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.cow_copies = 0

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Reusable (refcount-0, content-addressed) pages parked in the LRU."""
        return len(self._lru)

    @property
    def reclaimable_pages(self) -> int:
        """Pages a fresh allocation can obtain: free + evictable cached."""
        return len(self._free) + len(self._lru)

    def pages_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_fit(self, num_tokens: int) -> bool:
        """True iff a FRESH slot could ever hold num_tokens: within both the
        per-slot page cap (permanent) and the current free pool (transient)."""
        need = self.pages_for(num_tokens)
        return need <= self.max_pages_per_slot and need <= self.reclaimable_pages

    def fits_slot_cap(self, num_tokens: int) -> bool:
        """Permanent-capacity check only (retrying can't fix a False)."""
        return self.pages_for(num_tokens) <= self.max_pages_per_slot

    def _take_page(self) -> int | None:
        if self._free:
            return self._free.pop()
        if self._lru:  # evict the least-recently-released cached block
            page, _ = self._lru.popitem(last=False)
            self._spill(page)
            self._drop_key(page)
            self.evictions += 1
            _PREFIX_EVICTIONS.inc(model=self.model)
            return page
        return None

    def _spill(self, page: int) -> None:
        """Offer an about-to-be-evicted registered page to the host tier
        (no-op without a sink). A sink failure loses the page from the
        tier — the later match is just a miss — never the eviction."""
        sink = self.spill_sink
        if sink is None:
            return
        key = self._key_of.get(page)
        if key is None:
            return
        try:
            sink(page, key)
        except Exception as e:  # noqa: BLE001 — spill is best-effort
            from gridllm_tpu.utils.logging import get_logger

            get_logger("kvcache").warning(
                "host-tier spill failed; page content lost from tier",
                model=self.model, page=page, error=str(e))

    def _drop_key(self, page: int) -> None:
        key = self._key_of.pop(page, None)
        if key is not None and self._page_by_key.get(key) == page:
            del self._page_by_key[key]

    def match_prefix(self, slot: int, token_ids: list[int]) -> int:
        """Pin the longest cached prefix of `token_ids` to a FRESH slot.

        Walks the hash chain one full page at a time, bumping each matched
        page's refcount (removing it from the eviction LRU) and appending
        it to the slot's page list. The match is capped at the last page
        boundary strictly below len(token_ids): the final token must be
        recomputed to produce the sampled-token logits, so a fully-cached
        prompt still prefills its tail. Returns the number of cached
        TOKENS (a multiple of page_size; 0 when caching is off)."""
        if self.cache_pages == 0:
            return 0
        owned = self._owned.setdefault(slot, [])
        if owned:  # match only seeds a fresh slot
            return 0
        ps = self.page_size
        max_full = min((len(token_ids) - 1) // ps, self.max_pages_per_slot)
        key = b""
        matched = 0
        cow = False
        for i in range(max_full):
            key = _page_chain_key(key, token_ids[i * ps:(i + 1) * ps])
            page = self._page_by_key.get(key)
            if page is None and self.restore_source is not None:
                # tiered KV cache (ISSUE 11): the chain misses in HBM but
                # the host tier may hold the spilled page — the engine
                # callback pages it back in (claim + device write +
                # register) and we keep walking, so a long request's
                # eviction storm costs restores, not cold prefills
                try:
                    page = self.restore_source(key)
                except Exception as e:  # noqa: BLE001 — degrade to cold
                    from gridllm_tpu.utils.logging import get_logger

                    get_logger("kvcache").warning(
                        "host-tier restore failed; cold prefill",
                        model=self.model, error=str(e))
                    page = None
            if page is None:
                break
            self._lru.pop(page, None)
            self._refs[page] = self._refs.get(page, 0) + 1
            owned.append(page)
            matched += 1
        else:
            # whole cap matched: if the NEXT full page is cached too, the
            # request is about to write into a page the cache holds — the
            # partial-tail copy-on-write (rebuilt privately by prefill)
            if (max_full + 1) * ps <= len(token_ids):
                tail_key = _page_chain_key(
                    key, token_ids[max_full * ps:(max_full + 1) * ps])
                cow = tail_key in self._page_by_key
        # stage the accounting; the successful alloc() commits it (an
        # admission that bounces off an exhausted pool retries this whole
        # sequence and must not re-count the same prompt)
        self._staged_stats[slot] = (
            matched, self.pages_for(len(token_ids)), cow)
        return matched * ps

    def _commit_match_stats(self, slot: int) -> None:
        staged = self._staged_stats.pop(slot, None)
        if staged is None:
            return
        matched, prompt_pages, cow = staged
        self.hits += matched
        self.misses += prompt_pages - matched
        if matched:
            _PREFIX_HITS.inc(matched, model=self.model)
        if prompt_pages - matched:
            _PREFIX_MISSES.inc(prompt_pages - matched, model=self.model)
        if cow:
            self.cow_copies += 1
            _PREFIX_COW.inc(model=self.model)

    def alloc(self, slot: int, num_tokens: int) -> list[int] | None:
        """Ensure `slot` owns enough pages for `num_tokens` total tokens.
        Returns the slot's full page list, or None if the pool is exhausted
        (caller must preempt/queue — mirrors the scheduler holding jobs when
        no worker has capacity, reference JobScheduler.ts:176-204). Pages
        pinned by match_prefix count toward the total; fresh pages come
        from the free list first, then evict the reuse LRU."""
        from gridllm_tpu import faults

        if faults.check("alloc.alloc"):
            # injected pool exhaustion: exercises the caller's requeue/
            # backpressure path without actually draining the pool
            return None
        owned = self._owned.setdefault(slot, [])
        need = self.pages_for(num_tokens) - len(owned)
        if need > self.reclaimable_pages:
            return None
        if need > self.max_pages_per_slot - len(owned):
            return None
        for _ in range(max(0, need)):
            page = self._take_page()
            assert page is not None  # guarded by reclaimable check above
            self._refs[page] = 1
            owned.append(page)
        self._commit_match_stats(slot)
        return owned

    def free(self, slot: int, token_ids: list[int] | None = None) -> None:
        """Release a slot's pages. With `token_ids` (the request's final
        context, prompt + generated — KV fully written on device), full
        pages are first registered under their chain keys so future
        requests can match them. Each page's refcount then drops; at zero a
        registered page parks in the reuse LRU, an unregistered one returns
        to the free list."""
        self._staged_stats.pop(slot, None)  # uncommitted match: retry path
        owned = self._owned.pop(slot, [])
        if token_ids is not None and self.cache_pages != 0:
            n_full = min(len(token_ids) // self.page_size, len(owned))
            key = b""
            for i in range(n_full):
                key = _page_chain_key(
                    key, token_ids[i * self.page_size:(i + 1) * self.page_size]
                )
                page = owned[i]
                cur = self._page_by_key.get(key)
                if cur is None and page not in self._key_of:
                    # first holder of this content wins; a page already
                    # registered under another key (matched from cache)
                    # keeps its identity, duplicates stay unregistered and
                    # fall back to the free list on release
                    self._page_by_key[key] = page
                    self._key_of[page] = key
        for page in owned:
            self._release_page(page)

    def _release_page(self, page: int) -> None:
        """Drop one reference to `page`; at zero, park a registered page
        in the reuse LRU (bounded by cache_pages) or return an
        unregistered one to the free list. Shared by free() and
        unpin_pages() so both sides of an export/import pin obey the
        same refcount/LRU rules."""
        refs = self._refs.get(page, 1) - 1
        if refs > 0:
            self._refs[page] = refs
            return
        self._refs.pop(page, None)
        if page in self._key_of:
            self._lru[page] = None  # most-recently released
            cap = self.cache_pages
            while cap > 0 and len(self._lru) > cap:
                old, _ = self._lru.popitem(last=False)
                self._spill(old)
                self._drop_key(old)
                self.evictions += 1
                _PREFIX_EVICTIONS.inc(model=self.model)
                self._free.append(old)
        else:
            self._free.append(page)

    def evict_cached(self, pages: list[int]) -> int:
        """Force-drop refcount-0 cached pages to the free list WITHOUT
        the spill hook — the suspend-to-host park path (engine
        ``park_to_host``) calls this after it has already copied the
        pages into the host tier, which is what actually frees the HBM.
        Pages still pinned by a live request (not in the LRU) are left
        untouched: a shared page must never be freed mid-decode. Returns
        the number of pages dropped."""
        n = 0
        for page in pages:
            if page in self._lru:
                self._lru.pop(page)
                self._drop_key(page)
                self._free.append(page)
                n += 1
        return n

    # -- KV-page migration (ISSUE 7) ----------------------------------------
    #
    # The transfer subsystem moves the cached full-page prefix of a prompt
    # between workers. On the export side pin_prefix/unpin_pages bracket
    # the device gather (a refcount pin keeps the pages from being evicted
    # or handed to a fresh allocation mid-copy); on the import side
    # install_page registers externally produced pages under their chain
    # keys so the very next admission's match_prefix can share them.

    def chain_keys(self, token_ids: list[int],
                   n_pages: int | None = None) -> list[bytes]:
        """Chain keys for the first `n_pages` FULL pages of token_ids.
        Default cap is one page below len (the last token is always
        recomputed — the same cap match_prefix applies, so export and a
        later match agree on coverage); the import side passes the exact
        page count its wire payload covers."""
        ps = self.page_size
        cap = (len(token_ids) - 1) // ps if n_pages is None else n_pages
        cap = min(cap, len(token_ids) // ps)
        keys: list[bytes] = []
        key = b""
        for i in range(cap):
            key = _page_chain_key(key, token_ids[i * ps:(i + 1) * ps])
            keys.append(key)
        return keys

    def pin_prefix(self, token_ids: list[int]) -> tuple[list[int], int]:
        """Bump refcounts on the cached pages covering token_ids' longest
        full-page prefix (no slot involved). Returns (pages, tokens
        covered); release with unpin_pages. Pinned pages leave the
        eviction LRU, so a concurrent admission cannot reclaim them."""
        pages: list[int] = []
        if self.cache_pages == 0:
            return pages, 0
        for key in self.chain_keys(token_ids):
            page = self._page_by_key.get(key)
            if page is None:
                break
            self._lru.pop(page, None)
            self._refs[page] = self._refs.get(page, 0) + 1
            pages.append(page)
        return pages, len(pages) * self.page_size

    def unpin_pages(self, pages: list[int]) -> None:
        for page in pages:
            self._release_page(page)

    def peek_key(self, key: bytes) -> int | None:
        """The page cached under `key`, if any (no state change)."""
        return self._page_by_key.get(key)

    def claim_page(self) -> int | None:
        """Take a pool page for externally imported content, PINNED at
        refcount 1 and deliberately UNREGISTERED: the chain key must not
        become matchable until the page's KV data has actually landed on
        the device (a concurrent admission matching an unwritten page
        would silently decode over garbage). Callers write the data,
        then register_claimed() + unpin_pages(). Returns None when the
        pool has nothing reclaimable."""
        if self.cache_pages == 0:
            return None
        page = self._take_page()
        if page is None:
            return None
        self._refs[page] = 1
        return page

    def register_claimed(self, page: int, key: bytes) -> None:
        """Publish a claimed page under its chain key AFTER its data was
        written. If a concurrent import registered the same content
        first, the first registration wins and this page stays
        unregistered (it returns to the free list on unpin — exactly the
        duplicate rule free() applies)."""
        if key in self._page_by_key or page in self._key_of:
            return
        self._page_by_key[key] = page
        self._key_of[page] = key

    def table_row(self, slot: int) -> list[int]:
        owned = self._owned.get(slot, [])
        return owned + [-1] * (self.max_pages_per_slot - len(owned))
