"""Batched, jit-once token sampling.

Implements the Ollama sampler option surface the reference forwards opaquely
(reference: server/src/routes/ollama.ts:26-48 — temperature, top_k, top_p,
min_p, seed, repeat_penalty; OllamaService.ts:197-226 passes them through to
the external engine). Here they are *device-side per-slot arrays*, so one
compiled sampler serves every concurrent request in the continuous batch —
no recompiles when options differ across slots.

Determinism contract (Ollama `seed` semantics): token i of a request with
seed s depends only on (s, i) — threefry fold_in chain, independent of which
slot the request landed in or what else is batched.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# Sampling operates on the static top-K logits (full-vocab sort per step is
# MXU-hostile); mass outside the top 64 is negligible for every supported
# sampler setting (top_k caps at TOPK; top_p tail beyond 64 tokens ~0).
TOPK = 64


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["temperature", "top_k", "top_p", "min_p", "repeat_penalty", "seed", "step"],
    meta_fields=[],
)
@dataclasses.dataclass
class SamplingParams:
    """Per-slot sampler state, all arrays of shape [S]."""

    temperature: jnp.ndarray  # f32; <=0 → greedy
    top_k: jnp.ndarray        # i32; <=0 → disabled
    top_p: jnp.ndarray        # f32; >=1 → disabled
    min_p: jnp.ndarray        # f32; <=0 → disabled
    repeat_penalty: jnp.ndarray  # f32; 1.0 → disabled
    seed: jnp.ndarray         # i32 per-request seed
    step: jnp.ndarray         # i32 tokens generated so far (drives the rng chain)

    @staticmethod
    def defaults(max_slots: int) -> "SamplingParams":
        s = max_slots
        return SamplingParams(
            temperature=jnp.full((s,), 0.8, jnp.float32),
            top_k=jnp.full((s,), 40, jnp.int32),
            top_p=jnp.full((s,), 0.9, jnp.float32),
            min_p=jnp.zeros((s,), jnp.float32),
            repeat_penalty=jnp.full((s,), 1.1, jnp.float32),
            seed=jnp.zeros((s,), jnp.int32),
            step=jnp.zeros((s,), jnp.int32),
        )


def _slot_gumbel(seed: jnp.ndarray, step: jnp.ndarray, k: int) -> jnp.ndarray:
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.random.gumbel(key, (k,), jnp.float32)


def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    token_counts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Sample one token per slot. logits: [S, V] → [S] int32.

    token_counts ([S, V] int32, optional): occurrence counts of tokens in
    each slot's context, for repeat_penalty (CTRL-style: positive logits
    divided, negative multiplied).
    """
    logits = logits.astype(jnp.float32)

    if token_counts is not None:
        pen = params.repeat_penalty[:, None]
        seen = token_counts > 0
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / pen, logits * pen), logits
        )

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    topk = min(TOPK, logits.shape[-1])
    vals, idx = jax.lax.top_k(logits, topk)  # [S, topk], sorted desc

    j = jnp.arange(topk)[None, :]
    k_eff = jnp.where(params.top_k <= 0, topk, jnp.minimum(params.top_k, topk))
    keep = j < k_eff[:, None]

    # Ollama/llama.cpp sampler-chain order: truncation (top_k → top_p →
    # min_p) runs on UNSCALED probabilities; temperature rescales only the
    # final distribution the draw is taken from.
    masked = jnp.where(keep, vals, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < params.top_p[:, None]  # token starts inside the p-mass
    keep &= probs >= params.min_p[:, None] * probs[:, :1]
    keep = keep.at[:, 0].set(True)  # never mask the argmax

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = vals / temp
    gumbel = jax.vmap(lambda s, t: _slot_gumbel(s, t, topk))(params.seed, params.step)
    choice = jnp.argmax(jnp.where(keep, scaled + gumbel, -jnp.inf), axis=-1)
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(params.temperature <= 0.0, greedy, sampled)
