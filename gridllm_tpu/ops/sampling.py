"""Batched, jit-once token sampling.

Implements the Ollama sampler option surface the reference forwards opaquely
(reference: server/src/routes/ollama.ts:26-48 — temperature, top_k, top_p,
min_p, seed, repeat_penalty; OllamaService.ts:197-226 passes them through to
the external engine). Here they are *device-side per-slot arrays*, so one
compiled sampler serves every concurrent request in the continuous batch —
no recompiles when options differ across slots.

Determinism contract (Ollama `seed` semantics): token i of a request with
seed s depends only on (s, i) — threefry fold_in chain, independent of which
slot the request landed in or what else is batched.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# Sampling operates on the static top-K logits (full-vocab sort per step is
# MXU-hostile); mass outside the top 128 is negligible for every supported
# sampler setting (top_k clamps at TOPK — was 64 in round 3, lifted per
# VERDICT r03 weak #7; top_p tail beyond 128 tokens ~0).
TOPK = 128


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["temperature", "top_k", "top_p", "min_p", "repeat_penalty",
                 "repeat_last_n", "seed", "step"],
    meta_fields=[],
)
@dataclasses.dataclass
class SamplingParams:
    """Per-slot sampler state, all arrays of shape [S]."""

    temperature: jnp.ndarray  # f32; <=0 → greedy
    top_k: jnp.ndarray        # i32; <=0 → disabled
    top_p: jnp.ndarray        # f32; >=1 → disabled
    min_p: jnp.ndarray        # f32; <=0 → disabled
    repeat_penalty: jnp.ndarray  # f32; 1.0 → disabled
    # window size the penalty applies over (llama.cpp penalty_last_n):
    # 0 → disabled, host resolves -1 → context size and clamps to the
    # engine's window buffer width
    repeat_last_n: jnp.ndarray   # i32
    seed: jnp.ndarray         # i32 per-request seed
    step: jnp.ndarray         # i32 tokens generated so far (drives the rng chain)

    @staticmethod
    def defaults(max_slots: int) -> "SamplingParams":
        s = max_slots
        return SamplingParams(
            temperature=jnp.full((s,), 0.8, jnp.float32),
            top_k=jnp.full((s,), 40, jnp.int32),
            top_p=jnp.full((s,), 0.9, jnp.float32),
            min_p=jnp.zeros((s,), jnp.float32),
            repeat_penalty=jnp.full((s,), 1.1, jnp.float32),
            repeat_last_n=jnp.full((s,), 64, jnp.int32),  # Ollama default
            seed=jnp.zeros((s,), jnp.int32),
            step=jnp.zeros((s,), jnp.int32),
        )


def _slot_gumbel(seed: jnp.ndarray, step: jnp.ndarray, k: int) -> jnp.ndarray:
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.random.gumbel(key, (k,), jnp.float32)


def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    token_counts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Sample one token per slot. logits: [S, V] → [S] int32.

    token_counts ([S, V] int32, optional): occurrence counts of tokens in
    each slot's context, for repeat_penalty (CTRL-style: positive logits
    divided, negative multiplied).
    """
    logits = logits.astype(jnp.float32)

    if token_counts is not None:
        pen = params.repeat_penalty[:, None]
        seen = token_counts > 0
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / pen, logits * pen), logits
        )

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    topk = min(TOPK, logits.shape[-1])
    vals, idx = jax.lax.top_k(logits, topk)  # [S, topk], sorted desc

    j = jnp.arange(topk)[None, :]
    k_eff = jnp.where(params.top_k <= 0, topk, jnp.minimum(params.top_k, topk))
    keep = j < k_eff[:, None]

    # Ollama/llama.cpp sampler-chain order: truncation (top_k → top_p →
    # min_p) runs on UNSCALED probabilities; temperature rescales only the
    # final distribution the draw is taken from.
    masked = jnp.where(keep, vals, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < params.top_p[:, None]  # token starts inside the p-mass
    keep &= probs >= params.min_p[:, None] * probs[:, :1]
    keep = keep.at[:, 0].set(True)  # never mask the argmax

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = vals / temp
    gumbel = jax.vmap(lambda s, t: _slot_gumbel(s, t, topk))(params.seed, params.step)
    choice = jnp.argmax(jnp.where(keep, scaled + gumbel, -jnp.inf), axis=-1)
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(params.temperature <= 0.0, greedy, sampled)


# ---------------------------------------------------------------------------
# repeat-penalty window maintenance (llama.cpp penalty_last_n semantics)
# ---------------------------------------------------------------------------
# The engine keeps, per slot, the last ≤ repeat_last_n context tokens in a
# fixed [S, W] buffer (right-aligned: window[:, W-wlen:] are the tokens,
# oldest first) plus the [S, V] occurrence counts the penalty reads. W is a
# static engine-config cap; the host clamps repeat_last_n into [0, W].
# Round 3 penalized over the WHOLE context (documented divergence); these
# helpers close it (VERDICT r03 weak #7 / next-round #10).


def window_set_slot(
    window: jnp.ndarray,   # [S, W] i32
    wlen: jnp.ndarray,     # [S] i32
    counts: jnp.ndarray,   # [S, V] i32
    slot: jnp.ndarray,     # scalar i32
    chunk: jnp.ndarray,    # [T] i32 padded token chunk
    start: jnp.ndarray,    # scalar — 0 resets the slot's window first
    clen: jnp.ndarray,     # scalar — valid tokens in `chunk`
    rl: jnp.ndarray,       # scalar — slot's repeat_last_n (≥ 0)
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Append `chunk[:clen]` to one slot's window (reset when start == 0)
    and rebuild that slot's counts row. One call covers fresh prefill
    (start=0) and chunked-prefill continuation alike."""
    w = window.shape[1]
    rl = jnp.minimum(rl, w)
    old = window[slot]
    ol = jnp.where(start == 0, 0, wlen[slot])
    total = ol + clen
    m = jnp.minimum(total, rl)
    j = jnp.arange(w)
    # virtual ordered sequence [0, total): first the old window (oldest
    # first), then the chunk; keep its last m entries
    src = total - m + j                      # global index, valid where j < m
    from_old = src < ol
    old_idx = jnp.clip(w - ol + src, 0, w - 1)
    chunk_idx = jnp.clip(src - ol, 0, chunk.shape[0] - 1)
    tok = jnp.where(from_old, old[old_idx], chunk[chunk_idx])
    valid = j < m
    dst = jnp.where(valid, j + (w - m), w)   # right-align; w drops
    row = jnp.zeros((w,), jnp.int32).at[dst].set(
        jnp.where(valid, tok, 0), mode="drop"
    )
    window = window.at[slot].set(row)
    wlen = wlen.at[slot].set(m)
    counts = counts.at[slot].set(0)
    ids = jnp.where(valid, tok, vocab)       # vocab sentinel drops padding
    counts = counts.at[slot, ids].add(1, mode="drop")
    return window, wlen, counts


def window_push(
    window: jnp.ndarray,   # [S, W] i32
    wlen: jnp.ndarray,     # [S] i32
    counts: jnp.ndarray,   # [S, V] i32
    tok: jnp.ndarray,      # [S] i32 — one new token per slot
    active: jnp.ndarray,   # [S] bool — inactive slots untouched
    rl: jnp.ndarray,       # [S] i32 — per-slot repeat_last_n
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Push one token per active slot into its window, evicting (and
    un-counting) the oldest token once the window is at repeat_last_n."""
    s = jnp.arange(window.shape[0])
    w = window.shape[1]
    cap = jnp.minimum(jnp.maximum(rl, 0), w)
    full = wlen >= cap
    evict_pos = jnp.clip(w - wlen, 0, w - 1)
    evicted = jnp.take_along_axis(window, evict_pos[:, None], axis=1)[:, 0]
    do_evict = active & full & (cap > 0)
    counts = counts.at[s, jnp.where(do_evict, evicted, vocab)].add(
        -1, mode="drop"
    )
    pushed = jnp.roll(window, -1, axis=1).at[:, -1].set(tok)
    window = jnp.where(active[:, None], pushed, window)
    wlen = jnp.where(active, jnp.minimum(wlen + 1, cap), wlen)
    counts = counts.at[s, jnp.where(active & (cap > 0), tok, vocab)].add(
        1, mode="drop"
    )
    return window, wlen, counts
