"""Batched, jit-once token sampling.

Implements the Ollama sampler option surface the reference forwards opaquely
(reference: server/src/routes/ollama.ts:26-48 — temperature, top_k, top_p,
min_p, seed, repeat_penalty; OllamaService.ts:197-226 passes them through to
the external engine). Here they are *device-side per-slot arrays*, so one
compiled sampler serves every concurrent request in the continuous batch —
no recompiles when options differ across slots.

Determinism contract (Ollama `seed` semantics): token i of a request with
seed s depends only on (s, i) — threefry fold_in chain, independent of which
slot the request landed in or what else is batched.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from gridllm_tpu.analysis import numcheck

# Sampling operates on the static top-K logits (full-vocab sort per step is
# MXU-hostile); mass outside the top 128 is negligible for every supported
# sampler setting (top_k clamps at TOPK — was 64 in round 3, lifted per
# VERDICT r03 weak #7; top_p tail beyond 128 tokens ~0).
TOPK = 128


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["temperature", "top_k", "top_p", "min_p", "repeat_penalty",
                 "repeat_last_n", "seed", "step"],
    meta_fields=[],
)
@dataclasses.dataclass
class SamplingParams:
    """Per-slot sampler state, all arrays of shape [S]."""

    temperature: jnp.ndarray  # f32; <=0 → greedy
    top_k: jnp.ndarray        # i32; <=0 → disabled
    top_p: jnp.ndarray        # f32; >=1 → disabled
    min_p: jnp.ndarray        # f32; <=0 → disabled
    repeat_penalty: jnp.ndarray  # f32; 1.0 → disabled
    # window size the penalty applies over (llama.cpp penalty_last_n):
    # 0 → disabled, host resolves -1 → context size and clamps to the
    # engine's window buffer width
    repeat_last_n: jnp.ndarray   # i32
    seed: jnp.ndarray         # i32 per-request seed
    step: jnp.ndarray         # i32 tokens generated so far (drives the rng chain)

    @staticmethod
    def defaults(max_slots: int) -> "SamplingParams":
        s = max_slots
        return SamplingParams(
            temperature=jnp.full((s,), 0.8, jnp.float32),
            top_k=jnp.full((s,), 40, jnp.int32),
            top_p=jnp.full((s,), 0.9, jnp.float32),
            min_p=jnp.zeros((s,), jnp.float32),
            repeat_penalty=jnp.full((s,), 1.1, jnp.float32),
            repeat_last_n=jnp.full((s,), 64, jnp.int32),  # Ollama default
            seed=jnp.zeros((s,), jnp.int32),
            step=jnp.zeros((s,), jnp.int32),
        )


def _slot_gumbel(seed: jnp.ndarray, step: jnp.ndarray, k: int) -> jnp.ndarray:
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.random.gumbel(key, (k,), jnp.float32)


def _sampler_dists(
    logits: jnp.ndarray,
    params: SamplingParams,
    token_counts: jnp.ndarray | None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The shared sampler chain: repeat penalty → top-K extraction →
    truncation masks → temperature. Returns (greedy [S], idx [S, topk],
    keep [S, topk], scaled [S, topk]) where the effective sampling
    distribution is softmax(scaled) restricted to `keep`, over the token
    ids in `idx`. sample_tokens and spec_accept (the speculative
    accept/reject kernel) both build on this so the verified target
    distribution is EXACTLY the one the plain decode path samples from."""
    logits = logits.astype(jnp.float32)
    # numerics sanitizer (GRIDLLM_SANITIZE=1): a NaN/Inf logit here is the
    # first host-observable symptom of a diverged kernel upstream
    numcheck.check_finite("sampler.logits", logits)

    if token_counts is not None:
        pen = params.repeat_penalty[:, None]
        seen = token_counts > 0
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / pen, logits * pen), logits
        )

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    topk = min(TOPK, logits.shape[-1])
    vals, idx = jax.lax.top_k(logits, topk)  # [S, topk], sorted desc

    j = jnp.arange(topk)[None, :]
    k_eff = jnp.where(params.top_k <= 0, topk, jnp.minimum(params.top_k, topk))
    keep = j < k_eff[:, None]

    # Ollama/llama.cpp sampler-chain order: truncation (top_k → top_p →
    # min_p) runs on UNSCALED probabilities; temperature rescales only the
    # final distribution the draw is taken from.
    masked = jnp.where(keep, vals, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < params.top_p[:, None]  # token starts inside the p-mass
    keep &= probs >= params.min_p[:, None] * probs[:, :1]
    keep = keep.at[:, 0].set(True)  # never mask the argmax

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = vals / temp
    return greedy, idx, keep, scaled


def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    token_counts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Sample one token per slot. logits: [S, V] → [S] int32.

    token_counts ([S, V] int32, optional): occurrence counts of tokens in
    each slot's context, for repeat_penalty (CTRL-style: positive logits
    divided, negative multiplied).
    """
    greedy, idx, keep, scaled = _sampler_dists(logits, params, token_counts)
    topk = idx.shape[-1]
    gumbel = jax.vmap(lambda s, t: _slot_gumbel(s, t, topk))(params.seed, params.step)
    choice = jnp.argmax(jnp.where(keep, scaled + gumbel, -jnp.inf), axis=-1)
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    return jnp.where(params.temperature <= 0.0, greedy, sampled)


# ---------------------------------------------------------------------------
# speculative decoding: batched accept/reject over a candidate block
# ---------------------------------------------------------------------------


def _spec_keys(seed: jnp.ndarray, step: jnp.ndarray, topk: int):
    """Per-slot (uniform, gumbel[topk]) draws for one emitted-token index.
    Derived from the SAME (seed, step) chain sample_tokens uses, but
    sub-folded — the spec path needs two draws per emitted token (accept
    test + fallback sample), so sampled spec-on streams are deterministic
    per (seed, step) yet not bit-equal to spec-off (the target
    DISTRIBUTION is preserved exactly; only greedy streams are
    byte-identical, which is the documented contract)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    u = jax.random.uniform(jax.random.fold_in(key, 1), (), jnp.float32)
    g = jax.random.gumbel(jax.random.fold_in(key, 2), (topk,), jnp.float32)
    return u, g


def spec_accept(
    logits: jnp.ndarray,      # [S, K1, V] fp32 — verify-forward logits
    candidates: jnp.ndarray,  # [S, K1] — col 0 = committed last token,
                              # cols 1..K1-1 = drafted candidates
    dlen: jnp.ndarray,        # [S] i32 — valid drafts per slot (0..K1-1)
    params: SamplingParams,
    counts: jnp.ndarray,      # [S, V] i32 repeat-penalty counts
    window: jnp.ndarray,      # [S, W] i32 repeat-penalty window
    wlen: jnp.ndarray,        # [S] i32
    active: jnp.ndarray,      # [S] bool
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, SamplingParams]:
    """Keep the longest accepted candidate prefix plus one corrected token.

    logits[s, j] is the model's next-token distribution AFTER consuming
    candidates[s, :j+1]; the scan below walks j = 0..K1-1, at each step
    emitting exactly one token for every still-"alive" slot:

    - greedy (temperature <= 0): the emitted token is argmax of the
      penalized logits — identical to the sequential decode path — and the
      slot stays alive iff the next draft equals it. Greedy spec-on
      streams are therefore byte-identical to spec-off.
    - sampled: exact rejection sampling against the n-gram drafter's
      point-mass proposal q = δ(draft): accept the draft with probability
      p(draft) under the FULL truncated/penalized/temperature-scaled
      target distribution; on rejection, sample the corrected token from
      the target with the draft masked out (the normalized residual
      max(p - q, 0)). This preserves the target distribution exactly.
    - once a draft is rejected (or drafts run out), the step emits its
      corrected/bonus token and the slot leaves the span.

    Repeat-penalty bookkeeping runs INSIDE the scan via the same
    window_push the decode block uses, so counts/window evolve exactly as
    a sequential run's would — position j's distribution sees every token
    emitted at positions < j. params.step advances by the true number of
    emitted tokens per slot (n_emit), keeping the (seed, step) rng chain
    aligned with the emitted stream.

    Returns (out [K1, S] emitted tokens — row j valid iff j < n_emit[s];
    n_emit [S] in [1, K1] for active slots, 0 for inactive; new_tokens [S]
    — the last emitted token per slot, the next block's input; counts;
    window; wlen; params with step advanced)."""
    s, k1, _ = logits.shape
    # verify logits arrive f32 by contract; the cast is a no-op there and
    # pins the rejection-sampling math to f32 for any other caller
    logits = logits.astype(jnp.float32)
    topk = min(TOPK, logits.shape[-1])
    greedy_mode = params.temperature <= 0.0
    # draft checked at scan step j is candidates[:, j+1]; the last step
    # never has one (bonus-token position)
    drafts_next = jnp.concatenate(
        [candidates[:, 1:], jnp.zeros((s, 1), candidates.dtype)], axis=1
    )

    def body(carry, j):
        counts, window, wlen, emitted, alive = carry
        lg = jax.lax.dynamic_index_in_dim(logits, j, axis=1, keepdims=False)
        greedy, idx, keep, scaled = _sampler_dists(lg, params, counts)
        d = jax.lax.dynamic_index_in_dim(
            drafts_next, j, axis=1, keepdims=False
        ).astype(jnp.int32)
        has_draft = j < dlen

        # -- sampled path: rejection sampling vs the point-mass proposal
        u, gum = jax.vmap(lambda sd, st: _spec_keys(sd, st, topk))(
            params.seed, params.step + emitted
        )
        probs = jax.nn.softmax(jnp.where(keep, scaled, -jnp.inf), axis=-1)
        is_d = keep & (idx == d[:, None])
        p_d = jnp.sum(jnp.where(is_d, probs, 0.0), axis=-1)
        # fallback (residual) sample: target with the rejected draft masked
        fb_keep = keep & ~(has_draft[:, None] & is_d)
        any_fb = jnp.any(fb_keep, axis=-1)
        choice = jnp.argmax(jnp.where(fb_keep, scaled + gum, -jnp.inf), axis=-1)
        fallback = jnp.take_along_axis(
            idx, choice[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
        # ~any_fb: the draft is the ONLY kept token, so p(draft) = 1 and a
        # float-rounding reject would have nothing to fall back on
        s_acc = has_draft & ((u < p_d) | ~any_fb)
        s_tok = jnp.where(s_acc, d, fallback)

        # -- greedy path: emitted token is the argmax either way
        g_acc = has_draft & (d == greedy)

        tok = jnp.where(greedy_mode, greedy, s_tok)
        acc = jnp.where(greedy_mode, g_acc, s_acc)
        emit = alive & active
        window, wlen, counts = window_push(
            window, wlen, counts, tok, emit, params.repeat_last_n, vocab
        )
        emitted = emitted + emit.astype(jnp.int32)
        alive = alive & acc
        return (counts, window, wlen, emitted, alive), jnp.where(emit, tok, 0)

    init = (counts, window, wlen, jnp.zeros((s,), jnp.int32),
            jnp.ones((s,), bool))
    (counts, window, wlen, n_emit, _), out = jax.lax.scan(
        body, init, jnp.arange(k1, dtype=jnp.int32)
    )
    # last emitted token per slot = the next block's input token
    last = jnp.take_along_axis(
        out.T, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
    )[:, 0]
    params = dataclasses.replace(params, step=params.step + n_emit)
    return out, n_emit, last, counts, window, wlen, params


def _spec_tree_keys(seed: jnp.ndarray, step: jnp.ndarray, topk: int,
                    rounds: int):
    """Per-slot (uniform[rounds], gumbel[topk]) draws for one emitted-token
    index of the TREE accept walk: one uniform per candidate child round
    (multi-round rejection needs an independent accept test per sibling)
    plus the shared residual-fallback gumbel. Same (seed, step) chain as
    _spec_keys, sub-folded at 3+round so chain and tree draws never
    collide; deterministic per (seed, step) but not bit-equal to the
    chain accept (only greedy streams are byte-identical, the documented
    contract)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    u = jnp.stack([
        jax.random.uniform(jax.random.fold_in(key, 3 + c), (), jnp.float32)
        for c in range(rounds)
    ])
    g = jax.random.gumbel(jax.random.fold_in(key, 2), (topk,), jnp.float32)
    return u, g


def spec_accept_tree(
    logits: jnp.ndarray,       # [S, N, V] fp32 — tree-verify logits
    node_tokens: jnp.ndarray,  # [S, N] — col 0 = committed root token,
                               # cols 1..N-1 = drafted tree nodes
    parents,                   # [N] host ints (static topology,
                               # topological: parents[i] < i, root -1)
    node_valid: jnp.ndarray,   # [S, N] bool — per-slot live nodes (root
                               # always True; ancestor-closed)
    params: SamplingParams,
    counts: jnp.ndarray,       # [S, V] i32 repeat-penalty counts
    window: jnp.ndarray,       # [S, W] i32 repeat-penalty window
    wlen: jnp.ndarray,         # [S] i32
    active: jnp.ndarray,       # [S] bool
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, jnp.ndarray, SamplingParams]:
    """Tree generalization of spec_accept (ISSUE 18): walk the accepted
    root-to-leaf path through a static-topology draft tree under the same
    rejection-sampling rule.

    logits[s, i] is the model's next-token distribution AFTER consuming
    the root-to-node-i path (the tree-masked verify forward guarantees
    node i's query row attends exactly its ancestors). The scan walks
    depth steps; at each step the current node's children are tested in
    node order:

    - greedy (temperature <= 0): the step emits argmax of the penalized
      logits at the current node — identical to the sequential decode
      path — and descends into the (first) child carrying that token.
      Greedy spec-on streams stay byte-identical to spec-off.
    - sampled: SpecInfer-style multi-round rejection. Child c with token
      x is accepted w.p. residual(x) where the residual starts as the
      full truncated/penalized/temperature-scaled target and every
      rejected sibling's token is zeroed + renormalized; if all children
      reject, the step emits a sample from the final residual. This
      preserves the target distribution exactly.
    - a step with no accepted child emits its corrected/bonus token and
      ends the walk.

    Repeat-penalty counts/window evolve token-by-token inside the scan
    (window_push), exactly as a sequential run's would.

    Returns (out [N, S] emitted tokens — row j valid iff j < n_emit[s];
    path [S, N] — path[s, j] = tree node whose optimistically-written KV
    row backs committed position lengths[s]+1+j, 0 where the emitted
    token was a correction/bonus (no KV) or beyond n_emit; n_emit [S];
    last [S]; counts; window; wlen; params with step advanced)."""
    import numpy as np

    s, n, _ = logits.shape
    parents_np = np.asarray(parents, np.int64).tolist()
    assert len(parents_np) == n
    logits = logits.astype(jnp.float32)
    topk = min(TOPK, logits.shape[-1])
    greedy_mode = params.temperature <= 0.0

    def body(carry, j):
        counts, window, wlen, emitted, alive, cur = carry
        lg = jnp.take_along_axis(logits, cur[:, None, None], axis=1)[:, 0]
        greedy, idx, keep, scaled = _sampler_dists(lg, params, counts)
        u, gum = jax.vmap(
            lambda sd, st: _spec_tree_keys(sd, st, topk, max(n - 1, 1))
        )(params.seed, params.step + emitted)
        probs = jax.nn.softmax(jnp.where(keep, scaled, -jnp.inf), axis=-1)

        fb_keep = keep
        acc_node = jnp.full((s,), -1, jnp.int32)
        for c in range(1, n):
            tok_c = node_tokens[:, c].astype(jnp.int32)
            considered = (
                node_valid[:, c] & (cur == parents_np[c]) & (acc_node < 0)
            )
            is_tok = fb_keep & (idx == tok_c[:, None])
            num = jnp.sum(jnp.where(is_tok, probs, 0.0), axis=-1)
            den = jnp.sum(jnp.where(fb_keep, probs, 0.0), axis=-1)
            p_c = num / jnp.maximum(den, 1e-30)
            # forced acceptance: rejecting would leave an empty residual
            # (this child's token is the only kept mass left)
            forced = ~jnp.any(fb_keep & (idx != tok_c[:, None]), axis=-1)
            s_acc = considered & ((u[:, c - 1] < p_c) | forced)
            g_acc = considered & (tok_c == greedy)
            acc = jnp.where(greedy_mode, g_acc, s_acc)
            acc_node = jnp.where(acc, jnp.int32(c), acc_node)
            rejected = considered & ~acc & ~greedy_mode
            fb_keep = fb_keep & ~(rejected[:, None] & (idx == tok_c[:, None]))

        has = acc_node >= 0
        acc_tok = jnp.take_along_axis(
            node_tokens, jnp.maximum(acc_node, 0)[:, None], axis=1
        )[:, 0].astype(jnp.int32)
        choice = jnp.argmax(jnp.where(fb_keep, scaled + gum, -jnp.inf),
                            axis=-1)
        fallback = jnp.take_along_axis(
            idx, choice[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
        tok = jnp.where(greedy_mode, greedy, jnp.where(has, acc_tok,
                                                       fallback))
        emit = alive & active
        window, wlen, counts = window_push(
            window, wlen, counts, tok, emit, params.repeat_last_n, vocab
        )
        emitted = emitted + emit.astype(jnp.int32)
        cur = jnp.where(has & emit, acc_node, cur)
        alive = alive & has
        return (
            (counts, window, wlen, emitted, alive, cur),
            (jnp.where(emit, tok, 0),
             jnp.where(emit & has, acc_node, 0)),
        )

    init = (counts, window, wlen, jnp.zeros((s,), jnp.int32),
            jnp.ones((s,), bool), jnp.zeros((s,), jnp.int32))
    (counts, window, wlen, n_emit, _, _), (out, path) = jax.lax.scan(
        body, init, jnp.arange(n, dtype=jnp.int32)
    )
    last = jnp.take_along_axis(
        out.T, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
    )[:, 0]
    params = dataclasses.replace(params, step=params.step + n_emit)
    return out, path.T, n_emit, last, counts, window, wlen, params


# ---------------------------------------------------------------------------
# repeat-penalty window maintenance (llama.cpp penalty_last_n semantics)
# ---------------------------------------------------------------------------
# The engine keeps, per slot, the last ≤ repeat_last_n context tokens in a
# fixed [S, W] buffer (right-aligned: window[:, W-wlen:] are the tokens,
# oldest first) plus the [S, V] occurrence counts the penalty reads. W is a
# static engine-config cap; the host clamps repeat_last_n into [0, W].
# Round 3 penalized over the WHOLE context (documented divergence); these
# helpers close it (VERDICT r03 weak #7 / next-round #10).


def window_set_slot(
    window: jnp.ndarray,   # [S, W] i32
    wlen: jnp.ndarray,     # [S] i32
    counts: jnp.ndarray,   # [S, V] i32
    slot: jnp.ndarray,     # scalar i32
    chunk: jnp.ndarray,    # [T] i32 padded token chunk
    start: jnp.ndarray,    # scalar — 0 resets the slot's window first
    clen: jnp.ndarray,     # scalar — valid tokens in `chunk`
    rl: jnp.ndarray,       # scalar — slot's repeat_last_n (≥ 0)
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Append `chunk[:clen]` to one slot's window (reset when start == 0)
    and rebuild that slot's counts row. One call covers fresh prefill
    (start=0) and chunked-prefill continuation alike."""
    w = window.shape[1]
    rl = jnp.minimum(rl, w)
    old = window[slot]
    ol = jnp.where(start == 0, 0, wlen[slot])
    total = ol + clen
    m = jnp.minimum(total, rl)
    j = jnp.arange(w)
    # virtual ordered sequence [0, total): first the old window (oldest
    # first), then the chunk; keep its last m entries
    src = total - m + j                      # global index, valid where j < m
    from_old = src < ol
    old_idx = jnp.clip(w - ol + src, 0, w - 1)
    chunk_idx = jnp.clip(src - ol, 0, chunk.shape[0] - 1)
    tok = jnp.where(from_old, old[old_idx], chunk[chunk_idx])
    valid = j < m
    dst = jnp.where(valid, j + (w - m), w)   # right-align; w drops
    row = jnp.zeros((w,), jnp.int32).at[dst].set(
        jnp.where(valid, tok, 0), mode="drop"
    )
    window = window.at[slot].set(row)
    wlen = wlen.at[slot].set(m)
    counts = counts.at[slot].set(0)
    ids = jnp.where(valid, tok, vocab)       # vocab sentinel drops padding
    counts = counts.at[slot, ids].add(1, mode="drop")
    return window, wlen, counts


def window_push(
    window: jnp.ndarray,   # [S, W] i32
    wlen: jnp.ndarray,     # [S] i32
    counts: jnp.ndarray,   # [S, V] i32
    tok: jnp.ndarray,      # [S] i32 — one new token per slot
    active: jnp.ndarray,   # [S] bool — inactive slots untouched
    rl: jnp.ndarray,       # [S] i32 — per-slot repeat_last_n
    vocab: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Push one token per active slot into its window, evicting (and
    un-counting) the oldest token once the window is at repeat_last_n."""
    s = jnp.arange(window.shape[0])
    w = window.shape[1]
    cap = jnp.minimum(jnp.maximum(rl, 0), w)
    full = wlen >= cap
    evict_pos = jnp.clip(w - wlen, 0, w - 1)
    evicted = jnp.take_along_axis(window, evict_pos[:, None], axis=1)[:, 0]
    do_evict = active & full & (cap > 0)
    counts = counts.at[s, jnp.where(do_evict, evicted, vocab)].add(
        -1, mode="drop"
    )
    pushed = jnp.roll(window, -1, axis=1).at[:, -1].set(tok)
    window = jnp.where(active[:, None], pushed, window)
    wlen = jnp.where(active, jnp.minimum(wlen + 1, cap), wlen)
    counts = counts.at[s, jnp.where(active & (cap > 0), tok, vocab)].add(
        1, mode="drop"
    )
    return window, wlen, counts
