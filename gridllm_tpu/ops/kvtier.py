"""Host-RAM KV tier: the spill target behind the HBM prefix cache.

Tiered KV cache (ISSUE 11). The ref-counted ``PageAllocator`` keeps
refcount-0 prefix-cache pages in an HBM reuse LRU; when a fresh
allocation (one long request is enough) evicts from that LRU, the page's
content used to be simply gone — the next ``match_prefix`` paid a full
cold prefill. With the tier enabled (``GRIDLLM_KV_HOST_BYTES`` > 0) the
engine copies each evicted page to host memory first, encoded with the
PR 7 chunked wire format as the spill codec (``transfer/wire.py
build_spill_header``: same version/crc/digest discipline as a KV
migration, addressed by the page's content-addressed CHAIN KEY), and a
later ``match_prefix`` walking the same chain pages the content back
into a fresh pool page — so one long request no longer destroys every
other stream's warm TTFT.

Spill quantization: fp16/bf16 pools quantize each page to int8 on spill
(``GRIDLLM_KV_SPILL_INT8``, default on) with ONE symmetric scale per
(layer, page) — "scale-per-page" — halving host bytes; ``=0`` spills the
raw dtype, making tier-on streams byte-identical to tier-off. Resident
int8 pools (``GRIDLLM_KV_INT8``) spill their int8 rows + per-row scales
verbatim (no further loss).

The tier is a bounded LRU over whole pages; the capacity IS the enable
knob. All methods are thread-safe (one internal lock); callers hold the
engine's ``_alloc_lock`` anyway on the spill/restore paths. "Pinned host
memory": on CPU-backed processes these are ordinary numpy buffers; a
true pinned-host placement (``jax.device_put`` with a ``pinned_host``
memory kind) is a drop-in upgrade once the serving fleet wants
device-async restores.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from gridllm_tpu.obs import default_registry

_OBS = default_registry()
_SPILLS = _OBS.counter(
    "gridllm_kv_tier_spills_total",
    "KV pages spilled from the HBM prefix cache into the host tier, "
    "by model.",
    ("model",),
)
_RESTORES = _OBS.counter(
    "gridllm_kv_tier_restores_total",
    "KV pages restored (paged back into HBM) from the host tier on "
    "match_prefix hits, by model.",
    ("model",),
)
_MISSES = _OBS.counter(
    "gridllm_kv_tier_misses_total",
    "Host-tier lookups that found nothing (chain key never spilled or "
    "already evicted), by model.",
    ("model",),
)
_EVICTIONS = _OBS.counter(
    "gridllm_kv_tier_evictions_total",
    "KV pages evicted from the host tier's byte-bounded LRU, by model.",
    ("model",),
)
_SPILL_BYTES = _OBS.counter(
    "gridllm_kv_tier_spill_bytes_total",
    "Encoded bytes written into the host tier by page spills, by model.",
    ("model",),
)
_RESTORE_BYTES = _OBS.counter(
    "gridllm_kv_tier_restore_bytes_total",
    "Encoded bytes read back from the host tier by page restores, "
    "by model.",
    ("model",),
)
_RESTORE_FAILURES = _OBS.counter(
    "gridllm_kv_tier_restore_failures_total",
    "Host-tier restores that failed (injected fault, pool pressure, or "
    "integrity error) and degraded to a cold prefill, by model.",
    ("model",),
)
_TIER_PAGES = _OBS.gauge(
    "gridllm_kv_tier_pages",
    "KV pages resident per cache tier (hbm = refcount-0 pages in the "
    "HBM reuse LRU, host = pages in the host-RAM tier), by model.",
    ("model", "tier"),
)
_TIER_BYTES = _OBS.gauge(
    "gridllm_kv_tier_bytes",
    "KV bytes resident per cache tier (hbm = reuse-LRU pages at pool "
    "bytes/page, host = encoded spill bytes), by model.",
    ("model", "tier"),
)


def set_tier_gauges(model: str, hbm_pages: int, hbm_bytes: int,
                    host_pages: int, host_bytes: int) -> None:
    """One choke point for the per-tier residency gauges (the engine's
    _update_kv_gauges calls it so scrape values always move together)."""
    _TIER_PAGES.set(hbm_pages, model=model, tier="hbm")
    _TIER_BYTES.set(hbm_bytes, model=model, tier="hbm")
    _TIER_PAGES.set(host_pages, model=model, tier="host")
    _TIER_BYTES.set(host_bytes, model=model, tier="host")


def quantize_page(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric int8 quantization with ONE scale per (layer, page):
    x [L, 1, ps, KVH, D] float → (int8 values, float32 scales [L, 1]).
    The scale is amax/127 so the full range is representable; an
    all-zero page keeps scale 1.0 (dequant stays exact zeros)."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=(2, 3, 4))
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(xf / scale[:, :, None, None, None]),
                -127, 127).astype(np.int8)
    return q, scale


def quantize_rows_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side per-row symmetric int8 quantization (numpy mirror of
    ops.kvcache.quantize_kv_rows): x [..., KVH, D] float → (int8 values,
    float32 scales [...]). Used when fp wire pages land on an int8 pool
    (migration import / fp-spill restore)."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=(-2, -1))
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(xf / scale[..., None, None]),
                -127, 127).astype(np.int8)
    return q, scale


def dequantize_page(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Inverse of :func:`quantize_page` (float32 result; the caller casts
    to the pool dtype)."""
    return np.asarray(q, np.float32) * scale[:, :, None, None, None]


class HostKVTier:
    """Byte-bounded LRU of spilled KV pages, keyed by prefix-cache chain
    key. Stores each page as its wire-codec (header, payload) pair so a
    restore goes back through the Assembler's digest check — a corrupted
    host buffer fails loudly into the cold-prefill path instead of
    silently decoding garbage."""

    def __init__(self, capacity_bytes: int, model: str = "",
                 spill_int8: bool = True):
        self.capacity_bytes = max(int(capacity_bytes), 0)
        self.model = model or "unknown"
        self.spill_int8 = bool(spill_int8)
        self._lock = threading.Lock()
        # key → (header, payload); insertion order is the LRU order
        # (move_to_end on hit)
        self._recs: dict[bytes, tuple[dict[str, Any], bytes]] = {}
        self._bytes = 0
        # cumulative plain-int mirrors of the obs counters so
        # /admin/memory and bench read without touching the registry
        self.spills = 0
        self.restores = 0
        self.misses = 0
        self.evictions = 0
        self.restore_failures = 0

    # -- capacity -----------------------------------------------------------

    def __contains__(self, key: bytes) -> bool:
        with self._lock:
            return key in self._recs

    @property
    def pages(self) -> int:
        with self._lock:
            return len(self._recs)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "pages": len(self._recs),
                "bytes": self._bytes,
                "capacityBytes": self.capacity_bytes,
                "spillDtype": "int8-page" if self.spill_int8 else "raw",
                "spills": self.spills,
                "restores": self.restores,
                "misses": self.misses,
                "evictions": self.evictions,
                "restoreFailures": self.restore_failures,
            }

    # -- spill / restore ----------------------------------------------------

    def put(self, key: bytes, k: np.ndarray, v: np.ndarray,
            k_scale: np.ndarray | None = None,
            v_scale: np.ndarray | None = None,
            quant: str | None = None) -> bool:
        """Spill one page. ``k``/``v``: [L, 1, ps, KVH, D] host arrays at
        the UNPADDED model head dim. With ``quant`` (``int8-rows``) the
        arrays are already int8 and the scales ride along verbatim;
        otherwise fp pages int8-quantize here per the tier policy.
        Returns False when the page exceeds the whole tier capacity."""
        from gridllm_tpu.transfer.wire import build_spill_header

        if quant is None and self.spill_int8 and k.dtype != np.int8:
            k, k_scale = quantize_page(k)
            v, v_scale = quantize_page(v)
            quant = "int8-page"
        header, payload = build_spill_header(
            key.hex(), self.model, k, v,
            k_scale=k_scale, v_scale=v_scale, quant=quant,
        )
        size = len(payload)
        if size > self.capacity_bytes:
            return False
        with self._lock:
            old = self._recs.pop(key, None)
            if old is not None:
                self._bytes -= len(old[1])
            self._recs[key] = (header, payload)
            self._bytes += size
            self.spills += 1
            while self._bytes > self.capacity_bytes and self._recs:
                oldest = next(iter(self._recs))
                if oldest == key and len(self._recs) == 1:
                    break
                _, dropped = self._recs.pop(oldest)
                self._bytes -= len(dropped)
                self.evictions += 1
                _EVICTIONS.inc(model=self.model)
        _SPILLS.inc(model=self.model)
        _SPILL_BYTES.inc(size, model=self.model)
        return True

    def get(self, key: bytes) -> tuple[
        np.ndarray, np.ndarray, np.ndarray | None, np.ndarray | None,
        str | None,
    ] | None:
        """Decode one page (LRU-promoted, NOT removed — the HBM copy the
        caller installs will re-spill for free on its next eviction, the
        ``put`` above short-circuiting on the existing record). Returns
        (k, v, k_scale, v_scale, quant) or None on a miss. A failed
        digest/shape check counts as a restore failure and drops the
        record. Success accounting happens in :meth:`mark_restored` —
        only AFTER the caller actually lands the page on device."""
        from gridllm_tpu.transfer.wire import (
            Assembler,
            WireError,
            spill_arrays,
        )

        with self._lock:
            rec = self._recs.get(key)
            if rec is None:
                self.misses += 1
                _MISSES.inc(model=self.model)
                return None
            # promote: reinsert at the MRU end
            self._recs.pop(key)
            self._recs[key] = rec
        header, payload = rec
        try:
            asm = Assembler(dict(header))
            asm.feed_raw(payload)
            k, v, ks, vs = spill_arrays(header, asm.payload())
        except (WireError, ValueError) as e:
            self.note_restore_failure()
            self.drop(key)
            from gridllm_tpu.utils.logging import get_logger

            get_logger("kvtier").warning(
                "host-tier page failed integrity check; dropped",
                model=self.model, error=str(e))
            return None
        return k, v, ks, vs, header.get("quant")

    def mark_restored(self, key: bytes) -> None:
        with self._lock:
            rec = self._recs.get(key)
            size = len(rec[1]) if rec else 0
            self.restores += 1
        _RESTORES.inc(model=self.model)
        if size:
            _RESTORE_BYTES.inc(size, model=self.model)

    def note_restore_failure(self) -> None:
        with self._lock:
            self.restore_failures += 1
        _RESTORE_FAILURES.inc(model=self.model)

    def drop(self, key: bytes) -> None:
        with self._lock:
            rec = self._recs.pop(key, None)
            if rec is not None:
                self._bytes -= len(rec[1])

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()
            self._bytes = 0
