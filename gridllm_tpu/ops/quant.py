"""Int8 weight-only quantization (VERDICT r03 next-round #6).

Why: BASELINE config #3 serves llama3:70b on a v5e-8 worker group.
70B at bf16 is ~140 GB of matmul weights against 8×16 = 128 GB of slice
HBM — arithmetically impossible. Per-out-channel symmetric int8 halves
the matmul weights (~69 GB) and fits with room for the KV pool. Decode is
weights-bandwidth-bound, so int8 also roughly halves the per-step HBM
traffic (the same reason llama.cpp/Ollama default to quantized weights —
parity of APPROACH with the reference stack, built TPU-style).

Scheme:
- per-out-channel symmetric: scale[o] = max|W[:, o]| / 127,
  q = round(W / scale) in int8. Exactness of the matmul form:
  x @ W == (x @ q) * scale (up to rounding) because scale is constant
  along the contracted axis.
- weight-only: activations stay bf16. The matmul upcasts q to the
  activation dtype on the fly (XLA fuses the convert into the dot's
  operand read) — the HBM win is the point; int8 MXU compute would need
  activation quantization, a later step.
- `QuantizedTensor` is a pytree node, so sharding/donation/jit treat the
  (q, scale) pair like any other leaves. parallel/sharding.py resolves
  leaf specs by the nearest named dict key, which still names the
  original weight ("wq" etc.) — q inherits the weight's sharding; scale
  falls back to replicated (tiny).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["q", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class QuantizedTensor:
    """int8 weights + per-out-channel scale. q: [..., in, out] int8;
    scale: [..., out] float32 (broadcasts over the removed `in` axis)."""

    q: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_array(w, contract_axis: int = -2) -> QuantizedTensor:
    """Per-out-channel symmetric int8 over the contracted axis (default:
    second-to-last, matching the [in, out] / [L, in, out] weight layout)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=contract_axis)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(
        jnp.round(w / jnp.expand_dims(scale, contract_axis)),
        -127, 127,
    ).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale.astype(jnp.float32))


def qdot(x: jnp.ndarray, w, precision=None,
         preferred_element_type=None) -> jnp.ndarray:
    """jnp.dot that transparently handles QuantizedTensor weights:
    (x @ q) * scale — scale applied on the output channel."""
    if isinstance(w, QuantizedTensor):
        y = jnp.dot(x, w.q.astype(x.dtype), precision=precision,
                    preferred_element_type=preferred_element_type)
        return y * w.scale.astype(y.dtype)
    return jnp.dot(x, w, precision=precision,
                   preferred_element_type=preferred_element_type)


# the llama-skeleton matmul leaves that quantize; everything else (norms,
# biases, embed — the gather table doubles as the tied lm_head and feeds
# fp32 logits — rope, router) stays in the load dtype
QUANT_LEAVES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)

# subtrees whose leaves consume weights with plain `@`, not qdot — their
# "wq"/"wo" NAMES collide with QUANT_LEAVES but must never quantize (the
# llava vision tower/projector; small next to the LM anyway)
NO_QUANT_SUBTREES = frozenset({"vision", "projector"})


def quantize_params(params: dict[str, Any]) -> dict[str, Any]:
    """Quantize the known matmul leaves of a llama-family pytree in place
    (returns a new pytree; non-matmul leaves pass through)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for name, leaf in node.items():
            if isinstance(leaf, dict):
                out[name] = leaf if name in NO_QUANT_SUBTREES else walk(leaf)
            elif name in QUANT_LEAVES:
                out[name] = quantize_array(leaf)
            else:
                out[name] = leaf
        return out

    return walk(params)


def quantize_np_leaf(name: str, arr):
    """Host-side variant for the checkpoint loader: quantize one assembled
    numpy leaf before it ever reaches the device (a 70B load must never
    materialize the bf16 copy in HBM — the int8+scale pair is what gets
    device_put). Returns the leaf unchanged when the name is not a
    quantized matmul. Arrays stay numpy until placement."""
    import numpy as np

    if name not in QUANT_LEAVES:
        return arr

    def quant2d(w32):
        amax = np.max(np.abs(w32), axis=-2)
        scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
        q = np.clip(
            np.round(w32 / np.expand_dims(scale, -2)), -127, 127
        ).astype(np.int8)
        return q, scale

    arr = np.asarray(arr)
    if arr.ndim <= 2:
        q, scale = quant2d(arr.astype(np.float32))
        return QuantizedTensor(q=q, scale=scale)
    # stacked [L, ...] leaves: quantize one layer slice at a time so the
    # fp32 temporaries stay ~1/L of the leaf (a 70B w_down is ~19G
    # elements — a whole-leaf fp32 copy would be ~75 GiB of host RAM,
    # defeating loader.py's peak-RAM design)
    q = np.empty(arr.shape, np.int8)
    scale = np.empty(arr.shape[:-2] + arr.shape[-1:], np.float32)
    flat_q = q.reshape((-1,) + arr.shape[-2:])
    flat_s = scale.reshape((-1,) + arr.shape[-1:])
    flat_w = arr.reshape((-1,) + arr.shape[-2:])
    for i in range(flat_w.shape[0]):
        flat_q[i], flat_s[i] = quant2d(flat_w[i].astype(np.float32))
    return QuantizedTensor(q=q, scale=scale)


def params_nbytes(params: Any) -> int:
    """Total parameter bytes (counting int8 leaves at 1 byte) — the
    memory-math half of the 70B-fits-v5e-8 assertion. Works on real
    arrays and eval_shape ShapeDtypeStructs alike."""
    import math

    return sum(
        math.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(params)
    )
