"""TPU-native compute primitives.

This package is the compute path the reference outsources to Ollama/llama.cpp
(reference: client/src/services/OllamaService.ts:17-27 — an HTTP adapter to an
external engine; SURVEY.md §0). Everything here is functional JAX: static
shapes, scan-friendly, shardable. Pure-jnp reference implementations live
beside Pallas TPU kernels; the engine picks per-platform.
"""

from gridllm_tpu.ops.layers import (
    apply_rope,
    precompute_rope,
    rms_norm,
    RopeScaling,
)
from gridllm_tpu.ops.kvcache import PagedKVCache
from gridllm_tpu.ops.attention import (
    attention_prefill,
    paged_attention_decode,
)
from gridllm_tpu.ops.sampling import SamplingParams, sample_tokens

__all__ = [
    "apply_rope",
    "precompute_rope",
    "rms_norm",
    "RopeScaling",
    "PagedKVCache",
    "attention_prefill",
    "paged_attention_decode",
    "SamplingParams",
    "sample_tokens",
]
