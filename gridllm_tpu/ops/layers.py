"""Core transformer primitives: RMSNorm and rotary embeddings.

No reference analogue (the reference has no compute path of its own —
SURVEY.md §0); conventions follow the HF Llama formulation (split-half
rotate, norm in fp32) so HF checkpoints load bit-compatibly.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm computed in fp32, cast back to the input dtype."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * weight.astype(jnp.float32)).astype(dtype)


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3-style NTK rope rescaling (HF `rope_scaling` dict)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


def precompute_rope(
    head_dim: int,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Inverse frequencies [head_dim//2], fp32, with optional llama3 scaling."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is not None:
        low_wavelen = scaling.original_max_position_embeddings / scaling.low_freq_factor
        high_wavelen = scaling.original_max_position_embeddings / scaling.high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        # smooth interpolation between scaled and unscaled bands
        smooth = (scaling.original_max_position_embeddings / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / scaling.factor
        inv_freq = jnp.where(
            wavelen > low_wavelen,
            scaled,
            jnp.where(wavelen < high_wavelen, inv_freq, (1.0 - smooth) * scaled + smooth * inv_freq),
        )
    return inv_freq


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate `x` [..., T, H, D] by position-dependent angles.

    Uses the HF split-half convention: the first D/2 lanes pair with the
    last D/2 (`rotate_half`), NOT interleaved pairs — this is what HF Llama
    checkpoints are trained with.
    `positions`: [..., T] int32 absolute positions.
    """
    dtype = x.dtype
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., T, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # [..., T, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :]
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """Classic LayerNorm (mean-centered, affine w/ bias) in fp32 — the
    BERT-family norm; decoder families use rms_norm."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    x = (x - mu) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
