"""Pallas TPU attention kernels (SURVEY.md §7 step 5: "paged KV cache +
Pallas flash-attention kernel" is where the baseline metric is won).

Two kernels, each with the pure-jnp implementation in ops/attention.py as
its numerical oracle (tests/test_pallas.py runs both in interpret mode on
CPU and asserts equality):

- `flash_prefill`: causal GQA flash attention over one prompt chunk.
  Grid (KVH, q-blocks); K/V for the grid's kv head stay VMEM-resident
  across q blocks; online-softmax accumulation over BK-sized key blocks,
  everything fp32 on the accumulator side, matmuls on the MXU via
  dot_general(preferred_element_type=f32). Causal + length masking via
  broadcasted_iota — no materialized [T, T] mask.

- `paged_decode`: one-token-per-slot decode attention directly against
  the HBM page pool. Grid (slots,); the slot's page table row and length
  are scalar-prefetched (PrefetchScalarGridSpec) so the kernel can DMA
  exactly the valid pages HBM→VMEM, double-buffered to overlap the next
  page's fetch with the current page's math. This is the "stream only
  valid pages" design the jnp oracle's gather materializes densely
  (PAPERS.md "Ragged Paged Attention" — pattern reference only).

Plus two KV-write kernels (`paged_write_decode`, `paged_write_chunk`):
XLA lowers the jnp scatter form of the page-pool update to a serialized
scatter that costs ~12 ms/step (decode) and ~18 ms/prefill for a 3B model
on v5e — measured dominant over the attention math itself (round-4
profiling). These kernels instead DMA exactly the written rows/pages into
the pool in place (input_output_aliases), reducing the write to its true
bandwidth cost (~KB per token per layer).

The reference has no analogue (all compute was Ollama's,
client/src/services/OllamaService.ts); kernel selection lives in
ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# repo supports; resolve whichever this jaxlib ships
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

# masking sentinel for the online-softmax paths (same value as
# ops/attention.py's _NEG_INF): large enough that exp(x - m) underflows
# to exactly 0 for masked columns, small enough to stay finite in f32 —
# every kernel computes logits in f32, so the value is a deliberate
# dtype commitment (it would overflow f16; dtype-discipline keeps it
# named so the policy is auditable here, once)
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# flash prefill
# ---------------------------------------------------------------------------

def _flash_prefill_kernel(
    seqlen_ref,  # SMEM (1, 2): [valid tokens, sliding window (0 = full)]
    q_ref,       # VMEM (BQ, 1, G, D) — this q block, this kv head
    k_ref,       # VMEM (1, T, D)     — all keys for this kv head
    v_ref,       # VMEM (1, T, D)
    o_ref,       # VMEM (BQ, 1, G, D)
    *, bq: int, bk: int, t: int, softcap: float,
):
    qi = pl.program_id(1)
    seq_len = seqlen_ref[0, 0]
    window = seqlen_ref[0, 1]
    g, d = q_ref.shape[2], q_ref.shape[3]
    scale = jax.lax.rsqrt(jnp.float32(d))

    q = q_ref[:, 0].reshape(bq * g, d).astype(jnp.float32) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 0)
    q_pos = qi * bq + rows // g                       # query position per row
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 1)

    # key blocks that can contribute to this q block: causal upper bound,
    # tightened by the actual sequence length; with a sliding window the
    # blocks fully BELOW the window are skipped too
    nk = jnp.minimum(
        pl.cdiv((qi + 1) * bq, bk), pl.cdiv(jnp.maximum(seq_len, 1), bk)
    )
    kb0 = jnp.where(
        window > 0, jnp.maximum(qi * bq - window + 1, 0) // bk, 0
    )

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * bk, bk)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * bk, bk)].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ*G, BK]
        if softcap:  # gemma2: tanh capping BEFORE masking
            logits = softcap * jnp.tanh(logits / softcap)
        k_pos = kb * bk + cols
        dist = q_pos - k_pos
        mask = (dist >= 0) & (k_pos < seq_len) & (
            (window <= 0) | (dist < window)
        )
        logits = jnp.where(mask, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq * g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq * g, 1), jnp.float32)
    acc0 = jnp.zeros((bq * g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(kb0, nk, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    o_ref[:, 0] = out.reshape(bq, g, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "softcap"))
def flash_prefill(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    interpret: bool = False,
    softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Causal GQA flash attention. Same contract as
    ops.attention.attention_prefill: q [B, T, H, D], k/v [B, T, KVH, D],
    seq_lens [B] → [B, T, H, D]. T must divide by the q block size
    (min(128, T)); the dispatch layer guarantees this for prefill buckets.
    `softcap` (static): gemma2 tanh logit capping. `window` (scalar, may
    be traced — gemma2 alternates per layer): sliding-window attention,
    0 = full; key blocks fully below a q block's window are skipped.
    """
    b, t, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    bq = min(128, t)
    bk = min(128, t)
    assert t % bq == 0 and t % bk == 0, (t, bq, bk)

    kernel = functools.partial(
        _flash_prefill_kernel, bq=bq, bk=bk, t=t, softcap=softcap
    )
    win = jnp.broadcast_to(jnp.asarray(window, jnp.int32), (b,))

    def one(qb, kb, vb, ln, wn):
        return pl.pallas_call(
            kernel,
            grid=(kvh, t // bq),
            in_specs=[
                pl.BlockSpec((1, 2), lambda kh, i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((bq, 1, g, d), lambda kh, i: (i, kh, 0, 0),
                             memory_space=pltpu.VMEM),
                # kv-head-major layout so the block's last two dims are
                # (T, D) — the TPU lowering requires last-two divisibility
                pl.BlockSpec((1, t, d), lambda kh, i: (kh, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, t, d), lambda kh, i: (kh, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bq, 1, g, d), lambda kh, i: (i, kh, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((t, kvh, g, d), q.dtype),
            interpret=interpret,
            cost_estimate=pl.CostEstimate(
                flops=4 * t * t * h * d // 2,
                bytes_accessed=(t * h * d + 2 * t * kvh * d) * q.dtype.itemsize,
                transcendentals=t * t * h,
            ),
        )(jnp.stack([ln, wn]).reshape(1, 2), qb.reshape(t, kvh, g, d),
          kb.transpose(1, 0, 2), vb.transpose(1, 0, 2))

    out = jax.vmap(one)(q, k, v, seq_lens.astype(jnp.int32), win)
    return out.reshape(b, t, h, d)


def _flash_prefill_stream_kernel(
    seqlen_ref,  # SMEM (1, 1): valid tokens
    q_ref,       # VMEM (BQ, 1, G, D) — this q block, this kv head
    k_ref,       # VMEM (1, BK, D)    — ONE key block (streamed from HBM)
    v_ref,       # VMEM (1, BK, D)
    o_ref,       # VMEM (BQ, 1, G, D)
    m_scr,       # VMEM (BQ*G, 1) f32 — online-softmax carry across k blocks
    l_scr,       # VMEM (BQ*G, 1) f32
    acc_scr,     # VMEM (BQ*G, D) f32
    *, bq: int, bk: int, softcap: float,
):
    """Streaming variant of _flash_prefill_kernel: the k-block loop is a
    GRID dimension, so K/V blocks are DMA'd HBM→VMEM per step instead of
    pinning [T, D] per head in VMEM — the long-context path past the
    _FLASH_KV_VMEM_CAP budget (VERDICT r03 weak #6 / next-round #9).
    Grid (KVH, q_blocks, k_blocks); the online-softmax state lives in
    scratch, initialized at kb == 0 and finalized into o_ref at the last
    k block."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    seq_len = seqlen_ref[0, 0]
    window = seqlen_ref[0, 1]
    g, d = q_ref.shape[2], q_ref.shape[3]
    scale = jax.lax.rsqrt(jnp.float32(d))

    @pl.when(kb == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: a k block strictly past this q block's last row contributes
    # nothing — skip its math, as do blocks fully below the sliding window
    # (the DMA already happened; index-map-level skipping would revisit
    # blocks and is not worth the complexity here)
    @pl.when(
        (kb * bk <= qi * bq + bq - 1) & (kb * bk < seq_len)
        & ((window <= 0) | ((kb + 1) * bk > qi * bq - window + 1))
    )
    def _():
        q = q_ref[:, 0].reshape(bq * g, d).astype(jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 0)
        q_pos = qi * bq + rows // g
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 1)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if softcap:  # gemma2: tanh capping BEFORE masking
            logits = softcap * jnp.tanh(logits / softcap)
        k_pos = kb * bk + cols
        dist = q_pos - k_pos
        mask = (dist >= 0) & (k_pos < seq_len) & (
            (window <= 0) | (dist < window)
        )
        logits = jnp.where(mask, logits, _NEG_INF)

        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new)
        m_scr[...] = m_new
        l_scr[...] = l * alpha + p.sum(axis=1, keepdims=True)
        acc_scr[...] = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == nk - 1)
    def _():
        out = acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        o_ref[:, 0] = out.reshape(bq, g, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "softcap"))
def flash_prefill_streamed(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    interpret: bool = False,
    softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Same contract as flash_prefill (incl. softcap/window); K/V stream
    from HBM block-by-block (VMEM holds one (BQ q, BK k) tile pair per
    step) — use for prefill buckets whose per-head K+V exceed the VMEM
    budget."""
    b, t, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    bq = min(128, t)
    bk = min(128, t)
    assert t % bq == 0 and t % bk == 0, (t, bq, bk)

    kernel = functools.partial(
        _flash_prefill_stream_kernel, bq=bq, bk=bk, softcap=softcap
    )
    win = jnp.broadcast_to(jnp.asarray(window, jnp.int32), (b,))

    def one(qb, kb_, vb, ln, wn):
        return pl.pallas_call(
            kernel,
            grid=(kvh, t // bq, t // bk),
            in_specs=[
                pl.BlockSpec((1, 2), lambda kh, i, kb: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((bq, 1, g, d), lambda kh, i, kb: (i, kh, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda kh, i, kb: (kh, kb, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda kh, i, kb: (kh, kb, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bq, 1, g, d), lambda kh, i, kb: (i, kh, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((t, kvh, g, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((bq * g, 1), jnp.float32),
                pltpu.VMEM((bq * g, 1), jnp.float32),
                pltpu.VMEM((bq * g, d), jnp.float32),
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
        )(jnp.stack([ln, wn]).reshape(1, 2), qb.reshape(t, kvh, g, d),
          kb_.transpose(1, 0, 2), vb.transpose(1, 0, 2))

    out = jax.vmap(one)(q, k, v, seq_lens.astype(jnp.int32), win)
    return out.reshape(b, t, h, d)


# ---------------------------------------------------------------------------
# paged decode
# ---------------------------------------------------------------------------

def _paged_decode_kernel(
    layer_ref,   # SMEM prefetch: [2] [layer to read, sliding window (0=full)]
    table_ref,   # SMEM prefetch: [S, maxp] page ids
    len_ref,     # SMEM prefetch: [S] lengths (see paged_decode docstring)
    q_ref,       # VMEM (1, H, D) — this slot's query
    k_hbm,       # ANY  [L, P, ps, KVH, D] — the FULL page pool, stays in HBM
    v_hbm,
    kc_ref,      # VMEM (1, KVH, D) — this slot's CURRENT token K (merge_cur)
    vc_ref,
    o_ref,       # VMEM (1, H, D)
    k_scr,       # VMEM (2, ps, KVH, D) double buffer
    v_scr,
    sems,        # DMA sems (2, 2): [buffer, k/v]
    *, ps: int, kvh: int, g: int, d: int, merge_cur: bool, softcap: float,
):
    s = pl.program_id(0)
    layer = layer_ref[0]
    window = layer_ref[1]
    length = len_ref[s]
    # the query's absolute position: prefix-only lengths put the current
    # token AT `length` (merge_cur); otherwise it is already in the pool
    # at length-1
    qpos = length if merge_cur else length - 1
    # clamp to the table width: pipelined decode blocks can push a
    # finished slot's device-side length past its capacity (host finishes
    # the slot while in-flight blocks still count it active); the page_no
    # lookup must never index past the table row
    n_pages = jnp.minimum(
        pl.cdiv(jnp.maximum(length, 1), ps), table_ref.shape[1]
    )
    scale = jax.lax.rsqrt(jnp.float32(d))
    q = (q_ref[0].reshape(kvh, g, d).astype(jnp.float32) * scale)

    # indexing the layer INSIDE the DMA (rather than slicing the pool in
    # the caller's scan body) avoids XLA materializing a per-layer pool
    # copy per scan iteration — the pool never moves, only pages do
    def k_dma(slot, page_no):
        page = jnp.maximum(table_ref[s, page_no], 0)
        return pltpu.make_async_copy(
            k_hbm.at[layer, page], k_scr.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, page_no):
        page = jnp.maximum(table_ref[s, page_no], 0)
        return pltpu.make_async_copy(
            v_hbm.at[layer, page], v_scr.at[slot], sems.at[slot, 1]
        )

    # pages the loop will actually visit: in merge_cur mode a length-0
    # (inactive) slot skips the loop entirely. The initial DMA start MUST
    # be guarded by the same bound — an async copy that is started but
    # never waited leaves its semaphore signalled into the NEXT grid
    # iteration (scratch + semaphores persist across grid steps on TPU),
    # corrupting every later slot's double-buffer handshake. Interpret
    # mode completes copies synchronously and never sees this; real
    # Mosaic dies with an opaque device error (round-4 TPU bench crash).
    n_eff = jnp.where(length > 0, n_pages, 0) if merge_cur else n_pages
    # sliding window: pages whose every row is out of the window are never
    # visited — loop (and DMA) start at the window's first page
    p0 = jnp.where(
        window > 0, jnp.maximum(qpos - window + 1, 0) // ps, 0
    )
    p0 = jnp.minimum(p0, n_eff)  # degenerate slots: keep bounds sane

    @pl.when(n_eff > p0)
    def _():
        k_dma(0, p0).start()
        v_dma(0, p0).start()

    def body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p - p0, 2)

        @pl.when(p + 1 < n_eff)
        def _():
            nxt = jax.lax.rem(p + 1 - p0, 2)
            k_dma(nxt, p + 1).start()
            v_dma(nxt, p + 1).start()

        k_dma(slot, p).wait()
        v_dma(slot, p).wait()
        k_page = k_scr[slot]  # [ps, KVH, D]
        v_page = v_scr[slot]

        # per-kv-head 2D dots, unrolled over the (static, small) KVH —
        # Mosaic's tpu.matmul requires lhs/rhs batch dims in the same
        # position, which the [KVH,G,D]x[ps,KVH,D] batched form violates
        logits = jnp.stack([
            jax.lax.dot_general(
                q[h], k_page[:, h, :].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])  # [KVH, G, ps]
        if softcap:  # gemma2: tanh capping BEFORE masking
            logits = softcap * jnp.tanh(logits / softcap)
        pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (kvh, g, ps), 2)
        valid = (pos < length) & (
            (window <= 0) | (qpos - pos < window)
        )
        logits = jnp.where(valid, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        prob = jnp.exp(logits - m_new)
        l_new = l * alpha + prob.sum(axis=2, keepdims=True)
        acc_new = acc * alpha + jnp.stack([
            jax.lax.dot_general(
                prob[h], v_page[:, h, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])
        return m_new, l_new, acc_new

    m0 = jnp.full((kvh, g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, g, 1), jnp.float32)
    acc0 = jnp.zeros((kvh, g, d), jnp.float32)
    if merge_cur:
        # `length` counts the PREFIX only; the current token's K/V arrive
        # via kc/vc (not yet written to the pool — the engine writes all
        # layers at once after the layer scan). length == 0 (fresh slot
        # with empty pool) skips the page loop entirely (n_eff == 0; the
        # initial DMA start above is guarded by the same bound).
        m, l, acc = jax.lax.fori_loop(p0, n_eff, body, (m0, l0, acc0))
        # online-softmax merge of the single current-token column. The
        # current token's K is scaled along with q (q already carries
        # 1/sqrt(d)), matching the in-pool keys.
        kc = kc_ref[0].astype(jnp.float32)              # [KVH, D]
        vc = vc_ref[0].astype(jnp.float32)
        logit_c = (q * kc[:, None, :]).sum(-1, keepdims=True)  # [KVH, G, 1]
        if softcap:  # same capping as the in-pool columns (oracle parity)
            logit_c = softcap * jnp.tanh(logit_c / softcap)
        m_new = jnp.maximum(m, logit_c)
        alpha = jnp.exp(m - m_new)
        p_c = jnp.exp(logit_c - m_new)
        l = l * alpha + p_c
        acc = acc * alpha + p_c * vc[:, None, :]
        out = acc / jnp.maximum(l, 1e-30)
    else:
        _, l, acc = jax.lax.fori_loop(p0, n_pages, body, (m0, l0, acc0))
        out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.reshape(kvh * g, d).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("page_size", "interpret", "softcap"))
def paged_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    interpret: bool = False,
    softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Same contract as ops.attention.paged_attention_decode incl.
    softcap/window (gemma2/mistral): q [S, H, D],
    pools [P, ps, KVH, D] (or [L, P, ps, KVH, D] with `layer` selecting
    which layer to read — pass the FULL pool from inside a layer scan so
    no per-layer pool slice is ever materialized), page_table [S, maxp]
    → [S, H, D]. Reads only valid pages.

    Two modes (matching the oracle):
    - k_cur/v_cur None: `lengths` includes the already-written current
      token; attention runs purely over the pool.
    - k_cur/v_cur [S, KVH, D]: `lengths` counts the PREFIX only; the
      current token's K/V are merged in-register via one extra
      online-softmax step (the engine writes all layers' K/V into the pool
      once per step, after the layer scan — so the pool lags one token).

    Slots with length 0 (inactive) compute garbage rows cheaply — callers
    mask on `active`, matching the oracle. With a sliding window, pages
    fully below the window are never DMA'd — windowed decode reads
    O(window) context regardless of length.
    """
    s, h, d = q.shape
    if k_pages.ndim == 4:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.int32(0)
    kvh = k_pages.shape[3]
    g = h // kvh
    merge_cur = k_cur is not None
    if not merge_cur:
        k_cur = jnp.zeros((s, kvh, d), k_pages.dtype)
        v_cur = jnp.zeros((s, kvh, d), v_pages.dtype)

    kernel = functools.partial(
        _paged_decode_kernel, ps=page_size, kvh=kvh, g=g, d=d,
        merge_cur=merge_cur, softcap=softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, kvh, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kvh, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kvh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kvh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), q.dtype),
        interpret=interpret,
    )(jnp.stack([jnp.asarray(layer, jnp.int32).reshape(()),
                 jnp.asarray(window, jnp.int32).reshape(())]),
      page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages, k_cur, v_cur)


# ---------------------------------------------------------------------------
# chunked-prefill attention against the paged prefix
# ---------------------------------------------------------------------------


def _prefix_chunk_kernel(
    scal_ref,    # SMEM prefetch [4]: [layer, window (0=full), start, total]
    table_ref,   # SMEM prefetch [maxp]: this slot's page ids
    q_ref,       # VMEM (BQ, KVH, G, D) — this q block
    kc_ref,      # VMEM (C, KVH, D) — the WHOLE chunk's K (resident)
    vc_ref,
    k_hbm,       # ANY [L, P, ps, KVH, D] — the full page pool
    v_hbm,
    o_ref,       # VMEM (BQ, KVH, G, D)
    k_scr,       # VMEM (2, ps, KVH, D) double buffer (prefix pages)
    v_scr,
    sems,        # DMA sems (2, 2)
    *, ps: int, bq: int, bk: int, kvh: int, g: int, d: int,
    softcap: float,
):
    """Two-phase online softmax per q block: (1) stream the slot's PREFIX
    pages HBM→VMEM double-buffered (same DMA discipline as
    _paged_decode_kernel — every conditional start is guarded by the same
    bound as its wait); (2) the chunk's own K/V blocks from VMEM with
    causal masking. Positions: q row r of block qi is absolute
    start + qi*BQ + r; prefix rows are absolute [0, start); chunk K rows
    are absolute start + [0, C) with rows ≥ total (= start + valid)
    masked."""
    qi = pl.program_id(0)
    window = scal_ref[1]
    start = scal_ref[2]
    total = scal_ref[3]
    scale = jax.lax.rsqrt(jnp.float32(d))
    q = q_ref[...].astype(jnp.float32) * scale     # [BQ, KVH, G, D]

    q_rel = qi * bq + jax.lax.broadcasted_iota(
        jnp.int32, (kvh, bq * g, 1), 1
    ) // g                                          # chunk-relative q pos
    q_abs = start + q_rel

    layer = scal_ref[0]

    def k_dma(slot, page_no):
        page = jnp.maximum(table_ref[page_no], 0)
        return pltpu.make_async_copy(
            k_hbm.at[layer, page], k_scr.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, page_no):
        page = jnp.maximum(table_ref[page_no], 0)
        return pltpu.make_async_copy(
            v_hbm.at[layer, page], v_scr.at[slot], sems.at[slot, 1]
        )

    # prefix pages: [0, start) — ceil so a partial last page is visited
    # (its rows ≥ start are masked); with a sliding window, pages fully
    # below this q block's lowest window edge are never DMA'd
    n_pref = jnp.minimum(
        pl.cdiv(jnp.maximum(start, 0), ps), table_ref.shape[0]
    )
    p0 = jnp.where(
        window > 0, jnp.maximum(start + qi * bq - window + 1, 0) // ps, 0
    )
    p0 = jnp.minimum(p0, n_pref)

    @pl.when(n_pref > p0)
    def _():
        k_dma(0, p0).start()
        v_dma(0, p0).start()

    def pref_body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p - p0, 2)

        @pl.when(p + 1 < n_pref)
        def _():
            nxt = jax.lax.rem(p + 1 - p0, 2)
            k_dma(nxt, p + 1).start()
            v_dma(nxt, p + 1).start()

        k_dma(slot, p).wait()
        v_dma(slot, p).wait()
        k_page = k_scr[slot]                        # [ps, KVH, D]
        v_page = v_scr[slot]

        logits = jnp.stack([
            jax.lax.dot_general(
                q[:, h].reshape(bq * g, d),
                k_page[:, h, :].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])                                          # [KVH, BQ*G, ps]
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        pos = p * ps + jax.lax.broadcasted_iota(
            jnp.int32, (kvh, bq * g, ps), 2
        )
        valid = (pos < start) & (
            (window <= 0) | (q_abs - pos < window)
        )
        logits = jnp.where(valid, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        prob = jnp.exp(logits - m_new)
        l_new = l * alpha + prob.sum(axis=2, keepdims=True)
        acc_new = acc * alpha + jnp.stack([
            jax.lax.dot_general(
                prob[h], v_page[:, h, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])
        return m_new, l_new, acc_new

    m0 = jnp.full((kvh, bq * g, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, bq * g, 1), jnp.float32)
    acc0 = jnp.zeros((kvh, bq * g, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(p0, n_pref, pref_body, (m0, l0, acc0))

    # phase 2: the chunk's own K/V — causal within the chunk
    nkb = pl.cdiv((qi + 1) * bq, bk)
    kb0 = jnp.where(
        window > 0, jnp.maximum(qi * bq - window + 1, 0) // bk, 0
    )
    kb0 = jnp.minimum(kb0, nkb)

    def chunk_body(kb, carry):
        m, l, acc = carry
        k_blk = kc_ref[pl.ds(kb * bk, bk)]          # [BK, KVH, D]
        v_blk = vc_ref[pl.ds(kb * bk, bk)]
        logits = jnp.stack([
            jax.lax.dot_general(
                q[:, h].reshape(bq * g, d),
                k_blk[:, h, :].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])                                          # [KVH, BQ*G, BK]
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        krel = kb * bk + jax.lax.broadcasted_iota(
            jnp.int32, (kvh, bq * g, bk), 2
        )
        dist = q_rel - krel
        valid = (dist >= 0) & (start + krel < total) & (
            (window <= 0) | (dist < window)
        )
        logits = jnp.where(valid, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        prob = jnp.exp(logits - m_new)
        l_new = l * alpha + prob.sum(axis=2, keepdims=True)
        acc_new = acc * alpha + jnp.stack([
            jax.lax.dot_general(
                prob[h], v_blk[:, h, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])
        return m_new, l_new, acc_new

    _, l, acc = jax.lax.fori_loop(kb0, nkb, chunk_body, (m, l, acc))
    out = acc / jnp.maximum(l, 1e-30)               # [KVH, BQ*G, D]
    o_ref[...] = (
        out.reshape(kvh, bq, g, d).transpose(1, 0, 2, 3).astype(o_ref.dtype)
    )


@functools.partial(jax.jit,
                   static_argnames=("page_size", "interpret", "softcap"))
def prefix_chunk(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    total_len: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray,
    v_cur: jnp.ndarray,
    layer: jnp.ndarray | None = None,
    interpret: bool = False,
    softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Kernel form of ops.attention.attention_prefix_chunk (k_cur mode):
    one chunk of queries [1, C, H, D] against the slot's full cached
    context — prefix K/V streamed from the page pool page-by-page
    (double-buffered DMA), the chunk's own K/V ([C, KVH, D], not yet in
    the pool) VMEM-resident with causal masking. `start` is the absolute
    position of q[0]; `total_len` = start + valid rows in this chunk.
    This keeps >prefill_chunk prompts on the kernel path (VERDICT r04 #5)
    — the jnp fallback gathers the whole prefix densely per layer.
    """
    _, c, h, d = q.shape
    if k_pages.ndim == 4:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.int32(0)
    kvh = k_pages.shape[3]
    g = h // kvh
    bq = min(128, c)
    bk = min(128, c)
    assert c % bq == 0 and c % bk == 0, (c, bq, bk)

    kernel = functools.partial(
        _prefix_chunk_kernel, ps=page_size, bq=bq, bk=bk, kvh=kvh,
        g=g, d=d, softcap=softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(c // bq,),
        in_specs=[
            pl.BlockSpec((bq, kvh, g, d), lambda i, *_: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, kvh, d), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, kvh, d), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((bq, kvh, g, d), lambda i, *_: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kvh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kvh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    scal = jnp.stack([
        jnp.asarray(layer, jnp.int32).reshape(()),
        jnp.asarray(window, jnp.int32).reshape(()),
        jnp.asarray(start, jnp.int32).reshape(()),
        jnp.asarray(total_len, jnp.int32).reshape(()),
    ])
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c, kvh, g, d), q.dtype),
        interpret=interpret,
    )(scal, table_row.astype(jnp.int32), q[0].reshape(c, kvh, g, d),
      k_cur, v_cur, k_pages, v_pages)
    return out.reshape(1, c, h, d)


# ---------------------------------------------------------------------------
# unified ragged paged attention (ISSUE 6)
# ---------------------------------------------------------------------------


def _ragged_attn_kernel(
    *refs,
    ps: int, bq: int, bk: int, c: int, kvh: int, g: int, d: int,
    td: int, nct: int, softcap: float, has_chunk: bool, has_group: bool,
    quant: bool = False, has_tree: bool = False,
):
    """One grid over query-token tiles serving all phases at once
    (the Ragged Paged Attention shape): tiles [0, nct) are the prefill
    chunk's BQ-row blocks (prefix pages streamed HBM→VMEM double-buffered
    + the chunk's own resident K/V, causally masked — the
    _prefix_chunk_kernel math); tiles [nct, nct+S) are one slot each —
    Td query rows (1 = decode, K+1 = spec-verify) against the slot's
    paged context with the Td fresh K/V columns merged in-register (the
    _paged_decode_kernel math generalized from 1 to Td tokens). The DMA
    discipline is shared: every conditional start is guarded by the same
    bound as its wait (scratch + semaphores persist across grid steps)."""
    it = iter(refs)
    scal_ref = next(it)      # SMEM [4]: layer, window, chunk_start, total
    if has_group:
        lens_ref = next(it)      # SMEM [S] per-slot context lengths
        gtable_ref = next(it)    # SMEM [S, maxp]
    if has_tree:
        tpos_ref = next(it)      # SMEM [Td] node depths (tree verify)
        tbits_ref = next(it)     # SMEM [Td] ancestor bitmasks (bit j of
                                 # entry i = node j on node i's root path)
    if has_chunk:
        crow_ref = next(it)      # SMEM [maxp] chunk slot's page row
        qc_ref = next(it)        # VMEM (BQ, KVH, G, D)
        kc_ref = next(it)        # VMEM (C, KVH, D) — resident chunk K
        vc_ref = next(it)
    if has_group:
        qg_ref = next(it)        # VMEM (1, Td, KVH, G, D)
        kg_ref = next(it)        # VMEM (1, Td, KVH, D)
        vg_ref = next(it)
    k_hbm = next(it)             # ANY [L, P, ps, KVH, D]
    v_hbm = next(it)
    if quant:
        ks_hbm = next(it)        # ANY [L, P, ps] f32 per-row scales
        vs_hbm = next(it)
    oc_ref = next(it) if has_chunk else None
    og_ref = next(it) if has_group else None
    k_scr = next(it)             # VMEM (2, ps, KVH, D) double buffer
    v_scr = next(it)
    sems = next(it)              # DMA sems (2, 2)
    if quant:
        ks_scr = next(it)        # VMEM (2, ps) f32 scale double buffer
        vs_scr = next(it)
        sc_sems = next(it)       # DMA sems (2, 2)

    i = pl.program_id(0)
    layer = scal_ref[0]
    window = scal_ref[1]
    scale = jax.lax.rsqrt(jnp.float32(d))
    # flat-lane pools (d % 128 != 0, ISSUE 6): pages are STORED unpadded
    # (the KV-bytes win) and lane-padded here, in-register after the
    # load, so every dot still runs on 128-lane minors — numerically
    # exact (zero lanes meet zero q lanes), same compute as the legacy
    # lane-padded-pool kernels, half the HBM bytes/bandwidth
    dp = -(-d // 128) * 128

    def _lp(x):
        """Zero-pad a loaded value's last dim from d to the lane tile."""
        if dp == d:
            return x
        return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, dp - d)])

    def attend_pages(page_of, ctx_limit, n_table, q_f32, q_abs, q_lo,
                     r, carry):
        """Stream the pages holding keys [0, ctx_limit) (double-buffered)
        into the online-softmax carry. q_f32: [R, KVH, G, D]-ish accessed
        per head as [R, D]; q_abs: [R] absolute query positions (q_lo =
        q_abs minimum, for the window's first-page skip)."""

        def k_dma(slot, page_no):
            page = jnp.maximum(page_of(page_no), 0)
            return pltpu.make_async_copy(
                k_hbm.at[layer, page], k_scr.at[slot], sems.at[slot, 0]
            )

        def v_dma(slot, page_no):
            page = jnp.maximum(page_of(page_no), 0)
            return pltpu.make_async_copy(
                v_hbm.at[layer, page], v_scr.at[slot], sems.at[slot, 1]
            )

        def scale_dmas(slot, page_no):
            # int8 pools (ISSUE 11): the page's [ps] per-row scale rows
            # ride their own small DMAs next to the page copies
            page = jnp.maximum(page_of(page_no), 0)
            return (
                pltpu.make_async_copy(
                    ks_hbm.at[layer, page], ks_scr.at[slot],
                    sc_sems.at[slot, 0]),
                pltpu.make_async_copy(
                    vs_hbm.at[layer, page], vs_scr.at[slot],
                    sc_sems.at[slot, 1]),
            )

        n_pages = jnp.minimum(
            pl.cdiv(jnp.maximum(ctx_limit, 0), ps), n_table
        )
        p0 = jnp.where(window > 0, jnp.maximum(q_lo - window + 1, 0) // ps,
                       0)
        p0 = jnp.minimum(p0, n_pages)

        @pl.when(n_pages > p0)
        def _():
            k_dma(0, p0).start()
            v_dma(0, p0).start()
            if quant:
                for dma in scale_dmas(0, p0):
                    dma.start()

        def body(p, carry):
            m, l, acc = carry
            slot = jax.lax.rem(p - p0, 2)

            @pl.when(p + 1 < n_pages)
            def _():
                nxt = jax.lax.rem(p + 1 - p0, 2)
                k_dma(nxt, p + 1).start()
                v_dma(nxt, p + 1).start()
                if quant:
                    for dma in scale_dmas(nxt, p + 1):
                        dma.start()

            k_dma(slot, p).wait()
            v_dma(slot, p).wait()
            k_page = k_scr[slot]                    # [ps, KVH, D]
            v_page = v_scr[slot]
            if quant:
                # dequant epilogue: the flat-row page load multiplies by
                # its [ps, 1] scale column right after the DMA — the dots
                # below see exactly the values an fp pool would hold
                for dma in scale_dmas(slot, p):
                    dma.wait()
                kscale = ks_scr[slot].reshape(ps, 1)
                vscale = vs_scr[slot].reshape(ps, 1)

            def k_head(h):
                x = k_page[:, h, :].astype(jnp.float32)
                if quant:
                    x = x * kscale
                return _lp(x)

            def v_head(h):
                x = v_page[:, h, :].astype(jnp.float32)
                if quant:
                    x = x * vscale
                return _lp(x)

            logits = jnp.stack([
                jax.lax.dot_general(
                    q_f32[h], k_head(h),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for h in range(kvh)
            ])                                      # [KVH, R, ps]
            if softcap:
                logits = softcap * jnp.tanh(logits / softcap)
            pos = p * ps + jax.lax.broadcasted_iota(
                jnp.int32, (kvh, r, ps), 2
            )
            valid = (pos < ctx_limit) & (
                (window <= 0) | (q_abs[None, :, None] - pos < window)
            )
            logits = jnp.where(valid, logits, _NEG_INF)

            m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
            alpha = jnp.exp(m - m_new)
            prob = jnp.exp(logits - m_new)
            l_new = l * alpha + prob.sum(axis=2, keepdims=True)
            acc_new = acc * alpha + jnp.stack([
                jax.lax.dot_general(
                    prob[h], v_head(h),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for h in range(kvh)
            ])
            return m_new, l_new, acc_new

        return jax.lax.fori_loop(p0, n_pages, body, carry)

    def chunk_tile():
        start = scal_ref[2]
        total = scal_ref[3]
        r = bq * g
        q = qc_ref[...].astype(jnp.float32) * scale  # [BQ, KVH, G, D]
        q_heads = [_lp(q[:, h].reshape(r, d)) for h in range(kvh)]
        # row → chunk-relative token index (rows are token-major: g rows
        # per token)
        q_rel = i * bq + jax.lax.broadcasted_iota(jnp.int32, (r,), 0) // g
        q_abs = start + q_rel

        m0 = jnp.full((kvh, r, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((kvh, r, 1), jnp.float32)
        acc0 = jnp.zeros((kvh, r, dp), jnp.float32)
        m, l, acc = attend_pages(
            lambda p: crow_ref[p], start, crow_ref.shape[0], q_heads,
            q_abs, start + i * bq, r, (m0, l0, acc0),
        )

        # phase 2: the chunk's own K/V blocks, causal within the chunk
        nkb = pl.cdiv((i + 1) * bq, bk)
        kb0 = jnp.where(
            window > 0, jnp.maximum(i * bq - window + 1, 0) // bk, 0
        )
        kb0 = jnp.minimum(kb0, nkb)

        def chunk_body(kb, carry):
            m, l, acc = carry
            k_blk = kc_ref[pl.ds(kb * bk, bk)]      # [BK, KVH, D]
            v_blk = vc_ref[pl.ds(kb * bk, bk)]
            logits = jnp.stack([
                jax.lax.dot_general(
                    q_heads[h], _lp(k_blk[:, h, :].astype(jnp.float32)),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for h in range(kvh)
            ])                                      # [KVH, R, BK]
            if softcap:
                logits = softcap * jnp.tanh(logits / softcap)
            krel = kb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (kvh, r, bk), 2
            )
            dist = q_rel[None, :, None] - krel
            valid = (dist >= 0) & (start + krel < total) & (
                (window <= 0) | (dist < window)
            )
            logits = jnp.where(valid, logits, _NEG_INF)

            m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
            alpha = jnp.exp(m - m_new)
            prob = jnp.exp(logits - m_new)
            l_new = l * alpha + prob.sum(axis=2, keepdims=True)
            acc_new = acc * alpha + jnp.stack([
                jax.lax.dot_general(
                    prob[h], _lp(v_blk[:, h, :].astype(jnp.float32)),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for h in range(kvh)
            ])
            return m_new, l_new, acc_new

        _, l, acc = jax.lax.fori_loop(kb0, nkb, chunk_body, (m, l, acc))
        out = (acc / jnp.maximum(l, 1e-30))[..., :d]  # [KVH, R, D]
        oc_ref[...] = (
            out.reshape(kvh, bq, g, d).transpose(1, 0, 2, 3)
            .astype(oc_ref.dtype)
        )

    def group_tile():
        s = i - nct if has_chunk else i
        length = lens_ref[s]
        r = td * g
        q = qg_ref[0].astype(jnp.float32) * scale   # [Td, KVH, G, D]
        q_heads = [_lp(q[:, h].reshape(r, d)) for h in range(kvh)]
        tok = jax.lax.broadcasted_iota(jnp.int32, (r,), 0) // g
        if has_tree:
            # tree verify (ISSUE 18): row token i's LOGICAL position is
            # length + depth[i] (its storage position stays length + i).
            # The topology rides in as two static-length scalar-prefetch
            # rows; td unrolled scalar reads per tile (td <= 32).
            depths = jnp.stack([tpos_ref[j] for j in range(td)])
            row_depth = jnp.broadcast_to(depths[:, None], (td, g)).reshape(r)
            q_abs = length + row_depth
        else:
            q_abs = length + tok

        m0 = jnp.full((kvh, r, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((kvh, r, 1), jnp.float32)
        acc0 = jnp.zeros((kvh, r, dp), jnp.float32)
        m, l, acc = attend_pages(
            lambda p: gtable_ref[s, p], length, gtable_ref.shape[1],
            q_heads, q_abs, length, r, (m0, l0, acc0),
        )

        # merge the Td fresh columns (candidates not yet in the pool):
        # column j is the slot's token at absolute position length + j;
        # row token i attends columns j <= i (verify causality; Td = 1
        # degenerates to the decode kernel's single current-token merge)
        kg = kg_ref[0].astype(jnp.float32)          # [Td, KVH, D]
        vg = vg_ref[0].astype(jnp.float32)
        logits = jnp.stack([
            jax.lax.dot_general(
                q_heads[h], _lp(kg[:, h, :]),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])                                          # [KVH, R, Td]
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        col = jax.lax.broadcasted_iota(jnp.int32, (kvh, r, td), 2)
        if has_tree:
            # fresh column j is tree node j: valid iff ancestor-or-self
            # of the row's node (bit j of the row's ancestor bitmask),
            # windowed on logical (depth) distance — ancestor implies
            # dist >= 0, so no separate causal term
            bits = jnp.stack([tbits_ref[j] for j in range(td)])
            row_bits = jnp.broadcast_to(bits[:, None], (td, g)).reshape(r)
            anc = ((row_bits[None, :, None] >> col) & 1) != 0
            dist = row_depth[None, :, None] - jnp.broadcast_to(
                depths[None, None, :], (kvh, r, td))
            valid = anc & ((window <= 0) | (dist < window))
        else:
            dist = tok[None, :, None] - col
            valid = (dist >= 0) & ((window <= 0) | (dist < window))
        logits = jnp.where(valid, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        prob = jnp.exp(logits - m_new)
        l = l * alpha + prob.sum(axis=2, keepdims=True)
        acc = acc * alpha + jnp.stack([
            jax.lax.dot_general(
                prob[h], _lp(vg[:, h, :]),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])
        out = (acc / jnp.maximum(l, 1e-30))[..., :d]  # [KVH, R, D]
        og_ref[0] = (
            out.reshape(kvh, td, g, d).transpose(1, 0, 2, 3)
            .astype(og_ref.dtype)
        )

    if has_chunk and has_group:
        @pl.when(i < nct)
        def _():
            chunk_tile()

        @pl.when(i >= nct)
        def _():
            group_tile()
    elif has_chunk:
        chunk_tile()
    else:
        group_tile()


@functools.partial(jax.jit,
                   static_argnames=("page_size", "interpret", "softcap"))
def ragged_attention(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_size: int,
    q_chunk: jnp.ndarray | None = None,
    chunk_row: jnp.ndarray | None = None,
    chunk_start: jnp.ndarray | None = None,
    chunk_total: jnp.ndarray | None = None,
    k_chunk: jnp.ndarray | None = None,
    v_chunk: jnp.ndarray | None = None,
    q_group: jnp.ndarray | None = None,
    page_table: jnp.ndarray | None = None,
    group_lengths: jnp.ndarray | None = None,
    k_group: jnp.ndarray | None = None,
    v_group: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    interpret: bool = False,
    softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    tree_pos: jnp.ndarray | None = None,
    tree_bits: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray | None, jnp.ndarray | None]:
    """Kernel form of ops.attention.ragged_paged_attention: ONE launch,
    static grid (C/BQ chunk tiles + S group tiles) serving chunked
    prefill, decode (Td=1), and spec-verify (Td=K+1) at once. See the
    dispatcher's docstring for the region contracts. Unlike the legacy
    kernels this one accepts d < 128 pools when the PER-SHARD KVH*D is
    lane-aligned: pages are STORED unpadded (contiguous [ps, KVH*D]-byte
    rows, so the page DMA stays tile-aligned) and the loaded values are
    zero-padded to 128 lanes in-register before every dot — same compute
    as the lane-padded-pool kernels, half the HBM bytes/bandwidth."""
    has_chunk = q_chunk is not None
    has_group = q_group is not None
    assert has_chunk or has_group
    has_tree = tree_pos is not None
    assert not has_tree or has_group
    quant = k_scale is not None
    if k_pages.ndim == 4:
        k_pages = k_pages[None]
        v_pages = v_pages[None]
        if quant:
            k_scale = k_scale[None]
            v_scale = v_scale[None]
    if layer is None:
        layer = jnp.int32(0)
    kvh, d = k_pages.shape[-2], k_pages.shape[-1]
    h = (q_chunk if has_chunk else q_group).shape[-2]
    g = h // kvh
    dtype = (q_chunk if has_chunk else q_group).dtype

    nct = 0
    c = bq = bk = 0
    if has_chunk:
        c = q_chunk.shape[1]
        bq = min(128, c)
        bk = min(128, c)
        assert c % bq == 0 and c % bk == 0, (c, bq, bk)
        nct = c // bq
    s = td = 0
    if has_group:
        s, td = q_group.shape[:2]

    kernel = functools.partial(
        _ragged_attn_kernel, ps=page_size, bq=bq, bk=bk, c=c, kvh=kvh,
        g=g, d=d, td=td, nct=nct, softcap=softcap,
        has_chunk=has_chunk, has_group=has_group, quant=quant,
        has_tree=has_tree,
    )

    scal = jnp.stack([
        jnp.asarray(layer, jnp.int32).reshape(()),
        jnp.asarray(window, jnp.int32).reshape(()),
        (jnp.asarray(chunk_start, jnp.int32).reshape(())
         if has_chunk else jnp.int32(0)),
        (jnp.asarray(chunk_total, jnp.int32).reshape(())
         if has_chunk else jnp.int32(0)),
    ])

    prefetch: list = [scal]
    if has_group:
        prefetch += [group_lengths.astype(jnp.int32),
                     page_table.astype(jnp.int32)]
    if has_tree:
        prefetch += [tree_pos.astype(jnp.int32),
                     tree_bits.astype(jnp.int32)]
    if has_chunk:
        prefetch += [chunk_row.astype(jnp.int32)]

    # block index clamps: chunk operands pin to their last tile during
    # group steps (and vice versa at index 0) — those blocks are simply
    # not re-fetched/written outside their region
    last_ct = max(nct - 1, 0)

    in_specs = []
    args = []
    if has_chunk:
        in_specs += [
            pl.BlockSpec((bq, kvh, g, d),
                         lambda i, *_: (jnp.minimum(i, last_ct), 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, kvh, d), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, kvh, d), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
        args += [q_chunk[0].reshape(c, kvh, g, d), k_chunk, v_chunk]
    if has_group:
        def _gidx(i, *_):
            return (jnp.maximum(i - nct, 0), 0, 0, 0, 0)

        def _gidx4(i, *_):
            return (jnp.maximum(i - nct, 0), 0, 0, 0)

        in_specs += [
            pl.BlockSpec((1, td, kvh, g, d), _gidx,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, td, kvh, d), _gidx4,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, td, kvh, d), _gidx4,
                         memory_space=pltpu.VMEM),
        ]
        args += [q_group.reshape(s, td, kvh, g, d), k_group, v_group]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    args += [k_pages, v_pages]
    if quant:
        # int8 pool (ISSUE 11): per-row scales stay in HBM and are DMA'd
        # page-by-page next to the value pages (dequant epilogue)
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    out_specs = []
    out_shape = []
    if has_chunk:
        out_specs.append(
            pl.BlockSpec((bq, kvh, g, d),
                         lambda i, *_: (jnp.minimum(i, last_ct), 0, 0, 0),
                         memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((c, kvh, g, d), dtype))
    if has_group:
        out_specs.append(
            pl.BlockSpec((1, td, kvh, g, d),
                         lambda i, *_: (jnp.maximum(i - nct, 0), 0, 0, 0, 0),
                         memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((s, td, kvh, g, d), dtype))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(nct + s,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kvh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kvh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ] + ([
            pltpu.VMEM((2, page_size), jnp.float32),
            pltpu.VMEM((2, page_size), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ] if quant else []),
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(*prefetch, *args)
    it = iter(outs)
    out_chunk = out_group = None
    if has_chunk:
        out_chunk = next(it).reshape(1, c, h, d)
    if has_group:
        out_group = next(it).reshape(s, td, h, d)
    return out_chunk, out_group


# ---------------------------------------------------------------------------
# paged KV writes (in-place DMA; replaces XLA scatter on the hot path)
# ---------------------------------------------------------------------------
#
# XLA lowers the jnp scatter form of the page-pool update to a serialized
# scatter costing ~12 ms/step (decode) and ~18 ms/prefill for a 3B model on
# v5e — measured dominant over the attention math itself (round-4
# profiling). Worse, updating per-layer pool slices INSIDE the layer scan
# defeats input/output buffer aliasing, adding full-pool copies. These
# kernels run ONCE per jitted step, at top level, over all layers — where
# jit donation guarantees a true in-place update — and DMA exactly the
# written rows/pages.


def _write_decode_all_kernel(
    page_idx_ref,  # SMEM prefetch: [S] destination page per slot (P = skip)
    offset_ref,    # SMEM prefetch: [S] row within the page
    k_new_ref,     # VMEM (1, S, KVH, D) — this layer's new rows
    v_new_ref,
    k_in,          # ANY [L, P, ps, KVH, D] — aliased with k_out
    v_in,
    k_out,
    v_out,
    sems,          # DMA sems [S, 2]
    *, num_pages: int, s: int,
):
    del k_in, v_in  # alias of the outputs; only written here
    layer = pl.program_id(0)
    for i in range(s):  # static unroll: all slots' DMAs go out together
        page = page_idx_ref[i]
        off = offset_ref[i]

        @pl.when(page < num_pages)
        def _(i=i, page=page, off=off):
            pltpu.make_async_copy(
                k_new_ref.at[0, i], k_out.at[layer, page, off], sems.at[i, 0]
            ).start()
            pltpu.make_async_copy(
                v_new_ref.at[0, i], v_out.at[layer, page, off], sems.at[i, 1]
            ).start()

    for i in range(s):
        page = page_idx_ref[i]

        @pl.when(page < num_pages)
        def _(i=i, page=page):
            # wait descriptors must match the started copies' shapes
            off = offset_ref[i]
            pltpu.make_async_copy(
                k_new_ref.at[0, i], k_out.at[layer, page, off], sems.at[i, 0]
            ).wait()
            pltpu.make_async_copy(
                v_new_ref.at[0, i], v_out.at[layer, page, off], sems.at[i, 1]
            ).wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_write_decode(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    page_idx: jnp.ndarray,
    offset: jnp.ndarray,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write one [KVH, D] row per (layer, slot) into the page pool, in place.

    k_pages/v_pages: [L, P, ps, KVH, D] (the FULL pool, all layers);
    k_new/v_new: [L, S, KVH, D]; page_idx: [S] destination page id with the
    out-of-bounds sentinel `num_pages` meaning "skip this slot" (inactive /
    past capacity / unmapped — the hazards ops.kvcache._safe_page_idx masks
    for the scatter path); offset: [S] row within the page. Pages are
    slot-exclusive, so rows never collide.

    The pools are input_output_aliased; under jit+donation this is a true
    in-place update — HBM traffic is just the written rows (~L*S*KVH*D*2
    bytes per step).
    """
    L, _, _, kvh, d = k_pages.shape
    s = k_new.shape[1]
    num_pages = k_pages.shape[1]
    kernel = functools.partial(
        _write_decode_all_kernel, num_pages=num_pages, s=s
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, s, kvh, d), lambda l, *_: (l, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, kvh, d), lambda l, *_: (l, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[pltpu.SemaphoreType.DMA((s, 2))],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # inputs are numbered across (scalar prefetch ops, tensor ops):
        # 0: page_idx, 1: offset, 2: k_new, 3: v_new, 4: k_pages, 5: v_pages
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(
        page_idx.astype(jnp.int32), offset.astype(jnp.int32),
        k_new, v_new, k_pages, v_pages,
    )


def _write_chunk_all_kernel(
    dst_pages_ref,  # SMEM prefetch: [T//ps] destination page per chunk page
    k_new_ref,      # VMEM (1, ps, KVH, D) — this (layer, chunk page)'s rows
    v_new_ref,
    k_in,           # ANY [L, P, ps, KVH, D] — aliased with k_out
    v_in,
    k_out,
    v_out,
    sems,           # DMA sems [2]
    *, num_pages: int,
):
    del k_in, v_in
    layer = pl.program_id(0)
    c = pl.program_id(1)
    page = dst_pages_ref[c]

    @pl.when(page < num_pages)
    def _():
        ck = pltpu.make_async_copy(
            k_new_ref.at[0], k_out.at[layer, page], sems.at[0]
        )
        cv = pltpu.make_async_copy(
            v_new_ref.at[0], v_out.at[layer, page], sems.at[1]
        )
        ck.start()
        cv.start()
        ck.wait()
        cv.wait()


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_write_chunk(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    page_size: int,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a prefill chunk's K/V (all layers) into one slot's pages,
    in place.

    k_pages/v_pages: [L, P, ps, KVH, D]; k_new/v_new: [L, T, KVH, D] with
    T % page_size == 0. `start` (the absolute position of row 0) must be
    page-aligned — a traced value the engine guarantees: fresh prefills
    start at 0 and chunked prefill chunks at multiples of prefill_chunk,
    which EngineConfig rounds to a multiple of the page size.

    Whole pages are DMA'd, including the padding tail of the last partial
    page: padded rows land in pages this slot owns (capacity ≥ length) and
    attention masks positions ≥ length, so the garbage is never read — and
    a later chunk overwrites it with real data. Pages fully past `length`
    (bucket padding) and unmapped (-1) entries are skipped.
    """
    L, _, _, kvh, d = k_pages.shape
    t = k_new.shape[1]
    assert t % page_size == 0, (t, page_size)
    n_chunk_pages = t // page_size
    num_pages = k_pages.shape[1]

    first_page = start // page_size
    c = jnp.arange(n_chunk_pages, dtype=jnp.int32)
    idx = jnp.minimum(first_page + c, table_row.shape[0] - 1)
    mapped = table_row[idx]
    covered = c * page_size < length  # page holds at least one valid row
    dst = jnp.where(covered & (mapped >= 0), mapped, num_pages).astype(jnp.int32)

    kernel = functools.partial(_write_chunk_all_kernel, num_pages=num_pages)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(L, n_chunk_pages),
        in_specs=[
            pl.BlockSpec((1, page_size, kvh, d),
                         lambda l, c, *_: (l, c, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, page_size, kvh, d),
                         lambda l, c, *_: (l, c, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,))],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # 0: dst pages (prefetch), 1: k_new, 2: v_new, 3: k_pages, 4: v_pages
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(dst, k_new, v_new, k_pages, v_pages)


