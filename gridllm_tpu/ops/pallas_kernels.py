"""Pallas TPU attention kernels (SURVEY.md §7 step 5: "paged KV cache +
Pallas flash-attention kernel" is where the baseline metric is won).

Two kernels, each with the pure-jnp implementation in ops/attention.py as
its numerical oracle (tests/test_pallas.py runs both in interpret mode on
CPU and asserts equality):

- `flash_prefill`: causal GQA flash attention over one prompt chunk.
  Grid (KVH, q-blocks); K/V for the grid's kv head stay VMEM-resident
  across q blocks; online-softmax accumulation over BK-sized key blocks,
  everything fp32 on the accumulator side, matmuls on the MXU via
  dot_general(preferred_element_type=f32). Causal + length masking via
  broadcasted_iota — no materialized [T, T] mask.

- `paged_decode`: one-token-per-slot decode attention directly against
  the HBM page pool. Grid (slots,); the slot's page table row and length
  are scalar-prefetched (PrefetchScalarGridSpec) so the kernel can DMA
  exactly the valid pages HBM→VMEM, double-buffered to overlap the next
  page's fetch with the current page's math. This is the "stream only
  valid pages" design the jnp oracle's gather materializes densely
  (PAPERS.md "Ragged Paged Attention" — pattern reference only).

The reference has no analogue (all compute was Ollama's,
client/src/services/OllamaService.ts); kernel selection lives in
ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# ---------------------------------------------------------------------------
# flash prefill
# ---------------------------------------------------------------------------

def _flash_prefill_kernel(
    seqlen_ref,  # SMEM (1, 1): valid tokens
    q_ref,       # VMEM (BQ, 1, G, D) — this q block, this kv head
    k_ref,       # VMEM (1, T, D)     — all keys for this kv head
    v_ref,       # VMEM (1, T, D)
    o_ref,       # VMEM (BQ, 1, G, D)
    *, bq: int, bk: int, t: int,
):
    qi = pl.program_id(1)
    seq_len = seqlen_ref[0, 0]
    g, d = q_ref.shape[2], q_ref.shape[3]
    scale = jax.lax.rsqrt(jnp.float32(d))

    q = q_ref[:, 0].reshape(bq * g, d).astype(jnp.float32) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 0)
    q_pos = qi * bq + rows // g                       # query position per row
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq * g, bk), 1)

    # key blocks that can contribute to this q block: causal upper bound,
    # tightened by the actual sequence length
    nk = jnp.minimum(
        pl.cdiv((qi + 1) * bq, bk), pl.cdiv(jnp.maximum(seq_len, 1), bk)
    )

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * bk, bk)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * bk, bk)].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ*G, BK]
        k_pos = kb * bk + cols
        mask = (q_pos >= k_pos) & (k_pos < seq_len)
        logits = jnp.where(mask, logits, -1e30)

        m_new = jnp.maximum(m, logits.max(axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new)
        l_new = l * alpha + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq * g, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((bq * g, 1), jnp.float32)
    acc0 = jnp.zeros((bq * g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    o_ref[:, 0] = out.reshape(bq, g, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_prefill(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal GQA flash attention. Same contract as
    ops.attention.attention_prefill: q [B, T, H, D], k/v [B, T, KVH, D],
    seq_lens [B] → [B, T, H, D]. T must divide by the q block size
    (min(128, T)); the dispatch layer guarantees this for prefill buckets.
    """
    b, t, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    bq = min(128, t)
    bk = min(128, t)
    assert t % bq == 0 and t % bk == 0, (t, bq, bk)

    kernel = functools.partial(_flash_prefill_kernel, bq=bq, bk=bk, t=t)

    def one(qb, kb, vb, ln):
        return pl.pallas_call(
            kernel,
            grid=(kvh, t // bq),
            in_specs=[
                pl.BlockSpec((1, 1), lambda kh, i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((bq, 1, g, d), lambda kh, i: (i, kh, 0, 0),
                             memory_space=pltpu.VMEM),
                # kv-head-major layout so the block's last two dims are
                # (T, D) — the TPU lowering requires last-two divisibility
                pl.BlockSpec((1, t, d), lambda kh, i: (kh, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, t, d), lambda kh, i: (kh, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((bq, 1, g, d), lambda kh, i: (i, kh, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((t, kvh, g, d), q.dtype),
            interpret=interpret,
            cost_estimate=pl.CostEstimate(
                flops=4 * t * t * h * d // 2,
                bytes_accessed=(t * h * d + 2 * t * kvh * d) * q.dtype.itemsize,
                transcendentals=t * t * h,
            ),
        )(ln.reshape(1, 1), qb.reshape(t, kvh, g, d),
          kb.transpose(1, 0, 2), vb.transpose(1, 0, 2))

    out = jax.vmap(one)(q, k, v, seq_lens.astype(jnp.int32))
    return out.reshape(b, t, h, d)


# ---------------------------------------------------------------------------
# paged decode
# ---------------------------------------------------------------------------

def _paged_decode_kernel(
    table_ref,   # SMEM prefetch: [S, maxp] page ids
    len_ref,     # SMEM prefetch: [S] lengths (incl. current token)
    q_ref,       # VMEM (1, H, D) — this slot's query
    k_hbm,       # ANY  [P, ps, KVH, D] — one layer's page pool, stays in HBM
    v_hbm,
    o_ref,       # VMEM (1, H, D)
    k_scr,       # VMEM (2, ps, KVH, D) double buffer
    v_scr,
    sems,        # DMA sems (2, 2): [buffer, k/v]
    *, ps: int, kvh: int, g: int, d: int,
):
    s = pl.program_id(0)
    length = len_ref[s]
    n_pages = pl.cdiv(jnp.maximum(length, 1), ps)
    scale = jax.lax.rsqrt(jnp.float32(d))
    q = (q_ref[0].reshape(kvh, g, d).astype(jnp.float32) * scale)

    def k_dma(slot, page_no):
        page = jnp.maximum(table_ref[s, page_no], 0)
        return pltpu.make_async_copy(k_hbm.at[page], k_scr.at[slot], sems.at[slot, 0])

    def v_dma(slot, page_no):
        page = jnp.maximum(table_ref[s, page_no], 0)
        return pltpu.make_async_copy(v_hbm.at[page], v_scr.at[slot], sems.at[slot, 1])

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p, 2)

        @pl.when(p + 1 < n_pages)
        def _():
            nxt = jax.lax.rem(p + 1, 2)
            k_dma(nxt, p + 1).start()
            v_dma(nxt, p + 1).start()

        k_dma(slot, p).wait()
        v_dma(slot, p).wait()
        k_page = k_scr[slot]  # [ps, KVH, D]
        v_page = v_scr[slot]

        # per-kv-head 2D dots, unrolled over the (static, small) KVH —
        # Mosaic's tpu.matmul requires lhs/rhs batch dims in the same
        # position, which the [KVH,G,D]x[ps,KVH,D] batched form violates
        logits = jnp.stack([
            jax.lax.dot_general(
                q[h], k_page[:, h, :].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])  # [KVH, G, ps]
        pos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (kvh, g, ps), 2)
        logits = jnp.where(pos < length, logits, -1e30)

        m_new = jnp.maximum(m, logits.max(axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        prob = jnp.exp(logits - m_new)
        l_new = l * alpha + prob.sum(axis=2, keepdims=True)
        acc_new = acc * alpha + jnp.stack([
            jax.lax.dot_general(
                prob[h], v_page[:, h, :].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(kvh)
        ])
        return m_new, l_new, acc_new

    m0 = jnp.full((kvh, g, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((kvh, g, 1), jnp.float32)
    acc0 = jnp.zeros((kvh, g, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.reshape(kvh * g, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Same contract as ops.attention.paged_attention_decode: q [S, H, D],
    pools [P, ps, KVH, D], page_table [S, maxp], lengths [S] (incl. the
    already-written current token) → [S, H, D]. Reads only valid pages.

    Slots with length 0 (inactive) compute garbage rows cheaply (page 0,
    one iteration) — callers mask on `active`, matching the oracle.
    """
    s, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh

    kernel = functools.partial(
        _paged_decode_kernel, ps=page_size, kvh=kvh, g=g, d=d
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kvh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kvh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)
