"""Attention: causal prefill and paged decode.

Pure-jnp reference implementations — correct on CPU and TPU, numerically
the oracle for the Pallas kernels in `ops/pallas_kernels.py`. Softmax is
computed in fp32 regardless of input dtype (bf16 accumulation loses real
accuracy at long context).

GQA convention: q has H heads, k/v have KVH heads, H % KVH == 0; kv heads
are logically repeated H//KVH times (implemented via reshape-grouping, no
materialized repeat).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gridllm_tpu.ops.kvcache import gather_kv

_NEG_INF = -1e30


def attention_prefill(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
) -> jnp.ndarray:
    """Causal self-attention over one self-contained chunk (whole prompt).

    q: [B, T, H, D]; k/v: [B, T, KVH, D]; seq_lens: [B] valid tokens
    (padding keys masked out). Chunked prefill against an existing cached
    prefix is NOT handled here — that variant must read prefix K/V from the
    page pool and will land with the Pallas kernels. Returns [B, T, H, D].
    """
    b, t, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qf = q.astype(jnp.float32).reshape(b, t, kvh, g, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # [B, KVH, G, Tq, Tk]
    logits = jnp.einsum("btkgd,bskd->bkgts", qf, kf, precision=jax.lax.Precision.HIGHEST) * scale

    q_pos = jnp.arange(t)[:, None]  # [Tq, 1]
    k_pos = jnp.arange(t)[None, :]  # [1, Tk]
    causal = q_pos >= k_pos
    valid = k_pos < seq_lens[:, None, None, None, None]
    mask = causal[None, None, None] & valid
    logits = jnp.where(mask, logits, _NEG_INF)

    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, vf, precision=jax.lax.Precision.HIGHEST)
    return out.reshape(b, t, h, d).astype(q.dtype)


def paged_attention_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
) -> jnp.ndarray:
    """One-token-per-slot decode attention against the paged cache.

    q: [S, H, D] (the single new token per slot, post-rope);
    k_pages/v_pages: [P, page_size, KVH, D] (one layer's pool);
    page_table: [S, max_pages]; lengths: [S] valid tokens per slot
    *including* the current token (already written to the cache).
    Returns [S, H, D].

    Reference implementation: materializes each slot's max context via
    gather. The Pallas kernel (ops/pallas_kernels.py) streams only valid
    pages instead.
    """
    s, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    def one_slot(qi, row, ln):
        ks, vs = gather_kv(k_pages, v_pages, row, page_size)  # [N, KVH, D]
        qf = qi.astype(jnp.float32).reshape(kvh, g, d)
        logits = jnp.einsum("kgd,nkd->kgn", qf, ks.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST) * scale
        valid = jnp.arange(ks.shape[0]) < ln
        logits = jnp.where(valid[None, None, :], logits, _NEG_INF)
        probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        return jnp.einsum("kgn,nkd->kgd", probs, vs.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST).reshape(h, d)

    out = jax.vmap(one_slot)(q, page_table, lengths)
    return out.astype(q.dtype)
