"""Attention: causal prefill and paged decode.

Public entry points (`attention_prefill`, `paged_attention_decode`)
dispatch between the Pallas TPU kernels (ops/pallas_kernels.py) and the
pure-jnp reference implementations (`*_ref` here) — the jnp versions are
correct on CPU and TPU and are the numerical oracle for the kernels
(tests/test_pallas.py). Softmax is computed in fp32 regardless of input
dtype (bf16 accumulation loses real accuracy at long context).

Kernel selection: env `GRIDLLM_PALLAS` = "auto" (default: kernels on TPU
backends only), "1" (force on), "0" (force off), "interpret" (kernels in
interpreter mode — CPU testing).

GQA convention: q has H heads, k/v have KVH heads, H % KVH == 0; kv heads
are logically repeated H//KVH times (implemented via reshape-grouping, no
materialized repeat).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.analysis import numcheck
from gridllm_tpu.utils.config import env_bool
from gridllm_tpu.ops.kvcache import (
    QuantPages,
    _env_mode,
    _pallas_mode,
    _shard_map_kernel,
    gather_kv,
    kernel_mesh_axis,
    record_kernel_path,
)

__all__ = [
    "attention_prefill", "paged_attention_decode", "attention_prefix_chunk",
    "paged_attention_verify", "ragged_paged_attention",
    "ragged_paged_attention_ref", "ragged_attention_enabled",
    "attention_prefill_ref", "paged_attention_decode_ref",
    "_env_mode", "_pallas_mode",  # re-export: policy lives in ops/kvcache.py
]


def ragged_attention_enabled() -> bool:
    """Ragged paged attention (ISSUE 6): one unified kernel/launch serving
    chunked prefill, decode, and spec-verify over a ragged per-slot
    descriptor layout, replacing the three per-phase dispatchers below.
    Env `GRIDLLM_RAGGED_ATTN` = "1" (default: on) routes the model
    decode/verify/chunk paths (and the engine's mixed admission steps)
    through `ragged_paged_attention`; "0" is the escape hatch restoring
    the legacy dispatchers exactly. Resolved at trace time — flip it
    before building an engine, not mid-serving."""
    return env_bool("GRIDLLM_RAGGED_ATTN")

_NEG_INF = -1e30


# VMEM budget for flash_prefill's resident per-head K+V (the kernel pins
# [T, D] of each; Mosaic rejects kernels past ~16 MB/core at compile
# time). Buckets past this route to flash_prefill_streamed, which DMAs
# K/V from HBM block-by-block instead of pinning them.
_FLASH_KV_VMEM_CAP = 8 * 1024 * 1024


def _lane_pad_qkv(q, k_cur, v_cur, dpool):
    """Pad query + current K/V to a lane-padded pool's head dim (engine
    allocates D=128 pages for d=64 models so qwen2.5-class paths keep the
    kernels — VERDICT r04 #5). q is pre-scaled so the downstream
    rsqrt(dpool) equals rsqrt(d); callers slice outputs back to d. Exact:
    padded k lanes meet zero q lanes in every dot; padded v lanes produce
    zeros that are sliced away."""
    d = q.shape[-1]
    pad = [(0, 0)] * (q.ndim - 1) + [(0, dpool - d)]
    q = jnp.pad(q * jnp.sqrt(jnp.float32(dpool) / d).astype(q.dtype), pad)
    if k_cur is not None:
        cpad = [(0, 0)] * (k_cur.ndim - 1) + [(0, dpool - d)]
        k_cur = jnp.pad(k_cur, cpad)
        v_cur = jnp.pad(v_cur, cpad)
    return q, k_cur, v_cur


def _prefill_kernel(q, k, v, seq_lens, window, *, interpret, softcap):
    """The kernel leg of attention_prefill: d-padding + VMEM routing.
    Shapes may be shard-local (called from inside the meshed shard_map)."""
    from gridllm_tpu.ops import pallas_kernels
    from gridllm_tpu.ops.kvcache import lane_pad_dim

    t, d = q.shape[1], q.shape[3]
    dp = lane_pad_dim(d)  # also in interpret mode, so tests cover it
    if dp != d:
        pad = [(0, 0)] * (q.ndim - 1) + [(0, dp - d)]
        # correct the kernel's rsqrt(dp) scale back to rsqrt(d)
        q = jnp.pad(q * jnp.sqrt(jnp.float32(dp) / d).astype(q.dtype), pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    kv_bytes = 2 * t * dp * q.dtype.itemsize
    fn = (
        pallas_kernels.flash_prefill
        if kv_bytes <= _FLASH_KV_VMEM_CAP
        else pallas_kernels.flash_prefill_streamed
    )
    out = fn(q, k, v, seq_lens, interpret=interpret, softcap=softcap,
             window=window)
    return out[..., :d] if dp != d else out


def attention_prefill(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    use_pallas: bool | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    mesh=None,
) -> jnp.ndarray:
    """Causal GQA prefill attention (see attention_prefill_ref for the
    contract). Kernel routing (VERDICT r03 weak #6 / next-round #9):

    - per-head K+V within the VMEM budget → flash_prefill (K/V resident);
    - past the budget → flash_prefill_streamed (K/V stream from HBM as a
      grid dimension) — long prefill buckets keep the kernel path;
    - head_dim not a multiple of the 128-lane tile (d=64 models, e.g.
      qwen2.5:0.5b) → q/k/v are ZERO-PADDED to 128 lanes at the kernel
      boundary and the output sliced back. Exact: padded dims contribute
      0 to every q·k dot and 0·p to the output; the kernel's internal
      1/sqrt(d_padded) scale is corrected by pre-scaling q.

    `logit_softcap` (gemma2's tanh capping, static) and `window`
    (sliding-window attention; 0 = full; may be a traced per-layer scalar)
    are handled INSIDE the kernels — windowed buckets also skip the key
    blocks below each q block's window.

    Under `mesh` (VERDICT r04 #2) the kernel runs inside a full-manual
    shard_map with heads split over tp — attention is embarrassingly
    parallel over kv-head groups, so each shard runs the kernel on its
    head slice with no collectives (ops/kvcache.py kernel_mesh_axis).
    """
    use, interpret = _pallas_mode(use_pallas)
    t, d = q.shape[1], q.shape[3]
    if not use or t % min(128, t) != 0:
        record_kernel_path("attention_prefill", False)
        return attention_prefill_ref(
            q, k, v, seq_lens, logit_softcap=logit_softcap, window=window
        )
    mode, ax = kernel_mesh_axis(mesh, k.shape[2], q.shape[2])
    if mode == "ref":
        record_kernel_path("attention_prefill", False)
        return attention_prefill_ref(
            q, k, v, seq_lens, logit_softcap=logit_softcap, window=window
        )
    record_kernel_path("attention_prefill", True)
    kernel = partial(
        _prefill_kernel, interpret=interpret, softcap=float(logit_softcap)
    )

    def _shadow(out):
        # numerics sanitizer (analysis/numcheck.py): padding rows are
        # unspecified kernel output — compare the valid region only, the
        # same contract the differential tests apply
        if not numcheck.active():
            return out
        return numcheck.shadow(
            "attention_prefill", out,
            lambda: attention_prefill_ref(
                q, k, v, seq_lens, logit_softcap=logit_softcap,
                window=window),
            valid=jnp.arange(q.shape[1])[None, :] < seq_lens[:, None],
        )

    if mode == "direct":
        return _shadow(kernel(q, k, v, seq_lens, window))
    from jax.sharding import PartitionSpec as P

    # window always travels as a scalar operand — the kernels read it from
    # SMEM at runtime either way, so there is nothing to specialize
    hs = P(None, None, ax, None)
    sm = _shard_map_kernel(
        mesh, kernel, in_specs=(hs, hs, hs, P(None), P()), out_specs=hs,
    )
    return _shadow(sm(q, k, v, seq_lens, jnp.asarray(window, jnp.int32)))


def paged_attention_decode(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    mesh=None,
) -> jnp.ndarray:
    """Paged decode attention (see paged_attention_decode_ref for the
    contract). With k_cur/v_cur ([S, KVH, D]), `lengths` counts the
    cached PREFIX only and the current token's K/V are merged in-register
    (one extra online-softmax step) — the engine defers all pool writes to
    one all-layer kernel after the layer scan, so the pool lags one token
    during decode. Pools may be the FULL [L, P, ps, KVH, D] stack with
    `layer` selecting the layer to read (pass from inside a layer scan so
    no per-layer pool slice is materialized). Routes to the page-streaming
    kernel when enabled. Mosaic requires 128-lane-aligned page slices;
    d=64 models (qwen2.5 class) keep the kernel path via the engine's
    lane-padded pool (ops.kvcache.lane_pad_dim) — the dispatch pads
    q/k_cur/v_cur to the pool's D and slices the output back, exactly.
    `logit_softcap` (static) and `window` (may be traced,
    gemma2 alternates per layer) are handled inside the kernel — windowed
    decode never DMAs pages below the window.

    Under `mesh` (VERDICT r04 #2): full-manual shard_map with heads split
    over tp — each shard runs the kernel on its kv-head slice of the page
    pool, no collectives (the wo row-parallel psum that follows stays
    GSPMD's, outside the wrapper)."""
    d, dpool = q.shape[-1], k_pages.shape[-1]
    if dpool != d:
        q, k_cur, v_cur = _lane_pad_qkv(q, k_cur, v_cur, dpool)
        out = paged_attention_decode(
            q, k_pages, v_pages, page_table, lengths, page_size,
            k_cur=k_cur, v_cur=v_cur, layer=layer, use_pallas=use_pallas,
            logit_softcap=logit_softcap, window=window, mesh=mesh,
        )
        return out[..., :d]
    use, interpret = _pallas_mode(use_pallas)
    mode, ax = kernel_mesh_axis(mesh, k_pages.shape[-2], q.shape[1])
    # int8 pools (ISSUE 11) read through the ragged kernel's dequant
    # epilogue or the jnp fallback; the legacy decode kernel has no
    # scale plumbing, so a quantized pool takes the reference path here
    if use and mode != "ref" and not isinstance(k_pages, QuantPages) \
            and (interpret or q.shape[-1] % 128 == 0):
        from gridllm_tpu.ops import pallas_kernels

        record_kernel_path("attention_decode", True)
        kernel = partial(
            pallas_kernels.paged_decode, page_size=page_size,
            interpret=interpret, softcap=float(logit_softcap),
        )

        def _shadow(out):
            if not numcheck.active():
                return out

            def ref():
                kp, vp = k_pages, v_pages
                if kp.ndim == 5:
                    li = jnp.int32(0) if layer is None else layer
                    kp = jax.lax.dynamic_index_in_dim(kp, li,
                                                      keepdims=False)
                    vp = jax.lax.dynamic_index_in_dim(vp, li,
                                                      keepdims=False)
                return paged_attention_decode_ref(
                    q, kp, vp, page_table, lengths, page_size,
                    k_cur=k_cur, v_cur=v_cur,
                    logit_softcap=logit_softcap, window=window)

            # without the current-token merge a length-0 slot is garbage
            # by contract (callers mask on active); with it, even a fresh
            # slot's single-column softmax is specified output
            return numcheck.shadow(
                "attention_decode", out, ref,
                valid=None if k_cur is not None else lengths > 0)

        if mode == "direct":
            return _shadow(kernel(q, k_pages, v_pages, page_table, lengths,
                                  k_cur=k_cur, v_cur=v_cur, layer=layer,
                                  window=window))
        from jax.sharding import PartitionSpec as P

        pool = P(*((None,) * (k_pages.ndim - 2)), ax, None)
        hs = P(None, ax, None)
        # optional/traced operands (k_cur/v_cur, layer, window) must enter
        # through in_specs — shard_map bodies cannot close over tracers.
        # window is always an operand: the kernels read it from SMEM at
        # runtime either way, so there is nothing to specialize.
        opt = {"window": (jnp.asarray(window, jnp.int32), P())}
        if k_cur is not None:
            opt["k_cur"], opt["v_cur"] = (k_cur, hs), (v_cur, hs)
        if layer is not None:
            opt["layer"] = (layer, P())
        names = sorted(opt)

        def sm_body(q, kp, vp, pt, lens, *dyn):
            return kernel(q, kp, vp, pt, lens, **dict(zip(names, dyn)))

        args = [q, k_pages, v_pages, page_table, lengths]
        specs = [hs, pool, pool, P(*((None,) * page_table.ndim)), P(None)]
        args += [opt[n][0] for n in names]
        specs += [opt[n][1] for n in names]
        sm = _shard_map_kernel(mesh, sm_body, in_specs=tuple(specs),
                               out_specs=hs)
        return _shadow(sm(*args))
    record_kernel_path("attention_decode", False)
    if k_pages.ndim == 5:  # fallback: materialize the layer slice
        li = jnp.int32(0) if layer is None else layer
        if isinstance(k_pages, QuantPages):
            k_pages, v_pages = k_pages.layer(li), v_pages.layer(li)
        else:
            k_pages = jax.lax.dynamic_index_in_dim(k_pages, li,
                                                   keepdims=False)
            v_pages = jax.lax.dynamic_index_in_dim(v_pages, li,
                                                   keepdims=False)
    return paged_attention_decode_ref(
        q, k_pages, v_pages, page_table, lengths, page_size,
        k_cur=k_cur, v_cur=v_cur, logit_softcap=logit_softcap,
        window=window,
    )


def attention_prefix_chunk(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    total_len: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    mesh=None,
) -> jnp.ndarray:
    """Chunked-prefill attention: one chunk of queries against the slot's
    FULL cached context (prefix + this chunk), read from the page pool.

    q: [1, T, H, D] — chunk queries at absolute positions start + arange(T);
    k_pages/v_pages: [P, page_size, KVH, D] one layer's pool; table_row:
    [max_pages] the slot's pages; start: scalar absolute position of q[0];
    total_len: scalar = start + valid tokens in this chunk. Without
    k_cur/v_cur the chunk's K/V must already be in the pool; with them
    ([T, KVH, D], pool writes deferred to after the layer scan) the chunk
    rows are overlaid onto the gathered context at positions start+i.
    Returns [1, T, H, D].

    This is what `attention_prefill_ref`'s docstring named as missing in
    round 1 ("chunked prefill against an existing cached prefix") — the
    piece that makes prompts longer than the largest bucket run as repeated
    fixed-shape chunk programs instead of per-length recompiles
    (VERDICT.md #4). Dispatch: pallas_kernels.prefix_chunk (prefix pages
    streamed from HBM, chunk K/V resident) when the chunk fits the VMEM
    budget; jnp fallback (dense prefix gather) otherwise — both mesh-aware
    (full-manual shard_map over tp, like paged_attention_decode).
    """
    dq, dpool = q.shape[-1], k_pages.shape[-1]
    if dpool != dq:
        q, k_cur, v_cur = _lane_pad_qkv(q, k_cur, v_cur, dpool)
        out = attention_prefix_chunk(
            q, k_pages, v_pages, table_row, start, total_len, page_size,
            k_cur=k_cur, v_cur=v_cur, layer=layer, use_pallas=use_pallas,
            logit_softcap=logit_softcap, window=window, mesh=mesh,
        )
        return out[..., :dq]
    _, t, h, d = q.shape
    kvh = k_pages.shape[-2]
    use, interpret = _pallas_mode(use_pallas)
    mode, ax = kernel_mesh_axis(mesh, kvh, h)
    # kernel path: the chunk flash kernel streams prefix pages from HBM
    # and keeps the chunk's K/V resident — gated on the chunk's per-layer
    # K+V fitting the VMEM budget and Mosaic's lane alignment. The budget
    # is per SHARD: under tp the resident chunk is kvh/tp heads wide.
    kvh_local = kvh // mesh.shape["tp"] if ax == "tp" else kvh
    if (
        use and mode != "ref" and k_cur is not None
        and not isinstance(k_pages, QuantPages)
        and (interpret or d % 128 == 0)
        and t % min(128, t) == 0
        and 2 * t * kvh_local * d * q.dtype.itemsize <= _FLASH_KV_VMEM_CAP
    ):
        from gridllm_tpu.ops import pallas_kernels

        record_kernel_path("attention_prefix_chunk", True)
        kernel = partial(
            pallas_kernels.prefix_chunk, page_size=page_size,
            interpret=interpret, softcap=float(logit_softcap),
        )

        def _shadow(out):
            if not numcheck.active():
                return out
            return numcheck.shadow(
                "attention_prefix_chunk", out,
                lambda: _prefix_chunk_ref(
                    q, k_pages, v_pages, table_row, start, total_len,
                    page_size, k_cur=k_cur, v_cur=v_cur, layer=layer,
                    logit_softcap=logit_softcap, window=window),
                # rows past the chunk's valid length are bucket padding
                valid=jnp.arange(q.shape[1])[None, :] < total_len - start,
            )

        if mode == "direct":
            return _shadow(kernel(q, k_pages, v_pages, table_row, start,
                                  total_len, k_cur=k_cur, v_cur=v_cur,
                                  layer=layer, window=window))
        from jax.sharding import PartitionSpec as P

        pool = P(*((None,) * (k_pages.ndim - 2)), ax, None)
        hs = P(None, None, ax, None)
        cur = P(None, ax, None)
        opt = {
            "start": (start, P()),
            "total_len": (total_len, P()),
            "window": (jnp.asarray(window, jnp.int32), P()),
        }
        if layer is not None:
            opt["layer"] = (layer, P())
        names = sorted(opt)

        def sm_body(q, kp, vp, row, kc, vc, *dyn):
            kw = dict(zip(names, dyn))
            return kernel(q, kp, vp, row, kw.pop("start"),
                          kw.pop("total_len"), k_cur=kc, v_cur=vc, **kw)

        args = [q, k_pages, v_pages, table_row, k_cur, v_cur]
        specs = [hs, pool, pool, P(None), cur, cur]
        args += [opt[n][0] for n in names]
        specs += [opt[n][1] for n in names]
        sm = _shard_map_kernel(mesh, sm_body, in_specs=tuple(specs),
                               out_specs=hs)
        return _shadow(sm(*args))
    record_kernel_path("attention_prefix_chunk", False)
    return _prefix_chunk_ref(
        q, k_pages, v_pages, table_row, start, total_len, page_size,
        k_cur=k_cur, v_cur=v_cur, layer=layer,
        logit_softcap=logit_softcap, window=window,
    )


def _prefix_chunk_ref(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    table_row: jnp.ndarray,
    start: jnp.ndarray,
    total_len: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """jnp reference for chunked-prefill attention against a paged prefix
    (the fallback leg of attention_prefix_chunk, factored out so
    ragged_paged_attention's chunk region shares it VERBATIM — ragged-on
    and ragged-off jnp paths must stay bit-identical)."""
    _, t, h, d = q.shape
    kvh = k_pages.shape[-2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    if k_pages.ndim == 5:
        # full [L, P, ps, KVH, D] pool + layer index: gather exactly the
        # slot's pages from the selected layer (a combined advanced index —
        # never a whole-layer pool slice)
        li = jnp.int32(0) if layer is None else layer
        rows = jnp.maximum(table_row, 0)
        n = table_row.shape[0] * page_size
        if isinstance(k_pages, QuantPages):
            ks = k_pages.layer(li).take(rows).reshape(n, kvh, d)
            vs = v_pages.layer(li).take(rows).reshape(n, kvh, d)
        else:
            ks = k_pages[li, rows].reshape(n, kvh, d)
            vs = v_pages[li, rows].reshape(n, kvh, d)
    else:
        ks, vs = gather_kv(k_pages, v_pages, table_row, page_size)  # [N, KVH, D]
    if k_cur is not None:
        # overlay the fresh chunk at absolute positions [start, start+T):
        # pad by T rows so the dynamic_update_slice stays in bounds at the
        # capacity edge (start ≤ N; padded rows are sliced off again)
        pad = jnp.zeros((t, kvh, d), ks.dtype)
        n = ks.shape[0]
        ks = jax.lax.dynamic_update_slice(
            jnp.concatenate([ks, pad]), k_cur.astype(ks.dtype), (start, 0, 0)
        )[:n]
        vs = jax.lax.dynamic_update_slice(
            jnp.concatenate([vs, pad]), v_cur.astype(vs.dtype), (start, 0, 0)
        )[:n]
    qf = q.astype(jnp.float32).reshape(t, kvh, g, d)
    q_pos = start + jnp.arange(t)              # [T] absolute
    k_pos = jnp.arange(ks.shape[0])            # [N] absolute
    # causal over absolute positions covers both the prefix (k_pos < start
    # <= q_pos) and intra-chunk causality; total_len guards stale data in
    # owned-but-not-yet-valid page tails for padded q rows
    w = jnp.asarray(window, jnp.int32)
    dist = q_pos[:, None] - k_pos[None, :]
    mask = (
        (dist >= 0) & ((w <= 0) | (dist < w))
        & (k_pos[None, :] < total_len)
    )

    logits = jnp.einsum(
        "tkgd,nkd->kgtn", qf, ks.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ) * scale
    logits = _softcap(logits, logit_softcap)
    logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum(
        "kgtn,nkd->tkgd", probs, vs.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return out.reshape(1, t, h, d).astype(q.dtype)


def paged_attention_verify(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray,
    v_cur: jnp.ndarray,
    layer: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Batched multi-token decode attention — the speculative-verify step
    (ISSUE 5): S slots × T candidate tokens each, attending the slot's
    paged prefix plus the candidates before them. With
    `tree_pos`/`tree_mask` the candidates form a token tree (ISSUE 18,
    see paged_attention_verify_ref) — the per-slot chunk-kernel loop
    cannot express an ancestor mask, so tree verify always takes the
    batched reference here (the fused ragged kernel carries the tree
    leg).

    q: [S, T, H, D] (candidate queries, post-rope); k_cur/v_cur:
    [S, T, KVH, D] (the candidates' fresh K/V, not yet in the pool);
    lengths: [S] cached-prefix length per slot — candidate i of slot s
    sits at absolute position lengths[s] + i. Returns [S, T, H, D].

    Kernel path: per-slot dispatch through attention_prefix_chunk with
    start = lengths[s] and total_len = lengths[s] + T — chunked prefill
    against a cached prefix IS verify attention with every chunk row
    valid, so the paged-prefix streaming kernel (runtime start/total
    scalars, lane-padded pools, meshed shard_map) is reused wholesale;
    the slot loop is static and T tiny (spec_k + 1). A fused
    ragged-verify kernel (one grid over slots, the Ragged Paged Attention
    shape) can replace the loop later without touching callers.

    jnp path: ONE batched reference (vmap over slots of the dense prefix
    gather) — tracing S separate chunk fallbacks per layer would bloat
    CPU compiles S-fold for the same math.
    """
    t = q.shape[1]
    use, interpret = _pallas_mode(use_pallas)
    mode, _ax = kernel_mesh_axis(mesh, k_cur.shape[2], q.shape[2])
    if tree_pos is not None:
        record_kernel_path("attention_verify", False)
        return paged_attention_verify_ref(
            q, k_pages, v_pages, page_table, lengths, page_size, k_cur,
            v_cur, layer=layer, logit_softcap=logit_softcap, window=window,
            tree_pos=tree_pos, tree_mask=tree_mask,
        )
    if use and mode != "ref" and not isinstance(k_pages, QuantPages):
        outs = [
            attention_prefix_chunk(
                q[i][None], k_pages, v_pages, page_table[i], lengths[i],
                lengths[i] + t, page_size, k_cur=k_cur[i], v_cur=v_cur[i],
                layer=layer, use_pallas=use_pallas,
                logit_softcap=logit_softcap, window=window, mesh=mesh,
            )
            for i in range(q.shape[0])
        ]
        return jnp.concatenate(outs, axis=0)
    record_kernel_path("attention_verify", False)
    return paged_attention_verify_ref(
        q, k_pages, v_pages, page_table, lengths, page_size, k_cur, v_cur,
        layer=layer, logit_softcap=logit_softcap, window=window,
    )


def paged_attention_verify_ref(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray,
    v_cur: jnp.ndarray,
    layer: jnp.ndarray | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Batched verify-attention reference: vmap over slots of the dense
    per-slot gather + candidate overlay + causal mask — the same math as
    attention_prefix_chunk's fallback with start = lengths[s] and every
    candidate row valid. Pools may be one layer [P, ps, KVH, D] or the
    full [L, P, ps, KVH, D] stack with `layer` selecting (pass from
    inside a layer scan). Returns [S, T, H, D].

    Tree verify (ISSUE 18): with `tree_pos` ([T] i32 — node depths) and
    `tree_mask` ([T, T] bool — ancestor-or-self, row i marks node i's
    root-to-i path) the T candidates form a static-topology token TREE
    instead of a chain. Node i's K/V row is still stored/overlaid at
    absolute position lengths[s] + i, but its ROPE/logical position is
    lengths[s] + tree_pos[i]; node i's query attends the whole prefix
    plus exactly its tree ancestors (and itself), with the sliding
    window measured in LOGICAL distance. The topology is shared by all
    slots (a jit constant — the recompile tripwire stays green); per-slot
    raggedness lives in the accept walk, not the mask, because node
    validity is ancestor-closed so a live query never attends a dead
    node. A chain (tree_pos = arange(T), tree_mask = lower-triangular)
    produces the exact same mask as the legacy branch, but the legacy
    trace is kept verbatim on a separate branch so chain spec stays
    bit-identical."""
    s, t, h, d = q.shape
    tree = tree_pos is not None
    if tree:
        tree_pos = jnp.asarray(tree_pos, jnp.int32)
        tree_mask = jnp.asarray(tree_mask, bool)
    kvh = k_pages.shape[-2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    w = jnp.asarray(window, jnp.int32)
    if k_pages.ndim == 5:
        li = jnp.int32(0) if layer is None else layer
        if isinstance(k_pages, QuantPages):
            k_pages, v_pages = k_pages.layer(li), v_pages.layer(li)
        else:
            k_pages = jax.lax.dynamic_index_in_dim(k_pages, li,
                                                   keepdims=False)
            v_pages = jax.lax.dynamic_index_in_dim(v_pages, li,
                                                   keepdims=False)

    def one_slot(qi, row, start, kc, vc):
        ks, vs = gather_kv(k_pages, v_pages, row, page_size)  # [N, KVH, D]
        # overlay the candidates at absolute positions [start, start+T):
        # pad by T rows so the update stays in bounds at the capacity
        # edge (padded rows are sliced off again; the out-of-capacity
        # case is a finished slot whose output is discarded)
        pad = jnp.zeros((t, kvh, ks.shape[-1]), ks.dtype)
        n = ks.shape[0]
        ks = jax.lax.dynamic_update_slice(
            jnp.concatenate([ks, pad]), kc.astype(ks.dtype), (start, 0, 0)
        )[:n]
        vs = jax.lax.dynamic_update_slice(
            jnp.concatenate([vs, pad]), vc.astype(vs.dtype), (start, 0, 0)
        )[:n]
        qf = qi.astype(jnp.float32).reshape(t, kvh, g, d)
        k_pos = jnp.arange(n)
        total = start + t
        if tree:
            # logical positions: query node i at start + depth[i]; a key
            # in the candidate region [start, start+T) is node j at
            # logical start + depth[j], a prefix key sits at its own
            # index. Candidate keys are valid iff ancestor-or-self;
            # prefix keys iff causal — both windowed on logical distance.
            q_pos = start + tree_pos
            is_cand = (k_pos >= start) & (k_pos < total)
            node = jnp.clip(k_pos - start, 0, t - 1)
            k_log = jnp.where(is_cand, start + tree_pos[node], k_pos)
            dist = q_pos[:, None] - k_log[None, :]
            mask = (
                jnp.where(is_cand[None, :], tree_mask[:, node], dist >= 0)
                & ((w <= 0) | (dist < w))
                & (k_pos[None, :] < total)
            )
        else:
            q_pos = start + jnp.arange(t)
            dist = q_pos[:, None] - k_pos[None, :]
            mask = (
                (dist >= 0) & ((w <= 0) | (dist < w))
                & (k_pos[None, :] < total)
            )
        logits = jnp.einsum(
            "tkgd,nkd->kgtn", qf, ks.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ) * scale
        logits = _softcap(logits, logit_softcap)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
        probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        out = jnp.einsum(
            "kgtn,nkd->tkgd", probs, vs.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )
        return out.reshape(t, h, d)

    out = jax.vmap(one_slot)(q, page_table, lengths, k_cur, v_cur)
    return out.astype(q.dtype)


def ragged_paged_attention(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_size: int,
    q_chunk: jnp.ndarray | None = None,
    chunk_row: jnp.ndarray | None = None,
    chunk_start: jnp.ndarray | None = None,
    chunk_total: jnp.ndarray | None = None,
    k_chunk: jnp.ndarray | None = None,
    v_chunk: jnp.ndarray | None = None,
    q_group: jnp.ndarray | None = None,
    page_table: jnp.ndarray | None = None,
    group_lengths: jnp.ndarray | None = None,
    k_group: jnp.ndarray | None = None,
    v_group: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    mesh=None,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray | None, jnp.ndarray | None]:
    """Unified ragged paged attention (ISSUE 6, Ragged Paged Attention
    design): causal paged attention for a ragged token batch — one prefill
    CHUNK region plus S fixed-stride per-slot GROUPS — in a single kernel
    launch, replacing the three per-phase dispatchers
    (attention_prefix_chunk / paged_attention_decode /
    paged_attention_verify) and the per-slot Python loop verify used.

    Tree verify (ISSUE 18): `tree_pos` [Td] i32 + `tree_mask` [Td, Td]
    bool turn the GROUP region's Td tokens into a static-topology token
    tree (see paged_attention_verify_ref for the exact mask semantics).
    The topology is a jit constant shared by every slot; the kernel
    carries it as two scalar-prefetch rows (depths + ancestor BITMASKS,
    one int32 per node — hence Td <= 32 on the kernel path, larger
    budgets fall back to the jnp reference). The non-tree trace is
    untouched — tree args absent compiles the exact pre-ISSUE-18 kernel.

    Regions (either may be absent; descriptors are per-sequence
    `(query_len, context_len, page_table_row)` in the RPA sense):

    - chunk: q_chunk [1, C, H, D] — one slot's prefill chunk at absolute
      positions chunk_start + i, prefix pages via chunk_row [max_pages],
      fresh K/V k_chunk/v_chunk [C, KVH, D] overlaid causally;
      chunk_total = chunk_start + valid rows (query_len = valid rows).
    - group: q_group [S, Td, H, D] — Td query tokens per slot (Td = 1 for
      decode, K+1 for spec-verify) at positions group_lengths[s] + i
      against page_table[s]; fresh K/V k_group/v_group [S, Td, KVH, D]
      merged in-register. Slots with length 0 (inactive) compute garbage
      cheaply — callers mask on `active`, matching the legacy ops.

    Pools may be one layer [P, ps, KVH, D] or the full stack with `layer`
    selecting (pass from inside a layer scan). Returns (chunk_out,
    group_out), each shaped like its q (None when the region is absent).

    Kernel path: ONE pallas_call with a static grid over query-token tiles
    (C/BQ chunk tiles + S group tiles, pallas_kernels.ragged_attention) —
    a mixed prefill+decode+verify engine step is a single launch. d=64
    models keep the kernel path WITHOUT the 2x lane-padded pool when the
    per-shard (KVH*D) % 128 == 0: pages are stored unpadded (tile-aligned
    flat rows for the DMA) and lane-padded in-register at load — the
    KV-bytes win /admin/memory itemizes. jnp path: the per-region
    legacy references, shared verbatim, so greedy streams are
    bit-identical ragged-on vs ragged-off on the fallback path.
    """
    some_q = q_chunk if q_chunk is not None else q_group
    d, dpool = some_q.shape[-1], k_pages.shape[-1]
    if dpool != d:
        # lane-padded pool (legacy layout or KVH*D not lane-aligned):
        # pad q/fresh-K/V at the boundary and slice back, exactly as the
        # legacy dispatchers do
        if q_chunk is not None:
            q_chunk, k_chunk, v_chunk = _lane_pad_qkv(
                q_chunk, k_chunk, v_chunk, dpool)
        if q_group is not None:
            q_group, k_group, v_group = _lane_pad_qkv(
                q_group, k_group, v_group, dpool)
        oc, og = ragged_paged_attention(
            k_pages, v_pages, page_size,
            q_chunk=q_chunk, chunk_row=chunk_row, chunk_start=chunk_start,
            chunk_total=chunk_total, k_chunk=k_chunk, v_chunk=v_chunk,
            q_group=q_group, page_table=page_table,
            group_lengths=group_lengths, k_group=k_group, v_group=v_group,
            layer=layer, use_pallas=use_pallas, logit_softcap=logit_softcap,
            window=window, mesh=mesh, tree_pos=tree_pos,
            tree_mask=tree_mask,
        )
        return (
            oc[..., :d] if oc is not None else None,
            og[..., :d] if og is not None else None,
        )

    h = some_q.shape[-2]
    kvh = k_pages.shape[-2]
    use, interpret = _pallas_mode(use_pallas)
    mode, ax = kernel_mesh_axis(mesh, kvh, h)
    # per-SHARD head count: under tp the kernel runs inside a shard_map
    # with kv heads split, so both the lane and VMEM gates must look at
    # what one shard actually sees
    kvh_local = kvh // mesh.shape["tp"] if ax == "tp" else kvh
    # Mosaic lane alignment: either classic 128-lane head dim, or the
    # ragged flat-lane layout — page rows viewed as [ps, KVH*D], aligned
    # whenever the SHARD's KVH*D divides the lane tile (d=64 models with
    # enough kv heads per shard)
    lanes_ok = interpret or d % 128 == 0 or (kvh_local * d) % 128 == 0
    chunk_ok = True
    if q_chunk is not None:
        c = q_chunk.shape[1]
        # the chunk's fresh K/V stay VMEM-resident — same budget gate as
        # attention_prefix_chunk (per shard under tp)
        chunk_ok = (
            c % min(128, c) == 0
            and 2 * c * kvh_local * d * q_chunk.dtype.itemsize
            <= _FLASH_KV_VMEM_CAP
        )
    quant = isinstance(k_pages, QuantPages)
    if quant and mode == "wrap":
        # int8 pools are single-device by engine policy (no shard_map
        # plumbing for the scale operands) — a meshed call is a wiring
        # bug upstream; serve the exact jnp path instead of guessing
        mode = "ref"
    has_tree = tree_pos is not None and q_group is not None
    tree_kw = {}
    if has_tree:
        if q_group.shape[1] > 32:
            # one int32 ancestor bitmask per node on the kernel path —
            # oversized budgets take the exact jnp reference instead
            mode = "ref"
        else:
            # topology is a host constant (static per process); pack the
            # ancestor rows into int32 bitmasks for the scalar-prefetch
            # lane of the kernel (bit j of row i = node j on node i's
            # root path)
            tm = np.asarray(tree_mask, bool)
            bits = np.zeros((tm.shape[0],), np.uint32)
            for j in range(tm.shape[1]):
                bits |= tm[:, j].astype(np.uint32) << np.uint32(j)
            tree_kw = {
                "tree_pos": jnp.asarray(np.asarray(tree_pos, np.int32),
                                        dtype=jnp.int32),
                "tree_bits": jnp.asarray(bits.view(np.int32),
                                         dtype=jnp.int32),
            }
    if use and mode != "ref" and lanes_ok and chunk_ok:
        from gridllm_tpu.ops import pallas_kernels

        record_kernel_path("attention_ragged", True)

        def _shadow(outs):
            # numerics sanitizer: shadow the whole launch against the
            # region-by-region jnp reference (QuantPages pools dequantize
            # through gather_kv/take inside the refs, so the int8 dequant
            # epilogue is compared against the jnp quant path)
            if not numcheck.active():
                return outs
            vc = vg = None
            if q_chunk is not None:
                vc = (jnp.arange(q_chunk.shape[1])[None, :]
                      < chunk_total - chunk_start)
            if q_group is not None:
                vg = group_lengths > 0
            return numcheck.shadow(
                "attention_ragged", outs,
                lambda: ragged_paged_attention_ref(
                    k_pages, v_pages, page_size,
                    q_chunk=q_chunk, chunk_row=chunk_row,
                    chunk_start=chunk_start, chunk_total=chunk_total,
                    k_chunk=k_chunk, v_chunk=v_chunk, q_group=q_group,
                    page_table=page_table, group_lengths=group_lengths,
                    k_group=k_group, v_group=v_group, layer=layer,
                    logit_softcap=logit_softcap, window=window,
                    tree_pos=tree_pos, tree_mask=tree_mask),
                valid=(vc, vg),
            )

        if quant:
            # dequant epilogue (ISSUE 11): the kernel DMAs the int8 page
            # AND its [ps] scale row, multiplying after the load in the
            # flat-row read path — half the page HBM bytes per step
            kd, ksc = k_pages.data, k_pages.scale
            vd, vsc = v_pages.data, v_pages.scale
            if kd.ndim == 4:
                kd, vd = kd[None], vd[None]
                ksc, vsc = ksc[None], vsc[None]
            kernel = partial(
                pallas_kernels.ragged_attention, page_size=page_size,
                interpret=interpret, softcap=float(logit_softcap),
            )
            return _shadow(kernel(
                kd, vd,
                q_chunk=q_chunk, chunk_row=chunk_row,
                chunk_start=chunk_start, chunk_total=chunk_total,
                k_chunk=k_chunk, v_chunk=v_chunk,
                q_group=q_group, page_table=page_table,
                group_lengths=group_lengths, k_group=k_group,
                v_group=v_group, layer=layer, window=window,
                k_scale=ksc, v_scale=vsc, **tree_kw,
            ))
        kp = k_pages if k_pages.ndim == 5 else k_pages[None]
        vp = v_pages if v_pages.ndim == 5 else v_pages[None]
        kernel = partial(
            pallas_kernels.ragged_attention, page_size=page_size,
            interpret=interpret, softcap=float(logit_softcap),
        )
        if mode == "direct":
            return _shadow(kernel(
                kp, vp,
                q_chunk=q_chunk, chunk_row=chunk_row,
                chunk_start=chunk_start, chunk_total=chunk_total,
                k_chunk=k_chunk, v_chunk=v_chunk,
                q_group=q_group, page_table=page_table,
                group_lengths=group_lengths, k_group=k_group,
                v_group=v_group, layer=layer, window=window, **tree_kw,
            ))
        from jax.sharding import PartitionSpec as P

        pool = P(None, None, None, ax, None)
        # dynamic operand assembly (shard_map bodies cannot close over
        # tracers): name → (value, spec); sorted for a stable order
        opt = {"window": (jnp.asarray(window, jnp.int32), P())}
        if layer is not None:
            opt["layer"] = (layer, P())
        if q_chunk is not None:
            opt["q_chunk"] = (q_chunk, P(None, None, ax, None))
            opt["chunk_row"] = (chunk_row, P(None))
            opt["chunk_start"] = (chunk_start, P())
            opt["chunk_total"] = (chunk_total, P())
            opt["k_chunk"] = (k_chunk, P(None, ax, None))
            opt["v_chunk"] = (v_chunk, P(None, ax, None))
        if q_group is not None:
            opt["q_group"] = (q_group, P(None, None, ax, None))
            opt["page_table"] = (page_table, P(None, None))
            opt["group_lengths"] = (group_lengths, P(None))
            opt["k_group"] = (k_group, P(None, None, ax, None))
            opt["v_group"] = (v_group, P(None, None, ax, None))
        for tn, tv in tree_kw.items():
            opt[tn] = (tv, P(None))
        names = sorted(opt)

        out_specs = (
            (P(None, None, ax, None),) if q_chunk is not None else ()
        ) + (
            (P(None, None, ax, None),) if q_group is not None else ()
        )

        def sm_tuple(kp, vp, *dyn):
            oc, og = kernel(kp, vp, **dict(zip(names, dyn)))
            return tuple(o for o in (oc, og) if o is not None)

        sm = _shard_map_kernel(
            mesh, sm_tuple,
            in_specs=(pool, pool, *(opt[n][1] for n in names)),
            out_specs=out_specs,
        )
        outs = sm(kp, vp, *(opt[n][0] for n in names))
        it = iter(outs)
        return _shadow((
            next(it) if q_chunk is not None else None,
            next(it) if q_group is not None else None,
        ))

    record_kernel_path("attention_ragged", False)
    return ragged_paged_attention_ref(
        k_pages, v_pages, page_size,
        q_chunk=q_chunk, chunk_row=chunk_row, chunk_start=chunk_start,
        chunk_total=chunk_total, k_chunk=k_chunk, v_chunk=v_chunk,
        q_group=q_group, page_table=page_table,
        group_lengths=group_lengths, k_group=k_group, v_group=v_group,
        layer=layer, logit_softcap=logit_softcap, window=window,
        tree_pos=tree_pos, tree_mask=tree_mask,
    )


def ragged_paged_attention_ref(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_size: int,
    q_chunk: jnp.ndarray | None = None,
    chunk_row: jnp.ndarray | None = None,
    chunk_start: jnp.ndarray | None = None,
    chunk_total: jnp.ndarray | None = None,
    k_chunk: jnp.ndarray | None = None,
    v_chunk: jnp.ndarray | None = None,
    q_group: jnp.ndarray | None = None,
    page_table: jnp.ndarray | None = None,
    group_lengths: jnp.ndarray | None = None,
    k_group: jnp.ndarray | None = None,
    v_group: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
    tree_pos: jnp.ndarray | None = None,
    tree_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray | None, jnp.ndarray | None]:
    """jnp reference for the unified ragged launch — the per-region
    legacy references composed VERBATIM (the fallback leg of
    ragged_paged_attention, and the oracle the KERNELS registry and the
    numerics sanitizer hold the ragged kernel to). Greedy streams stay
    bit-identical ragged-on vs ragged-off on the jnp path because each
    region delegates to the exact legacy reference. Tree verify
    (`tree_pos`/`tree_mask`, ISSUE 18) routes the group region through
    paged_attention_verify_ref's tree branch."""
    out_chunk = out_group = None
    if q_chunk is not None:
        out_chunk = _prefix_chunk_ref(
            q_chunk, k_pages, v_pages, chunk_row, chunk_start, chunk_total,
            page_size, k_cur=k_chunk, v_cur=v_chunk, layer=layer,
            logit_softcap=logit_softcap, window=window,
        )
    if q_group is not None:
        td = q_group.shape[1]
        if tree_pos is not None:
            out_group = paged_attention_verify_ref(
                q_group, k_pages, v_pages, page_table, group_lengths,
                page_size, k_group, v_group, layer=layer,
                logit_softcap=logit_softcap, window=window,
                tree_pos=tree_pos, tree_mask=tree_mask,
            )
        elif td == 1:
            # Td == 1 IS legacy decode — delegate to its reference so the
            # ragged-on jnp path stays bit-identical to ragged-off decode
            kp, vp = k_pages, v_pages
            if kp.ndim == 5:
                li = jnp.int32(0) if layer is None else layer
                if isinstance(kp, QuantPages):
                    kp, vp = kp.layer(li), vp.layer(li)
                else:
                    kp = jax.lax.dynamic_index_in_dim(kp, li,
                                                      keepdims=False)
                    vp = jax.lax.dynamic_index_in_dim(vp, li,
                                                      keepdims=False)
            out_group = paged_attention_decode_ref(
                q_group[:, 0], kp, vp, page_table, group_lengths, page_size,
                k_cur=k_group[:, 0], v_cur=v_group[:, 0],
                logit_softcap=logit_softcap, window=window,
            )[:, None]
        else:
            out_group = paged_attention_verify_ref(
                q_group, k_pages, v_pages, page_table, group_lengths,
                page_size, k_group, v_group, layer=layer,
                logit_softcap=logit_softcap, window=window,
            )
    return out_chunk, out_group


def _softcap(logits: jnp.ndarray, cap: float) -> jnp.ndarray:
    """gemma2's attn_logit_softcapping: cap * tanh(logits / cap), applied
    BEFORE masking (HF Gemma2Attention order)."""
    return cap * jnp.tanh(logits / cap) if cap else logits


def attention_prefill_ref(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Causal self-attention over one self-contained chunk (whole prompt).

    q: [B, T, H, D]; k/v: [B, T, KVH, D]; seq_lens: [B] valid tokens
    (padding keys masked out). Chunked prefill against an existing cached
    prefix is NOT handled here — that variant must read prefix K/V from the
    page pool and will land with the Pallas kernels. Returns [B, T, H, D].

    `logit_softcap`: tanh capping of attention logits (gemma2).
    `window`: sliding-window attention — a query attends keys at distance
    < window only (0 = full causal; may be a traced per-layer scalar).
    """
    b, t, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qf = q.astype(jnp.float32).reshape(b, t, kvh, g, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # [B, KVH, G, Tq, Tk]
    logits = jnp.einsum("btkgd,bskd->bkgts", qf, kf, precision=jax.lax.Precision.HIGHEST) * scale
    logits = _softcap(logits, logit_softcap)

    q_pos = jnp.arange(t)[:, None]  # [Tq, 1]
    k_pos = jnp.arange(t)[None, :]  # [1, Tk]
    causal = q_pos >= k_pos
    w = jnp.asarray(window, jnp.int32)
    in_window = (w <= 0) | (q_pos - k_pos < w)
    valid = k_pos < seq_lens[:, None, None, None, None]
    mask = (causal & in_window)[None, None, None] & valid
    logits = jnp.where(mask, logits, _NEG_INF)

    probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, vf, precision=jax.lax.Precision.HIGHEST)
    return out.reshape(b, t, h, d).astype(q.dtype)


def paged_attention_decode_ref(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    page_size: int,
    k_cur: jnp.ndarray | None = None,
    v_cur: jnp.ndarray | None = None,
    logit_softcap: float = 0.0,
    window: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """One-token-per-slot decode attention against the paged cache.

    q: [S, H, D] (the single new token per slot, post-rope);
    k_pages/v_pages: [P, page_size, KVH, D] (one layer's pool);
    page_table: [S, max_pages]. Without k_cur/v_cur, lengths: [S] valid
    tokens per slot *including* the current token (already written to the
    cache). With k_cur/v_cur ([S, KVH, D]), lengths counts the cached
    prefix only and the current token is overlaid at position lengths[s]
    before attending (pool writes deferred — see paged_attention_decode).
    Returns [S, H, D].

    `logit_softcap`/`window` as in attention_prefill_ref (the current
    token sits at position total-1; keys at distance >= window from it
    are masked).

    Reference implementation: materializes each slot's max context via
    gather. The Pallas kernel (ops/pallas_kernels.py) streams only valid
    pages instead.
    """
    s, h, d = q.shape
    kvh = k_pages.shape[2]
    g = h // kvh
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    merge_cur = k_cur is not None
    if not merge_cur:
        k_cur = jnp.zeros((s, kvh, d), k_pages.dtype)
        v_cur = jnp.zeros((s, kvh, d), v_pages.dtype)
    w = jnp.asarray(window, jnp.int32)

    def one_slot(qi, row, ln, kc, vc):
        ks, vs = gather_kv(k_pages, v_pages, row, page_size)  # [N, KVH, D]
        total = ln
        if merge_cur:
            # current token overlaid at index ln (clamped within capacity;
            # mode="drop" guards the full-capacity edge, where the caller
            # has already finished the slot)
            ks = ks.at[ln].set(kc, mode="drop")
            vs = vs.at[ln].set(vc, mode="drop")
            total = ln + 1
        qf = qi.astype(jnp.float32).reshape(kvh, g, d)
        logits = jnp.einsum("kgd,nkd->kgn", qf, ks.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST) * scale
        logits = _softcap(logits, logit_softcap)
        k_pos = jnp.arange(ks.shape[0])
        valid = k_pos < total
        valid &= (w <= 0) | ((total - 1) - k_pos < w)
        logits = jnp.where(valid[None, None, :], logits, _NEG_INF)
        probs = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        return jnp.einsum("kgn,nkd->kgd", probs, vs.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST).reshape(h, d)

    out = jax.vmap(one_slot)(q, page_table, lengths, k_cur, v_cur)
    return out.astype(q.dtype)
