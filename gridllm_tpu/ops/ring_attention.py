"""Ring attention: sequence-parallel causal prefill over the "sp" mesh axis.

Long-context design (SURVEY.md §5.7: the reference's only long-context
story was forwarding `num_ctx` to Ollama; sequence parallelism is new
capability). The sequence dimension is sharded across sp devices; each
device keeps its Q chunk resident and the K/V chunks rotate around the
ring via `jax.lax.ppermute` (neighbour hops ride ICI — mesh.py puts "sp"
innermost so ring neighbours are ICI-adjacent). Online-softmax merging
makes the result exactly equal to full causal attention: per rotation
step each device folds one K/V chunk into its running (max, denom, acc)
triple, fp32 throughout.

Communication cost: n-1 neighbour exchanges of the local K/V chunk
(2·T/n·KVH·D each) fully overlappable with the chunk's attention math;
peak memory is O(T/n) per device instead of the O(T) an all-gather of
K/V would need — the property that makes million-token contexts feasible
(PAPERS.md ring/blockwise attention — pattern reference only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gridllm_tpu.ops.kvcache import _shard_map_kernel

_NEG_INF = -1e30


def _chunk_attention(q, k, v, q_start, k_start, seq_lens, carry):
    """Fold one K/V chunk into the online-softmax carry.

    q: [B, C, KVH, G, D] (fp32, pre-scaled); k/v: [B, C, KVH, D];
    q_start/k_start: scalar global offsets of the chunks;
    carry: (m [B,C,KVH,G,1], l [B,C,KVH,G,1], acc [B,C,KVH,G,D]).
    """
    m, l, acc = carry
    # fp32 by the caller's contract (q pre-scaled, carries f32); the casts
    # are no-ops there and enforce the policy for any other caller
    q = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    c = q.shape[1]
    logits = jnp.einsum(
        "btkgd,bskd->btkgs", q, k, precision=jax.lax.Precision.HIGHEST
    )  # [B, Cq, KVH, G, Ck]
    q_pos = q_start + jnp.arange(c)[:, None, None, None]        # [Cq,1,1,1]
    k_pos = k_start + jnp.arange(c)[None, None, None, :]        # [1,1,1,Ck]
    valid = k_pos < seq_lens[:, None, None, None, None]
    mask = (q_pos >= k_pos)[None] & valid
    logits = jnp.where(mask, logits, _NEG_INF)

    m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "btkgs,bskd->btkgd", p, v, precision=jax.lax.Precision.HIGHEST
    )
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,
    mesh: Mesh,
) -> jnp.ndarray:
    """Causal GQA attention with the T axis sharded over mesh axis "sp".

    Same contract as ops.attention.attention_prefill: q [B, T, H, D],
    k/v [B, T, KVH, D], seq_lens [B] → [B, T, H, D]. T must divide by
    sp. Callable inside jit; sharding constraints are applied here so the
    caller does not need pre-sharded operands.
    """
    n = mesh.shape["sp"]
    b, t, h, d = q.shape
    if n == 1 or t % n:
        # sp=1, or a bucket too ragged to split (trace-time check; every
        # standard prefill bucket divides by sp <= 64)
        if n > 1:
            import warnings

            warnings.warn(
                f"ring_attention: T={t} not divisible by sp={n}; falling "
                "back to full (quadratic-memory) attention for this bucket "
                "— fix the prefill bucket sizes", stacklevel=2,
            )
        from gridllm_tpu.ops.attention import attention_prefill_ref

        return attention_prefill_ref(q, k, v, seq_lens)

    kvh = k.shape[2]
    g = h // kvh
    c = t // n
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    # also split kv heads over "tp" when divisible — without this a tp x sp
    # mesh would all-gather heads at the shard_map boundary and compute all
    # H heads on every tp device (tp-fold redundant attention FLOPs)
    tp = mesh.shape["tp"]
    head_ax = "tp" if (tp > 1 and kvh % tp == 0) else None

    def local(q_loc, k_loc, v_loc, lens):
        # q_loc: [B, C, H/tp, D]; k_loc/v_loc: [B, C, KVH/tp, D]; lens: [B]
        i = jax.lax.axis_index("sp")
        kvh_l = k_loc.shape[2]
        qf = (q_loc.astype(jnp.float32) * scale).reshape(b, c, kvh_l, g, d)
        m = jnp.full((b, c, kvh_l, g, 1), _NEG_INF, jnp.float32)
        l = jnp.zeros((b, c, kvh_l, g, 1), jnp.float32)
        acc = jnp.zeros((b, c, kvh_l, g, d), jnp.float32)
        kv = (k_loc.astype(jnp.float32), v_loc.astype(jnp.float32))
        perm = [(p, (p + 1) % n) for p in range(n)]

        carry = (m, l, acc)
        for step in range(n):
            j = (i - step) % n  # chunk id this device currently holds
            carry = _chunk_attention(
                qf, kv[0], kv[1], i * c, j * c, lens, carry
            )
            if step != n - 1:
                # rotate AFTER compute so the transfer overlaps the next
                # step's math under XLA's async collectives
                kv = jax.lax.ppermute(kv, "sp", perm)
        _, l, acc = carry
        out = acc / jnp.maximum(l, 1e-30)
        return out.reshape(b, c, kvh_l * g, d).astype(q_loc.dtype)

    # routed through the version-resolving wrapper (jax.shard_map/check_vma
    # vs experimental shard_map/check_rep — ppermute's value motion defeats
    # the replication check either way)
    sm = _shard_map_kernel(
        mesh, local,
        in_specs=(
            P(None, "sp", head_ax),
            P(None, "sp", head_ax),
            P(None, "sp", head_ax),
            P(),
        ),
        out_specs=P(None, "sp", head_ax),
    )
    return sm(q, k, v, seq_lens.astype(jnp.int32))
