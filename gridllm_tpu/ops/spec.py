"""Speculative-decoding drafters (ISSUE 5).

A drafter proposes up to K candidate continuation tokens for one slot from
host-visible state (the slot's full token history, prompt + generated).
The engine verifies all K in ONE batched model forward (llama.verify_step)
and keeps the longest accepted prefix plus one corrected token — so a
drafter never affects *what* is generated, only how many model forwards it
takes (greedy streams are byte-identical spec-on vs spec-off; sampled
streams keep the rejection-sampled target distribution, ops/sampling.py
spec_accept).

Phase 1 is model-free **prompt-lookup / n-gram drafting** (arXiv:2304.04487
-class): match the last n tokens of the slot's history against the earlier
history (prompt included) and propose the continuation that followed the
most recent occurrence. It costs no extra checkpoint, runs on CPU tier-1,
and wins exactly where decode is most wasteful — repetitive/templated
output (code edits, extraction, "repeat the policy clause" workloads),
where acceptance routinely exceeds 50%. On novel text it degrades to
proposing nothing, which the engine handles as a plain decode step.

Phase 2 (ISSUE 18) is **model-based drafting + token trees**: a tiny
same-family draft model (DraftModelDrafter) loaded next to the target,
sharing the device mesh, runs one batched catch-up forward plus K greedy
decode steps per verify step against its own small paged-KV pool, and
emits a STATIC-topology token tree — a depth-K greedy chain plus
(width-1) first-level sibling alternatives whose logits come free from
the first draft step. The tree's parent/depth/ancestor arrays are fixed
per process (tree_topology), so every verify shape stays static and the
recompile tripwire stays green; per-slot raggedness travels as a boolean
node-validity mask (data, not shape). n-gram remains the default and the
fallback whenever no draft model is configured (GRIDLLM_SPEC_DRAFT_MODEL
empty) or the configured one is incompatible with the target.

The interface is deliberately tiny: the engine calls `draft(ids, k)` per
slot (chain drafters) or `draft_batch(ids_by_slot, k, width)` (tree
drafters, batched over all slots in one device dispatch).
"""

from __future__ import annotations

import time
from typing import Protocol, Sequence

import numpy as np

from gridllm_tpu.utils.config import env_int, env_str


class Drafter(Protocol):
    """One method: propose up to k likely next tokens for a slot."""

    def draft(self, ids: Sequence[int], k: int) -> list[int]:
        """ids: the slot's full context so far (prompt + generated, oldest
        first; the LAST element is the most recent emitted token). Returns
        0..k proposed continuation tokens — an empty list means "no
        proposal", which the engine runs as a normal decode step."""
        ...


class NgramDrafter:
    """Prompt-lookup drafting: longest-suffix n-gram match over the slot's
    own history.

    For n from `max_n` down to `min_n`, find the most recent earlier
    occurrence of the history's last-n tokens and propose the tokens that
    followed it. Longest match first — a longer matched context is a
    stronger predictor, and the first hit wins (most recent occurrence, the
    llama.cpp/vLLM prompt-lookup convention).

    `lookback` bounds how far back the scan walks (0 = the whole history);
    worst case is O(max_n × min(len, lookback)) per call, a few µs at chat
    context lengths — noise next to a model forward.
    """

    kind = "ngram"

    def __init__(self, max_n: int = 4, min_n: int = 1, lookback: int = 0):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"bad n-gram range [{min_n}, {max_n}]")
        self.max_n = max_n
        self.min_n = min_n
        self.lookback = max(lookback, 0)

    def draft(self, ids: Sequence[int], k: int) -> list[int]:
        ids = list(ids)
        n_ids = len(ids)
        if k <= 0 or n_ids < self.min_n + 1:
            return []
        lo = 0 if not self.lookback else max(n_ids - self.lookback, 0)
        for n in range(min(self.max_n, n_ids - 1), self.min_n - 1, -1):
            suffix = ids[n_ids - n:]
            # most recent occurrence strictly before the suffix itself
            for i in range(n_ids - n - 1, lo - 1, -1):
                if ids[i : i + n] == suffix:
                    cont = ids[i + n : i + n + k]
                    if cont:
                        return cont
                    break  # suffix only recurs at the very end — shorter n
        return []


def make_drafter(kind: str | None = None) -> Drafter:
    """Host-only drafter factory (env-pluggable): GRIDLLM_SPEC_DRAFTER
    selects the implementation ("ngram"), GRIDLLM_SPEC_NGRAM_MAX / _MIN /
    GRIDLLM_SPEC_LOOKBACK tune the matcher. The model-based drafter is
    NOT built here — it needs the engine's mesh/dtype/loader context, so
    the engine constructs DraftModelDrafter directly and falls back to
    this factory when no draft model is configured."""
    kind = kind or env_str("GRIDLLM_SPEC_DRAFTER")
    if kind == "ngram":
        return NgramDrafter(
            max_n=env_int("GRIDLLM_SPEC_NGRAM_MAX"),
            min_n=env_int("GRIDLLM_SPEC_NGRAM_MIN"),
            lookback=env_int("GRIDLLM_SPEC_LOOKBACK"),
        )
    raise ValueError(f"unknown drafter: {kind!r}")


# ---------------------------------------------------------------------------
# token-tree topology (ISSUE 18)
# ---------------------------------------------------------------------------
#
# A draft tree is N nodes in topological order (parents[i] < i). Node 0 is
# the ROOT: the committed last token, matching column 0 of the chain verify
# block — its KV lags the pool exactly like a decode step's input token.
# Nodes 1..N-1 carry drafted tokens; node i's KV is written optimistically
# at storage position base + i, while its ROPE/logical position is
# base + depth[i]. The topology is FIXED per process (depth-K greedy chain
# at nodes 1..K, first-level siblings at K+1..N-1, all children of the
# root), so parents/depth/ancestor arrays are jit-time constants and only
# the per-slot node-validity mask is runtime data.


def tree_depths(parents: np.ndarray) -> np.ndarray:
    """Node depths from a topological parent array (parents[0] == -1,
    parents[i] < i). Root depth 0."""
    n = len(parents)
    depth = np.zeros(n, np.int32)
    for i in range(1, n):
        p = int(parents[i])
        if not 0 <= p < i:
            raise ValueError(f"parents must be topological; node {i} -> {p}")
        depth[i] = depth[p] + 1
    return depth


def tree_ancestor_mask(parents: np.ndarray) -> np.ndarray:
    """[N, N] bool: anc[i, j] iff node j is an ancestor of i OR i itself —
    exactly the key columns node i's query row may attend inside the
    candidate block (the root-to-i path IS the sequential prefix)."""
    n = len(parents)
    anc = np.zeros((n, n), bool)
    for i in range(n):
        j = i
        while j >= 0:
            anc[i, j] = True
            j = int(parents[j])
    return anc


def tree_ancestor_bits(parents: np.ndarray) -> np.ndarray:
    """The ancestor mask packed row-wise into int32 bitmasks (bit j of
    entry i = anc[i, j]) — the SMEM-friendly form the Pallas ragged
    kernel's group region consumes. Node budget therefore caps at 32."""
    anc = tree_ancestor_mask(parents)
    n = len(parents)
    if n > 32:
        raise ValueError(f"tree node budget {n} > 32 (bitmask packing)")
    bits = np.zeros(n, np.int32)
    for i in range(n):
        for j in range(n):
            if anc[i, j]:
                bits[i] |= 1 << j
    return bits


def tree_topology(k: int, width: int) -> np.ndarray:
    """The process-static draft topology: a depth-`k` chain (nodes 1..k,
    each the child of the previous) plus `width - 1` extra first-level
    alternatives (children of the root — their logits come free from the
    draft model's first decode step). width == 1 is the pure chain;
    k == 0 degenerates to the root alone."""
    if k < 0 or width < 1:
        raise ValueError(f"bad tree shape k={k} width={width}")
    parents = [-1] + list(range(k)) + [0] * (width - 1 if k else 0)
    return np.asarray(parents, np.int32)


class DraftModelDrafter:
    """Model-based drafting (ISSUE 18): a tiny same-family draft model with
    its own small paged-KV pool, batched over all slots.

    Per engine verify step the drafter (1) diffs each slot's host context
    against what its draft cache has consumed and rolls the cache back to
    the common prefix (pure length bookkeeping — rejected speculation and
    corrections rewind for free), (2) ingests the new tokens in fixed-width
    catch-up chunks through the draft model's verify forward, and (3) runs
    K greedy decode steps emitting the chain plus the top-(width-1)
    first-step alternatives. Drafted tokens' KV stays in the draft pool
    optimistically: accepted tokens are identical tokens at identical
    positions, so the next call's common-prefix diff keeps their KV and
    only mispredictions re-ingest.

    All device work happens in exactly two jitted programs with static
    shapes (one catch-up width, one draft depth), so the recompile
    tripwire stays green; slots whose context outgrows the draft pool
    simply stop proposing (the engine then runs plain verify steps).
    """

    kind = "model"
    tree = True

    def __init__(self, mod, cfg, params, *, max_slots: int, page_size: int,
                 max_pages_per_slot: int, mesh=None, ingest_width: int = 64,
                 dtype=None, wrap=None):
        import jax
        import jax.numpy as jnp

        from gridllm_tpu.ops.kvcache import PagedKVCache, rollback_to_length

        self.mod, self.cfg, self.params = mod, cfg, params
        self.mesh = mesh
        self.max_slots = max_slots
        self.page_size = page_size
        self.draft_ns = 0  # cumulative host wall time inside draft_batch
        self._w = max(int(ingest_width), 1)
        # every slot owns a fixed page stripe — no allocator, the table is
        # a constant (the draft pool is tiny; simplicity beats packing)
        table = np.arange(max_slots * max_pages_per_slot, dtype=np.int32)
        table = table.reshape(max_slots, max_pages_per_slot)
        self.max_context = min(cfg.max_seq_len,
                               max_pages_per_slot * page_size)

        def _new_cache():
            cache = PagedKVCache.create(
                cfg.num_layers, max_slots * max_pages_per_slot, page_size,
                cfg.num_kv_heads, cfg.head_dim_, max_slots,
                max_pages_per_slot,
                dtype=jnp.dtype(dtype) if dtype is not None
                else jnp.bfloat16,
            )
            cache = PagedKVCache(
                k=cache.k, v=cache.v,
                page_table=jnp.asarray(table, dtype=jnp.int32),
                lengths=cache.lengths, page_size=page_size,
            )
            if mesh is not None:
                from gridllm_tpu.parallel.sharding import shard_cache
                cache = shard_cache(cache, mesh)
            return cache

        self._new_cache = _new_cache
        self.cache = _new_cache()
        # host-side per-slot view of what the draft pool holds: the token
        # prefix whose KV is valid (possibly AHEAD of the engine thanks to
        # optimistic draft writes)
        self._ctx: list[list[int]] = [[] for _ in range(max_slots)]

        from functools import partial

        @partial(jax.jit, donate_argnums=(1,))
        def ingest_fn(params, cache, tokens, tlen, lengths, active):
            # fixed-width catch-up chunk: consume `tlen` new tokens per
            # slot (right-padded to the static width), writing their KV
            cache = PagedKVCache(
                k=cache.k, v=cache.v, page_table=cache.page_table,
                lengths=lengths, page_size=page_size,
            )
            logits, cache = mod.verify_step(
                params, cfg, tokens, cache, active, mesh=mesh)
            cache = rollback_to_length(
                cache, jnp.minimum(cache.lengths + tlen, self.max_context))
            # the chunk's last valid row IS the next-token distribution
            last = jnp.take_along_axis(
                logits, jnp.maximum(tlen - 1, 0)[:, None, None], axis=1
            )[:, 0]
            return last, cache

        @partial(jax.jit, static_argnames=("k", "width"),
                 donate_argnums=(1,))
        def draft_fn(params, cache, last_logits, active, *, k, width):
            # K greedy steps from the catch-up logits; the first step's
            # top-`width` alternatives ride along (alts[:, 0] == chain[0])
            alts = jax.lax.top_k(last_logits, width)[1].astype(jnp.int32)
            tok = alts[:, 0]
            chain = [tok]
            for _ in range(k - 1):
                logits, cache = mod.decode_step(
                    params, cfg, tok, cache, active, mesh=mesh)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                chain.append(tok)
            return jnp.stack(chain, axis=1), alts, cache

        wrap = wrap or (lambda name, fn: fn)
        self._ingest_fn = wrap("draft_ingest", ingest_fn)
        self._draft_fn = wrap("draft_step", draft_fn)

    def reset_slot(self, slot: int) -> None:
        """Invalidate a slot's draft context (request finished/replaced)."""
        self._ctx[slot] = []

    def reset(self) -> None:
        """Rebuild the draft pool wholesale. The jitted entries donate
        the cache, so an exception mid-call can leave self.cache
        referencing deleted buffers — same failure mode as the engine's
        reset_device_state, which calls this alongside its own rebuild."""
        self.cache = self._new_cache()
        self._ctx = [[] for _ in range(self.max_slots)]

    def draft(self, ids: Sequence[int], k: int) -> list[int]:
        """Drafter-protocol chain compatibility: slot-0 batched call."""
        out = self.draft_batch({0: list(ids)}, k, 1)
        return out.get(0, ([], []))[0]

    def draft_batch(
        self, ids_by_slot: dict[int, list[int]], k: int, width: int,
    ) -> dict[int, tuple[list[int], list[int]]]:
        """One batched draft pass. Returns per slot (chain tokens ≤ k,
        first-level alternative tokens ≤ width-1). Slots that would
        overflow the draft pool (or were not asked for) are absent."""
        import jax
        import numpy as _np

        t0 = time.perf_counter_ns()
        s = self.max_slots
        live: list[int] = []
        for slot, ids in ids_by_slot.items():
            # +k: the decode steps write chain[0..k-2] past the context;
            # +1 headroom for the padded ingest chunk's junk tail
            if len(ids) + k + 1 > self.max_context or not ids:
                self._ctx[slot] = []
                continue
            live.append(slot)
        if not live or k <= 0:
            self.draft_ns += time.perf_counter_ns() - t0
            return {}

        # host diff: longest common prefix between the draft pool's view
        # and the engine's context decides the rollback point
        base = _np.zeros(s, _np.int32)
        todo: dict[int, list[int]] = {}
        for slot in live:
            ids = ids_by_slot[slot]
            ctx = self._ctx[slot]
            n = 0
            for a, b in zip(ctx, ids):
                if a != b:
                    break
                n += 1
            base[slot] = n
            todo[slot] = ids[n:]
            self._ctx[slot] = list(ids)  # consumed after the catch-up

        active_np = _np.zeros(s, bool)
        for slot in live:
            active_np[slot] = True
        active = jax.numpy.asarray(active_np)

        # fixed-width catch-up chunks; all but the final chunk only write
        # KV, the final chunk's last-row logits seed the draft chain
        w = self._w
        rounds = max((max(len(v) for v in todo.values()) + w - 1) // w, 1)
        last_logits = None
        for r in range(rounds):
            toks = _np.zeros((s, w), _np.int32)
            tlen = _np.zeros(s, _np.int32)
            for slot in live:
                seg = todo[slot][r * w:(r + 1) * w]
                if not seg:
                    # already caught up (optimistic draft KV matched, or a
                    # later round for a short slot): re-feed the final
                    # token so this chunk still yields next-token logits
                    seg = [self._ctx[slot][-1]]
                    base[slot] -= 1
                toks[slot, :len(seg)] = seg
                tlen[slot] = len(seg)
            last_logits, self.cache = self._ingest_fn(
                self.params, self.cache, jax.numpy.asarray(toks),
                jax.numpy.asarray(tlen), jax.numpy.asarray(base + 0),
                active)
            base += tlen
        chain, alts, self.cache = self._draft_fn(
            self.params, self.cache, last_logits, active,
            k=k, width=max(width, 1))
        chain = _np.asarray(jax.device_get(chain))
        alts = _np.asarray(jax.device_get(alts))
        out: dict[int, tuple[list[int], list[int]]] = {}
        for slot in live:
            ch = [int(t) for t in chain[slot]]
            # the decode steps consumed chain[:-1] and wrote their KV
            self._ctx[slot] = self._ctx[slot] + ch[:-1]
            out[slot] = (ch, [int(t) for t in alts[slot][1:]])
        self.draft_ns += time.perf_counter_ns() - t0
        return out
