"""Speculative-decoding drafters (ISSUE 5).

A drafter proposes up to K candidate continuation tokens for one slot from
host-visible state (the slot's full token history, prompt + generated).
The engine verifies all K in ONE batched model forward (llama.verify_step)
and keeps the longest accepted prefix plus one corrected token — so a
drafter never affects *what* is generated, only how many model forwards it
takes (greedy streams are byte-identical spec-on vs spec-off; sampled
streams keep the rejection-sampled target distribution, ops/sampling.py
spec_accept).

Phase 1 is model-free **prompt-lookup / n-gram drafting** (arXiv:2304.04487
-class): match the last n tokens of the slot's history against the earlier
history (prompt included) and propose the continuation that followed the
most recent occurrence. It costs no extra checkpoint, runs on CPU tier-1,
and wins exactly where decode is most wasteful — repetitive/templated
output (code edits, extraction, "repeat the policy clause" workloads),
where acceptance routinely exceeds 50%. On novel text it degrades to
proposing nothing, which the engine handles as a plain decode step.

The interface is deliberately tiny so a small draft *model* can land later
as another Drafter implementation without touching the engine: the engine
only ever calls `draft(ids, k)` per slot between verify steps.
"""

from __future__ import annotations

from typing import Protocol, Sequence

from gridllm_tpu.utils.config import env_int, env_str


class Drafter(Protocol):
    """One method: propose up to k likely next tokens for a slot."""

    def draft(self, ids: Sequence[int], k: int) -> list[int]:
        """ids: the slot's full context so far (prompt + generated, oldest
        first; the LAST element is the most recent emitted token). Returns
        0..k proposed continuation tokens — an empty list means "no
        proposal", which the engine runs as a normal decode step."""
        ...


class NgramDrafter:
    """Prompt-lookup drafting: longest-suffix n-gram match over the slot's
    own history.

    For n from `max_n` down to `min_n`, find the most recent earlier
    occurrence of the history's last-n tokens and propose the tokens that
    followed it. Longest match first — a longer matched context is a
    stronger predictor, and the first hit wins (most recent occurrence, the
    llama.cpp/vLLM prompt-lookup convention).

    `lookback` bounds how far back the scan walks (0 = the whole history);
    worst case is O(max_n × min(len, lookback)) per call, a few µs at chat
    context lengths — noise next to a model forward.
    """

    def __init__(self, max_n: int = 4, min_n: int = 1, lookback: int = 0):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"bad n-gram range [{min_n}, {max_n}]")
        self.max_n = max_n
        self.min_n = min_n
        self.lookback = max(lookback, 0)

    def draft(self, ids: Sequence[int], k: int) -> list[int]:
        ids = list(ids)
        n_ids = len(ids)
        if k <= 0 or n_ids < self.min_n + 1:
            return []
        lo = 0 if not self.lookback else max(n_ids - self.lookback, 0)
        for n in range(min(self.max_n, n_ids - 1), self.min_n - 1, -1):
            suffix = ids[n_ids - n:]
            # most recent occurrence strictly before the suffix itself
            for i in range(n_ids - n - 1, lo - 1, -1):
                if ids[i : i + n] == suffix:
                    cont = ids[i + n : i + n + k]
                    if cont:
                        return cont
                    break  # suffix only recurs at the very end — shorter n
        return []


def make_drafter(kind: str | None = None) -> Drafter:
    """Drafter factory (env-pluggable): GRIDLLM_SPEC_DRAFTER selects the
    implementation ("ngram" is the only phase-1 option; a draft-model
    drafter slots in here later), GRIDLLM_SPEC_NGRAM_MAX / _MIN /
    GRIDLLM_SPEC_LOOKBACK tune the n-gram matcher."""
    kind = kind or env_str("GRIDLLM_SPEC_DRAFTER")
    if kind == "ngram":
        return NgramDrafter(
            max_n=env_int("GRIDLLM_SPEC_NGRAM_MAX"),
            min_n=env_int("GRIDLLM_SPEC_NGRAM_MIN"),
            lookback=env_int("GRIDLLM_SPEC_LOOKBACK"),
        )
    raise ValueError(f"unknown drafter: {kind!r}")
