"""KERNELS: the typed Pallas-kernel registry (gridcheck v3, ISSUE 14).

Every Pallas kernel in ``ops/pallas_kernels.py`` is declared here ONCE —
with the jnp reference that is its numerical oracle, the
``gridllm_kernel_dispatch_total`` label its dispatcher records under, the
tolerance its differential test (and the runtime numerics sanitizer,
``analysis/numcheck.py``) holds it to, and the named test that owns the
kernel-vs-reference differential. The ``kernel-parity`` analyzer rule
cross-checks all of it both ways: an unregistered ``pl.pallas_call``
site, a registered kernel whose reference or test went missing, a
dispatch label the registry doesn't know (or vice versa), and drift in
the README "Kernels" table are each a ``--strict`` failure.

This mirrors the ``ENV_VARS`` (utils/config.py) and ``CHANNELS``
(bus/base.py) pattern: pure data, importable without jax, parsed from
the AST by the rule so ``--root`` on another checkout validates THAT
checkout's registry.

Tolerances are the BF16-input bound (the loosest dtype the serving path
feeds the kernels); f32 differential tests pass far inside it. The two
KV-write kernels are data movement, not math — their oracle is the
scatter form and the bound is exact (0); the numerics sanitizer covers
them with the NaN/Inf tripwire instead of value shadowing.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One Pallas kernel's parity contract."""

    name: str         # public entry fn in ops/pallas_kernels.py
    reference: str    # "module:function" jnp oracle under gridllm_tpu/ops/
    dispatch: str     # gridllm_kernel_dispatch_total op label
    rtol: float       # differential-test / numcheck relative tolerance
    atol: float       # ... absolute tolerance
    test: str         # "tests/file.py::test_name" owning differential test
    description: str


KERNELS: tuple[KernelSpec, ...] = (
    KernelSpec(
        name="flash_prefill",
        reference="attention:attention_prefill_ref",
        dispatch="attention_prefill",
        rtol=3e-2, atol=3e-2,
        test="tests/test_pallas.py::test_flash_prefill_matches_ref",
        description="causal GQA flash attention over one prompt chunk, "
                    "K/V VMEM-resident per kv head",
    ),
    KernelSpec(
        name="flash_prefill_streamed",
        reference="attention:attention_prefill_ref",
        dispatch="attention_prefill",
        rtol=3e-2, atol=3e-2,
        test="tests/test_pallas.py::test_flash_prefill_streamed_matches_ref",
        description="flash prefill past the VMEM budget: K/V blocks "
                    "stream from HBM as a grid dimension",
    ),
    KernelSpec(
        name="paged_decode",
        reference="attention:paged_attention_decode_ref",
        dispatch="attention_decode",
        rtol=3e-2, atol=3e-2,
        test="tests/test_pallas.py::test_paged_decode_matches_ref",
        description="one-token decode attention against the HBM page "
                    "pool, double-buffered page DMA",
    ),
    KernelSpec(
        name="prefix_chunk",
        reference="attention:_prefix_chunk_ref",
        dispatch="attention_prefix_chunk",
        rtol=3e-2, atol=3e-2,
        test="tests/test_pallas.py::test_prefix_chunk_kernel_matches_jnp",
        description="chunked-prefill attention: prefix pages streamed "
                    "from HBM, the chunk's own K/V resident",
    ),
    KernelSpec(
        name="ragged_attention",
        reference="attention:ragged_paged_attention_ref",
        dispatch="attention_ragged",
        rtol=3e-2, atol=3e-2,
        test="tests/test_ragged_attention.py::"
             "test_ragged_kernel_mixed_batch_matches_ref",
        description="unified ragged paged attention: one launch serving "
                    "chunked prefill, decode, and spec-verify tiles "
                    "(int8 pools via the dequant epilogue)",
    ),
    KernelSpec(
        name="paged_write_decode",
        reference="kvcache:write_decode",
        dispatch="write_decode",
        rtol=0.0, atol=0.0,
        test="tests/test_pallas.py::test_paged_write_decode_matches_scatter",
        description="in-place per-row KV pool write (decode / flattened "
                    "spec-verify rows), DMA instead of XLA scatter",
    ),
    KernelSpec(
        name="paged_write_chunk",
        reference="kvcache:write_prefill",
        dispatch="write_prefill",
        rtol=0.0, atol=0.0,
        test="tests/test_pallas.py::"
             "test_paged_write_chunk_matches_scatter_valid_region",
        description="in-place whole-page KV pool write for one slot's "
                    "prefill chunk, all layers",
    ),
)

# Dispatch labels with NO kernel of their own: jnp-only dispatchers whose
# kernel leg routes through another registered kernel (verify loops over
# prefix_chunk per slot; write_multi flattens onto paged_write_decode).
# The kernel-parity rule requires the union of KERNELS dispatch labels
# and this table to equal the set of record_kernel_path(...) literals in
# ops/ exactly, both ways.
EXTRA_DISPATCH_LABELS: dict[str, str] = {
    "attention_verify": "per-slot loop over the prefix_chunk kernel "
                        "(a fused tree-verify kernel can replace it "
                        "without touching callers)",
    "write_multi": "multi-token append flattened onto paged_write_decode",
}


def kernel_names() -> tuple[str, ...]:
    return tuple(k.name for k in KERNELS)


def dispatch_labels() -> frozenset[str]:
    """Every legal gridllm_kernel_dispatch_total op label."""
    return frozenset(k.dispatch for k in KERNELS) | frozenset(
        EXTRA_DISPATCH_LABELS)


def by_dispatch(label: str) -> tuple[KernelSpec, ...]:
    return tuple(k for k in KERNELS if k.dispatch == label)


def tolerance(label: str) -> tuple[float, float]:
    """(rtol, atol) the numerics sanitizer applies to a dispatch label —
    the loosest bound among the kernels sharing it (they share a
    reference when they share a label)."""
    specs = by_dispatch(label)
    if not specs:
        raise KeyError(f"unknown kernel dispatch label {label!r}")
    return (max(k.rtol for k in specs), max(k.atol for k in specs))
