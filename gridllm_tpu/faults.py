"""Deterministic fault injection (ISSUE 9).

Every failure path the fault-tolerance machinery claims to survive —
worker loss, bus loss, transfer failure, allocator pressure, engine step
crashes — is reachable through a SEEDED, site-keyed injection layer, so
chaos scenarios are reproducible test cases instead of bespoke
process-kill scripts.

Spec grammar (``GRIDLLM_FAULT_SPEC``, comma-separated)::

    site=P        inject with probability P (0..1) per call, drawn from a
                  per-site RNG seeded by (GRIDLLM_FAULT_SEED, site) —
                  the decision SEQUENCE is a pure function of the seed
    site=@N       inject exactly the Nth call to the site (1-based)
    site=@N+      inject every call from the Nth on

Sites are fixed (``SITES``) so a typo'd site name fails loudly at spec
parse instead of silently injecting nothing:

    bus.publish       raise from the bus publish path (message never sent)
    bus.deliver       drop a delivered message before its handler runs
    kvx.send          fail a KV-migration send (sender falls back locally)
    kvx.import        fail a KV-migration import (receiver NACKs)
    alloc.alloc       simulate KV page-pool exhaustion (alloc returns None)
    kvtier.spill      skip a host-tier page spill (the evicted page is
                      simply lost from the tier — a later match is a miss)
    kvtier.restore    fail a host-tier page restore (the admission
                      degrades to a cold prefill — counted miss, never a
                      wedged request)
    worker.heartbeat  skip one worker heartbeat (key not refreshed)
    engine.step       raise from the engine runner's pump (step-failure
                      recovery: abort + device-state rebuild)
    broker.accept     gridbus drops an accepted connection before reading
                      a byte (dying / conn-table-exhausted broker)
    broker.reply      gridbus writes half a reply then resets the
                      connection (crash mid-reply; clients must abandon
                      the torn reply stream, never resync into it)
    broker.fsync      gridbus AOF fsync stalls, freezing the broker event
                      loop the way a saturated disk does
    probe.issue       raise from the canary prober before a probe is
                      submitted (the round is counted as an error, never
                      a golden-hash verdict)
    health.baseline   drop one baseline observation before it reaches the
                      EWMA detector (a deaf detector round)
    swap.load         raise from the worker's admin load path before the
                      engine is constructed (the op reports ok=false and
                      no half-built engine survives)
    swap.unload       raise from the worker's admin unload path before
                      the engine is torn down (the op reports ok=false;
                      the model stays resident and servable)
    swap.snapshot_restore  fail a host-RAM weight-snapshot restore (the
                      load degrades to the disk/init path — slower,
                      never a wedged request)

The hot-path cost with no spec configured is one module-global boolean
check. Tests drive the layer through :func:`configure` directly; the env
spec exists for chaos runs against real deployments (CI ``fault-smoke``).
"""

from __future__ import annotations

import random
import threading

from gridllm_tpu.obs import default_registry
from gridllm_tpu.utils.config import env_int, env_str

SITES = (
    "bus.publish",
    "bus.deliver",
    "kvx.send",
    "kvx.import",
    "alloc.alloc",
    "kvtier.spill",
    "kvtier.restore",
    "worker.heartbeat",
    "engine.step",
    "broker.accept",
    "broker.reply",
    "broker.fsync",
    "probe.issue",
    "health.baseline",
    "swap.load",
    "swap.unload",
    "swap.snapshot_restore",
)

_INJECTED = default_registry().counter(
    "gridllm_faults_injected_total",
    "Deterministic fault injections fired, by site (faults.py). Nonzero "
    "outside a chaos run means GRIDLLM_FAULT_SPEC is live in production.",
    ("site",),
)


class InjectedFault(RuntimeError):
    """Raised by raise-style sites; spelled out in error messages so a
    chaos run's failure paths are distinguishable from organic ones."""


class _Site:
    __slots__ = ("mode", "arg", "rng", "calls")

    def __init__(self, mode: str, arg: float, seed: int, name: str):
        self.mode = mode          # "p" | "at" | "from"
        self.arg = arg
        # per-site stream: decisions depend only on (seed, site, call #)
        self.rng = random.Random(f"{seed}|{name}")
        self.calls = 0

    def fire(self) -> bool:
        self.calls += 1
        if self.mode == "p":
            return self.rng.random() < self.arg
        if self.mode == "at":
            return self.calls == int(self.arg)
        return self.calls >= int(self.arg)  # "from"


def parse_spec(spec: str, seed: int) -> dict[str, _Site]:
    """Parse a fault spec; raises ValueError on unknown sites or malformed
    entries (a chaos knob that silently injects nothing is worse than a
    loud startup failure)."""
    table: dict[str, _Site] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"fault spec entry {entry!r}: expected site=value")
        site, _, val = entry.partition("=")
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {', '.join(SITES)})")
        val = val.strip()
        if val.startswith("@"):
            body = val[1:]
            mode = "from" if body.endswith("+") else "at"
            body = body.rstrip("+")
            n = int(body)
            if n < 1:
                raise ValueError(f"fault spec {entry!r}: call index is 1-based")
            table[site] = _Site(mode, float(n), seed, site)
        else:
            p = float(val)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"fault spec {entry!r}: probability not in [0, 1]")
            table[site] = _Site("p", p, seed, site)
    return table


# Module state: _armed is the one-boolean hot-path gate; _table holds the
# per-site decision state. _loaded gates the lazy env read so a process
# that never sets GRIDLLM_FAULT_SPEC pays nothing beyond the flag check.
_lock = threading.Lock()
_armed = False
_loaded = False
_table: dict[str, _Site] = {}


def configure(spec: str | None, seed: int = 0) -> None:
    """Install a fault spec programmatically (tests / chaos harnesses).
    ``None`` or "" disarms. Replaces any env-derived state."""
    global _armed, _loaded, _table
    with _lock:
        _table = parse_spec(spec, seed) if spec else {}
        _armed = bool(_table)
        _loaded = True


def reset() -> None:
    """Disarm and forget; the next check re-reads the environment."""
    global _armed, _loaded, _table
    with _lock:
        _table = {}
        _armed = False
        _loaded = False


def _ensure_loaded() -> None:
    global _armed, _loaded, _table
    with _lock:
        if _loaded:
            return
        spec = env_str("GRIDLLM_FAULT_SPEC")
        _table = parse_spec(spec, env_int("GRIDLLM_FAULT_SEED")) if spec else {}
        _armed = bool(_table)
        _loaded = True


def check(site: str) -> bool:
    """True when the site should inject THIS call (skip/degrade-style
    sites: dropped delivery, skipped heartbeat, simulated exhaustion)."""
    if _loaded and not _armed:
        return False
    _ensure_loaded()
    if not _armed:
        return False
    with _lock:
        st = _table.get(site)
        fired = st.fire() if st is not None else False
    if fired:
        _INJECTED.inc(site=site)
    return fired


def inject(site: str) -> None:
    """Raise :class:`InjectedFault` when the site fires (raise-style
    sites: bus publish, transfer send/import, engine step)."""
    if check(site):
        raise InjectedFault(f"injected fault at {site}")
