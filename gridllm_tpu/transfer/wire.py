"""Versioned chunked wire format for paged-KV state migration.

Disaggregated serving (ISSUE 7) ships a request's prefilled KV pages from
a prefill-pool worker to a decode-pool worker. The unit of transfer is
the longest CACHED FULL-PAGE PREFIX of the request's prompt — exactly
what ops/kvcache.py's content-addressed prefix cache registers when the
prefill finishes, and exactly what the decode side's ``match_prefix``
will re-derive from the token ids. The wire therefore carries:

- a JSON header: format version, request/model identity, pool geometry
  (page size, layer/head/dim counts), dtype, kvLayout (``ragged`` pools
  are UNPADDED — ISSUE 6 — while ``legacy`` kernel pools may be
  lane-padded; the wire always carries the UNPADDED model head dim and
  each side pads/slices to its own pool), the weight-quant mode (info
  only; KV bytes are the engine dtype either way), the token ids the
  pages cover, and a blake2b digest of the full payload;
- a raw payload: K bytes then V bytes, each [L, n_pages, ps, KVH, D]
  C-contiguous in the header's dtype;
- chunk frames: the payload split into ``chunkBytes`` pieces, each with
  its sequence number and a crc32 — one bus message per chunk
  (``kvx:{request_id}``), or the whole payload in one HTTP POST for
  large transfers (transfer/migrate.py picks the path).

The header travels OUT OF BAND (inside the receiver-prepare control
message), so the chunk stream itself is header-free and idempotent:
duplicate chunks are ignored, a crc/digest mismatch fails the import
loudly and the sender falls back to serving the request locally.
"""

from __future__ import annotations

import base64
import hashlib
import json
import zlib
from typing import Any

import numpy as np

WIRE_VERSION = 1


def _np_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype name to numpy, including ml_dtypes extras
    (bfloat16 — the default KV dtype — is a registered numpy dtype via
    jax's ml_dtypes dependency, but only reachable through it)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def payload_bytes(k: np.ndarray, v: np.ndarray) -> bytes:
    """K then V, C-contiguous raw bytes."""
    return np.ascontiguousarray(k).tobytes() + np.ascontiguousarray(v).tobytes()


def build_header(
    request_id: str,
    model: str,
    tokens: list[int],
    k: np.ndarray,
    v: np.ndarray,
    *,
    kv_layout: str = "legacy",
    quant: str | None = None,
    chunk_bytes: int = 256 * 1024,
) -> tuple[dict[str, Any], bytes]:
    """(header, payload) for one export. ``k``/``v``: [L, n, ps, KVH, D]
    host arrays already sliced to the UNPADDED model head dim."""
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if k.ndim != 5:
        raise ValueError(f"expected [L, n, ps, KVH, D] pages, got {k.shape}")
    n_layers, n_pages, page_size, kv_heads, head_dim = k.shape
    if n_pages * page_size != len(tokens):
        raise ValueError(
            f"{n_pages} pages of {page_size} cover "
            f"{n_pages * page_size} tokens, not {len(tokens)}")
    payload = payload_bytes(k, v)
    chunk_bytes = max(int(chunk_bytes), 1)
    header = {
        "v": WIRE_VERSION,
        "requestId": request_id,
        "model": model,
        "dtype": str(k.dtype),
        "pageSize": page_size,
        "numLayers": n_layers,
        "kvHeads": kv_heads,
        "headDim": head_dim,
        "numPages": n_pages,
        "kvLayout": kv_layout,
        "quant": quant,
        "tokens": [int(t) for t in tokens],
        "totalBytes": len(payload),
        "chunkBytes": chunk_bytes,
        "numChunks": -(-len(payload) // chunk_bytes),
        "digest": hashlib.blake2b(payload, digest_size=16).hexdigest(),
    }
    return header, payload


def iter_chunks(header: dict[str, Any], payload: bytes):
    """Yield (seq, frame_json) chunk frames for the bus path."""
    cb = int(header["chunkBytes"])
    for seq in range(int(header["numChunks"])):
        piece = payload[seq * cb:(seq + 1) * cb]
        yield seq, json.dumps({
            "seq": seq,
            "crc": zlib.crc32(piece) & 0xFFFFFFFF,
            "data": base64.b64encode(piece).decode("ascii"),
        })


def build_spill_header(
    key_hex: str,
    model: str,
    k: np.ndarray,
    v: np.ndarray,
    *,
    k_scale: np.ndarray | None = None,
    v_scale: np.ndarray | None = None,
    quant: str | None = None,
    chunk_bytes: int = 256 * 1024,
) -> tuple[dict[str, Any], bytes]:
    """(header, payload) for ONE host-tier page spill (ISSUE 11). The
    spill codec IS the migration wire format — same version, chunk/crc
    framing, and whole-payload digest — addressed by the prefix cache's
    CHAIN KEY instead of token ids (at eviction time the allocator knows
    the key, not the tokens; a later ``match_prefix`` re-derives the same
    key from the prompt and restores). ``k``/``v``: [L, 1, ps, KVH, D]
    host arrays sliced to the UNPADDED model head dim. ``quant`` names
    the scale layout riding in ``k_scale``/``v_scale`` (float32):
    ``int8-page`` = one scale per (layer, page) — the host-side spill
    quantization of an fp pool; ``int8-rows`` = per-row scales copied
    verbatim from a resident int8 pool (GRIDLLM_KV_INT8)."""
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if k.ndim != 5 or k.shape[1] != 1:
        raise ValueError(f"expected [L, 1, ps, KVH, D] page, got {k.shape}")
    if (k_scale is None) != (v_scale is None) or (
        (quant is None) != (k_scale is None)
    ):
        raise ValueError("quant and k_scale/v_scale travel together")
    payload = payload_bytes(k, v)
    scale_shape: list[int] = []
    if k_scale is not None:
        k_scale = np.ascontiguousarray(k_scale, np.float32)
        v_scale = np.ascontiguousarray(v_scale, np.float32)
        if k_scale.shape != v_scale.shape:
            raise ValueError(
                f"scale shape mismatch: {k_scale.shape} vs {v_scale.shape}")
        scale_shape = list(k_scale.shape)
        payload += k_scale.tobytes() + v_scale.tobytes()
    n_layers, _, page_size, kv_heads, head_dim = k.shape
    chunk_bytes = max(int(chunk_bytes), 1)
    header = {
        "v": WIRE_VERSION,
        "kind": "kv-spill",
        "chainKey": key_hex,
        "model": model,
        "dtype": str(k.dtype),
        "pageSize": page_size,
        "numLayers": n_layers,
        "kvHeads": kv_heads,
        "headDim": head_dim,
        "numPages": 1,
        "quant": quant,
        "scaleShape": scale_shape,
        "totalBytes": len(payload),
        "chunkBytes": chunk_bytes,
        "numChunks": -(-len(payload) // chunk_bytes),
        "digest": hashlib.blake2b(payload, digest_size=16).hexdigest(),
    }
    return header, payload


def spill_arrays(
    header: dict[str, Any], payload: bytes
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None, np.ndarray | None]:
    """(k, v, k_scale, v_scale) from a verified spill payload (feed it
    through :class:`Assembler` first — that is what checks the digest)."""
    h = header
    dtype = _np_dtype(h["dtype"])
    shape = (int(h["numLayers"]), int(h["numPages"]), int(h["pageSize"]),
             int(h["kvHeads"]), int(h["headDim"]))
    n = int(np.prod(shape)) * dtype.itemsize
    scale_shape = tuple(int(s) for s in (h.get("scaleShape") or []))
    sn = int(np.prod(scale_shape)) * 4 if scale_shape else 0
    if len(payload) != 2 * n + 2 * sn:
        raise WireError(
            f"spill payload {len(payload)} bytes does not match "
            f"2×{n} + 2×{sn} for shape {shape} {dtype}")
    k = np.frombuffer(payload[:n], dtype=dtype).reshape(shape)
    v = np.frombuffer(payload[n:2 * n], dtype=dtype).reshape(shape)
    k_scale = v_scale = None
    if sn:
        k_scale = np.frombuffer(
            payload[2 * n:2 * n + sn], dtype=np.float32).reshape(scale_shape)
        v_scale = np.frombuffer(
            payload[2 * n + sn:], dtype=np.float32).reshape(scale_shape)
    return k, v, k_scale, v_scale


class WireError(RuntimeError):
    """Integrity/shape failure during reassembly — the import is aborted
    and the sender falls back to local serving."""


class Assembler:
    """Reassemble one transfer from chunk frames (bus) or the whole
    payload (HTTP). Duplicate chunks are ignored; crc32 guards each
    chunk, the header digest guards the whole payload."""

    def __init__(self, header: dict[str, Any]):
        if int(header.get("v", -1)) != WIRE_VERSION:
            raise WireError(f"unsupported wire version {header.get('v')!r}")
        self.header = header
        self.total = int(header["numChunks"])
        self._chunks: dict[int, bytes] = {}
        self._payload: bytes | None = None

    @property
    def received(self) -> int:
        return len(self._chunks)

    @property
    def contiguous(self) -> int:
        """Highest seq N such that chunks 0..N-1 all arrived — the
        receiver advertises this for sender-side backpressure."""
        n = 0
        while n in self._chunks:
            n += 1
        return n

    @property
    def complete(self) -> bool:
        return self._payload is not None or len(self._chunks) >= self.total

    def feed(self, frame: str) -> bool:
        """One bus chunk frame; returns True when the transfer completed."""
        rec = json.loads(frame)
        seq = int(rec["seq"])
        if seq < 0 or seq >= self.total or seq in self._chunks:
            return self.complete
        piece = base64.b64decode(rec["data"])
        if (zlib.crc32(piece) & 0xFFFFFFFF) != int(rec["crc"]):
            raise WireError(f"crc mismatch on chunk {seq}")
        self._chunks[seq] = piece
        return self.complete

    def feed_raw(self, payload: bytes) -> bool:
        """The HTTP fast path: the whole payload in one body."""
        self._payload = payload
        return True

    def payload(self) -> bytes:
        if self._payload is None:
            if not self.complete:
                raise WireError(
                    f"incomplete transfer: {self.received}/{self.total}")
            self._payload = b"".join(
                self._chunks[i] for i in range(self.total))
        if len(self._payload) != int(self.header["totalBytes"]):
            raise WireError(
                f"payload size {len(self._payload)} != "
                f"{self.header['totalBytes']}")
        digest = hashlib.blake2b(self._payload, digest_size=16).hexdigest()
        if digest != self.header["digest"]:
            raise WireError("payload digest mismatch")
        return self._payload

    def arrays(self) -> tuple[list[int], np.ndarray, np.ndarray]:
        """(tokens, k, v) with k/v reshaped to [L, n, ps, KVH, D]."""
        h = self.header
        payload = self.payload()
        dtype = _np_dtype(h["dtype"])
        shape = (int(h["numLayers"]), int(h["numPages"]), int(h["pageSize"]),
                 int(h["kvHeads"]), int(h["headDim"]))
        n = int(np.prod(shape)) * dtype.itemsize
        if len(payload) != 2 * n:
            raise WireError(
                f"payload {len(payload)} bytes does not match 2×{n} for "
                f"shape {shape} {dtype}")
        k = np.frombuffer(payload[:n], dtype=dtype).reshape(shape)
        v = np.frombuffer(payload[n:], dtype=dtype).reshape(shape)
        return [int(t) for t in h["tokens"]], k, v
