"""KV-page migration for disaggregated prefill/decode serving (ISSUE 7).

``wire``: versioned, chunked, checksummed serialization of paged-KV
state; ``migrate``: the sender/receiver protocol over the bus (with a
direct worker-to-worker HTTP fallback for large transfers) plus the
migration metrics. The engine-side export/import lives on
``InferenceEngine`` (export_prefix_pages / import_prefix_pages); the
control flow (two-phase placement, handoff, fallback) in
scheduler/scheduler.py and worker/service.py.
"""

from gridllm_tpu.transfer.migrate import (
    KVImportManager,
    ack_key,
    kvx_channel,
    kvx_settings,
    ready_key,
    recv_key,
    send_kv,
)
from gridllm_tpu.transfer.wire import (
    WIRE_VERSION,
    Assembler,
    WireError,
    build_header,
    iter_chunks,
)

__all__ = [
    "KVImportManager",
    "Assembler",
    "WireError",
    "WIRE_VERSION",
    "ack_key",
    "build_header",
    "iter_chunks",
    "kvx_channel",
    "kvx_settings",
    "ready_key",
    "recv_key",
    "send_kv",
]
