"""KV-page migration: sender/receiver machinery over the bus (+ HTTP).

Disaggregated serving (ISSUE 7) data plane. One migration:

1. The scheduler assigns the job to a PREFILL worker with a pre-planned
   decode target in ``metadata.disagg``.
2. The prefill worker finishes prefill (engine export mode), exports the
   prompt's cached full-page KV prefix, and calls :func:`send_kv`:
   - a ``kv_import`` prepare message (carrying the wire header) goes to
     the decode worker's job channel; its :class:`KVImportManager`
     subscribes ``kvx:{request_id}`` and sets the ready key;
   - the payload streams as crc-checked chunk frames with windowed
     backpressure against the receiver's advertised contiguous-seq key —
     or, past ``GRIDLLM_KVX_HTTP_BYTES``, as ONE direct worker-to-worker
     HTTP POST to the decode worker's health port (``/kvx/{id}``);
   - the receiver verifies the digest, installs the pages through the
     engine's ref-counted allocator (they immediately join the
     content-addressed prefix cache), and sets the ack key.
3. On a positive ack the prefill worker hands the job off
   (``job:handoff``); any failure or timeout falls back to serving the
   request locally — the transfer is an optimization, never a
   correctness dependency.

All coordination uses bus KEYS (TTL'd), not pub/sub, where ordering
matters (ready/recv/ack): pub/sub has no replay, keys make the protocol
race-free across the in-memory bus and the RESP broker alike.

Env knobs (documented in README "Disaggregated serving"):
  GRIDLLM_KVX_CHUNK_BYTES   chunk size for the bus path (default 262144)
  GRIDLLM_KVX_WINDOW        chunks in flight before awaiting recv
                            progress (default 8)
  GRIDLLM_KVX_TIMEOUT_MS    end-to-end transfer deadline (default 15000)
  GRIDLLM_KVX_HTTP_BYTES    payload size beyond which the direct HTTP
                            path is tried first (default 8388608)
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, Callable

from gridllm_tpu import faults
from gridllm_tpu.bus.base import kvx_channel, worker_job_channel
from gridllm_tpu.obs import default_flight_recorder, default_registry
from gridllm_tpu.transfer.wire import Assembler, WireError, iter_chunks
from gridllm_tpu.utils.config import env_int_lenient
from gridllm_tpu.utils.logging import get_logger

log = get_logger("transfer")

# -- obs (tentpole): migration accounting on the process registry -------------
_OBS = default_registry()
_MIGRATIONS = _OBS.counter(
    "gridllm_kv_migrations_total",
    "KV-page migrations by side (send/recv) and outcome (ok/failed/"
    "timeout/released/rejected).",
    ("side", "outcome"),
)
_MIG_BYTES = _OBS.histogram(
    "gridllm_kv_migration_bytes",
    "Payload bytes per completed KV migration (sender side).",
    buckets=(1e4, 1e5, 1e6, 1e7, 1e8, 1e9),
)
_MIG_SECONDS = _OBS.histogram(
    "gridllm_kv_migration_seconds",
    "Wall seconds per KV migration attempt (sender side, prepare → ack).",
)
_MIG_INFLIGHT = _OBS.gauge(
    "gridllm_kv_migrations_inflight",
    "KV migrations currently in flight in this process (both sides).",
)


def ready_key(xfer_id: str) -> str:
    return f"kvx:ready:{xfer_id}"


def recv_key(xfer_id: str) -> str:
    return f"kvx:recv:{xfer_id}"


def ack_key(xfer_id: str) -> str:
    return f"kvx:ack:{xfer_id}"


def kvx_settings() -> dict[str, int]:
    # lenient reads: these are resolved mid-migration, never at startup —
    # a malformed knob must degrade to the registry default, not fail the
    # handoff after prefill+export already succeeded
    return {
        "chunk_bytes": max(env_int_lenient("GRIDLLM_KVX_CHUNK_BYTES"), 1),
        "window": max(env_int_lenient("GRIDLLM_KVX_WINDOW"), 1),
        "timeout_ms": max(env_int_lenient("GRIDLLM_KVX_TIMEOUT_MS"), 1),
        "http_bytes": max(env_int_lenient("GRIDLLM_KVX_HTTP_BYTES"), 0),
    }


async def _poll_key(bus, key: str, deadline: float,
                    interval: float = 0.02) -> str | None:
    """Poll a bus key until it appears or the deadline passes."""
    while True:
        val = await bus.get(key)
        if val is not None:
            return val
        if time.monotonic() >= deadline:
            return None
        await asyncio.sleep(interval)


async def _send_http(addr: str, request_id: str, payload: bytes,
                     timeout_s: float) -> dict[str, Any] | None:
    """Direct worker-to-worker POST of the whole payload; returns the
    receiver's ack dict, or None when the HTTP path is unusable (caller
    falls back to bus chunks)."""
    import aiohttp

    url = f"http://{addr}/kvx/{request_id}"
    try:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout_s)
        ) as sess:
            async with sess.post(url, data=payload) as resp:
                return await resp.json()
    except Exception as e:  # noqa: BLE001 — any transport failure → bus path
        log.warning("kvx http path failed; falling back to bus",
                    request_id=request_id, addr=addr, error=str(e))
        return None


async def send_kv(
    bus,
    request_id: str,
    target_worker: str,
    header: dict[str, Any],
    payload: bytes,
    *,
    target_addr: str | None = None,
    from_worker: str = "",
    aborted: set[str] | None = None,
    settings: dict[str, int] | None = None,
) -> tuple[bool, str, dict[str, Any]]:
    """Run one migration as the sender. Returns (ok, reason, stats);
    ``ok=False`` means the caller must serve the request locally.

    ``aborted`` is the worker's live set of released/cancelled job ids —
    checked between windows so a ``kv_release`` (scheduler orphan path)
    stops the stream promptly instead of timing out."""
    import uuid

    s = settings or kvx_settings()
    t0 = time.monotonic()
    deadline = t0 + s["timeout_ms"] / 1000.0
    # per-ATTEMPT transfer id: the chunk channel and every coordination
    # key are namespaced by it, never by the request id alone — a
    # requeued job's fresh migration must not consume the TTL'd ack (or
    # straggler chunks) of a released earlier attempt
    xfer = uuid.uuid4().hex
    stats: dict[str, Any] = {"bytes": len(payload), "path": "bus",
                             "chunks": int(header["numChunks"])}
    _MIG_INFLIGHT.inc()
    try:
        # kvx.send fault site (faults.py): an injected failure takes the
        # same except-path a dead transport would — the sender serves the
        # request locally and the migration is counted failed
        faults.inject("kvx.send")
        # receiver prepare: the decode worker's KVImportManager subscribes
        # the chunk channel and sets the ready key (header travels here,
        # out of band of the chunk stream)
        await bus.publish(worker_job_channel(target_worker), json.dumps({
            "type": "kv_import",
            "jobId": request_id,
            "xfer": xfer,
            "fromWorker": from_worker,
            "header": header,
        }))
        # wait for readiness, but also watch the ack key: a prepare-time
        # rejection (bad header / wire-version mismatch) NACKs without
        # ever becoming ready, and the sender must fall back immediately
        # instead of eating the whole transfer timeout
        while True:
            if await bus.get(ready_key(xfer)) is not None:
                break
            raw_nack = await bus.get(ack_key(xfer))
            if raw_nack is not None:
                ack = json.loads(raw_nack)
                _MIGRATIONS.inc(side="send", outcome="rejected")
                return False, str(ack.get("error") or "import_rejected"), stats
            if time.monotonic() >= deadline:
                _MIGRATIONS.inc(side="send", outcome="timeout")
                return False, "receiver_not_ready", stats
            await asyncio.sleep(0.02)

        # a kv_release may have landed while awaiting readiness — stop
        # BEFORE committing the payload to either path (the HTTP path in
        # particular would otherwise upload the whole thing just to be 409'd)
        if aborted is not None and request_id in aborted:
            _MIGRATIONS.inc(side="send", outcome="released")
            return False, "released", stats

        sent_via_http = False
        if target_addr and s["http_bytes"] and len(payload) >= s["http_bytes"]:
            ack = await _send_http(
                target_addr, request_id, payload,
                timeout_s=max(deadline - time.monotonic(), 0.1))
            if ack is not None:
                stats["path"] = "http"
                sent_via_http = True
                stats["seconds"] = time.monotonic() - t0
                if ack.get("ok"):
                    stats["tokens"] = int(ack.get("tokens", 0))
                    _MIGRATIONS.inc(side="send", outcome="ok")
                    _MIG_BYTES.observe(len(payload))
                    _MIG_SECONDS.observe(stats["seconds"])
                    return True, "", stats
                _MIGRATIONS.inc(side="send", outcome="rejected")
                return False, str(ack.get("error") or "import_rejected"), stats

        if not sent_via_http:
            # bus path: windowed chunk stream with receiver-driven
            # backpressure — never more than `window` chunks past the
            # receiver's advertised contiguous sequence number
            window = s["window"]
            for seq, frame in iter_chunks(header, payload):
                if aborted is not None and request_id in aborted:
                    _MIGRATIONS.inc(side="send", outcome="released")
                    return False, "released", stats
                while seq - await _recv_progress(bus, xfer) >= window:
                    if time.monotonic() >= deadline:
                        _MIGRATIONS.inc(side="send", outcome="timeout")
                        return False, "backpressure_timeout", stats
                    await asyncio.sleep(0.01)
                await bus.publish(kvx_channel(xfer), frame)

        raw_ack = await _poll_key(bus, ack_key(xfer), deadline)
        stats["seconds"] = time.monotonic() - t0
        if raw_ack is None:
            _MIGRATIONS.inc(side="send", outcome="timeout")
            return False, "ack_timeout", stats
        ack = json.loads(raw_ack)
        if not ack.get("ok"):
            _MIGRATIONS.inc(side="send", outcome="rejected")
            return False, str(ack.get("error") or "import_rejected"), stats
        stats["tokens"] = int(ack.get("tokens", 0))
        _MIGRATIONS.inc(side="send", outcome="ok")
        _MIG_BYTES.observe(len(payload))
        _MIG_SECONDS.observe(stats["seconds"])
        return True, "", stats
    except Exception as e:  # noqa: BLE001 — transfer failure → local fallback
        stats["seconds"] = time.monotonic() - t0
        _MIGRATIONS.inc(side="send", outcome="failed")
        log.warning("kv migration send failed", request_id=request_id,
                    error=str(e))
        return False, f"send_error:{e}", stats
    finally:
        _MIG_INFLIGHT.dec()


async def _recv_progress(bus, xfer_id: str) -> int:
    raw = await bus.get(recv_key(xfer_id))
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


class _Import:
    __slots__ = ("assembler", "sub", "from_worker", "started", "finalizing",
                 "expire_task", "xfer")

    def __init__(self, assembler: Assembler, from_worker: str, xfer: str):
        self.assembler = assembler
        self.sub = None
        self.from_worker = from_worker
        self.started = time.monotonic()
        self.finalizing = False
        self.expire_task: asyncio.Task | None = None
        self.xfer = xfer  # per-attempt id namespacing channel + keys


class KVImportManager:
    """Decode-side receiver: one instance per WorkerService.

    ``resolve_engine(model)`` must return the engine whose pool the
    pages install into (WorkerService._resolve_engine). Installed pages
    land refcount-0 in the engine's content-addressed prefix cache, so
    the decode job's normal admission (``match_prefix``) finds them —
    the warm-path replay then yields a token stream bit-identical to
    unified serving (the PR 3 invariant this subsystem leans on)."""

    def __init__(self, bus, resolve_engine: Callable[[str], Any],
                 worker_id: str = "", tracer=None):
        self.bus = bus
        self.resolve_engine = resolve_engine
        self.worker_id = worker_id
        self.tracer = tracer
        self.imported: dict[str, int] = {}  # request_id → tokens installed
        # request_id → payload bytes imported, popped once into the usage
        # attribution of the decode job's result (ISSUE 16)
        self.imported_bytes: dict[str, int] = {}
        self._pending: dict[str, _Import] = {}
        self.flightrec = default_flight_recorder()

    def take_imported_bytes(self, rid: str) -> int:
        """Pop the migrated-bytes tally for a request (0 if none) —
        consumed exactly once by the decode worker's usage payload."""
        return self.imported_bytes.pop(rid, 0)

    @property
    def inflight(self) -> int:
        return len(self._pending)

    async def prepare(self, msg: dict[str, Any]) -> None:
        """Handle a ``kv_import`` prepare message: subscribe the chunk
        channel, then advertise readiness via the ready key. A fresh
        attempt for a job we already hold state for SUPERSEDES it — the
        old attempt's sender is gone (requeue/replan) and its partial
        assembly must not swallow the new stream."""
        rid = str(msg.get("jobId") or "")
        header = msg.get("header")
        xfer = str(msg.get("xfer") or rid)
        if not rid or not isinstance(header, dict):
            return
        old = self._pending.get(rid)
        if old is not None:
            if old.xfer == xfer:
                return  # duplicate prepare for the same attempt
            await self._finish(rid, ok=False, error="superseded")
        try:
            state = _Import(Assembler(header),
                            str(msg.get("fromWorker") or ""), xfer)
        except WireError as e:
            await self._ack(xfer, ok=False, error=str(e))
            return
        self._pending[rid] = state
        _MIG_INFLIGHT.inc()

        async def on_chunk(_ch: str, frame: str) -> None:
            await self._feed(rid, frame)

        state.sub = await self.bus.subscribe(kvx_channel(xfer), on_chunk)

        # sender-failure safety net: a sender that crashes or falls back
        # mid-stream never completes this transfer, and the scheduler's
        # kv_release only covers the paths it sees (fallback handoff,
        # orphan) — expire the assembly state locally so buffered chunks
        # and the subscription can never leak for the process lifetime
        ttl_s = max(kvx_settings()["timeout_ms"] / 1000.0 * 2, 30.0)

        async def expire() -> None:
            await asyncio.sleep(ttl_s)
            cur = self._pending.get(rid)
            if cur is state and not state.finalizing:
                log.warning("kv import expired; dropping partial state",
                            request_id=rid, received=state.assembler.received)
                _MIGRATIONS.inc(side="recv", outcome="timeout")
                await self._finish(rid, ok=False, error="receive timeout")

        state.expire_task = asyncio.ensure_future(expire())
        await self.bus.set_with_expiry(ready_key(xfer), "1", ttl_s=60.0)

    async def _feed(self, rid: str, frame: str) -> None:
        state = self._pending.get(rid)
        if state is None or state.finalizing:
            return
        try:
            done = state.assembler.feed(frame)
            # advertise contiguous progress for sender backpressure
            await self.bus.set_with_expiry(
                recv_key(state.xfer), str(state.assembler.contiguous),
                ttl_s=60.0)
        except WireError as e:
            await self._finish(rid, ok=False, error=str(e))
            return
        if done:
            state.finalizing = True
            await self._finalize(rid)

    async def feed_http(self, rid: str, payload: bytes) -> dict[str, Any]:
        """The direct HTTP path: whole payload in one body. The prepare
        message must have arrived first (it carries the header)."""
        state = self._pending.get(rid)
        if state is None:
            return {"ok": False, "error": "no pending import (prepare "
                                          "message not seen)"}
        if state.finalizing:
            return {"ok": False, "error": "import already finalizing"}
        state.finalizing = True
        state.assembler.feed_raw(payload)
        return await self._finalize(rid)

    async def _finalize(self, rid: str) -> dict[str, Any]:
        state = self._pending.get(rid)
        assert state is not None
        t0 = time.time()  # tracer spans use wall-clock epoch seconds
        try:
            # kvx.import fault site: the receiver NACKs exactly as it
            # would on a digest/geometry mismatch; the sender falls back
            faults.inject("kvx.import")
            tokens_list, k, v = state.assembler.arrays()
            header = state.assembler.header
            engine = self.resolve_engine(header.get("model", ""))
            if engine is None:
                raise WireError(f"model not served here: {header.get('model')}")
            installed = await asyncio.to_thread(
                engine.import_prefix_pages, tokens_list, k, v, header)
            self.imported[rid] = installed
            while len(self.imported) > 256:  # bounded: newest kept
                self.imported.pop(next(iter(self.imported)))
            self.imported_bytes[rid] = int(header["totalBytes"])
            while len(self.imported_bytes) > 256:
                self.imported_bytes.pop(next(iter(self.imported_bytes)))
            if self.tracer is not None:
                self.tracer.record(
                    rid, "kvx.import", t0, time.time(),
                    tokens=installed, bytes=int(header["totalBytes"]),
                    fromWorker=state.from_worker)
            _MIGRATIONS.inc(side="recv", outcome="ok")
            self.flightrec.record(
                "transfer", "kv_imported", request=rid,
                worker=self.worker_id, tokens=installed,
                bytes=int(header["totalBytes"]))
            return await self._finish(rid, ok=True, tokens=installed)
        except Exception as e:  # noqa: BLE001 — NACK the sender, never crash
            _MIGRATIONS.inc(side="recv", outcome="failed")
            log.warning("kv import failed", request_id=rid, error=str(e))
            return await self._finish(rid, ok=False, error=str(e))

    async def _finish(self, rid: str, ok: bool, tokens: int = 0,
                      error: str = "") -> dict[str, Any]:
        state = self._pending.pop(rid, None)
        xfer = state.xfer if state is not None else rid
        ack: dict[str, Any] = {"ok": ok, "tokens": tokens}
        if error:
            ack["error"] = error
        # Synchronous cleanup first (gauge + expire timer survive any
        # cancellation below), then the ack, then the unsubscribe —
        # strictly in that order. _finish usually runs inside the chunk
        # channel's OWN handler pump, and unsubscribing that subscription
        # cancels the very task executing this coroutine; before this
        # ordering the CancelledError landed mid-ack (the sender saw a
        # timeout) while desyncing the bus connection's reply stream.
        # The unsubscribe is detached (and exception-guarded — the bus
        # may be dead by now) from a finally so it runs even when the
        # ack itself is cancelled.
        if state is not None:
            _MIG_INFLIGHT.dec()
            if (state.expire_task is not None
                    and state.expire_task is not asyncio.current_task()):
                state.expire_task.cancel()
        try:
            await self._ack(xfer, **ack)
        finally:
            if state is not None and state.sub is not None:
                sub = state.sub

                async def _unsub() -> None:
                    try:
                        await sub.unsubscribe()
                    except Exception:  # noqa: BLE001 — bus may be gone
                        pass

                asyncio.ensure_future(_unsub())
        return ack

    async def _ack(self, xfer_id: str, **ack: Any) -> None:
        try:
            await self.bus.set_with_expiry(
                ack_key(xfer_id), json.dumps(ack), ttl_s=60.0)
        except Exception as e:  # noqa: BLE001
            log.warning("kvx ack publish failed", xfer=xfer_id,
                        error=str(e))

    async def release(self, rid: str) -> None:
        """Scheduler-driven release (orphaned mid-migration): drop any
        partially assembled state and stop listening. Pages already
        installed are refcount-0 cached content — valid KV for their
        token prefix — so they stay in the LRU and age out normally."""
        if rid in self._pending:
            _MIGRATIONS.inc(side="recv", outcome="released")
            self.flightrec.record("transfer", "kv_released", request=rid,
                                  worker=self.worker_id)
            await self._finish(rid, ok=False, error="released")

    async def shutdown(self) -> None:
        for rid in list(self._pending):
            await self._finish(rid, ok=False, error="worker stopping")
