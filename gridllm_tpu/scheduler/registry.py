"""Worker registry: the server-side worker table + liveness machinery.

Reference analogue: server/src/services/WorkerRegistry.ts (516 LoC). Same
behavioral surface:

- in-memory table mirrored to the bus hash ``workers`` (crash-reload on boot,
  WorkerRegistry.ts:76-110)
- subscribes ``worker:registered/unregistered/heartbeat/status_update/
  disconnected`` (WorkerRegistry.ts:17-55)
- three liveness mechanisms (SURVEY.md §3.5): cleanup sweep on heartbeat
  staleness (:182-219), connection monitor with a quick-disconnect window
  probing the worker's ``heartbeat:{id}`` TTL key (:125-180), and the
  fast-path ``worker:disconnected`` publish from the worker's own socket-close
  handler (:352-369)
- unknown-heartbeat healing: reload from bus or request re-registration via
  ``worker:reregister:{id}`` (:261-323, :496-515)
- model→worker queries and job-count/status accounting (:383-494)

Events emitted: ``worker_registered``, ``worker_removed``, ``worker_heartbeat``,
``worker_status_changed`` (WorkerRegistry.ts:244,378,275,342).

TPU extension: capability records may carry ``topology`` and ``shardLayouts``
(utils/types.py) — a multi-host TPU slice registers as ONE logical worker.
"""

from __future__ import annotations

import asyncio
import json
import time

from gridllm_tpu.bus.base import (
    CH_HEALTH_STATE,
    CH_WORKER_DISCONNECTED,
    CH_WORKER_HEARTBEAT,
    CH_WORKER_REGISTERED,
    CH_WORKER_STATUS_UPDATE,
    CH_WORKER_UNREGISTERED,
    MessageBus,
    Subscription,
    liveness_suspended,
    worker_reregister_channel,
)
from gridllm_tpu.obs import Counter, Gauge, MetricsRegistry, default_flight_recorder
from gridllm_tpu.utils.config import SchedulerConfig
from gridllm_tpu.utils.events import EventEmitter
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import WorkerInfo

log = get_logger("scheduler.registry")

WORKERS_KEY = "workers"


class WorkerRegistry(EventEmitter):
    def __init__(self, bus: MessageBus, config: SchedulerConfig | None = None,
                 observer: bool = False):
        super().__init__()
        self.bus = bus
        self.config = config or SchedulerConfig()
        # Observer mode (ISSUE 15): a stateless gateway replica consumes
        # the heartbeat/registration fan-out for routing and health views
        # but issues NO death verdicts — the cleanup sweep and TTL probe
        # stay off, so only scheduler shards (which own the orphan
        # machinery for their partitions) remove silent workers. Explicit
        # announcements (unregistered/disconnected) still apply: they are
        # the worker's own word, not a liveness judgment.
        self.observer = observer
        self.workers: dict[str, WorkerInfo] = {}
        self._subs: list[Subscription] = []
        self._tasks: list[asyncio.Task] = []
        self._running = False
        self.metrics: MetricsRegistry | None = None
        self._workers_gauge: Gauge | None = None
        self._live_gauge: Gauge | None = None
        self._removed_total: Counter | None = None
        # partition-aware liveness (ISSUE 10): logs the hold transitions
        # exactly once per partition episode
        self._liveness_held = False

    def attach_metrics(self, metrics: MetricsRegistry) -> None:
        """Wire worker-liveness instruments onto a registry (called by
        JobScheduler.__init__ so gateway /metrics sees them): a by-status
        gauge collected at render time plus a removals counter by reason."""
        self.metrics = metrics
        self._workers_gauge = metrics.gauge(
            "gridllm_workers", "Registered workers, by status.", ("status",))
        self._live_gauge = metrics.gauge(
            "gridllm_workers_live",
            "Live (online or busy) workers, by fleet role "
            "(unified/prefill/decode) — the disaggregated-serving pool "
            "sizes (ISSUE 7).",
            ("role",))
        self._removed_total = metrics.counter(
            "gridllm_workers_removed_total",
            "Workers removed from the registry, by reason "
            "(unregistered/disconnected/heartbeat_timeout/aliveness_probe).",
            ("reason",),
        )
        metrics.add_collector("worker_registry", self._collect)

    def _collect(self) -> None:
        if self._workers_gauge is None:
            return
        for status, n in self.get_worker_count().items():
            if status == "total":  # derivable; exporting it double-counts
                continue           # every worker under sum(gridllm_workers)
            self._workers_gauge.set(n, status=status)
        if self._live_gauge is not None:
            for role, n in self.role_counts().items():
                self._live_gauge.set(n, role=role)

    # -- lifecycle ----------------------------------------------------------
    async def initialize(self) -> None:
        self._running = True
        from gridllm_tpu.analysis import statecheck

        if statecheck.enabled():
            # shared-state sanitizer (ISSUE 13): the worker map is
            # event-loop state; flag any lockless cross-thread write
            statecheck.track_object(self, "registry", ("workers",))
        for channel, handler in [
            (CH_WORKER_REGISTERED, self._on_registered),
            (CH_WORKER_UNREGISTERED, self._on_unregistered),
            (CH_WORKER_HEARTBEAT, self._on_heartbeat),
            (CH_WORKER_STATUS_UPDATE, self._on_status_update),
            (CH_WORKER_DISCONNECTED, self._on_disconnected),
            (CH_HEALTH_STATE, self._on_health_state),
        ]:
            self._subs.append(await self.bus.subscribe(channel, handler))
        await self._load_existing_workers()
        if not self.observer:
            self._tasks.append(asyncio.create_task(self._cleanup_loop()))
            self._tasks.append(
                asyncio.create_task(self._connection_monitor_loop()))
        else:
            # observers still age out silently-dead workers LOCALLY —
            # the shards' authoritative removals are not broadcast, so
            # without this a gateway replica's /health/workers would
            # list a SIGKILLed worker forever. Local prune only: no bus
            # hdel, no removal verdict, just this process's view.
            self._tasks.append(
                asyncio.create_task(self._observer_prune_loop()))
        log.info("worker registry initialized", workers=len(self.workers),
                 observer=self.observer)

    async def shutdown(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()

    async def _load_existing_workers(self) -> None:
        """Crash recovery: reload the `workers` hash, dropping stale entries
        (reference: WorkerRegistry.ts:76-110)."""
        stored = await self.bus.hgetall(WORKERS_KEY)
        timeout_s = self.config.worker_heartbeat_timeout_ms / 1000
        for worker_id, raw in stored.items():
            try:
                info = WorkerInfo.model_validate_json(raw)
            except Exception:
                await self.bus.hdel(WORKERS_KEY, worker_id)
                continue
            if time.time() - info.lastHeartbeat > timeout_s:
                log.worker("dropping stale worker on reload", worker_id)
                await self.bus.hdel(WORKERS_KEY, worker_id)
                continue
            self.workers[worker_id] = info

    # -- bus handlers -------------------------------------------------------
    async def _on_registered(self, _ch: str, raw: str) -> None:
        try:
            info = WorkerInfo.model_validate_json(raw)
        except Exception as e:
            log.error("bad registration payload", error=str(e))
            return
        is_new = info.workerId not in self.workers
        info.lastHeartbeat = time.time()
        prev = self.workers.get(info.workerId)
        if prev is not None:
            # a re-registration must not silently clear a health verdict
            # (ISSUE 19): the health monitor alone moves a quarantined
            # worker to probation (its worker_registered hook), and the
            # verdict replicates to observers over health:state
            info.healthState = prev.healthState
        self.workers[info.workerId] = info
        await self.bus.hset(WORKERS_KEY, info.workerId, info.model_dump_json())
        log.worker("worker registered", info.workerId,
                   models=info.model_names(), new=is_new)
        if is_new:
            default_flight_recorder().record(
                "registry", "worker_registered", worker=info.workerId,
                models=info.model_names())
        self.emit("worker_registered", info)

    async def _on_unregistered(self, _ch: str, raw: str) -> None:
        try:
            worker_id = json.loads(raw).get("workerId", raw)
        except Exception:
            worker_id = raw
        await self.remove_worker(worker_id, reason="unregistered")

    async def _on_heartbeat(self, _ch: str, raw: str) -> None:
        """reference: WorkerRegistry.ts:261-323 — includes the unknown-worker
        healing path (reload from bus, else request re-registration)."""
        try:
            data = json.loads(raw)
            worker_id = data["workerId"]
        except Exception:
            return
        info = self.workers.get(worker_id)
        if info is None:
            stored = await self.bus.hget(WORKERS_KEY, worker_id)
            if stored:
                try:
                    info = WorkerInfo.model_validate_json(stored)
                    self.workers[worker_id] = info
                    log.worker("worker reloaded from bus on heartbeat", worker_id)
                except Exception:
                    info = None
            if info is None:
                await self.request_worker_reregistration(worker_id)
                return
        info.lastHeartbeat = time.time()
        # Divergence from reference (which copied status/currentJobs from the
        # heartbeat): job accounting is registry-authoritative, driven by
        # mark_worker_busy/available on the job lifecycle. A heartbeat emitted
        # just before an assignment landed would otherwise erase the busy
        # mark and allow over-assignment past maxConcurrentTasks. Heartbeats
        # only refresh liveness and surface error states.
        if data.get("status") == "error":
            info.status = "error"
        # Prefix-affinity digest (ISSUE 3): the worker's recently-served
        # prefix keys ride each heartbeat; bounded here so a misbehaving
        # worker cannot bloat the registry hash
        prefixes = data.get("prefixKeys")
        if isinstance(prefixes, list):
            # keys arrive oldest→newest; keep the newest when truncating
            info.cachedPrefixes = [str(k) for k in prefixes[-64:]]
        # Disaggregated serving (ISSUE 7): role, decode-slot headroom,
        # and the worker-to-worker transfer address ride every heartbeat
        # so the scheduler's pool split and the KV sender's HTTP fallback
        # both work from live data
        role = data.get("role")
        if role in ("unified", "prefill", "decode"):
            info.role = role
        if "decodeSlotsFree" in data:
            try:
                info.decodeSlotsFree = max(int(data["decodeSlotsFree"]), 0)
            except (TypeError, ValueError):
                pass
        if data.get("httpAddr"):
            info.httpAddr = str(data["httpAddr"])
        # Capacity signals (ISSUE 16): per-model slot/KV headroom for the
        # demand tracker behind /admin/capacity; bounded (16 models, int
        # values only) so a misbehaving worker cannot bloat the registry
        mc = data.get("modelCapacity")
        if isinstance(mc, dict):
            bounded: dict[str, dict[str, int]] = {}
            for model, caps in list(mc.items())[:16]:
                if not isinstance(caps, dict):
                    continue
                try:
                    # "engine" is the alias-dedup identity token (ISSUE
                    # 20): copy-model aliases share it, so fleet totals
                    # can count the shared pool once
                    bounded[str(model)] = {
                        k: max(int(caps.get(k, 0)), 0)
                        for k in ("slotsFree", "slotsTotal", "kvPagesFree",
                                  "engine")
                    }
                except (TypeError, ValueError):
                    continue
            info.modelCapacity = bounded
        # Persist so a restarted server doesn't see a stale lastHeartbeat and
        # evict live workers (reference hsets every beat too).
        await self.bus.hset(WORKERS_KEY, worker_id, info.model_dump_json())
        self.emit("worker_heartbeat", worker_id, data)

    async def _on_status_update(self, _ch: str, raw: str) -> None:
        try:
            data = json.loads(raw)
            worker_id = data["workerId"]
        except Exception:
            return
        info = self.workers.get(worker_id)
        if info is None:
            return
        old = info.status
        info.status = data.get("status", info.status)
        info.currentJobs = int(data.get("currentJobs", info.currentJobs))
        if "capabilities" in data:
            try:
                info.capabilities = info.capabilities.model_validate(data["capabilities"])
            except Exception:
                pass
        info.lastHeartbeat = time.time()
        await self.bus.hset(WORKERS_KEY, worker_id, info.model_dump_json())
        if old != info.status:
            self.emit("worker_status_changed", worker_id, old, info.status)

    async def _on_health_state(self, _ch: str, raw: str) -> None:
        """Apply a health-monitor verdict broadcast on ``health:state``
        (ISSUE 19) — shards and observer replicas alike, so placement
        and /health/workers agree fleet-wide. The emitting shard already
        applied it locally; re-applying is idempotent."""
        try:
            data = json.loads(raw)
            worker_id = str(data["worker"])
            state = str(data["state"])
        except Exception:
            return
        self.apply_health_state(worker_id, state)

    def apply_health_state(self, worker_id: str, state: str) -> None:
        if state not in ("online", "degraded", "quarantined", "probation"):
            return
        info = self.workers.get(worker_id)
        if info is None or info.healthState == state:
            return
        old = info.healthState
        info.healthState = state
        log.worker("worker health state applied", worker_id,
                   old=old, new=state)
        self.emit("worker_health_changed", worker_id, old, state)

    async def _on_disconnected(self, _ch: str, raw: str) -> None:
        """Fast eviction path: the worker's own socket-close handler publishes
        this best-effort (reference: RedisConnectionManager.ts:158-179)."""
        try:
            worker_id = json.loads(raw).get("workerId", raw)
        except Exception:
            worker_id = raw
        await self.remove_worker(worker_id, reason="disconnected")

    # -- liveness loops -----------------------------------------------------
    def _liveness_suspended(self) -> bool:
        """Partition-aware liveness (ISSUE 10): while this process's OWN
        bus session is degraded — or within the rejoin grace after it
        recovers — every "worker died" verdict is suspended. Missing
        heartbeats during a partition mean WE were deaf, not that the
        fleet died; pronouncing workers dead then triggers a mass
        orphan-requeue storm of perfectly healthy jobs. Workers silent
        for organic reasons are caught on the first sweep after the
        grace expires — their lastHeartbeat keeps aging through the hold."""
        held = liveness_suspended(self.bus, self.config.bus_rejoin_grace_ms)
        if held and not self._liveness_held:
            log.warning("bus session degraded; suspending worker-death "
                        "verdicts")
            default_flight_recorder().record(
                "registry", "liveness_suspended", workers=len(self.workers))
        elif not held and self._liveness_held:
            log.info("bus session healthy; liveness verdicts resume")
            default_flight_recorder().record(
                "registry", "liveness_resumed", workers=len(self.workers))
        self._liveness_held = held
        return held

    async def _cleanup_loop(self) -> None:
        """Sweep workers whose lastHeartbeat exceeds the timeout
        (reference: WorkerRegistry.ts:112-123, 182-219)."""
        interval = self.config.worker_cleanup_interval_ms / 1000
        timeout_s = self.config.worker_heartbeat_timeout_ms / 1000
        while self._running:
            await asyncio.sleep(interval)
            if self._liveness_suspended():
                continue
            now = time.time()
            for worker_id, info in list(self.workers.items()):
                if now - info.lastHeartbeat > timeout_s:
                    log.worker("worker heartbeat timed out", worker_id,
                               silent_s=round(now - info.lastHeartbeat, 1))
                    await self.remove_worker(worker_id, reason="heartbeat_timeout")

    async def _observer_prune_loop(self) -> None:
        """Observer-mode staleness prune (ISSUE 15): drop workers whose
        heartbeats stopped from THIS process's table only. The bus hash
        and the death verdict (orphan machinery, removal metrics) belong
        to the scheduler shards; the same partition-aware liveness hold
        applies — a deaf bus session must not read as a fleet die-off."""
        interval = self.config.worker_cleanup_interval_ms / 1000
        timeout_s = self.config.worker_heartbeat_timeout_ms / 1000
        while self._running:
            await asyncio.sleep(interval)
            if self._liveness_suspended():
                continue
            now = time.time()
            for worker_id, info in list(self.workers.items()):
                if now - info.lastHeartbeat > timeout_s:
                    self.workers.pop(worker_id, None)
                    log.worker("stale worker pruned from observer view",
                               worker_id,
                               silent_s=round(now - info.lastHeartbeat, 1))
                    self.emit("worker_removed", worker_id, info,
                              "observer_stale")

    async def _connection_monitor_loop(self) -> None:
        """Quick-disconnect detection: any worker silent beyond the
        quick-disconnect window gets its `heartbeat:{id}` TTL key probed; a
        missing key means abrupt death (reference: WorkerRegistry.ts:125-180)."""
        interval = self.config.connection_monitor_interval_ms / 1000
        window_s = self.config.quick_disconnect_window_ms / 1000
        while self._running:
            await asyncio.sleep(interval)
            if liveness_suspended(self.bus, self.config.bus_rejoin_grace_ms):
                # same hold as the cleanup sweep (which owns the state
                # transition logging): during a partition the TTL probe
                # would ALSO misfire — the key expired because nobody
                # could refresh it through us, not because workers died
                continue
            now = time.time()
            for worker_id, info in list(self.workers.items()):
                if now - info.lastHeartbeat <= window_s:
                    continue
                ttl = await self.bus.ttl(f"heartbeat:{worker_id}")
                if ttl == -2:  # key expired/missing → worker died abruptly
                    log.worker("worker aliveness probe failed", worker_id)
                    await self.remove_worker(worker_id, reason="aliveness_probe")

    # -- mutation -----------------------------------------------------------
    async def remove_worker(self, worker_id: str, reason: str = "") -> None:
        info = self.workers.pop(worker_id, None)
        await self.bus.hdel(WORKERS_KEY, worker_id)
        if info is not None:
            if self._removed_total is not None:
                self._removed_total.inc(reason=reason or "unknown")
            log.worker("worker removed", worker_id, reason=reason)
            default_flight_recorder().record(
                "registry", "worker_removed", worker=worker_id,
                reason=reason or "unknown", currentJobs=info.currentJobs)
            self.emit("worker_removed", worker_id, info, reason)

    async def request_worker_reregistration(self, worker_id: str) -> None:
        """reference: WorkerRegistry.ts:496-515."""
        log.worker("requesting re-registration", worker_id)
        await self.bus.publish(
            worker_reregister_channel(worker_id),
            json.dumps({"type": "reregistration_request", "timestamp": time.time()}),
        )

    async def update_worker_job_count(self, worker_id: str, delta: int) -> None:
        """Busy/online transitions against maxConcurrentTasks
        (reference: WorkerRegistry.ts:421-454)."""
        info = self.workers.get(worker_id)
        if info is None:
            return
        info.currentJobs = max(0, info.currentJobs + delta)
        if delta < 0:  # job finished (reference: WorkerRegistry.ts:441-443)
            info.totalJobsProcessed += 1
        old = info.status
        # Divergence from reference (which used the server-wide
        # maxConcurrentJobsPerWorker config): the worker's own advertised
        # capacity governs, so TPU workers with continuous batching can take
        # maxBatchSlots concurrent jobs.
        cap = max(info.capabilities.maxConcurrentTasks, 1)
        # busy/online transitions only apply to workers that are actually
        # serving: a "draining" worker (ISSUE 9) must never be flipped
        # back into placement by job-count bookkeeping racing its drain —
        # the worker itself is the only authority that clears draining
        # (by restarting)
        if info.status in ("online", "busy"):
            if info.currentJobs >= cap:
                info.status = "busy"
            elif info.currentJobs < cap and info.status == "busy":
                info.status = "online"
        await self.bus.hset(WORKERS_KEY, worker_id, info.model_dump_json())
        if old != info.status:
            self.emit("worker_status_changed", worker_id, old, info.status)

    async def mark_worker_busy(self, worker_id: str) -> None:
        await self.update_worker_job_count(worker_id, +1)

    async def mark_worker_available(self, worker_id: str) -> None:
        await self.update_worker_job_count(worker_id, -1)

    # -- queries ------------------------------------------------------------
    def get_worker(self, worker_id: str) -> WorkerInfo | None:
        return self.workers.get(worker_id)

    def get_all_workers(self) -> list[WorkerInfo]:
        return list(self.workers.values())

    def get_online_workers(self) -> list[WorkerInfo]:
        return [w for w in self.workers.values() if w.status in ("online", "busy")]

    def get_available_workers(self) -> list[WorkerInfo]:
        return [
            w for w in self.workers.values()
            if w.status == "online"
            and w.currentJobs < max(w.capabilities.maxConcurrentTasks, 1)
            # quarantined workers (ISSUE 19) are routed around even
            # while their own status still says online — the health
            # verdict outranks the worker's word; degraded/probation
            # stay placeable (scored down in _select_worker)
            and w.healthState != "quarantined"
        ]

    def get_available_workers_by_model(self, model: str) -> list[WorkerInfo]:
        """reference: WorkerRegistry.ts:413."""
        return [w for w in self.get_available_workers() if model in w.model_names()]

    def get_workers_with_model(self, model: str) -> list[WorkerInfo]:
        return [w for w in self.get_online_workers() if model in w.model_names()]

    def get_all_available_models(self) -> list[dict]:
        """Aggregate model records across workers, annotated with
        num_workers_with_model (reference: WorkerRegistry.ts:484-494 +
        ollama.ts:507-571 gridllm_metadata)."""
        by_name: dict[str, dict] = {}
        for w in self.get_online_workers():
            for m in w.capabilities.availableModels:
                entry = by_name.setdefault(m.name, {**m.model_dump(exclude_none=True), "_workers": 0})
                entry["_workers"] += 1
        out = []
        for entry in by_name.values():
            n = entry.pop("_workers")
            entry["gridllm_metadata"] = {"num_workers_with_model": n}
            out.append(entry)
        return out

    def role_counts(self) -> dict[str, int]:
        """Live (online/busy) workers per fleet role (ISSUE 7) — the one
        source for both the gridllm_workers_live gauge and the
        /health/workers roles block."""
        live = {"unified": 0, "prefill": 0, "decode": 0}
        for w in self.get_online_workers():
            live[w.role] = live.get(w.role, 0) + 1
        return live

    def get_worker_count(self) -> dict[str, int]:
        all_w = list(self.workers.values())
        return {
            "total": len(all_w),
            "online": sum(1 for w in all_w if w.status == "online"),
            "busy": sum(1 for w in all_w if w.status == "busy"),
            "offline": sum(1 for w in all_w if w.status == "offline"),
            # draining (ISSUE 9): alive but refusing new work — excluded
            # from placement yet never force-removed while heartbeating
            "draining": sum(1 for w in all_w if w.status == "draining"),
        }
