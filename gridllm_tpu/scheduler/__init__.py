from gridllm_tpu.scheduler.registry import WorkerRegistry
from gridllm_tpu.scheduler.scheduler import JobScheduler

__all__ = ["WorkerRegistry", "JobScheduler"]
