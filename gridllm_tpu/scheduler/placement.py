"""Demand-driven model placement (ISSUE 20): the loop that makes the
fleet's resident model set elastic.

Each scheduler shard runs one :class:`ModelPlacementController`. Every
``GRIDLLM_PLACEMENT_INTERVAL_MS`` it compares per-model demand — the
PR 15 :class:`~gridllm_tpu.obs.capacity.DemandTracker` aggregates (queue
depth, arrival rate, scale hints) — against the replicas actually
resident on live workers, and closes the gap with targeted
``load_model`` / ``unload_model`` ops on the existing admin channel
(``worker:admin`` with a ``workerId`` key; only the named worker acts):

- **swap-in / scale-up**: a model with queued demand and zero replicas
  gets loaded immediately (the scheduler QUEUES zero-replica requests —
  ``note_unserved`` fires from the dispatch pass, so swap-in starts on
  the first held job, not the next tick); a served model with a standing
  queue and a positive scale hint gets one more replica.
- **scale-to-zero**: a model with no queued/active demand for longer
  than ``GRIDLLM_MODEL_IDLE_TTL_MS`` is unloaded replica by replica
  (always ``if_idle`` — the worker, the ground truth for in-flight
  work, declines the race where a request arrived in the window).
- **floors**: ``GRIDLLM_MODEL_FLOORS`` (``model=N,...``) pins SLO-class
  models to a minimum replica count — never unloaded below it, restored
  toward it when under.
- **hysteresis**: per-model ``GRIDLLM_SWAP_COOLDOWN_MS`` between
  actions, so demand flapping around a threshold cannot thrash
  load/unload cycles; at most one op in flight per model.

The controller is advisory machinery on top of a correct-by-itself
scheduler: with it disabled (interval 0, the default) placement is
static and nothing else changes — queued jobs for an unserved model
still wait for an operator-driven load.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any

from gridllm_tpu.bus.base import CH_WORKER_ADMIN, MessageBus, admin_result_channel
from gridllm_tpu.utils.config import env_int, env_str
from gridllm_tpu.utils.logging import get_logger

log = get_logger("scheduler.placement")

# answer budget for one targeted admin op: loads re-read checkpoints, so
# this is generous; a timeout counts as a failed action (cooldown applies,
# the next tick retries elsewhere)
OP_TIMEOUT_S = 120.0

# arrival-rate floor (req/s) below which EWMA residue counts as idle —
# the decayed rate never reaches exactly zero
IDLE_RATE_EPS = 1e-3


def parse_floors(spec: str) -> dict[str, int]:
    """``model=N,model2=M`` → {model: N}; malformed entries are skipped
    loudly (a typo'd floor silently scaling a model to zero is the worst
    failure mode this knob can have)."""
    floors: dict[str, int] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, val = entry.partition("=")
        try:
            floors[name.strip()] = max(int(val), 0)
        except ValueError:
            log.warning("ignoring malformed floor entry", entry=entry)
    return floors


class ModelPlacementController:
    """Per-shard elastic placement loop (see module docstring)."""

    def __init__(self, scheduler: Any, registry: Any, bus: MessageBus,
                 metrics: Any) -> None:
        self.scheduler = scheduler
        self.registry = registry
        self.bus = bus
        self.interval_ms = env_int("GRIDLLM_PLACEMENT_INTERVAL_MS")
        self.idle_ttl_ms = env_int("GRIDLLM_MODEL_IDLE_TTL_MS")
        self.cooldown_ms = env_int("GRIDLLM_SWAP_COOLDOWN_MS")
        self.floors = parse_floors(env_str("GRIDLLM_MODEL_FLOORS"))
        self._task: asyncio.Task | None = None
        self._wake = asyncio.Event()
        self._running = False
        # model → monotonic ts of last observed demand (queue/active/
        # arrivals); absent = not yet seen (stamped on first sight so a
        # freshly served model gets a full TTL before idle-unload)
        self._last_active: dict[str, float] = {}
        # model → monotonic ts of last completed action (hysteresis)
        self._last_action: dict[str, float] = {}
        self._inflight: set[str] = set()   # models with an op in flight
        self._unserved: set[str] = set()   # swap-in requests from dispatch
        self._swaps = metrics.counter(
            "gridllm_model_swaps_total",
            "Placement-controller admin ops by op (load/unload) and "
            "outcome (ok / declined / error / timeout).",
            ("op", "outcome"),
        )
        self._g_replicas = metrics.gauge(
            "gridllm_model_replicas",
            "Online workers currently serving each model, as seen by "
            "this shard's placement controller.",
            ("model",),
        )

    @property
    def enabled(self) -> bool:
        return self.interval_ms > 0

    def start(self) -> None:
        if not self.enabled or self._task is not None:
            return
        self._running = True
        self._task = asyncio.create_task(self._loop())
        log.info("placement controller started",
                 interval_ms=self.interval_ms, idle_ttl_ms=self.idle_ttl_ms,
                 cooldown_ms=self.cooldown_ms, floors=self.floors)

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    def note_unserved(self, model: str) -> None:
        """Dispatch found a queued job with zero owners: request an
        immediate swap-in instead of waiting out the tick interval."""
        if not self.enabled:
            return
        self._unserved.add(model)
        self._wake.set()

    # ------------------------------------------------------------- loop

    async def _loop(self) -> None:
        while self._running:
            try:
                self._wake.clear()
                try:
                    await asyncio.wait_for(
                        self._wake.wait(), self.interval_ms / 1000.0)
                except asyncio.TimeoutError:
                    pass
                if self._running:
                    await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the loop must survive
                log.warning("placement tick failed", error=str(e))

    async def tick(self) -> None:
        """One decision pass (public: tests drive it directly)."""
        snap = self.scheduler.capacity.snapshot().get("models", {})
        now = time.monotonic()
        names = set(snap) | set(self.floors) | set(self._unserved)
        for model in sorted(names):
            m = snap.get(model, {})
            replicas = [
                w for w in self.registry.get_workers_with_model(model)
                if getattr(w, "healthState", "online") != "quarantined"
            ]
            self._g_replicas.set(len(replicas), model=model)
            queue = int(m.get("queueDepth") or 0)
            busy = (queue > 0
                    or float(m.get("arrivalRate") or 0.0) > IDLE_RATE_EPS
                    or float(m.get("utilization") or 0.0) > 0.0
                    or model in self._unserved)
            if busy or model not in self._last_active:
                self._last_active[model] = now
            if model in self._inflight:
                continue
            floor = self.floors.get(model, 0)
            action: str | None = None
            if len(replicas) < floor:
                action = "load_model"
            elif queue > 0 and not replicas:
                action = "load_model"
            elif (queue > 0 and int(m.get("scaleHint") or 0) > 0
                  and replicas):
                action = "load_model"
            elif (self.idle_ttl_ms > 0 and replicas and not busy
                  and len(replicas) > floor
                  and (now - self._last_active[model]) * 1000.0
                  >= self.idle_ttl_ms):
                action = "unload_model"
            if action is None:
                self._unserved.discard(model)
                continue
            # hysteresis: one action per model per cooldown window. The
            # swap-in path (zero replicas, queued work) is exempt — a
            # model the fleet cannot serve at all must never wait out a
            # cooldown stamped by its own unload.
            held = (now - self._last_action.get(model, -1e9)) * 1000.0
            urgent = action == "load_model" and not replicas and (
                queue > 0 or model in self._unserved or floor > 0)
            if held < self.cooldown_ms and not urgent:
                continue
            target = (self._pick_load_target(model, replicas)
                      if action == "load_model"
                      else self._pick_unload_target(replicas))
            if target is None:
                continue
            self._inflight.add(model)
            self._last_action[model] = now
            try:
                outcome = await self._issue(action, model, target)
            finally:
                self._inflight.discard(model)
            if action == "load_model" and outcome == "ok":
                self._unserved.discard(model)
                # fresh capacity is live — drain any held jobs now
                self.scheduler.request_dispatch()

    # ------------------------------------------------------- target picks

    def _pick_load_target(self, model: str, replicas: list[Any]) -> str | None:
        """Least-loaded online worker not already serving the model:
        fewest resident models first (swap churn concentrates where it
        displaces least), then most free decode slots."""
        serving = {w.workerId for w in replicas}
        candidates = [
            w for w in self.registry.get_online_workers()
            if w.workerId not in serving
            and getattr(w, "healthState", "online") != "quarantined"
        ]
        if not candidates:
            return None
        candidates.sort(key=lambda w: (
            len(w.model_names()),
            -int(getattr(w, "decodeSlotsFree", 0) or 0),
            w.workerId,
        ))
        return candidates[0].workerId

    def _pick_unload_target(self, replicas: list[Any]) -> str | None:
        """Replica with the least in-flight work (the unload is if_idle —
        the worker still declines if anything raced in)."""
        if not replicas:
            return None
        return min(replicas, key=lambda w: (
            int(getattr(w, "currentJobs", 0) or 0), w.workerId,
        )).workerId

    # ------------------------------------------------------------ admin op

    async def _issue(self, op: str, model: str, worker_id: str) -> str:
        """One targeted admin op; returns the outcome label. The result
        subscription is live BEFORE the publish (no ack/answer race)."""
        rid = uuid.uuid4().hex[:12]
        done = asyncio.Event()
        result: dict[str, Any] = {}

        async def on_result(_ch: str, raw: str) -> None:
            msg = json.loads(raw)
            if msg.get("workerId") != worker_id or "ok" not in msg:
                return  # ack frame, or another worker's answer
            result.update(msg)
            done.set()

        sub = await self.bus.subscribe(admin_result_channel(rid), on_result)
        try:
            await self.bus.publish(CH_WORKER_ADMIN, json.dumps({
                "op": op, "id": rid, "model": model, "workerId": worker_id,
                # unloads are ALWAYS conditional: the worker is the ground
                # truth for in-flight work and declines when busy
                "if_idle": op == "unload_model",
            }))
            try:
                await asyncio.wait_for(done.wait(), OP_TIMEOUT_S)
            except asyncio.TimeoutError:
                self._swaps.inc(op=op.removesuffix("_model"), outcome="timeout")
                log.warning("placement op timed out", op=op, model=model,
                            workerId=worker_id)
                return "timeout"
        finally:
            await sub.unsubscribe()
        if result.get("ok"):
            outcome = "ok"
        elif "declined" in str(result.get("detail", "")):
            outcome = "declined"
        else:
            outcome = "error"
        self._swaps.inc(op=op.removesuffix("_model"), outcome=outcome)
        log.info("placement op finished", op=op, model=model,
                 workerId=worker_id, outcome=outcome,
                 detail=result.get("detail", ""))
        return outcome
