"""Job scheduler: priority queue, worker selection, failure machinery.

Reference analogue: server/src/services/JobScheduler.ts (909 LoC). Behavioral
surface preserved:

- priority queue (high > medium > low, FIFO within a class,
  JobScheduler.ts:144-151) mirrored to the bus for crash recovery
- least-loaded worker selection with performance-tier tiebreak (:317-360)
- assignment via ``worker:{id}:job`` publish with a staleness re-check
  (:362-432); per-job timeout; cancellation via the same channel (:530-536)
- orphan machinery: assignments older than the threshold whose worker is
  gone/silent are promoted to high priority and requeued at the FRONT with
  audit metadata (orphaned/originalWorkerId/orphanedAt/requeueCount,
  :219-315); worker disconnection requeues all its active jobs (:553-630)
- failed jobs retried ≤ retry_attempts with retry_delay (:463-514)
- ``submit_and_wait`` / ``submit_streaming_job`` / ``cancel_job`` (:666-856)

Deliberate divergences (fix-by-design, SURVEY.md §2.8 + BASELINE.md):
- event-driven dispatch instead of the 1 s polling tick — a queued job is
  dispatched the moment it's added or a worker frees up; the sweep loop
  remains only as the orphan/retry safety net
- per-job timeout timers are cancelled on completion (the reference leaked
  a live setTimeout per job)
- the queue persists as a bus hash entry per job (jobId → record with a
  sequence number), not one O(queue²) JSON blob
- on worker failure with retries remaining, the waiter on ``job:result:{id}``
  is NOT failed — the retry is transparent; only the final failure is
  delivered (the reference rejected the waiter on first failure yet retried
  anyway in the background)

Events: job_queued/assigned/completed/failed/timeout/orphaned
(reference wiring: server/src/index.ts:140-191).
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Any, Awaitable, Callable

from gridllm_tpu.bus.base import (
    CH_JOB_COMPLETED,
    CH_JOB_DRAIN,
    CH_JOB_FAILED,
    CH_JOB_HANDOFF,
    CH_JOB_PREEMPTED,
    CH_JOB_SNAPSHOT,
    MessageBus,
    Subscription,
    job_result_channel,
    job_stream_channel,
    liveness_suspended,
    worker_job_channel,
)
from gridllm_tpu.obs import (
    CANARY_TENANT,
    CanaryProber,
    DemandTracker,
    HangWatchdog,
    HealthMonitor,
    MetricsRegistry,
    SLOEngine,
    Tracer,
    UsageAccountant,
    aggregate_worker_capacity,
    classify_request,
    dedup_capacity_totals,
    default_flight_recorder,
)
from gridllm_tpu.obs.timeline import CRITICAL_PATH_SEGMENTS, critical_path
from gridllm_tpu.obs.tracer import TRACE_CHANNEL_PREFIX, trace_pattern
from gridllm_tpu.scheduler.registry import WorkerRegistry
from gridllm_tpu.utils.config import (
    SchedulerConfig,
    SLOConfig,
    WatchdogConfig,
    env_float,
)
from gridllm_tpu.utils.events import EventEmitter
from gridllm_tpu.utils.logging import bind_request_id, get_logger
from gridllm_tpu.utils.types import (
    InferenceRequest,
    JobAssignment,
    JobResult,
    Priority,
    StreamChunk,
    WorkerInfo,
)

log = get_logger("scheduler.jobs")

ACTIVE_JOBS_KEY = "active_jobs"
JOB_QUEUE_KEY = "job_queue"

_TIER_RANK = {"high": 0, "medium": 1, "low": 2}


def shard_queue_key(shard_idx: int) -> str:
    """Bus hash holding one shard's queued-job records (ISSUE 15). The
    unsharded scheduler keeps the legacy ``job_queue`` key, so a 1-shard
    control plane and the single-box layout share crash-recovery state."""
    return f"{JOB_QUEUE_KEY}:{shard_idx}"


def shard_active_key(shard_idx: int) -> str:
    """Bus hash holding one shard's active-assignment records (ISSUE 15)."""
    return f"{ACTIVE_JOBS_KEY}:{shard_idx}"


class JobTimeoutError(TimeoutError):
    pass


class JobCancelledError(RuntimeError):
    pass


class _QueuedJob:
    __slots__ = ("request", "seq", "enqueued_at")

    def __init__(self, request: InferenceRequest, seq: int):
        self.request = request
        self.seq = seq
        self.enqueued_at = time.time()

    def sort_key(self) -> tuple[int, int]:
        return (self.request.priority.rank, self.seq)


class JobScheduler(EventEmitter):
    def __init__(self, bus: MessageBus, registry: WorkerRegistry,
                 config: SchedulerConfig | None = None,
                 metrics: MetricsRegistry | None = None,
                 slo_config: SLOConfig | None = None,
                 watchdog_config: WatchdogConfig | None = None,
                 shard: Any | None = None):
        super().__init__()
        self.bus = bus
        self.registry = registry
        self.config = config or SchedulerConfig()
        # Scaled control plane (ISSUE 15): an optional ShardContext
        # (controlplane/partition.py, duck-typed to keep this module
        # import-free of controlplane/) restricting this scheduler to a
        # leased partition of the job-id space. None = the single-box
        # layout: this scheduler owns every job, is never fenced, and
        # persists under the legacy bus keys — behavior is bit-identical
        # to the pre-ISSUE-15 scheduler.
        self.shard = shard
        self.job_queue: list[_QueuedJob] = []
        self.active_jobs: dict[str, JobAssignment] = {}
        self._timeout_handles: dict[str, asyncio.TimerHandle] = {}
        self._retry_handles: dict[str, asyncio.TimerHandle] = {}
        self._seq = 0           # back-of-queue counter (grows)
        self._front_seq = 0     # front-of-queue counter (shrinks; orphans)
        self._subs: list[Subscription] = []
        self._sweep_task: asyncio.Task | None = None
        self._dispatch_scheduled = False
        self._dispatch_lock = asyncio.Lock()
        self._no_owner_warned: dict[str, float] = {}  # model → last warn time
        self._cancelled: dict[str, float] = {}        # jobId → cancel time
        self._running = False
        # observability (obs/): per-instance registry so each server (and
        # each test stack) starts from zeroed counters; cumulative stats in
        # get_stats() are sourced from HERE, so /health/* and /metrics can
        # never disagree. The tracer holds gateway-side span timelines and
        # ingests worker-side ones published on trace:{request_id}.
        self.metrics = metrics or MetricsRegistry()
        self.tracer = Tracer(source="gateway")
        self._jobs_total = self.metrics.counter(
            "gridllm_scheduler_jobs_total",
            "Job lifecycle events (queued/dispatched/completed/failed/"
            "timeout/cancelled/retried/orphaned/nacked/deadline_exceeded/"
            "retry_budget_exhausted/preempt_requested/preempted).",
            ("event",),
        )
        self._queue_wait = self.metrics.histogram(
            "gridllm_scheduler_queue_wait_seconds",
            "Time jobs spend queued before assignment to a worker.",
        )
        self._assignments = self.metrics.counter(
            "gridllm_scheduler_worker_assignments_total",
            "Jobs assigned, by worker.",
            ("worker",),
        )
        self._ttft = self.metrics.histogram(
            "gridllm_request_ttft_seconds",
            "Time from streaming-job submission to the first streamed "
            "token frame, by model.",
            ("model",),
        )
        self._queue_depth = self.metrics.gauge(
            "gridllm_scheduler_queue_depth", "Jobs currently queued.")
        # critical-path decomposition (ISSUE 17): each sealed request's
        # e2e latency split into additive segments by obs/timeline.py's
        # interval sweep over the stitched trace
        self._critical_path = self.metrics.histogram(
            "gridllm_critical_path_seconds",
            "Per-request e2e latency decomposed into additive "
            "critical-path segments (queue_wait/dispatch/prefill/"
            "decode_device/decode_host_stall/migration/suspend_resume); "
            "segments of one request sum to its traced e2e latency.",
            ("segment",),
        )
        self._cp_observed: dict[str, float] = {}  # rid → observed-at (bounded)
        self._active_gauge = self.metrics.gauge(
            "gridllm_scheduler_active_jobs",
            "Jobs currently assigned to workers.")
        self.metrics.add_collector("scheduler", self._collect_gauges)
        registry.attach_metrics(self.metrics)
        self._queue_spans: dict[str, Any] = {}  # jobId → open queue span
        # Disaggregated serving (ISSUE 7): jobs placed with a planned
        # prefill→decode handoff, jobId → {"from", "to", "at"}. Entries
        # clear on handoff/fallback/terminal events; a job orphaned while
        # still here died MID-MIGRATION and takes the migration_lost path
        # (KV release on both workers + front requeue).
        self._migrations: dict[str, dict[str, Any]] = {}
        self._disagg_total = self.metrics.counter(
            "gridllm_disagg_jobs_total",
            "Disaggregated-placement lifecycle events (planned/handoff/"
            "fallback/migration_lost/handoff_worker_lost/cross_role).",
            ("event",),
        )
        # Mid-stream fault tolerance (ISSUE 9): per-job decode-resume
        # watermarks. _resume_snap holds the latest worker-published
        # snapshot (generated token ids + text + resolved seed) for every
        # LIVE job — on orphan/retry/drain the snapshot is stamped into
        # metadata.resume so the replacement worker continues the decode
        # instead of restarting it. _stream_chars counts the chars this
        # gateway actually forwarded to the client, so a resumed stream
        # re-emits nothing the client already saw (exactly-once).
        self._resume_snap: dict[str, dict[str, Any]] = {}
        self._stream_chars: dict[str, int] = {}
        # Sharded control plane (ISSUE 15): recently-terminal job ids
        # (completions seen on the global channel — owned or not — plus
        # local failures/timeouts/cancels/sheds), bounded. A partition
        # can be owner-less for up to a lease TTL; a job that resolves
        # inside that window would otherwise be replayed as "active"
        # from the durable record at adoption, and the queue-hash
        # reconcile needs the same memory to tell a parked-submit ghost
        # from genuinely pending work.
        self._recent_done: dict[str, float] = {}
        # Preemption-based priority (ISSUE 11): victim jobId → request
        # time of an in-flight suspend-to-host ask. One preemption in
        # flight fleet-wide (a burst must not suspend the whole fleet);
        # stale entries (victim finished / worker never answered) prune
        # on the next trigger pass.
        self._preempting: dict[str, float] = {}
        self._resume_total = self.metrics.counter(
            "gridllm_resume_jobs_total",
            "Decode-resume lifecycle events (stamped = a requeue carried "
            "a resume watermark; drain_handoff = live migration moved the "
            "assignment; drain_requeued = drained job went back to the "
            "queue with its snapshot).",
            ("event",),
        )
        # Lease fencing (ISSUE 15): mutating operations a deposed or
        # partitioned shard REFUSED because its ownership lease was no
        # longer provably valid — nonzero here during a failover is the
        # fencing machinery working; nonzero in steady state means lease
        # renewals are not keeping up with the TTL.
        self._shard_fenced = self.metrics.counter(
            "gridllm_shard_fenced_ops_total",
            "Mutating scheduler operations refused because the shard's "
            "ownership lease was lost or stale, by operation "
            "(assign/timeout/orphan/failure/cancel/drain/preempt).",
            ("op",),
        )
        self._ctrl_submits = self.metrics.counter(
            "gridllm_ctrl_submits_total",
            "Control-plane submission fan-out events (ISSUE 15): "
            "published (gateway replica → ctrl:submit), accepted (owning "
            "shard enqueued), ignored (park of a non-owned submit "
            "failed), parked (non-owned submit written straight to its "
            "partition's durable queue record), reconciled (the owner's "
            "sweep found a durable queued record it never saw — a "
            "parked submit from an owner-less or missed-delivery "
            "window — and enqueued it).",
            ("event",),
        )
        # fleet-wide retry budget (token bucket, retries/min): a degraded
        # fleet burning retries faster than the refill sheds to immediate
        # failure instead of melting under a retry storm
        self._retry_tokens = float(self.config.retry_budget_per_min)
        self._retry_refill_t = time.monotonic()
        # interpretation layer (ISSUE 2): SLO judgments on the same
        # registry, the hang watchdog sweeping this scheduler's state
        # (started in initialize), and the process flight recorder
        self.slo = SLOEngine(slo_config, self.metrics)
        self.watchdog = HangWatchdog(self, watchdog_config)
        self.flightrec = default_flight_recorder()
        # fleet economics (ISSUE 16): per-tenant/per-model usage ledger
        # (exactly-once, folded from result payloads by the OWNING
        # shard) and the per-model demand/capacity model behind
        # /admin/capacity — both on this scheduler's instance registry
        self.usage = UsageAccountant(self.metrics)
        self.capacity = DemandTracker(
            self.metrics,
            queue_depths=self._queue_depth_by_model,
            worker_capacity=lambda: aggregate_worker_capacity(
                self.registry.get_online_workers()),
            pool_totals=lambda: dedup_capacity_totals(
                self.registry.get_online_workers()),
        )
        # elastic serving (ISSUE 20): the demand-driven model placement
        # loop — armed only when GRIDLLM_PLACEMENT_INTERVAL_MS > 0
        from gridllm_tpu.scheduler.placement import ModelPlacementController

        self.placement = ModelPlacementController(
            self, self.registry, self.bus, self.metrics)
        # active fleet health (ISSUE 19): per-worker regression baselines
        # driving the online/degraded/quarantined/probation state machine,
        # and the canary prober that feeds it golden-hash verdicts. The
        # prober is armed only when GRIDLLM_PROBE_INTERVAL_MS > 0.
        self.health = HealthMonitor(
            self.bus, self.registry, self.metrics,
            member=lambda: str(self.identity().get("member") or ""))
        self.prober = CanaryProber(self, self.registry, self.health,
                                   self.metrics)
        self._health_penalty = env_float("GRIDLLM_HEALTH_DEGRADED_PENALTY")
        # jobId → (first stream frame ts, last stream frame ts): the only
        # pre-completion sign of life a worker gives the gateway; feeds
        # the watchdog's decode-stall detection
        self._stream_progress: dict[str, tuple[float, float]] = {}

    # -- lifecycle ----------------------------------------------------------
    async def initialize(self) -> None:
        self._running = True
        from gridllm_tpu.analysis import statecheck

        if statecheck.enabled():
            # shared-state sanitizer (ISSUE 13): the job tables and
            # resume/migration maps are event-loop-thread state — any
            # cross-thread write with no common lock is a race the
            # lock-order graph cannot see. Dormant otherwise.
            statecheck.track_object(self, "scheduler", (
                "active_jobs", "job_queue", "_timeout_handles",
                "_retry_handles", "_migrations", "_resume_snap",
                "_stream_chars", "_preempting", "_cancelled",
                "_stream_progress", "_queue_spans"))
        for channel, handler in [
            (CH_JOB_COMPLETED, self._on_job_completed),
            (CH_JOB_FAILED, self._on_job_failed),
            (CH_JOB_HANDOFF, self._on_handoff),
            (CH_JOB_SNAPSHOT, self._on_snapshot),
            (CH_JOB_DRAIN, self._on_drain),
            (CH_JOB_PREEMPTED, self._on_preempted),
        ]:
            self._subs.append(await self.bus.subscribe(channel, handler))
        # worker-side span timelines arrive on trace:{request_id}; merging
        # them here is what stitches one end-to-end timeline per request
        self._subs.append(
            await self.bus.psubscribe(trace_pattern(), self._on_trace))
        await self._load_existing_jobs()
        self._sweep_task = asyncio.create_task(self._sweep_loop())
        self.watchdog.start()
        # new capacity → dispatch; lost worker → requeue its jobs
        self.registry.on("worker_registered", lambda *_: self.request_dispatch())
        self.registry.on("worker_status_changed", lambda *_: self.request_dispatch())
        self.registry.on("worker_removed", self._on_worker_removed)
        # active fleet health (ISSUE 19): registry signals feed the
        # baselines (heartbeat jitter is measured receiver-side from
        # arrival times); a re-registration is a quarantined worker's
        # only road back (→ probation). The prober no-ops unless armed.
        self.registry.on(
            "worker_heartbeat",
            lambda wid, *_: self.health.note_heartbeat(wid))
        self.registry.on(
            "worker_registered",
            lambda info, *_: self.health.note_registered(
                info.workerId, getattr(info, "status", "online") or "online"))
        self.registry.on(
            "worker_health_changed",
            lambda *_: self.request_dispatch())
        self.prober.start()
        self.placement.start()
        log.info("job scheduler initialized",
                 queued=len(self.job_queue), active=len(self.active_jobs))

    async def shutdown(self) -> None:
        self._running = False
        await self.placement.stop()
        await self.prober.stop()
        await self.watchdog.stop()
        if self._sweep_task:
            self._sweep_task.cancel()
            self._sweep_task = None
        for h in (*self._timeout_handles.values(), *self._retry_handles.values()):
            h.cancel()
        self._timeout_handles.clear()
        self._retry_handles.clear()
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()

    async def _load_existing_jobs(self) -> None:
        """Crash recovery from the bus (reference: JobScheduler.ts:82-126).
        Queued jobs reload in sequence order; active jobs whose assignment
        outlived the server restart are orphan-requeued immediately. A
        sharded scheduler (ISSUE 15) loads only the partitions it holds
        leases for — adopted partitions replay later via adopt_shard."""
        if self.shard is None:
            await self._load_jobs_from(JOB_QUEUE_KEY, ACTIVE_JOBS_KEY)
            return
        for idx in self.shard.held():
            await self._load_jobs_from(shard_queue_key(idx),
                                       shard_active_key(idx))

    async def _load_jobs_from(self, qkey: str, akey: str) -> dict[str, int]:
        """Replay one (queue hash, active hash) pair into local state —
        the shared body of boot-time crash recovery and shard adoption.
        Actives load FIRST so a stale queued record of a job that is
        actually running (e.g. an orphaned-partition park that raced the
        previous owner's dispatch) is recognized and dropped instead of
        re-dispatching a live job."""
        stored_active = await self.bus.hgetall(akey)
        n_active = 0
        for job_id, raw in stored_active.items():
            if job_id in self.active_jobs:
                continue
            if job_id in self._recent_done:
                # resolved while the partition was owner-less (ISSUE 15):
                # the worker's completion landed on the global channel
                # with no owner to account it — the durable record is
                # stale, not a live assignment
                await self.bus.hdel(akey, job_id)
                self._jobs_total.inc(event="completed")
                log.job("adopted job already resolved; record dropped",
                        job_id)
                continue
            try:
                assignment = JobAssignment.model_validate_json(raw)
            except Exception:
                await self.bus.hdel(akey, job_id)
                continue
            age_ms = (time.time() - assignment.assignedAt) * 1000
            if age_ms > assignment.timeout:
                await self.bus.hdel(akey, job_id)
                continue
            self.active_jobs[job_id] = assignment
            self._arm_timeout(assignment, remaining_ms=assignment.timeout - age_ms)
            n_active += 1

        stored_queue = await self.bus.hgetall(qkey)
        entries = []
        for job_id, raw in stored_queue.items():
            if job_id in self.active_jobs or job_id in self._recent_done:
                # the job is live (or already resolved) — the queued
                # record is a stale duplicate, not pending work
                await self.bus.hdel(qkey, job_id)
                continue
            try:
                rec = json.loads(raw)
                req = InferenceRequest.model_validate(rec["request"])
                entries.append(_QueuedJob(req, int(rec.get("seq", 0))))
            except Exception:
                await self.bus.hdel(qkey, job_id)
        entries.sort(key=_QueuedJob.sort_key)
        # merge (adoption joins a live queue): dedupe by id, keep sorted
        have = {qj.request.id for qj in self.job_queue}
        entries = [e for e in entries if e.request.id not in have]
        self.job_queue = sorted(self.job_queue + entries,
                                key=_QueuedJob.sort_key)
        if entries:
            self._seq = max(self._seq,
                            max(0, max(e.seq for e in entries)) + 1)
            self._front_seq = min(self._front_seq,
                                  min(0, min(e.seq for e in entries)))
        return {"queued": len(entries), "active": n_active}

    # -- shard ownership & lease fencing (ISSUE 15) --------------------------
    def _owns(self, job_id: str) -> bool:
        """Whether this scheduler's partition set covers the job. The
        unsharded scheduler owns everything; a sharded one consumes the
        global lifecycle channels (completed/failed/snapshot/handoff/
        drain/preempted) but acts only on jobs in its leased shards."""
        return self.shard is None or self.shard.owns(job_id)

    def _fence(self, op: str, job_id: str) -> bool:
        """Lease fence on every MUTATING path: True = proceed. A shard
        whose ownership lease for the job's partition is lost or stale
        (renewals not landing within the TTL) must refuse to assign,
        requeue, time out, or cancel — the partition's new owner replays
        the durable job state and owns those decisions now. Refusals are
        counted so a fencing storm is visible."""
        if self.shard is None or self.shard.fenced_job(job_id):
            return True
        self._shard_fenced.inc(op=op)
        log.warning("shard lease lost/stale; mutating op refused",
                    op=op, job_id=job_id)
        return False

    def _qkey(self, job_id: str) -> str:
        """Bus hash key holding this job's queued record."""
        if self.shard is None:
            return JOB_QUEUE_KEY
        return shard_queue_key(self.shard.shard_for(job_id))

    def _akey(self, job_id: str) -> str:
        """Bus hash key holding this job's active-assignment record."""
        if self.shard is None:
            return ACTIVE_JOBS_KEY
        return shard_active_key(self.shard.shard_for(job_id))

    def identity(self) -> dict[str, Any]:
        """Control-plane identity stamped into get_stats()/admin views so
        per-member numbers are never silently aggregated without their
        origin (ISSUE 15 satellite: health and scrapes agree per shard)."""
        if self.shard is None:
            return {"role": "local", "member": "local", "shards": [0],
                    "numShards": 1}
        return self.shard.identity()

    async def adopt_shard(self, shard_idx: int) -> dict[str, int]:
        """Failover adoption (ISSUE 15): after this member acquired the
        lease for a dead shard's partition (epoch bump), replay that
        shard's durable job state from the bus — queued records rejoin
        the local queue, live assignments are installed with their
        REMAINING timeout (the worker kept decoding through the shard
        death; its stream flows straight to the gateway replicas, so
        adoption is bookkeeping, not a restart). Jobs whose assignment
        outlived its timeout are dropped exactly as in crash recovery."""
        loaded = await self._load_jobs_from(
            shard_queue_key(shard_idx), shard_active_key(shard_idx))
        self.flightrec.record("scheduler", "shard_adopted",
                              shard=shard_idx, member=self.identity().get(
                                  "member"), **loaded)
        log.info("shard partition adopted", shard=shard_idx, **loaded)
        self.request_dispatch()
        return loaded

    def release_shard(self, shard_idx: int) -> dict[str, int]:
        """Deposition cleanup (ISSUE 15): drop every locally held job of
        a partition whose lease this member lost — WITHOUT touching the
        bus-persisted records (the new owner replays them) and without
        publishing cancellations or failures (the jobs are alive and now
        someone else's). Timers are disarmed so a deposed shard can never
        fire a timeout for a job it no longer owns."""
        if self.shard is None:
            return {"queued": 0, "active": 0}
        dropped_q = 0
        keep: list[_QueuedJob] = []
        for qj in self.job_queue:
            if self.shard.shard_for(qj.request.id) == shard_idx:
                dropped_q += 1
                self._end_queue_span(qj.request.id, released=True)
            else:
                keep.append(qj)
        self.job_queue = keep
        dropped_a = 0
        for job_id in list(self.active_jobs):
            if self.shard.shard_for(job_id) != shard_idx:
                continue
            self.active_jobs.pop(job_id, None)
            dropped_a += 1
            for handles in (self._timeout_handles, self._retry_handles):
                h = handles.pop(job_id, None)
                if h is not None:
                    h.cancel()
            self._migrations.pop(job_id, None)
            self._drop_resume_state(job_id)
            self._stream_progress.pop(job_id, None)
            self._preempting.pop(job_id, None)
        self.flightrec.record("scheduler", "shard_released",
                              shard=shard_idx, queued=dropped_q,
                              active=dropped_a)
        log.warning("shard partition released (lease lost)",
                    shard=shard_idx, queued=dropped_q, active=dropped_a)
        return {"queued": dropped_q, "active": dropped_a}

    # -- observability ------------------------------------------------------
    def _collect_gauges(self) -> None:
        """Render-time collector: point-in-time gauges from live state."""
        self._queue_depth.set(len(self.job_queue))
        self._active_gauge.set(len(self.active_jobs))

    async def _on_trace(self, channel: str, raw: str) -> None:
        """Ingest a worker-published span timeline (obs/tracer.py)."""
        try:
            data = json.loads(raw)
            rid = data.get("requestId") or channel[len(TRACE_CHANNEL_PREFIX):]
            spans = data.get("spans") or []
        except Exception:
            return
        if rid and isinstance(spans, list):
            self.tracer.ingest(rid, spans)
            # the worker half may land before OR after the gateway seals
            # the root span — both paths try, the guard keeps it to one
            # observation per request
            self._observe_critical_path(rid)

    def _observe_critical_path(self, request_id: str) -> None:
        """Decompose a sealed request's e2e latency into the additive
        ``gridllm_critical_path_seconds{segment}`` observations. No-op
        until the root span is sealed; at most once per request."""
        if request_id in self._cp_observed:
            return
        spans = self.tracer.export(request_id)
        if not spans:
            return
        seg = critical_path(spans)
        if seg is None:
            return
        self._cp_observed[request_id] = time.monotonic()
        if len(self._cp_observed) > 2048:  # bounded like _recent_done
            cutoff = sorted(self._cp_observed.values())[1024]
            self._cp_observed = {k: v for k, v in self._cp_observed.items()
                                 if v > cutoff}
        for name in CRITICAL_PATH_SEGMENTS:
            self._critical_path.observe(seg[name], segment=name)

    def _begin_queue_span(self, request: InferenceRequest, **meta: Any) -> None:
        """Open a queue.wait span for a (re)queued job; closed at dispatch
        or cancellation. Requeues (retry/orphan/nack) open a fresh one."""
        old = self._queue_spans.pop(request.id, None)
        if old is not None:
            self.tracer.end(old)
        self._queue_spans[request.id] = self.tracer.begin(
            request.id, "queue.wait",
            priority=request.priority.value, **meta)

    def _end_queue_span(self, job_id: str, **meta: Any) -> None:
        span = self._queue_spans.pop(job_id, None)
        if span is not None:
            self.tracer.end(span, **meta)

    def _queue_depth_by_model(self) -> dict[str, int]:
        """Live queued-job count per model (capacity snapshot input)."""
        out: dict[str, int] = {}
        for qj in list(self.job_queue):
            m = qj.request.model
            out[m] = out.get(m, 0) + 1
        return out

    # -- public API ---------------------------------------------------------
    async def add_job(self, request: InferenceRequest,
                      requeue: bool = False) -> str:
        """Queue a job and trigger dispatch (reference: JobScheduler.ts:651-664).
        ``requeue=True`` (the retry ladder) skips the ``queued`` counter so
        requeues are counted only by their own event (retried/nacked/
        orphaned) and ``queued`` balances against terminal events."""
        if self.shard is not None and not self.shard.owns(request.id):
            # safety net (ISSUE 15): a retry timer that fired after this
            # member lost the job's partition lease must not resurrect
            # the job here — its new owner replays the durable state
            log.warning("add_job for unowned partition dropped",
                        job_id=request.id)
            return request.id
        # per-class request deadline (ISSUE 9), stamped ONCE at first
        # submission so retries/orphans measure from the original submit
        md = request.metadata
        if "deadlineAt" not in md:
            deadline_ms = self._deadline_for(request)
            if deadline_ms > 0:
                md["deadlineAt"] = time.time() + deadline_ms / 1000
        qj = _QueuedJob(request, self._seq)
        self._seq += 1
        self.job_queue.append(qj)
        await self._persist_queued(qj)
        if not requeue:
            self._jobs_total.inc(event="queued")
            # demand signal (ISSUE 16): first submissions only — a requeue
            # is the same unit of demand still waiting, not new arrival
            self.capacity.note_arrival(request.model)
        self._begin_queue_span(request)
        log.job("job queued", request.id, model=request.model,
                priority=request.priority.value)
        self.emit("job_queued", request)
        self.request_dispatch()
        return request.id

    async def _submit_and_await(self, request: InferenceRequest,
                                timeout_ms: int | None,
                                extra_subs: list[tuple[str, Any]] | None = None,
                                ttft_ref: list | None = None,
                                settle: Callable[[JobResult],
                                                 Awaitable[None]] | None = None
                                ) -> JobResult:
        """Shared body of the synchronous submit APIs: subscribe the per-job
        result channel (plus any extras), queue, await with timeout+cancel.
        ``ttft_ref`` is the streaming path's one-slot TTFT holder (filled by
        its stream handler) so the SLO judgment sees the first-token time."""
        timeout_ms = timeout_ms or request.timeout or self.config.job_timeout_ms
        t_submit = time.time()
        slo_class = classify_request(request)
        loop = asyncio.get_running_loop()
        future: asyncio.Future[JobResult] = loop.create_future()

        async def on_result(_ch: str, raw: str) -> None:
            if not future.done():
                try:
                    future.set_result(JobResult.model_validate_json(raw))
                except Exception as e:
                    future.set_exception(e)

        md = request.metadata or {}
        endpoint = (md.get("openaiEndpoint") or md.get("ollamaEndpoint")
                    or md.get("endpoint") or "")
        subs: list[Subscription] = []
        outcome = "error"
        with bind_request_id(request.id):
            # begin() directly before the try whose finally ends it — a
            # raise in between would leak the span open (span-pairing rule)
            root = self.tracer.begin(request.id, "gateway.request",
                                     endpoint=endpoint, model=request.model,
                                     tenant=str(md.get("tenant") or ""))
            try:
                for channel, handler in extra_subs or []:
                    subs.append(await self.bus.subscribe(channel, handler))
                subs.append(await self.bus.subscribe(
                    job_result_channel(request.id), on_result))
                await self.add_job(request)
                try:
                    result = await asyncio.wait_for(future, timeout_ms / 1000)
                    if settle is not None:
                        # let trailing stream frames land BEFORE the
                        # finally unsubscribes (the result channel rides
                        # a separate pump and can beat queued frames)
                        await settle(result)
                    outcome = "success" if result.success else "failed"
                    self._judge_slo(slo_class, request, result,
                                    e2e_s=time.time() - t_submit,
                                    ttft_ref=ttft_ref)
                    return result
                except asyncio.TimeoutError:
                    outcome = "timeout"
                    if str(md.get("tenant") or "") != CANARY_TENANT:
                        # a timed-out canary is the prober's verdict to
                        # record, not an SLO miss (ISSUE 19)
                        self.slo.record(slo_class, ok=False,
                                        e2e_s=timeout_ms / 1000,
                                        model=request.model)
                    # end the root BEFORE cancel_job's tracer.abort seals
                    # the timeline, so the outcome lands on the span
                    self.tracer.end(root, outcome=outcome)
                    await self.cancel_job(request.id, reason="timeout")
                    raise JobTimeoutError(
                        f"Job {request.id} timed out after {timeout_ms} ms"
                    ) from None
            finally:
                # seal the trace BEFORE the awaited unsubscribes: a bus
                # error there must not leak the open root span
                self._stream_progress.pop(request.id, None)
                self._drop_resume_state(request.id)
                self.tracer.end(root, outcome=outcome)
                self.tracer.finish(request.id)
                self._observe_critical_path(request.id)
                for sub in subs:
                    await sub.unsubscribe()

    def _judge_slo(self, slo_class: str, request: InferenceRequest,
                   result: JobResult, e2e_s: float,
                   ttft_ref: list | None) -> None:
        """SLO judgment for a resolved submit: measurements come from the
        result's engine-measured timing fields plus the streaming TTFT."""
        tokens = 0
        itl_s = None
        resp = result.response
        if resp is not None:
            tokens = int(resp.eval_count or 0)
            if tokens > 1 and resp.eval_duration:
                itl_s = (resp.eval_duration / 1e9) / (tokens - 1)
        # health baselines (ISSUE 19): engine-measured decode cadence
        # feeds the serving worker's ITL baseline — canaries included
        # (they exercise the same decode path)
        if itl_s is not None and result.workerId:
            self.health.note_itl(result.workerId, itl_s)
        if str((request.metadata or {}).get("tenant") or "") == CANARY_TENANT:
            # canary traffic is a measurement instrument, not served
            # demand: it must never move SLO attainment (ISSUE 19)
            return
        self.slo.record(
            slo_class, ok=result.success,
            ttft_s=(ttft_ref[0] if ttft_ref else None),
            itl_s=itl_s, e2e_s=e2e_s, tokens=tokens,
            model=request.model,
        )

    async def submit_and_wait(self, request: InferenceRequest,
                              timeout_ms: int | None = None) -> JobResult:
        """Synchronous submit: queue, await the per-job result channel
        (reference: JobScheduler.ts:666-711)."""
        return await self._submit_and_await(request, timeout_ms)

    async def submit_streaming_job(
        self,
        request: InferenceRequest,
        on_chunk: Callable[[StreamChunk], Awaitable[None]],
        timeout_ms: int | None = None,
    ) -> JobResult:
        """Streaming submit: forward ``job:stream:{id}`` frames to on_chunk,
        return the final result (reference: JobScheduler.ts:713-856)."""
        t_submit = time.time()
        first = [True]
        ttft_ref: list = [None]
        # chars DELIVERED to the client so far — the closure owns the
        # authoritative count (terminal cleanup can race the map entry);
        # _stream_chars mirrors it for the orphan path's resume stamp
        delivered_ref = [0]

        async def on_stream(_ch: str, raw: str) -> None:
            try:
                chunk = StreamChunk.model_validate_json(raw)
            except Exception:
                return
            # exactly-once trim (ISSUE 9): frames carry the absolute char
            # offset of their text in the full response, so overlap
            # between a dying attempt's in-flight frames and the resumed
            # attempt's re-emission is cut HERE — the client never sees a
            # duplicate char, no matter how the handoff raced the stream
            if chunk.offset is not None and chunk.response:
                delivered = delivered_ref[0]
                off = int(chunk.offset)
                if off + len(chunk.response) <= delivered:
                    return  # wholly duplicate frame
                if off < delivered:
                    chunk.response = chunk.response[delivered - off:]
                    if chunk.message and "content" in chunk.message:
                        chunk.message = {**chunk.message,
                                        "content": chunk.response}
            now = time.time()
            if first[0]:
                first[0] = False
                ttft = now - t_submit
                ttft_ref[0] = ttft
                self._ttft.observe(ttft, model=request.model)
                self.tracer.event(request.id, "gateway.first_token",
                                  ttftMs=round(ttft * 1000, 3))
            # progress only while the job is live: a trailing frame
            # delivered after the result resolved (separate pump queues)
            # must not re-insert an entry the finally block just popped
            if request.id in self.active_jobs:
                first_ts = self._stream_progress.get(request.id,
                                                     (now, now))[0]
                self._stream_progress[request.id] = (first_ts, now)
            await on_chunk(chunk)
            # chars DELIVERED to the client (counted after on_chunk
            # returns): the resume watermark's exactly-once offset — a
            # resumed attempt starts emitting past this point (ISSUE 9).
            # The map mirror is gated on the job being live so a trailing
            # frame delivered after terminal cleanup cannot re-insert an
            # entry nothing would ever remove.
            if chunk.response:
                delivered_ref[0] += len(chunk.response)
                if request.id in self.active_jobs:
                    self._stream_chars[request.id] = delivered_ref[0]

        async def settle(result: JobResult) -> None:
            """Exactly-once stream completion (ISSUE 9): the final result
            can overtake queued stream frames (separate handler pumps) —
            wait briefly until the delivered chars reach the final text
            length, so the client's byte stream is complete before the
            subscription tears down. Only applies when frames were seen
            (format/tool/think requests suppress worker streaming)."""
            resp = result.response
            if resp is None or not result.success:
                return
            if delivered_ref[0] == 0:
                return  # nothing was ever streamed — nothing to settle
            text = resp.response
            if text is None and isinstance(resp.message, dict):
                text = resp.message.get("content")
            target = len(text or "")
            t0 = time.monotonic()
            while (delivered_ref[0] < target
                   and time.monotonic() - t0 < 2.0):
                await asyncio.sleep(0.005)

        return await self._submit_and_await(
            request, timeout_ms,
            extra_subs=[(job_stream_channel(request.id), on_stream)],
            ttft_ref=ttft_ref, settle=settle)

    async def publish_cancellation(self, worker_id: str, job_id: str,
                                   reason: str) -> None:
        """The one place the job_cancellation message is built — the
        waiter-cancel, timeout, and watchdog-hang paths all send the same
        shape to ``worker:{id}:job``."""
        await self.bus.publish(
            worker_job_channel(worker_id),
            json.dumps({"type": "job_cancellation", "jobId": job_id,
                        "reason": reason}),
        )

    async def cancel_job(self, job_id: str, reason: str = "cancelled") -> bool:
        """Cancel a queued, retrying, or active job (reference:
        JobScheduler.ts:874-908). The cancelled-set guards the race where a
        dispatch pass already snapshotted the queued job."""
        if not self._fence("cancel", job_id):
            return False
        self._cancelled[job_id] = time.time()
        self._migrations.pop(job_id, None)

        def account() -> None:
            # a cancel with reason="timeout" is the waiter-side timeout
            # path — count it as a timeout, not a user cancellation
            event = "timeout" if reason == "timeout" else "cancelled"
            self._jobs_total.inc(event=event)
            self._mark_done(job_id)
            self._drop_resume_state(job_id)
            self.flightrec.record("scheduler", event, job=job_id,
                                  reason=reason)
            self._end_queue_span(job_id, cancelled=True, reason=reason)
            self.tracer.abort(job_id, reason=reason)

        retry = self._retry_handles.pop(job_id, None)
        if retry is not None:
            retry.cancel()
            account()
            log.job("retrying job cancelled", job_id, reason=reason)
            return True
        for i, qj in enumerate(self.job_queue):
            if qj.request.id == job_id:
                self.job_queue.pop(i)
                await self.bus.hdel(self._qkey(job_id), job_id)
                account()
                log.job("queued job cancelled", job_id, reason=reason)
                return True
        # claim synchronously before the publish await — the armed
        # _handle_job_timeout can interleave there and the job must be
        # accounted (timeout vs cancelled) exactly once
        assignment = self.active_jobs.pop(job_id, None)
        if assignment is not None:
            try:
                await self.publish_cancellation(assignment.workerId, job_id,
                                                reason)
            finally:
                # the job is already claimed — even a dead bus must not
                # skip the terminal accounting and cleanup
                account()
                await self._clear_active(job_id, free_worker=True,
                                         assignment=assignment)
            log.job("active job cancelled", job_id,
                    worker_id=assignment.workerId, reason=reason)
            return True
        return False

    def get_active_jobs(self) -> list[JobAssignment]:
        return list(self.active_jobs.values())

    def get_job_queue(self) -> list[InferenceRequest]:
        return [qj.request for qj in sorted(self.job_queue, key=_QueuedJob.sort_key)]

    def get_queue_position(self, job_id: str) -> int | None:
        for pos, qj in enumerate(self.get_job_queue()):
            if qj.id == job_id:
                return pos
        return None

    def get_stats(self) -> dict[str, Any]:
        """Instantaneous queue/active sizes plus cumulative lifecycle
        counters sourced from the metrics registry — the same series
        /metrics exports, so health snapshots and scrapes cannot disagree."""
        jt = self._jobs_total
        completed = int(jt.value(event="completed"))
        failed = int(jt.value(event="failed"))
        timed_out = int(jt.value(event="timeout"))
        return {
            # shard identity (ISSUE 15 satellite): with a sharded control
            # plane these numbers are PER-PARTITION — any aggregation
            # must key by this block instead of silently summing unlabeled
            # snapshots from different members
            "shard": self.identity(),
            "queuedJobs": len(self.job_queue),
            "activeJobs": len(self.active_jobs),
            "totalJobsProcessed": completed,
            "totalJobsFailed": failed + timed_out,
            "totalJobsCompleted": completed,
            "totalJobsTimedOut": timed_out,
            "totalJobsCancelled": int(jt.value(event="cancelled")),
            "totalJobsRetried": int(jt.value(event="retried")),
            "totalJobsOrphaned": int(jt.value(event="orphaned")),
        }

    @property
    def total_completed(self) -> int:
        return int(self._jobs_total.value(event="completed"))

    @property
    def total_failed(self) -> int:
        # permanent failures + timeouts, matching the pre-obs attribute
        return (int(self._jobs_total.value(event="failed"))
                + int(self._jobs_total.value(event="timeout")))

    # -- dispatch -----------------------------------------------------------
    def request_dispatch(self) -> None:
        """Debounced event-driven dispatch: coalesce triggers into one task."""
        if self._dispatch_scheduled or not self._running:
            return
        self._dispatch_scheduled = True

        async def run() -> None:
            self._dispatch_scheduled = False
            try:
                await self._process_job_queue()
            except Exception as e:
                log.error("dispatch failed", error=str(e))

        asyncio.ensure_future(run())

    async def _process_job_queue(self) -> None:
        """Assign every queued job that has an available worker
        (reference: JobScheduler.ts:137-217). Serialized by a lock — dispatch
        triggers may overlap and double-assignment must be impossible."""
        async with self._dispatch_lock:
            if not self.job_queue:
                return
            assigned_ids: set[str] = set()
            now = time.time()
            for qj in sorted(list(self.job_queue), key=_QueuedJob.sort_key):
                if qj.request.id in self._cancelled:
                    assigned_ids.add(qj.request.id)  # drop from queue below
                    await self.bus.hdel(self._qkey(qj.request.id), qj.request.id)
                    self._end_queue_span(qj.request.id, cancelled=True)
                    continue
                md = qj.request.metadata or {}
                deadline_at = md.get("deadlineAt")
                if (deadline_at and now > float(deadline_at)
                        # a job that already RAN (orphan/drain/resume/
                        # preempt requeue) is past admission: the client
                        # may hold half a stream, so the resume machinery
                        # finishes it — the deadline only sheds work that
                        # never started
                        and not (md.get("resume") or md.get("orphaned")
                                 or md.get("drained")
                                 or md.get("preempted"))):
                    # past its class deadline while still queued: shed
                    # instead of occupying the queue (ISSUE 9); the
                    # gateway maps the failure to HTTP 504
                    assigned_ids.add(qj.request.id)
                    await self.bus.hdel(self._qkey(qj.request.id), qj.request.id)
                    await self._shed_deadline(qj.request)
                    continue
                worker, disagg = self._plan_placement(qj.request)
                if worker is None:
                    owners = self.registry.get_workers_with_model(qj.request.model)
                    if owners:
                        # preemption-based priority (ISSUE 11): the model
                        # is served but every worker is saturated — a
                        # waiting higher-priority job may suspend a
                        # lower-priority running one to the host KV tier
                        await self._maybe_preempt(qj, now)
                    if not owners:
                        # scale-to-zero and back (ISSUE 20): the job stays
                        # QUEUED (never rejected) and the placement
                        # controller is asked for an immediate swap-in
                        self.placement.note_unserved(qj.request.model)
                        # loud no-owner log (reference: JobScheduler.ts:176-204),
                        # rate-limited to once per model per 5 s
                        now = time.time()
                        if now - self._no_owner_warned.get(qj.request.model, 0) > 5:
                            self._no_owner_warned[qj.request.model] = now
                            log.warning("no worker serves model; job held",
                                        job_id=qj.request.id, model=qj.request.model)
                    continue
                if await self._assign_job(qj, worker, disagg=disagg):
                    assigned_ids.add(qj.request.id)
            if assigned_ids:
                # jobs added during assignment awaits stay for the next pass
                self.job_queue = [qj for qj in self.job_queue
                                  if qj.request.id not in assigned_ids]

    def _plan_placement(
        self, request: InferenceRequest
    ) -> tuple[WorkerInfo | None, dict[str, Any] | None]:
        """(worker, disagg-plan) for one queued job (ISSUE 7).

        Two-phase placement: when the fleet has BOTH a prefill pool and a
        decode pool for the model and the job is a plain generation, the
        job goes to a prefill worker with a pre-planned decode target
        stamped in the plan — the prefill worker migrates the finished KV
        pages there and the scheduler hands the assignment off on
        ``job:handoff``. Anything else (embeddings, image requests,
        homogeneous fleets, disagg disabled) takes whole-request
        placement; a requeued copy of a decode-phase job replans from
        scratch (its imported pages may be anywhere by now)."""
        md = request.metadata or {}
        md.pop("disagg", None)       # requeue hygiene: stale plans never
        md.pop("disaggPhase", None)  # survive a fresh placement pass
        # pinned placement (ISSUE 19): a canary probe measures ONE worker —
        # rerouting it elsewhere would grade the wrong machine, so a pin
        # either lands on its target or waits (and times out as a failed
        # probe, which is itself the verdict)
        pin = md.get("pinWorkerId")
        if pin:
            w = self.registry.get_worker(str(pin))
            if (w is not None and w.status == "online"
                    and request.model in w.model_names()
                    and w.currentJobs < max(
                        w.capabilities.maxConcurrentTasks, 1)):
                return w, None
            return None, None
        # same image collection the worker's collect_images() applies:
        # top-level (generate path) AND per-message (chat path) — a
        # vision request can never migrate, so it must not be planned
        has_images = bool(request.images) or any(
            m.get("images") for m in request.messages or [])
        generation = (request.request_type in ("inference", "chat", "generate")
                      and not has_images)
        # a resume-stamped job is already mid-decode: a two-phase
        # prefill→decode plan would re-split work the watermark makes
        # whole-request-cheap (the re-prefill rides the prefix cache)
        if self.config.disagg_enabled and generation and not md.get("resume"):
            pre = self._select_worker(request, role="prefill")
            dec = self._select_worker(request, role="decode")
            if pre is not None and dec is not None:
                return pre, {
                    "decodeWorkerId": dec.workerId,
                    "decodeAddr": dec.httpAddr or "",
                }
        return self._select_worker(request), None

    def _select_worker(self, request: InferenceRequest,
                       role: str | None = None) -> WorkerInfo | None:
        """Topology-aware selection (reference baseline: least-loaded then
        tier, JobScheduler.ts:317-360; TPU extension per SURVEY.md §2.6).

        Role strictness (ISSUE 7): candidates are filtered to the asked
        pool BEFORE scoring — cross-role placement is refused, never
        silently scored. ``role=None`` (whole-request placement) serves
        from the unified pool; when no unified worker exists the prefill
        pool substitutes (a prefill worker can always finish a request
        locally — that is the disagg fallback contract), and a
        decode-only fleet substitutes last, both counted under
        ``gridllm_disagg_jobs_total{event="cross_role"}`` so a
        misconfigured fleet is visible rather than wedged.

        Order of discrimination:
        1. context fit — a worker whose layout for this model cannot hold
           the request's `num_ctx` loses to one that can;
        2. proportional load — currentJobs / maxConcurrentTasks (absolute
           job counts are unfair between differently-sized workers) —
           minus the prefix-affinity bonus when the worker's heartbeat
           digest contains the job's prefixKey (ISSUE 3): cached-prefix
           overlap breaks load ties and outweighs load gaps up to
           prefix_affinity_weight, but never the availability cap, so a
           hot worker still sheds;
        3. layout headroom — more batch slots on the serving layout wins
           (a v5e-8 TP worker with 16 slots beats a single-chip 4-slot
           worker at equal relative load);
        4. performance tier.
        """
        candidates = self.registry.get_available_workers_by_model(request.model)
        # health gating (ISSUE 19): quarantined workers never serve (the
        # registry already drops them from availability; this guards
        # stale lists); probation workers serve only when nothing
        # healthier exists — canaries, not tenants, should prove them out
        candidates = [w for w in candidates
                      if w.healthState != "quarantined"]
        non_prob = [w for w in candidates if w.healthState != "probation"]
        if non_prob:
            candidates = non_prob
        if role in ("prefill", "decode"):
            candidates = [w for w in candidates if w.role == role]
        else:
            by_role: dict[str, list[WorkerInfo]] = {}
            for w in candidates:
                by_role.setdefault(w.role, []).append(w)
            if by_role.get("unified"):
                candidates = by_role["unified"]
            elif by_role.get("prefill") or by_role.get("decode"):
                candidates = (by_role.get("prefill")
                              or by_role.get("decode") or [])
                self._disagg_total.inc(event="cross_role")
            else:
                candidates = []
        if not candidates:
            return None
        opts = request.options or {}
        try:  # options is unvalidated client input — never let a bad
            # num_ctx abort the dispatch pass (head-of-line blocking)
            num_ctx = int(opts.get("num_ctx") or 0)
        except (TypeError, ValueError):
            num_ctx = 0
        prefix_key = (request.metadata or {}).get("prefixKey")
        affinity_w = self.config.prefix_affinity_weight

        def score(w: WorkerInfo) -> tuple[int, float, int, int, int]:
            caps = w.capabilities
            layout = next(
                (l for l in caps.shardLayouts if l.name == request.model), None
            )
            ctx_ok = layout is None or num_ctx <= 0 or num_ctx <= layout.maxSeqLen
            slots = layout.maxBatchSlots if layout is not None else 1
            load = w.currentJobs / max(caps.maxConcurrentTasks, 1)
            if prefix_key and affinity_w and prefix_key in w.cachedPrefixes:
                load -= affinity_w
            # health penalty (ISSUE 19): a degraded/probation worker
            # competes as if it carried extra load — traffic shifts to
            # healthy peers but the worker stays reachable (mirrors the
            # prefix-affinity bonus, opposite sign)
            if w.healthState in ("degraded", "probation"):
                load += self._health_penalty
            # decode-pool placement prefers the worker with the most open
            # batch slots (heartbeat-advertised headroom, ISSUE 7) — the
            # prefill pool orders purely by queue depth via `load`
            headroom = w.decodeSlotsFree if role == "decode" else 0
            return (
                0 if ctx_ok else 1,
                load,
                -headroom,
                -slots,
                _TIER_RANK.get(caps.performanceTier, 1),
            )

        return min(candidates, key=score)

    async def _assign_job(self, qj: _QueuedJob, worker: WorkerInfo,
                          disagg: dict[str, Any] | None = None) -> bool:
        """reference: JobScheduler.ts:362-432."""
        # staleness re-check right before assignment (:368-386)
        fresh = self.registry.get_worker(worker.workerId)
        if fresh is None or fresh.status != "online":
            return False
        silent_s = time.time() - fresh.lastHeartbeat
        if silent_s * 1000 > self.config.worker_heartbeat_timeout_ms:
            return False
        if not self._fence("assign", qj.request.id):
            # the double-assign gate (ISSUE 15): a deposed or partitioned
            # shard must NEVER publish an assignment — the partition's
            # new owner replays this job from the durable queue record
            # and assigns it itself
            return False

        request = qj.request
        if disagg is not None:
            # two-phase placement (ISSUE 7): the prefill worker reads the
            # decode target from metadata; the migration record makes the
            # orphan path release KV state on BOTH workers if the job dies
            # before the handoff resolves
            request.metadata["disagg"] = dict(disagg)
            self._migrations[request.id] = {
                "from": worker.workerId,
                "to": disagg["decodeWorkerId"],
                "at": time.time(),
            }
            self._disagg_total.inc(event="planned")
        timeout_ms = request.timeout or self.config.job_timeout_ms
        assignment = JobAssignment(
            jobId=request.id, workerId=worker.workerId,
            request=request, timeout=timeout_ms,
        )
        self.active_jobs[request.id] = assignment
        await self.bus.hset(self._akey(request.id), request.id,
                            assignment.model_dump_json())
        await self.bus.hdel(self._qkey(request.id), request.id)
        await self.registry.mark_worker_busy(worker.workerId)
        await self.bus.publish(
            worker_job_channel(worker.workerId),
            json.dumps({"type": "job_assignment", "job": assignment.model_dump(mode="json")}),
        )
        self._arm_timeout(assignment, remaining_ms=timeout_ms)
        self._jobs_total.inc(event="dispatched")
        self._assignments.inc(worker=worker.workerId)
        wait_s = max(0.0, time.time() - qj.enqueued_at)
        self._queue_wait.observe(wait_s)
        self.capacity.note_dispatch(request.model, wait_s)
        self._end_queue_span(request.id, worker=worker.workerId)
        self.tracer.event(request.id, "scheduler.dispatch",
                          worker=worker.workerId)
        log.job("job assigned", request.id, worker_id=worker.workerId)
        self.emit("job_assigned", assignment)
        return True

    def _arm_timeout(self, assignment: JobAssignment, remaining_ms: float) -> None:
        loop = asyncio.get_running_loop()
        job_id = assignment.jobId

        def fire() -> None:
            self._timeout_handles.pop(job_id, None)
            asyncio.ensure_future(self._handle_job_timeout(job_id))

        self._timeout_handles[job_id] = loop.call_later(remaining_ms / 1000, fire)

    # -- completion/failure handlers ---------------------------------------
    async def _on_job_completed(self, _ch: str, raw: str) -> None:
        """reference: JobScheduler.ts:434-461."""
        try:
            result = JobResult.model_validate_json(raw)
        except Exception:
            return
        self._mark_done(result.jobId)
        if not self._owns(result.jobId):
            # sharded control plane (ISSUE 15): lifecycle channels fan
            # out to every shard; only the partition owner accounts the
            # job (a non-owner counting "duplicate execution" here would
            # multiply every completion by M-1 shards)
            return
        if result.jobId not in self.active_jobs:
            # stale/duplicate completion — but in the race window where the
            # orphan sweep requeued this job just before its (successful)
            # result arrived, a copy of an already-answered request is still
            # sitting in the queue or on the retry ladder; purge it so it is
            # never executed again. Purging IS this job's completion (the
            # orphaned copy was its only live record), so count it.
            if await self._drop_resolved(result.jobId):
                self._jobs_total.inc(event="completed")
                self._drop_resume_state(result.jobId)
                # orphan-race completion still resolves the request — fold
                # its usage exactly as the normal path would (conservation:
                # every published usage payload is accounted once)
                self.usage.account(result.usage, "completed")
                if result.usage:
                    self.capacity.note_completion(
                        str(result.usage.get("model") or ""),
                        result.processingTimeMs / 1000)
                self.emit("job_completed", result)
                self.request_dispatch()
            else:
                # no pending copy either → the job already resolved through
                # another worker and THIS execution's tokens were wasted
                # work (the at-least-once cost goodput accounting exists
                # to surface)
                wasted = int(getattr(result.response, "eval_count", 0) or 0)
                self.slo.record_waste(wasted, reason="duplicate_execution")
                # the engine really spent these tokens and counted them on
                # its side of the ledger — account them under an explicit
                # "duplicate" outcome so per-tenant sums stay conserved
                self.usage.account(result.usage, "duplicate")
                self.flightrec.record(
                    "scheduler", "duplicate_completion",
                    job=result.jobId, worker=result.workerId, tokens=wasted)
            return
        assignment = self.active_jobs.get(result.jobId)
        self._migrations.pop(result.jobId, None)
        self._drop_resume_state(result.jobId)
        await self._clear_active(result.jobId, free_worker=True)
        self._jobs_total.inc(event="completed")
        # usage ledger + demand model (ISSUE 16): the owning shard folds
        # the result's cost payload exactly once
        self.usage.account(result.usage, "completed")
        model = (assignment.request.model if assignment is not None
                 else str((result.usage or {}).get("model") or ""))
        if model:
            self.capacity.note_completion(model,
                                          result.processingTimeMs / 1000)
        log.job("job completed", result.jobId, worker_id=result.workerId,
                ms=round(result.processingTimeMs, 1))
        self.emit("job_completed", result)
        self.request_dispatch()

    async def _on_job_failed(self, _ch: str, raw: str) -> None:
        """Retry with delay while attempts remain; deliver the final failure
        to the waiter only when they run out (reference: JobScheduler.ts:463-514,
        minus the waiter-rejects-on-first-failure defect)."""
        try:
            result = JobResult.model_validate_json(raw)
        except Exception:
            return
        if not self._owns(result.jobId) \
                or not self._fence("failure", result.jobId):
            return
        assignment = self.active_jobs.get(result.jobId)
        if assignment is None:
            return
        self._migrations.pop(result.jobId, None)
        await self._clear_active(result.jobId, free_worker=True)
        request = assignment.request
        if result.nack:
            # capacity NACK: the job never ran — requeue at the front
            # WITHOUT touching the retry ladder. Bounded by nackCount so a
            # pathological nack-storm still terminates via the real ladder.
            nacks = int(request.metadata.get("nackCount", 0)) + 1
            request.metadata["nackCount"] = nacks
            if nacks <= self.config.max_nacks:
                self._front_seq -= 1
                qj = _QueuedJob(request, self._front_seq)
                self.job_queue.insert(0, qj)
                await self._persist_queued(qj)
                self._jobs_total.inc(event="nacked")
                self.flightrec.record("scheduler", "nacked",
                                      job=result.jobId,
                                      worker=result.workerId, nacks=nacks)
                self._begin_queue_span(request, nacked=True)
                log.job("assignment NACKed; requeued (no retry consumed)",
                        result.jobId, worker_id=result.workerId, nacks=nacks)
                self.request_dispatch()
                return
            log.warning("nack storm; entering retry ladder",
                        job_id=result.jobId, nacks=nacks)
        retry_count = int(request.metadata.get("retryCount", 0))
        allow_retry = (retry_count < self.config.retry_attempts
                       and result.retryable)
        if allow_retry and not self._take_retry_token():
            # fleet-wide retry budget burning (ISSUE 9): shed to
            # immediate failure — a degraded fleet must not melt under
            # its own retry storm
            allow_retry = False
            self._jobs_total.inc(event="retry_budget_exhausted")
            self.flightrec.record("scheduler", "retry_budget_exhausted",
                                  job=result.jobId,
                                  error=str(result.error)[:200])
            result = result.model_copy(update={
                "error": f"retry_budget_exhausted: {result.error}",
                "retryable": False,
            })
        if allow_retry:
            request.metadata["retryCount"] = retry_count + 1
            request.metadata["lastError"] = result.error
            # capped exponential backoff with FULL jitter (ISSUE 9):
            # delay ~ U[0, min(cap, base·2^attempt)] — decorrelated
            # retries spread a thundering herd instead of re-spiking it
            delay_s = self._retry_backoff_ms(retry_count) / 1000 \
                * random.random()
            # a failed attempt may have streamed tokens already — resume
            # from the watermark so the retry never double-streams
            self._stamp_resume(request)
            self._jobs_total.inc(event="retried")
            self.tracer.event(result.jobId, "scheduler.retry",
                              attempt=retry_count + 1, error=result.error)
            self.flightrec.record("scheduler", "retry", job=result.jobId,
                                  attempt=retry_count + 1,
                                  error=str(result.error)[:200])
            log.job("job failed; retry scheduled", result.jobId,
                    attempt=retry_count + 1, delay_s=delay_s, error=result.error)

            def do_retry() -> None:
                self._retry_handles.pop(result.jobId, None)
                if self._running:
                    asyncio.ensure_future(self.add_job(request, requeue=True))

            loop = asyncio.get_running_loop()
            self._retry_handles[result.jobId] = loop.call_later(delay_s, do_retry)
        else:
            self._jobs_total.inc(event="failed")
            self._mark_done(result.jobId)
            self._drop_resume_state(result.jobId)
            self.usage.note_outcome(
                str(request.metadata.get("tenant") or ""),
                request.model, "failed")
            self.flightrec.record("scheduler", "failed", job=result.jobId,
                                  worker=result.workerId,
                                  tenant=str(request.metadata
                                             .get("tenant") or ""),
                                  model=request.model,
                                  error=str(result.error)[:200])
            self.tracer.abort(result.jobId, reason="failed")
            log.job("job failed permanently", result.jobId, error=result.error)
            await self.bus.publish(job_result_channel(result.jobId), result.model_dump_json())
            self.emit("job_failed", result)
        self.request_dispatch()

    async def _handle_job_timeout(self, job_id: str) -> None:
        """Server-side job timeout (reference: JobScheduler.ts:516-551)."""
        if not self._fence("timeout", job_id):
            # deposed shard (ISSUE 15): the partition's new owner re-armed
            # this job's timeout from the durable assignment — firing it
            # here would publish a cancellation + failure for a job that
            # is alive and someone else's
            return
        # claim the assignment synchronously BEFORE any await: the
        # waiter-side cancel_job(reason="timeout") can interleave during a
        # bus suspension and this timeout must be accounted exactly once
        assignment = self.active_jobs.pop(job_id, None)
        if assignment is None:
            return  # already completed/cancelled — benign
        self._migrations.pop(job_id, None)
        self._mark_done(job_id)
        self._drop_resume_state(job_id)
        self._jobs_total.inc(event="timeout")
        self.usage.note_outcome(
            str(assignment.request.metadata.get("tenant") or ""),
            assignment.request.model, "timeout")
        self.flightrec.record("scheduler", "timeout", job=job_id,
                              worker=assignment.workerId,
                              tenant=str(assignment.request.metadata
                                         .get("tenant") or ""),
                              model=assignment.request.model)
        # close any still-open spans for the job so a timeout storm cannot
        # leak tracer state (asserted by the chaos tests)
        self._end_queue_span(job_id, timeout=True)
        self.tracer.abort(job_id, reason="timeout")
        log.job("job timed out", job_id, worker_id=assignment.workerId)
        try:
            await self.publish_cancellation(assignment.workerId, job_id,
                                            "timeout")
        finally:
            # already claimed + accounted above — a dead bus must not skip
            # the persisted-record/timer/worker cleanup
            await self._clear_active(job_id, free_worker=True,
                                     assignment=assignment)
        result = JobResult(jobId=job_id, workerId=assignment.workerId,
                           success=False, error="Job timed out")
        await self.bus.publish(job_result_channel(job_id), result.model_dump_json())
        self.emit("job_timeout", result)
        self.request_dispatch()

    # -- disaggregated handoff (ISSUE 7) ------------------------------------
    async def _on_handoff(self, _ch: str, raw: str) -> None:
        """``job:handoff`` from a prefill worker after its KV migration
        resolved. ok=True → move the live assignment to the planned
        decode worker and dispatch the decode phase (the request now
        carries ``disaggPhase=decode``; the decode engine admits warm
        from the imported pages). ok=False → the prefill worker is
        already serving the request locally (graceful degradation) and
        this message only accounts the fallback."""
        try:
            data = json.loads(raw)
            job_id = data["jobId"]
        except Exception:
            return
        if not self._owns(job_id):
            return
        from_worker = str(data.get("fromWorker") or "")
        mig = self._migrations.get(job_id)
        if mig is not None and mig.get("from") != from_worker:
            # stale handoff from a PREVIOUS placement (the job was
            # orphaned and replanned meanwhile): the live migration
            # record belongs to the new placement and must survive
            return
        ok = bool(data.get("ok"))
        if not ok:
            self._migrations.pop(job_id, None)
            self._disagg_total.inc(event="fallback")
            self.flightrec.record(
                "scheduler", "disagg_fallback", job=job_id,
                worker=from_worker,
                reason=str(data.get("reason") or "")[:120])
            self.tracer.event(job_id, "scheduler.disagg_fallback",
                              reason=str(data.get("reason") or ""))
            # the decode worker prepared a receiver that will never see
            # (the rest of) the stream — release its assembly state so a
            # failed transfer cannot leak buffers there
            to_worker = str(data.get("toWorker")
                            or (mig or {}).get("to") or "")
            if to_worker:
                try:
                    await self.bus.publish(
                        worker_job_channel(to_worker),
                        json.dumps({"type": "kv_release", "jobId": job_id}))
                except Exception as e:  # noqa: BLE001 — best-effort
                    log.warning("kv_release publish failed", job_id=job_id,
                                worker=to_worker, error=str(e))
            return
        assignment = self.active_jobs.get(job_id)
        if assignment is None or assignment.workerId != from_worker:
            return  # resolved/cancelled meanwhile — stale handoff
        self._migrations.pop(job_id, None)
        to_worker = str(data.get("toWorker")
                        or (mig or {}).get("to") or "")
        self._disagg_total.inc(event="handoff")
        self.tracer.event(
            job_id, "scheduler.handoff",
            fromWorker=assignment.workerId, toWorker=to_worker,
            migratedTokens=int(data.get("tokens") or 0),
            bytes=int(data.get("bytes") or 0),
            transferMs=round(float(data.get("seconds") or 0) * 1000, 2),
            path=str(data.get("path") or ""))
        # release the prefill half: worker freed, timeout disarmed; the
        # decode assignment below re-arms with the job's full budget
        await self._clear_active(job_id, free_worker=True,
                                 assignment=assignment)
        if job_id in self._cancelled:
            # cancelled during the await above: cancel_job found the job
            # in no collection (we had just popped it) and accounted the
            # cancellation — re-adding would resurrect a dead job onto
            # the decode pool with nobody listening
            return
        target = self.registry.get_worker(to_worker)
        if target is None or target.status not in ("online", "busy"):
            # decode worker vanished after acking the import: its copy of
            # the pages died with it — requeue through the migration_lost
            # path (the prefill worker still holds a cached copy, so a
            # re-placement there is warm)
            self._disagg_total.inc(event="handoff_worker_lost")
            await self._orphan_job(assignment, reason="migration_lost")
            self.request_dispatch()
            return
        request = assignment.request
        request.metadata["disaggPhase"] = "decode"
        request.metadata["kvxTokens"] = int(data.get("tokens") or 0)
        handoff = JobAssignment(
            jobId=job_id, workerId=to_worker, request=request,
            timeout=assignment.timeout,
        )
        self.active_jobs[job_id] = handoff
        await self.bus.hset(self._akey(job_id), job_id,
                            handoff.model_dump_json())
        await self.registry.mark_worker_busy(to_worker)
        await self.bus.publish(
            worker_job_channel(to_worker),
            json.dumps({"type": "job_assignment",
                        "job": handoff.model_dump(mode="json")}),
        )
        self._arm_timeout(handoff, remaining_ms=handoff.timeout)
        self._assignments.inc(worker=to_worker)
        self.flightrec.record("scheduler", "handoff", job=job_id,
                              fromWorker=data.get("fromWorker"),
                              toWorker=to_worker,
                              tokens=int(data.get("tokens") or 0))
        log.job("job handed off to decode worker", job_id,
                from_worker=str(data.get("fromWorker")),
                worker_id=to_worker)
        self.emit("job_assigned", handoff)

    async def _drop_resolved(self, job_id: str) -> bool:
        """Remove every pending copy of a job whose result has already been
        delivered (queued entry, persisted queue record, retry timer).
        Returns True if a pending copy existed."""
        retry = self._retry_handles.pop(job_id, None)
        if retry is not None:
            retry.cancel()
        dropped = retry is not None
        for i, qj in enumerate(self.job_queue):
            if qj.request.id == job_id:
                self.job_queue.pop(i)
                await self.bus.hdel(self._qkey(job_id), job_id)
                dropped = True
                break
        if dropped:
            self._end_queue_span(job_id, resolved_elsewhere=True)
            log.job("already-resolved job purged from queue", job_id)
        return dropped

    # -- fault tolerance: resume watermarks + graceful drain (ISSUE 9) ------

    def _merge_snapshot(self, job_id: str, snap: dict[str, Any]) -> None:
        """Monotonic merge: a snapshot only replaces the stored one when
        it covers MORE generated tokens — late/out-of-order deliveries
        (and empty drain snapshots) can never roll the watermark back.
        A token-free snapshot still creates the entry when it carries a
        seed: workers publish one at generation start so an UNSEEDED
        sampled request that dies before its first token snapshot retries
        with the SAME resolved seed — a fresh seed would regenerate
        different text and the gateway's offset trim would splice two
        divergent samples into one corrupt stream."""
        try:
            tokens = [int(t) for t in snap.get("tokens") or []]
        except (TypeError, ValueError):
            return
        cur = self._resume_snap.get(job_id)
        if cur is None:
            if tokens or snap.get("seed") is not None:
                self._resume_snap[job_id] = {"tokens": tokens,
                                             "seed": snap.get("seed")}
            return
        if len(cur["tokens"]) >= len(tokens):
            return
        seed = snap.get("seed")
        self._resume_snap[job_id] = {
            "tokens": tokens,
            "seed": seed if seed is not None else cur.get("seed")}

    async def _on_snapshot(self, _ch: str, raw: str) -> None:
        """Worker-published decode-state watermark on ``job:snapshot``:
        the generated token ids (and resolved sampler seed) as of some
        point mid-decode. Stored per live job; orphan/retry/drain stamp
        it into the requeue so the replacement continues the decode."""
        try:
            data = json.loads(raw)
            job_id = data["jobId"]
        except Exception:
            return
        if not self._owns(job_id):
            return
        if job_id in self.active_jobs and isinstance(data.get("tokens"), list):
            self._merge_snapshot(job_id, data)
            if self.shard is not None:
                # sharded mode (ISSUE 15): stream frames flow worker →
                # gateway replicas, so the snapshot cadence is the only
                # per-job sign of life a shard sees — feed it to the
                # watchdog's progress map or every healthy long decode
                # would read as a dispatch/prefill hang
                now = time.time()
                first = self._stream_progress.get(job_id, (now, now))[0]
                self._stream_progress[job_id] = (first, now)

    def _stamp_resume(self, request: InferenceRequest) -> bool:
        """Attach the job's resume watermark to its metadata before a
        requeue/handoff: generated token ids, the resolved sampler seed,
        and the chars this gateway already delivered to the client (the
        exactly-once emission offset). No watermark → no stamp — the job
        restarts from zero exactly as before ISSUE 9. A token-free
        (seed-only) watermark still stamps: replaying the same seed makes
        an unseeded sampled restart byte-identical, which the gateway's
        overlap trim depends on."""
        snap = self._resume_snap.get(request.id)
        if snap is None:
            return False
        request.metadata["resume"] = {
            "tokens": list(snap["tokens"]),
            "seed": snap.get("seed"),
            "sentChars": int(self._stream_chars.get(request.id, 0)),
        }
        self._resume_total.inc(event="stamped")
        return True

    def _drop_resume_state(self, job_id: str) -> None:
        self._resume_snap.pop(job_id, None)
        self._stream_chars.pop(job_id, None)

    def _mark_done(self, job_id: str) -> None:
        """Record a terminal outcome for the sharded-mode resolved-job
        memory (adoption replay + queue-hash reconcile read it). No-op
        in local mode — nothing consults it there."""
        if self.shard is None:
            return
        self._recent_done[job_id] = time.time()
        while len(self._recent_done) > 1024:
            self._recent_done.pop(next(iter(self._recent_done)))

    async def _on_drain(self, _ch: str, raw: str) -> None:
        """``job:drain`` from a draining worker that suspended an active
        decode. migrated=True with a live target → move the assignment
        there (its KV pages were just imported — the resume admission is
        warm); otherwise front-requeue WITH the snapshot. Either way the
        gateway stream continues with no duplicate and no lost token."""
        try:
            data = json.loads(raw)
            job_id = data["jobId"]
        except Exception:
            return
        if not self._owns(job_id) or not self._fence("drain", job_id):
            return
        from_worker = str(data.get("fromWorker") or "")
        assignment = self.active_jobs.get(job_id)
        if assignment is None or assignment.workerId != from_worker:
            return  # resolved/reassigned meanwhile — stale drain report
        snap = data.get("snapshot")
        if isinstance(snap, dict):
            self._merge_snapshot(job_id, snap)
        self._migrations.pop(job_id, None)
        await self._clear_active(job_id, free_worker=True,
                                 assignment=assignment)
        if job_id in self._cancelled:
            # cancelled during the await — stay dead, and drop the
            # watermark _merge_snapshot above may have just re-created
            self._drop_resume_state(job_id)
            return
        request = assignment.request
        request.metadata.pop("disagg", None)
        request.metadata.pop("disaggPhase", None)
        self._stamp_resume(request)
        self._stream_progress.pop(job_id, None)
        to_worker = str(data.get("toWorker") or "")
        target = self.registry.get_worker(to_worker) if to_worker else None
        if (bool(data.get("migrated")) and target is not None
                and target.status in ("online", "busy")):
            handoff = JobAssignment(
                jobId=job_id, workerId=to_worker, request=request,
                timeout=assignment.timeout,
            )
            self.active_jobs[job_id] = handoff
            await self.bus.hset(self._akey(job_id), job_id,
                                handoff.model_dump_json())
            await self.registry.mark_worker_busy(to_worker)
            await self.bus.publish(
                worker_job_channel(to_worker),
                json.dumps({"type": "job_assignment",
                            "job": handoff.model_dump(mode="json")}),
            )
            self._arm_timeout(handoff, remaining_ms=handoff.timeout)
            self._assignments.inc(worker=to_worker)
            self._resume_total.inc(event="drain_handoff")
            self.tracer.event(job_id, "scheduler.drain_handoff",
                              fromWorker=from_worker, toWorker=to_worker,
                              tokens=int(data.get("tokens") or 0),
                              bytes=int(data.get("bytes") or 0))
            self.flightrec.record("scheduler", "drain_handoff", job=job_id,
                                  fromWorker=from_worker,
                                  toWorker=to_worker,
                                  tokens=int(data.get("tokens") or 0))
            log.job("job moved off draining worker", job_id,
                    from_worker=from_worker, worker_id=to_worker)
            self.emit("job_assigned", handoff)
        else:
            # mark the requeue as already-ran work: the deadline shed in
            # the dispatch pass exempts drained/orphaned/resumed jobs
            request.metadata["drained"] = True
            request.priority = Priority.high
            self._front_seq -= 1
            qj = _QueuedJob(request, self._front_seq)
            self.job_queue.insert(0, qj)
            await self._persist_queued(qj)
            self._resume_total.inc(event="drain_requeued")
            self.flightrec.record("scheduler", "drain_requeued",
                                  job=job_id, fromWorker=from_worker)
            self._begin_queue_span(request, drained=True)
            self.tracer.event(job_id, "scheduler.drain_requeued",
                              fromWorker=from_worker)
            log.job("drained job requeued with resume snapshot", job_id,
                    from_worker=from_worker)
            self.request_dispatch()

    # -- preemption-based priority (ISSUE 11) --------------------------------

    async def _maybe_preempt(self, qj: _QueuedJob, now: float) -> None:
        """Suspend-to-host trigger: a queued generation of a strictly
        higher priority class, unplaceable for preempt_after_ms while the
        model's workers are saturated, asks ONE worker to suspend its
        lowest-priority running generation (``job_preempt``). The victim
        parks its KV in the host tier, requeues at the BACK of its own
        class with its resume watermark (exactly-once via the drain/
        resume machinery), and pages back in when pressure clears."""
        cfg_ms = self.config.preempt_after_ms
        if cfg_ms <= 0:
            return
        req = qj.request
        if not self._fence("preempt", req.id):
            return
        if (now - qj.enqueued_at) * 1000 < cfg_ms:
            return
        # prune stale asks (victim resolved meanwhile / worker never
        # answered) so a lost publish cannot wedge preemption forever
        for jid, t in list(self._preempting.items()):
            if jid not in self.active_jobs or now - t > 15.0:
                self._preempting.pop(jid, None)
        if self._preempting:
            return  # one suspend-to-host in flight fleet-wide
        rank = req.priority.rank

        def preemptible(a: JobAssignment) -> bool:
            if (a.request.model != req.model
                    or a.request.priority.rank <= rank
                    or a.request.request_type not in ("inference", "chat",
                                                      "generate")):
                return False
            # a draining worker NACKs/ignores preempt asks (its jobs are
            # already being suspended out) — asking it would silently
            # stall the one-in-flight gate until the stale prune
            w = self.registry.get_worker(a.workerId)
            return w is not None and w.status in ("online", "busy")

        victims = [a for a in self.active_jobs.values() if preemptible(a)]
        if not victims:
            return
        # lowest priority first; among equals the most recently assigned
        # (least progress lost to the suspend/resume round trip)
        victim = max(victims,
                     key=lambda a: (a.request.priority.rank, a.assignedAt))
        self._preempting[victim.jobId] = now
        self._jobs_total.inc(event="preempt_requested")
        self.flightrec.record("scheduler", "preempt_requested",
                              job=victim.jobId, worker=victim.workerId,
                              waiting=req.id)
        self.tracer.event(victim.jobId, "scheduler.preempt_requested",
                          waitingJob=req.id, worker=victim.workerId)
        log.job("preempting lower-priority job for queued work",
                victim.jobId, worker_id=victim.workerId, waiting=req.id)
        try:
            await self.bus.publish(
                worker_job_channel(victim.workerId),
                json.dumps({"type": "job_preempt", "jobId": victim.jobId,
                            "reason": f"priority:{req.id}"}))
        except Exception as e:  # noqa: BLE001 — retried next dispatch pass
            self._preempting.pop(victim.jobId, None)
            log.warning("preempt publish failed", job_id=victim.jobId,
                        error=str(e))

    async def _on_preempted(self, _ch: str, raw: str) -> None:
        """``job:preempted`` from a worker that suspended a generation to
        the host KV tier. Requeue the victim at the BACK of its own
        priority class (the waiting higher-priority job must dispatch
        into the freed slot first) with its resume watermark stamped —
        when pressure clears it re-dispatches and its warm admission
        restores the parked pages from host."""
        try:
            data = json.loads(raw)
            job_id = data["jobId"]
        except Exception:
            return
        if not self._owns(job_id) or not self._fence("preempt", job_id):
            return
        from_worker = str(data.get("fromWorker") or "")
        self._preempting.pop(job_id, None)
        assignment = self.active_jobs.get(job_id)
        if assignment is None or assignment.workerId != from_worker:
            return  # resolved/reassigned meanwhile — stale report
        snap = data.get("snapshot")
        if isinstance(snap, dict):
            self._merge_snapshot(job_id, snap)
        self._migrations.pop(job_id, None)
        await self._clear_active(job_id, free_worker=True,
                                 assignment=assignment)
        if job_id in self._cancelled:
            self._drop_resume_state(job_id)
            return
        request = assignment.request
        request.metadata.pop("disagg", None)
        request.metadata.pop("disaggPhase", None)
        self._stamp_resume(request)
        self._stream_progress.pop(job_id, None)
        # already-ran marker: deadline shed exempts it, and the priority
        # deliberately stays the victim's own — back of ITS class, so the
        # preemptor (higher class) sorts first regardless of seq
        request.metadata["preempted"] = True
        qj = _QueuedJob(request, self._seq)
        self._seq += 1
        self.job_queue.append(qj)
        await self._persist_queued(qj)
        self._jobs_total.inc(event="preempted")
        self.flightrec.record("scheduler", "preempted", job=job_id,
                              fromWorker=from_worker,
                              parkedTokens=int(data.get("parkedTokens")
                                               or 0))
        self._begin_queue_span(request, preempted=True)
        self.tracer.event(job_id, "scheduler.preempted",
                          fromWorker=from_worker,
                          parkedTokens=int(data.get("parkedTokens") or 0))
        log.job("preempted job requeued with resume snapshot", job_id,
                from_worker=from_worker)
        self.request_dispatch()

    def _deadline_for(self, request: InferenceRequest) -> int:
        """Effective deadline (ms) for a request's SLO class; the class
        dict overrides the global default, 0 disables."""
        cls = classify_request(request)
        classes = self.config.request_deadline_classes or {}
        return int(classes.get(cls, self.config.request_deadline_ms))

    async def _shed_deadline(self, request: InferenceRequest) -> None:
        """Fail a queued job that outlived its class deadline: the waiter
        gets a non-retryable ``deadline_exceeded`` result (gateway → 504)
        and the queue slot frees immediately."""
        job_id = request.id
        self._mark_done(job_id)
        self._jobs_total.inc(event="deadline_exceeded")
        self.flightrec.record("scheduler", "deadline_exceeded", job=job_id,
                              model=request.model)
        self._end_queue_span(job_id, deadline_exceeded=True)
        self.tracer.abort(job_id, reason="deadline_exceeded")
        self._drop_resume_state(job_id)
        result = JobResult(jobId=job_id, workerId="", success=False,
                           error="deadline_exceeded", retryable=False)
        log.job("queued job shed past deadline", job_id,
                model=request.model)
        await self.bus.publish(job_result_channel(job_id),
                               result.model_dump_json())
        self.emit("job_failed", result)

    def _retry_backoff_ms(self, attempt: int) -> float:
        """Backoff ceiling for the Nth retry (0-based): base·2^N capped
        at retry_backoff_max_ms. The caller multiplies by U[0,1) (full
        jitter)."""
        base = max(self.config.retry_delay_ms, 0)
        cap = max(self.config.retry_backoff_max_ms, base)
        return float(min(cap, base * (2 ** max(attempt, 0))))

    def _take_retry_token(self) -> bool:
        """Token-bucket retry budget: refills at retry_budget_per_min,
        caps at one minute's worth. 0 = unlimited."""
        per_min = self.config.retry_budget_per_min
        if per_min <= 0:
            return True
        now = time.monotonic()
        self._retry_tokens = min(
            float(per_min),
            self._retry_tokens
            + (now - self._retry_refill_t) * per_min / 60.0)
        self._retry_refill_t = now
        if self._retry_tokens >= 1.0:
            self._retry_tokens -= 1.0
            return True
        return False

    # -- orphan machinery ---------------------------------------------------
    async def _on_worker_removed(self, worker_id: str, _info: WorkerInfo, reason: str) -> None:
        """Requeue all active jobs of a dead worker at the front with high
        priority (reference: JobScheduler.ts:553-630)."""
        doomed = [a for a in self.active_jobs.values() if a.workerId == worker_id]
        for assignment in doomed:
            await self._orphan_job(assignment, reason=f"worker_removed:{reason}")
        if doomed:
            self.request_dispatch()

    async def _orphan_job(self, assignment: JobAssignment, reason: str) -> None:
        """Promote to high priority, requeue at the FRONT, record audit
        metadata (reference: JobScheduler.ts:259-315).

        Mid-migration deaths (ISSUE 7): a job still carrying a live
        migration record died between its prefill placement and the
        handoff. Both ends must drop their KV-transfer state — the
        prefill worker's in-flight send, the decode worker's partially
        assembled import — BEFORE the requeue, or a late chunk stream
        could ghost into the retried job's transfer. The requeue reason
        becomes ``migration_lost`` and the stale plan is stripped so the
        fresh placement replans from live registry state."""
        job_id = assignment.jobId
        if not self._fence("orphan", job_id):
            return
        mig = self._migrations.pop(job_id, None)
        if mig is not None:
            reason = "migration_lost"
            self._disagg_total.inc(event="migration_lost")
            self.flightrec.record("scheduler", "migration_lost", job=job_id,
                                  fromWorker=mig["from"], toWorker=mig["to"])
            for wid in {mig["from"], mig["to"]}:
                try:
                    await self.bus.publish(
                        worker_job_channel(wid),
                        json.dumps({"type": "kv_release", "jobId": job_id}))
                except Exception as e:  # noqa: BLE001 — best-effort release
                    log.warning("kv_release publish failed", job_id=job_id,
                                worker=wid, error=str(e))
        await self._clear_active(job_id, free_worker=False)
        # mark the loss on the trace BEFORE the requeue opens fresh spans:
        # the dead worker will never publish its half of the timeline, and
        # /admin/trace must say so instead of showing an unexplained gap
        self.tracer.event(job_id, "scheduler.worker_lost",
                          worker=assignment.workerId, reason=reason)
        self._stream_progress.pop(job_id, None)
        self.flightrec.record("scheduler", "orphaned", job=job_id,
                              worker=assignment.workerId, reason=reason)
        request = assignment.request
        request.priority = Priority.high
        md = request.metadata
        md.pop("disagg", None)       # stale plan: the fresh dispatch pass
        md.pop("disaggPhase", None)  # replans against live pools
        # requeue hygiene (ISSUE 9): stripping the stale disagg plan must
        # NOT drop the resume watermark — a resume-eligible orphan
        # continues its decode on the replacement worker (any already-
        # stamped metadata.resume survives; a fresher snapshot wins)
        if self._stamp_resume(request):
            self.tracer.event(job_id, "scheduler.resume_stamped",
                              tokens=len(md["resume"]["tokens"]),
                              sentChars=md["resume"]["sentChars"])
        md["orphaned"] = True
        md["originalWorkerId"] = assignment.workerId
        md["orphanedAt"] = time.time()
        md["requeueCount"] = int(md.get("requeueCount", 0)) + 1
        # Front of queue: dedicated shrinking counter, so front inserts
        # survive crash-reload (concurrent orphans end up LIFO at the front,
        # matching the reference's unshift loop, JobScheduler.ts:585-618).
        self._front_seq -= 1
        qj = _QueuedJob(request, self._front_seq)
        self.job_queue.insert(0, qj)
        await self._persist_queued(qj)
        self._jobs_total.inc(event="orphaned")
        self._begin_queue_span(request, orphaned=True,
                               original_worker=assignment.workerId)
        log.job("job orphaned and requeued", job_id,
                original_worker=assignment.workerId, reason=reason,
                requeue_count=md["requeueCount"])
        self.emit("job_orphaned", request)

    async def _sweep_loop(self) -> None:
        """Safety-net sweep (reference: the 1 s tick, JobScheduler.ts:128-135
        — here only orphan detection + a dispatch fallback, plus the
        sharded queue-hash reconcile every few ticks)."""
        interval = self.config.sweep_interval_ms / 1000
        tick = 0
        while self._running:
            await asyncio.sleep(interval)
            tick += 1
            try:
                await self._check_for_orphaned_jobs()
                if self.shard is not None and tick % 5 == 0:
                    await self._reconcile_shard_queues()
                now = time.time()
                for job_id, at in list(self._cancelled.items()):
                    if now - at > 60:
                        del self._cancelled[job_id]
                if self.job_queue:
                    self.request_dispatch()
            except Exception as e:
                log.error("sweep failed", error=str(e))

    async def _reconcile_shard_queues(self) -> None:
        """Sharded-mode repair + garbage collection (ISSUE 15): walk the
        durable queue hash of every HELD partition and resolve records
        this scheduler does not have locally. Two sources produce them:
        non-owners park every submit they ignore (so an owner-less or
        missed-delivery window cannot lose the job), and a park racing
        past the owner's dispatch/cancel hdel leaves a ghost. Unknown
        records of live/resolved jobs are ghosts — collected; genuinely
        unknown requests are ADOPTED into the queue (the parked-submit
        recovery path)."""
        local = {qj.request.id for qj in self.job_queue}
        picked = 0
        for idx in self.shard.held():
            if not self.shard.lease.fenced(idx):
                continue  # stale lease: neither collect nor adopt
            qkey = shard_queue_key(idx)
            for job_id, raw in (await self.bus.hgetall(qkey)).items():
                if job_id in local:
                    continue
                if job_id in self.active_jobs                         or job_id in self._recent_done                         or job_id in self._retry_handles                         or job_id in self._cancelled:
                    # ghost of a dispatched/resolved/cancelled job
                    await self.bus.hdel(qkey, job_id)
                    continue
                try:
                    rec = json.loads(raw)
                    req = InferenceRequest.model_validate(rec["request"])
                except Exception:
                    await self.bus.hdel(qkey, job_id)
                    continue
                qj = _QueuedJob(req, self._seq)
                self._seq += 1
                self.job_queue.append(qj)
                self._begin_queue_span(req, reconciled=True)
                self._ctrl_submits.inc(event="reconciled")
                picked += 1
                log.job("parked submission reconciled into queue", job_id,
                        shard=idx)
        if picked:
            self.request_dispatch()

    async def _check_for_orphaned_jobs(self) -> None:
        """reference: JobScheduler.ts:219-257 — assignment older than the
        threshold AND worker gone or silent beyond the window."""
        if liveness_suspended(self.bus,
                              self.config.bus_rejoin_grace_ms):
            # partition-aware liveness (ISSUE 10): while our own bus
            # session is degraded (or within the rejoin grace) every
            # worker looks silent — orphaning their jobs would duplicate
            # work that is still streaming fine on the other side of the
            # partition. The registry holds its death verdicts on the
            # same signal; organic orphans are caught on the first sweep
            # after the grace expires.
            return
        now = time.time()
        threshold_s = self.config.orphan_assign_threshold_ms / 1000
        window_s = self.config.quick_disconnect_window_ms / 1000
        for assignment in list(self.active_jobs.values()):
            if now - assignment.assignedAt < threshold_s:
                continue
            worker = self.registry.get_worker(assignment.workerId)
            if worker is None or now - worker.lastHeartbeat > window_s:
                await self._orphan_job(assignment, reason="orphan_sweep")
        self.request_dispatch()

    # -- internals ----------------------------------------------------------
    async def _persist_queued(self, qj: _QueuedJob) -> None:
        await self.bus.hset(
            self._qkey(qj.request.id), qj.request.id,
            json.dumps({"seq": qj.seq, "request": qj.request.model_dump(mode="json")}),
        )

    async def _clear_active(self, job_id: str, free_worker: bool,
                            assignment: JobAssignment | None = None) -> None:
        """``assignment`` carries a pre-popped entry: callers that must claim
        the job synchronously before their first await pass it here so the
        worker is still released."""
        assignment = self.active_jobs.pop(job_id, None) or assignment
        await self.bus.hdel(self._akey(job_id), job_id)
        handle = self._timeout_handles.pop(job_id, None)
        if handle is not None:
            handle.cancel()
        if assignment is not None and free_worker:
            await self.registry.mark_worker_available(assignment.workerId)
