"""WorkerService: the active worker runtime.

Reference analogue: client/src/services/WorkerClientService.ts — connect,
self-register, heartbeat, execute assigned jobs, stream results back over
the bus. Deliberate divergences (fix-by-design, SURVEY.md §2.8):

- concurrency: the engine's continuous batch supersedes the reference's
  1-job gate; over-capacity assignments are NACKed with job:failed (the
  reference silently DROPPED them, WorkerClientService.ts:500-505, leaving
  recovery to the 10-minute timeout)
- chat keeps structured messages end-to-end (requestType "chat" actually
  reaches the chat path — unreachable in the reference, §2.2)
- stream frames may batch several tokens inside a flush window (the
  reference crossed Redis once per token, §3.4)
- timing fields are real engine measurements (the reference zeroed them on
  its OpenAI-facade path, §2.8)
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any

from gridllm_tpu.bus.base import MessageBus, Subscription
from gridllm_tpu.engine import GenerationRequest, GenerationResult, InferenceEngine
from gridllm_tpu.obs import Tracer, default_registry, trace_channel
from gridllm_tpu.utils.config import WorkerConfig
from gridllm_tpu.utils.events import EventEmitter
from gridllm_tpu.utils.logging import bind_request_id, get_logger
from gridllm_tpu.utils.types import (
    InferenceResponse,
    JobAssignment,
    JobResult,
    StreamChunk,
    WorkerInfo,
    iso_now,
)
from gridllm_tpu.worker.capabilities import gather_capabilities, total_slots
from gridllm_tpu.worker.chat import collect_images
from gridllm_tpu.worker.prompting import (
    build_generate_prompt,
    extract_json,
    json_instruction,
    parse_tool_calls,
    render_chat_full,
    split_thinking,
)

log = get_logger("worker")


# single source of truth shared with the advertised maxConcurrentTasks
_capacity = total_slots

# Worker-plane job outcomes (process-global registry; the worker's health
# port serves /metrics from it — worker/main.py)
_JOBS_TOTAL = default_registry().counter(
    "gridllm_worker_jobs_total",
    "Jobs executed by worker services in this process, by outcome "
    "(completed/failed/cancelled/nacked/duplicate_dropped).",
    ("event",),
)


class NonRetryableJobError(RuntimeError):
    """Failure that is permanent cluster-wide (e.g. generation on an
    embedding-only model) — published with retryable=False so the
    scheduler fails the job immediately instead of burning the retry
    ladder on an outcome that cannot change."""


class WorkerService(EventEmitter):
    """Events: registered, job_started, job_completed, job_failed, stopped."""

    def __init__(
        self,
        bus: MessageBus,
        engines: dict[str, InferenceEngine],
        config: WorkerConfig | None = None,
        stream_flush_ms: int = 20,
        engine_factory: Any | None = None,
    ):
        super().__init__()
        self.bus = bus
        self.engines = engines
        self.config = config or WorkerConfig()
        self.worker_id = self.config.worker_id
        self.stream_flush_s = stream_flush_ms / 1000.0
        self.current_jobs = 0
        self.total_processed = 0
        self.max_concurrent = _capacity(engines)
        # model management (/api/pull): builds an InferenceEngine for a
        # model name on demand (worker/main.py passes its config-bound
        # builder). None → load_model admin ops are rejected.
        self.engine_factory = engine_factory
        # multi-host worker groups disable ALL admin ops (load/unload/
        # copy), not just load: a slice builds identical engines on every
        # process for plan replay — a liaison-only unload would free the
        # liaison's HBM, orphan the followers' copies, and leave the
        # slice asymmetric with no way to reload (worker/main.py).
        self.admin_ops_enabled = True
        self._admin_lock = asyncio.Lock()
        self._admin_tasks: set[asyncio.Task] = set()
        self._running = False
        self._subs: list[Subscription] = []
        self._tasks: list[asyncio.Task] = []
        self._cancelled: set[str] = set()
        self._executing: set[str] = set()
        self._last_status: str | None = None
        # per-request execution spans; published on trace:{request_id} when
        # the job resolves so the gateway can stitch its side of the
        # timeline with ours (obs/tracer.py)
        self.tracer = Tracer(source=f"worker:{self.worker_id}")

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._running = True
        self._subs.append(await self.bus.subscribe(
            f"worker:{self.worker_id}:job", self._on_job_message))
        self._subs.append(await self.bus.subscribe(
            f"worker:reregister:{self.worker_id}", self._on_reregister))
        self._subs.append(await self.bus.subscribe(
            "worker:admin", self._on_admin))
        await self.register()
        self._tasks.append(asyncio.create_task(self._heartbeat_loop()))
        self._tasks.append(asyncio.create_task(self._resource_loop()))
        # each generation engine owns a dedicated dispatch thread with
        # pipelined decode blocks (engine.start(); VERDICT r03 #2 replaced
        # the per-step asyncio.to_thread pump)
        for eng in self.engines.values():
            if not eng.embedding_only:
                eng.start()
        self._tasks.append(asyncio.create_task(self._engine_watchdog()))
        log.info("worker started", workerId=self.worker_id,
                 models=list(self.engines))

    async def stop(self, announce: bool = True) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        for eng in self.engines.values():
            if not eng.embedding_only:
                await asyncio.to_thread(eng.stop)
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()
        if announce:
            await self.bus.publish(
                "worker:unregistered", json.dumps({"workerId": self.worker_id})
            )
        self.emit("stopped")

    def _info(self) -> WorkerInfo:
        return WorkerInfo(
            workerId=self.worker_id,
            capabilities=gather_capabilities(
                self.worker_id, self.engines,
                self.config.performance_tier or None,  # type: ignore[arg-type]
            ),
            status=self._status(),
            currentJobs=self.current_jobs,
            totalJobsProcessed=self.total_processed,
        )

    def _status(self) -> str:
        return "busy" if self.current_jobs >= self.max_concurrent else "online"

    async def register(self) -> None:
        info = self._info()
        await self.bus.hset("workers", self.worker_id, info.model_dump_json())
        await self.bus.publish("worker:registered", info.model_dump_json())
        self.emit("registered", info)

    async def _on_reregister(self, _ch: str, _raw: str) -> None:
        log.info("re-registration requested", workerId=self.worker_id)
        await self.register()

    # --------------------------------------------------- model management
    #
    # Ollama's pull/delete/copy, reimagined for a cluster: the gateway
    # broadcasts an admin op on `worker:admin`; every worker answers on
    # `admin:result:{op id}` with {workerId, ok, detail}. The reference
    # had client-side pullModel/deleteModel stubs that no route ever
    # called (client/src/services/OllamaService.ts:286-331) — here the
    # routes are real and the weights come from the worker's local
    # checkpoint root ("pull" = load-on-demand; this deployment has no
    # remote registry to download from).

    async def _on_admin(self, _ch: str, raw: str) -> None:
        msg = json.loads(raw)
        op, rid = msg.get("op"), msg.get("id")
        if not op or not rid:
            return
        # immediate ack BEFORE doing the (possibly minutes-long) work:
        # lets the gateway distinguish "loading a 70B checkpoint" from
        # "no worker speaks the admin protocol" and bail fast on the
        # latter instead of waiting out the whole op timeout. The op
        # itself runs in a SPAWNED task — the bus pump serializes handler
        # calls, and an op queued behind a long load would otherwise get
        # no ack within the gateway's grace window and be spuriously
        # failed. Ops still execute one at a time (self._admin_lock) so
        # concurrent loads of the same model cannot double-build.
        await self.bus.publish(f"admin:result:{rid}", json.dumps({
            "workerId": self.worker_id, "op": op, "ack": True,
        }))
        if not self.admin_ops_enabled:
            await self.bus.publish(f"admin:result:{rid}", json.dumps({
                "workerId": self.worker_id, "op": op, "ok": False,
                "detail": "model management disabled on multi-host "
                          "worker groups",
            }))
            return

        async def run_op() -> None:
            ok, detail = False, ""
            try:
                async with self._admin_lock:
                    if op == "load_model":
                        ok, detail = await self._admin_load(msg["model"])
                    elif op == "unload_model":
                        ok, detail = await self._admin_unload(
                            msg["model"], if_idle=bool(msg.get("if_idle"))
                        )
                    elif op == "copy_model":
                        ok, detail = await self._admin_copy(
                            msg["source"], msg["destination"]
                        )
                    else:
                        detail = f"unknown admin op {op!r}"
            except Exception as e:  # noqa: BLE001 — always answer the gateway
                detail = str(e)
            await self.bus.publish(f"admin:result:{rid}", json.dumps({
                "workerId": self.worker_id, "op": op, "ok": ok,
                "detail": detail,
            }))

        task = asyncio.create_task(run_op())
        self._admin_tasks.add(task)  # strong ref until done (GC hazard)
        task.add_done_callback(self._admin_tasks.discard)

    async def _admin_load(self, model: str) -> tuple[bool, str]:
        if self._resolve_engine(model) is not None:
            return True, "already loaded"
        if self.engine_factory is None:
            return False, "model management disabled on this worker"
        eng = await asyncio.to_thread(self.engine_factory, model)
        if not eng.embedding_only:
            eng.start()
        self.engines[model] = eng
        self.max_concurrent = _capacity(self.engines)
        await self.register()
        src = "checkpoint" if eng.config.checkpoint_path else "random-init"
        log.info("model loaded on demand", model=model, weights=src)
        return True, f"loaded ({src})"

    async def _admin_unload(self, model: str,
                            if_idle: bool = False) -> tuple[bool, str]:
        name = self._resolve_name(model)
        if name is None:
            return False, "not loaded here"
        if if_idle:
            # keep_alive sweeps must never abort work: the worker is the
            # ground truth for business — a request admitted in the
            # gateway's check-to-unload window is visible HERE (engine
            # slots/pending, or a job executing in this service)
            eng = self.engines[name]
            busy = self.current_jobs > 0 or (
                not eng.embedding_only
                and (bool(eng._slots) or bool(eng._pending))
            )
            if busy:
                return False, "busy (if_idle unload declined)"
        eng = self.engines.pop(name)
        # copies alias the same engine under other names; only stop the
        # runner when the last name referencing it is gone. Abort first:
        # stop() alone would leave in-flight/queued requests without their
        # error callback, hanging their clients until the gateway timeout.
        if eng not in self.engines.values() and not eng.embedding_only:
            eng.abort_all(f"model {name} unloaded")
            await asyncio.to_thread(eng.stop)
        self.max_concurrent = _capacity(self.engines)
        await self.register()
        log.info("model unloaded", model=name)
        return True, "unloaded"

    async def _admin_copy(self, source: str, dest: str) -> tuple[bool, str]:
        eng = self._resolve_engine(source)
        if eng is None:
            return False, "source not loaded here"
        if dest in self.engines:
            return True, "destination already exists"
        self.engines[dest] = eng  # alias: same engine, second name
        await self.register()
        log.info("model copied", source=source, destination=dest)
        return True, "copied"

    # -------------------------------------------------------------- loops

    async def _heartbeat_loop(self) -> None:
        interval = self.config.heartbeat_interval_ms / 1000.0
        while self._running:
            try:
                await self.bus.set_with_expiry(
                    f"heartbeat:{self.worker_id}", str(time.time()), ttl_s=interval * 2
                )
                await self.bus.publish("worker:heartbeat", json.dumps({
                    "workerId": self.worker_id,
                    "status": self._status(),
                    "currentJobs": self.current_jobs,
                }))
            except Exception as e:  # bus hiccup: keep beating
                log.warning("heartbeat failed", error=str(e))
            await asyncio.sleep(interval)

    async def _resource_loop(self) -> None:
        """Refresh capabilities + change-deduped status publishing
        (reference: WorkerClientService.ts:355-440)."""
        interval = self.config.resource_monitor_interval_ms / 1000.0
        while self._running:
            await asyncio.sleep(interval)
            try:
                info = self._info()
                await self.bus.hset("workers", self.worker_id, info.model_dump_json())
                await self._publish_status_if_changed()
            except Exception as e:
                log.warning("resource refresh failed", error=str(e))

    async def _publish_status_if_changed(self) -> None:
        status = self._status()
        if status != self._last_status:
            self._last_status = status
            await self.bus.publish("worker:status_update", json.dumps({
                "workerId": self.worker_id,
                "status": status,
                "currentJobs": self.current_jobs,
            }))

    async def _engine_watchdog(self) -> None:
        """The engine runner recovers from step failures itself (abort +
        device-state rebuild, engine/engine.py _run); if a runner dies for
        good (3 consecutive failures) the worker must stop advertising the
        model so the scheduler routes elsewhere (reference: worker drops
        from the registry via missed heartbeats — here the model list
        shrinks while the worker stays)."""
        while self._running:
            await asyncio.sleep(2.0)
            dead = [
                m for m, e in self.engines.items()
                if not e.embedding_only and not e.running
            ]
            if not dead:
                continue
            for m in dead:
                log.error("engine runner dead; dropping model", model=m)
            self.engines = {
                m: e for m, e in self.engines.items() if m not in dead
            }
            self.max_concurrent = _capacity(self.engines)
            try:
                await self.register()  # advertise the reduced model set
            except Exception as reg_err:
                log.warning("re-register after engine drop failed",
                            error=str(reg_err))

    # ---------------------------------------------------------------- jobs

    async def _on_job_message(self, _ch: str, raw: str) -> None:
        msg = json.loads(raw)
        if msg.get("type") == "job_cancellation":
            job_id = msg.get("jobId", "")
            self._cancelled.add(job_id)
            for eng in self.engines.values():
                if eng.cancel(job_id):
                    break
            return
        if msg.get("type") != "job_assignment":
            return
        assignment = JobAssignment.model_validate(msg["job"])
        if assignment.jobId in self._executing:
            # re-dispatch of a job we are ALREADY running: the scheduler's
            # orphan sweep re-orphans an in-flight job when first-compile
            # GIL pressure starves our heartbeat past the disconnect
            # window, then hands it straight back. The in-flight run will
            # publish the result; running it twice would waste a slot and
            # double-stream the client.
            _JOBS_TOTAL.inc(event="duplicate_dropped")
            self.tracer.event(assignment.jobId, "worker.duplicate_dropped",
                              worker=self.worker_id)
            log.warning("duplicate assignment dropped",
                        jobId=assignment.jobId)
            return
        if self.current_jobs >= self.max_concurrent:
            # NACK instead of the reference's silent drop
            _JOBS_TOTAL.inc(event="nacked")
            self.tracer.event(assignment.jobId, "worker.nack",
                              worker=self.worker_id,
                              currentJobs=self.current_jobs)
            await self._publish_failure(
                assignment, "worker at capacity", nack=True
            )
            await self._publish_trace(assignment.jobId)
            return
        # marked HERE (not in _execute) so two back-to-back deliveries
        # can't both pass the dedup check before either task starts
        self._executing.add(assignment.jobId)
        asyncio.ensure_future(self._execute(assignment))

    def _resolve_name(self, model: str) -> str | None:
        """Served-engine key for a requested model name: exact match, plus
        the one alias Ollama itself applies — a bare model name means the
        ':latest' tag and vice versa. (The round-1 dash heuristic —
        model.split('-')[0] — could only ever produce wrong or missed
        lookups, e.g. 'all-minilm' → 'all'.)"""
        if model in self.engines:
            return model
        if model.endswith(":latest") and model[: -len(":latest")] in self.engines:
            return model[: -len(":latest")]
        if ":" not in model and f"{model}:latest" in self.engines:
            return f"{model}:latest"
        return None

    def _resolve_engine(self, model: str) -> InferenceEngine | None:
        name = self._resolve_name(model)
        return None if name is None else self.engines[name]

    async def _execute(self, assignment: JobAssignment) -> None:
        req = assignment.request
        self.current_jobs += 1
        started = time.time()
        span = self.tracer.begin(req.id, "worker.execute",
                                 worker=self.worker_id, model=req.model,
                                 requestType=req.request_type)
        outcome = "failed"
        # everything that can raise (bus publishes included) sits inside the
        # try: the finally MUST run, or req.id leaks in _executing and every
        # future re-dispatch of this job is dropped as a duplicate
        try:
            await self._publish_status_if_changed()
            self.emit("job_started", assignment)
            with bind_request_id(req.id):
                engine = self._resolve_engine(req.model)
                if engine is None:
                    raise ValueError(f"model not served here: {req.model}")
                rtype = req.request_type
                if rtype == "embedding":
                    response = await self._run_embedding(engine, req)
                else:
                    response = await self._run_generation(engine, assignment)
                if response is None:
                    # cancelled — scheduler already resolved it
                    outcome = "cancelled"
                    return
                result = JobResult(
                    jobId=req.id, workerId=self.worker_id, success=True,
                    response=response,
                    processingTimeMs=(time.time() - started) * 1000,
                )
                await self.bus.publish("job:completed", result.model_dump_json())
                await self.bus.publish(f"job:result:{req.id}", result.model_dump_json())
                # only after BOTH publishes: a publish failure goes down the
                # retryable-failure path and must not be recorded completed
                self.total_processed += 1
                outcome = "completed"
                self.emit("job_completed", result)
        except Exception as e:
            log.warning("job failed", jobId=req.id, error=str(e))
            span.meta["error"] = str(e)
            await self._publish_failure(
                assignment, str(e),
                retryable=not isinstance(e, NonRetryableJobError),
            )
        finally:
            # local bookkeeping first — it must survive a dead bus; the
            # status publish goes last because it can raise on bus loss
            # (_publish_trace guards internally)
            self._executing.discard(req.id)
            self.current_jobs -= 1
            _JOBS_TOTAL.inc(event=outcome)
            self.tracer.end(span, outcome=outcome)
            await self._publish_trace(req.id)
            await self._publish_status_if_changed()

    async def _publish_trace(self, request_id: str) -> None:
        """Seal the request's span timeline and ship it to the gateway."""
        spans = self.tracer.finish(request_id)
        if not spans:
            return
        try:
            await self.bus.publish(trace_channel(request_id), json.dumps({
                "requestId": request_id,
                "workerId": self.worker_id,
                "spans": spans,
            }))
        except Exception as e:  # noqa: BLE001 — tracing must never fail a job
            log.warning("trace publish failed", request_id=request_id,
                        error=str(e))

    async def _publish_failure(
        self, assignment: JobAssignment, error: str, nack: bool = False,
        retryable: bool = True,
    ) -> None:
        result = JobResult(
            jobId=assignment.jobId, workerId=self.worker_id,
            success=False, error=error, retryable=retryable, nack=nack,
        )
        await self.bus.publish("job:failed", result.model_dump_json())
        if not nack:
            self.emit("job_failed", result)

    async def _run_embedding(
        self, engine: InferenceEngine, req
    ) -> InferenceResponse:
        texts = req.input if req.input is not None else req.prompt
        single = isinstance(texts, str)
        texts = [texts] if single else list(texts or [])
        t0 = time.perf_counter_ns()
        with self.tracer.span(req.id, "engine.embed", texts=len(texts)):
            vecs = await asyncio.to_thread(engine.embed, texts)
        dur = time.perf_counter_ns() - t0
        return InferenceResponse(
            id=req.id, model=req.model, created_at=iso_now(), done=True,
            embeddings=vecs, embedding=vecs[0] if single and vecs else None,
            total_duration=dur,
            prompt_eval_count=sum(len(t) for t in texts),
        )

    async def _run_generation(
        self, engine: InferenceEngine, assignment: JobAssignment
    ) -> InferenceResponse | None:
        req = assignment.request
        md = req.metadata or {}
        streaming = bool(req.stream)
        is_chat = req.request_type == "chat" or (
            req.messages is not None and req.prompt is None
        )
        fmt = req.format if req.format is not None else md.get("format")
        think = md.get("think")
        raw = bool(md.get("raw"))
        if is_chat:
            messages = list(req.messages or [])
            if md.get("system") and not any(
                m.get("role") == "system" for m in messages
            ):
                messages = [{"role": "system", "content": md["system"]}] + messages
            if fmt:
                messages = messages + [
                    {"role": "system", "content": json_instruction(fmt)}
                ]
            prompt = render_chat_full(
                messages, engine.tokenizer, tools=req.tools, think=think,
            )
        else:
            base = req.prompt or ""
            if fmt and not raw:
                base = base + json_instruction(fmt)
            prompt = build_generate_prompt(
                base, engine.tokenizer,
                system=md.get("system"), template=md.get("template"),
                suffix=md.get("suffix"), raw=raw,
            )

        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_chunk(delta: str, done: bool, res: GenerationResult | None):
            loop.call_soon_threadsafe(q.put_nowait, (delta, done, res))

        opts = dict(req.options or {})
        context = opts.pop("context", None) or getattr(req, "context", None)
        gen = GenerationRequest(
            id=req.id, prompt=prompt, options=opts,
            raw=raw or bool(opts.get("raw")), on_chunk=on_chunk,
            images=collect_images(req) or None,
        )
        # format / tools / think outputs are post-processed from the FULL
        # text; suppress intermediate stream frames so streamed bytes can
        # never disagree with the final extracted result (divergence from
        # Ollama's grammar-constrained streaming, documented in prompting.py)
        if fmt or req.tools or think:
            streaming = False
        if context:
            gen.prompt_ids = list(context) + engine.tokenizer.encode(
                prompt, add_bos=False
            )
        engine.submit(gen)
        t_submit = time.time()
        t_first: float | None = None

        buf = ""
        eval_count = 0
        last_flush = time.monotonic()
        while True:
            timeout = self.stream_flush_s if (streaming and buf) else None
            try:
                delta, done, res = await asyncio.wait_for(q.get(), timeout)
            except asyncio.TimeoutError:
                await self._flush_stream(req, buf, eval_count)
                buf, last_flush = "", time.monotonic()
                continue
            if delta and t_first is None:
                # only a frame actually carrying a token counts — a bare
                # done frame (cancel/error/immediate EOS) must not leave a
                # fake first-token mark on the trace
                t_first = time.time()
                self.tracer.event(req.id, "worker.first_token",
                                  sinceSubmitMs=round(
                                      (t_first - t_submit) * 1000, 3))
            buf += delta
            if done:
                assert res is not None
                # engine-stage spans: submit→first-token is the honest
                # prefill wait (queue + compile + prefill dispatch),
                # first-token→done the decode stretch; engine-measured ns
                # ride in meta for exact attribution
                now = time.time()
                tf = t_first if t_first is not None else now
                self.tracer.record(
                    req.id, "engine.prefill", t_submit, tf,
                    promptTokens=res.prompt_eval_count,
                    engineNs=res.prompt_eval_duration_ns)
                if res.eval_count:
                    self.tracer.record(
                        req.id, "engine.decode", tf, now,
                        tokens=res.eval_count,
                        engineNs=res.eval_duration_ns)
                if res.done_reason == "cancel":
                    return None
                if res.done_reason == "error":
                    msg = res.error or res.text or "generation failed"
                    if not res.retryable:
                        raise NonRetryableJobError(msg)
                    raise RuntimeError(msg)
                return await self._finalize_generation(
                    req, res, buf, is_chat, streaming,
                    fmt=fmt, tools=req.tools, think=think,
                )
            eval_count += 1
            if streaming and buf and (
                time.monotonic() - last_flush >= self.stream_flush_s
            ):
                await self._flush_stream(req, buf, eval_count)
                buf, last_flush = "", time.monotonic()

    async def _flush_stream(self, req, text: str, eval_count: int) -> None:
        if not text:
            return
        chunk = StreamChunk(
            id=req.id, model=req.model, created_at=iso_now(),
            response=text, done=False, eval_count=eval_count,
        )
        if req.request_type == "chat":
            chunk.message = {"role": "assistant", "content": text}
        await self.bus.publish(f"job:stream:{req.id}", chunk.model_dump_json())

    async def _finalize_generation(
        self, req, res: GenerationResult, tail: str, is_chat: bool,
        streaming: bool, fmt=None, tools=None, think=None,
    ) -> InferenceResponse:
        if streaming and tail:
            await self._flush_stream(req, tail, res.eval_count)
        response = InferenceResponse(
            id=req.id, model=req.model, created_at=iso_now(),
            done=True, done_reason=res.done_reason,
            total_duration=res.total_duration_ns,
            load_duration=res.load_duration_ns,
            prompt_eval_count=res.prompt_eval_count,
            prompt_eval_duration=res.prompt_eval_duration_ns,
            eval_count=res.eval_count,
            eval_duration=res.eval_duration_ns,
        )
        text = res.text
        thinking = None
        if think:
            thinking, text = split_thinking(text)
        tool_calls: list[dict] = []
        if is_chat and tools:
            tool_calls, text = parse_tool_calls(text)
        if fmt:
            text = extract_json(text)
        if is_chat:
            message: dict = {"role": "assistant", "content": text}
            if thinking:
                message["thinking"] = thinking
            if tool_calls:
                message["tool_calls"] = tool_calls
            response.message = message
        else:
            response.response = text
            response.thinking = thinking
            response.context = res.context
        return response
