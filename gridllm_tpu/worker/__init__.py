"""TPU worker runtime (SURVEY.md §7 `worker/`).

Reference analogue: client/src/services/WorkerClientService.ts (760 LoC) —
registration, heartbeats, job execution, streaming. The Ollama HTTP adapter
(OllamaService.ts) is replaced by in-process InferenceEngine instances.
"""

from gridllm_tpu.worker.service import WorkerService

__all__ = ["WorkerService"]
