"""``python -m gridllm_tpu.worker`` — same as the ``gridllm-worker``
console script, for PYTHONPATH-only (uninstalled) deployments."""

from gridllm_tpu.worker.main import main

main()
