"""Slice membership + failure propagation for multi-host logical workers.

The hard part SURVEY.md §7 step 6 names: reconciling a multi-host worker
group with the single-worker heartbeat/orphan protocol (§2.6). Rules:

- ONE logical worker: only the liaison (process 0) registers on the bus,
  heartbeats `heartbeat:{workerId}`, and executes the job protocol.
- EVERY process (liaison included) additionally maintains a member TTL key
  `heartbeat:group:{workerId}:{processId}` on the bus.
- Any member key expiring ⇒ the slice is broken ⇒ the WHOLE logical worker
  must fail fast: the liaison announces `worker:disconnected` and stops
  heartbeating, so the scheduler's orphan machinery requeues in-flight jobs
  (scheduler.py orphan path; reference analogue JobScheduler.ts:553-630).
  Followers exit so the operator's supervisor restarts the slice together.

A slice member that dies WITHOUT expiring its TTL first (clean exit) deletes
its key, which the monitors see immediately — same fast-eviction idea as the
reference's socket-close `worker:disconnected` publish
(RedisConnectionManager.ts:158-179).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Awaitable, Callable

from gridllm_tpu.bus.base import CH_WORKER_DISCONNECTED, MessageBus
from gridllm_tpu.parallel.distributed import GroupConfig
from gridllm_tpu.utils.logging import get_logger

log = get_logger("worker.group")


def member_key(worker_id: str, process_id: int) -> str:
    return f"heartbeat:group:{worker_id}:{process_id}"


class GroupMembership:
    """Per-process membership beacon + slice-health monitor.

    `on_slice_failure` fires (once) when any member of the slice goes
    silent. The liaison passes a callback that fails the logical worker;
    followers pass one that exits the process.
    """

    def __init__(
        self,
        bus: MessageBus,
        worker_id: str,
        group: GroupConfig,
        heartbeat_interval_s: float = 5.0,
        on_slice_failure: Callable[[str], Awaitable[None]] | None = None,
    ):
        self.bus = bus
        self.worker_id = worker_id
        self.group = group
        self.interval_s = heartbeat_interval_s
        self.on_slice_failure = on_slice_failure
        self._tasks: list[asyncio.Task] = []
        self._running = False
        self._failed = False
        # a member is only monitored after it has been seen once, so slice
        # startup (processes join over several seconds) is not a "failure"
        self._seen: set[int] = set()

    async def start(self) -> None:
        if not self.group.is_group:
            return
        self._running = True
        await self._beat_once()
        self._tasks.append(asyncio.create_task(self._beacon_loop()))
        self._tasks.append(asyncio.create_task(self._monitor_loop()))
        log.info("group membership active", worker=self.worker_id,
                 process=f"{self.group.process_id}/{self.group.num_processes}")

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        if self.group.is_group:
            try:
                await self.bus.delete(
                    member_key(self.worker_id, self.group.process_id)
                )
            except Exception:
                pass

    async def _beat_once(self) -> None:
        await self.bus.set_with_expiry(
            member_key(self.worker_id, self.group.process_id),
            str(time.time()), ttl_s=self.interval_s * 2,
        )

    async def _beacon_loop(self) -> None:
        while self._running:
            try:
                await self._beat_once()
            except Exception as e:
                log.warning("group beacon failed", error=str(e))
            await asyncio.sleep(self.interval_s)

    async def _monitor_loop(self) -> None:
        check_s = max(self.interval_s / 2, 0.05)
        while self._running:
            await asyncio.sleep(check_s)
            try:
                missing = await self._missing_members()
            except Exception as e:
                log.warning("group monitor bus error", error=str(e))
                continue
            if missing and not self._failed:
                self._failed = True
                reason = f"slice members lost: {sorted(missing)}"
                log.error("worker group broken", worker=self.worker_id,
                          reason=reason)
                if self.on_slice_failure is not None:
                    await self.on_slice_failure(reason)
                return

    async def _missing_members(self) -> set[int]:
        missing: set[int] = set()
        for pid in range(self.group.num_processes):
            if pid == self.group.process_id:
                continue
            val = await self.bus.get(member_key(self.worker_id, pid))
            if val is None:
                if pid in self._seen:
                    missing.add(pid)
            else:
                self._seen.add(pid)
        return missing


async def fail_logical_worker(bus: MessageBus, worker_id: str, reason: str) -> None:
    """Liaison-side slice failure: announce disconnection so the scheduler
    evicts the worker and orphans its jobs immediately (fast path — the
    heartbeat TTL would get there ~10 s later anyway)."""
    try:
        await bus.publish(CH_WORKER_DISCONNECTED, json.dumps({
            "workerId": worker_id, "reason": reason,
        }))
        await bus.hdel("workers", worker_id)
    except Exception as e:
        log.warning("failure announce failed", error=str(e))
