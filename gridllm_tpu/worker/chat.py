"""Chat-message templating: structured messages → a model prompt.

Fixes reference defect SURVEY.md §2.8: `/ollama/api/chat` flattened messages
to `role: content` lines AND routed them down the generate path
(server/src/routes/ollama.ts:367-370). Here messages survive to the worker
(metadata.requestType == "chat") and are templated per-model:

- HF tokenizers with a chat_template use `apply_chat_template` (the
  model's own trained format).
- Otherwise (byte tokenizer / templateless): a llama3-style plain-text
  header framing that keeps roles distinguishable.

Multimodal `images` are collected by collect_images() and travel to the
engine on GenerationRequest.images — per-model capability is the ENGINE's
call (a non-vision model rejects loudly; the reference just forwarded them
to Ollama, OllamaService.ts:197-226).
"""

from __future__ import annotations

from typing import Any

from gridllm_tpu.engine.tokenizer import Tokenizer


def collect_images(req) -> list[str]:
    """All base64 images on a request: top-level (generate path) plus
    per-message (chat path, incl. OpenAI content-array conversions)."""
    images = list(getattr(req, "images", None) or [])
    for m in getattr(req, "messages", None) or []:
        images.extend(m.get("images") or [])
    return images


def render_chat(messages: list[dict[str, Any]], tokenizer: Tokenizer) -> str:
    """Back-compat shim: tool/think-aware rendering lives in
    worker/prompting.py (render_chat_full); plain chats route through it."""
    from gridllm_tpu.worker.prompting import render_chat_full

    return render_chat_full(messages, tokenizer)
