"""Multi-host step-plan transport: liaison → followers over the bus.

Round-3 VERDICT missing #1: followers joined the jax group and then just
waited — but JAX multi-controller SPMD requires EVERY process to issue the
same computation, so a mesh spanning hosts with liaison-only dispatch
deadlocks on the first collective. This module closes the loop:

- The liaison's engines emit one compact record per device-dispatching
  action (engine.plan_sink: admit / block / deact / embed / reset — all
  plain host data). PlanPublisher stamps a sequence number and publishes
  them on ONE per-worker channel ``slice:{worker_id}:plan`` with the
  model name attached — a single totally-ordered stream, because a
  multi-model slice's engines all dispatch into the same global mesh and
  cross-model dispatch order must match across processes (the engines'
  shared dispatch_lock makes the liaison's emission order equal its
  dispatch order). The records ride the SAME bus the job protocol uses
  (SURVEY §5.8's two-plane design: bus for control, ICI/XLA collectives
  for array traffic).
- PlanFollower (on every non-liaison process) subscribes, checks the
  sequence is gapless (bus pub/sub has no replay: one lost record means
  irrecoverable divergence → fail the slice fast so the supervisor
  restarts it together), and replays each record through
  engine.apply_plan_op on a dedicated thread — the follower's analogue
  of the liaison's runner thread.

Latency: a record crosses the bus in ~ms while a decode block occupies
the devices for tens of ms, and dispatch is asynchronous on every
process — the collectives themselves rendezvous the slice, so follower
lag never stalls the liaison until it exceeds the device queue depth.
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
from typing import Awaitable, Callable

from gridllm_tpu.bus.base import MessageBus, Subscription, plan_channel
from gridllm_tpu.engine import InferenceEngine
from gridllm_tpu.utils.logging import get_logger

log = get_logger("worker.plan")


def ready_key(worker_id: str, process_id: int) -> str:
    """Bus key a follower sets once its plan subscription is LIVE — the
    liaison must not register (and start taking jobs) before every
    follower can hear the plan, or the first records land on a channel
    with no subscribers (pub/sub has no replay) and the slice diverges
    at startup."""
    return f"slice:{worker_id}:ready:{process_id}"


class PlanPublisher:
    """Liaison side: engine.plan_sink → ordered bus publishes.

    The sink is called from the engine's runner thread; records are
    queued thread-safely and drained by ONE async task so wire order
    always equals emission order (a create_task per publish could
    interleave at await points)."""

    def __init__(self, bus: MessageBus, channel: str,
                 loop: asyncio.AbstractEventLoop):
        self.bus = bus
        self.channel = channel
        self._loop = loop
        self._seq = 0
        self._q: asyncio.Queue[str] = asyncio.Queue()
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = self._loop.create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def sink(self, rec: dict) -> None:
        """engine.plan_sink entry — runner-thread safe."""
        self._seq += 1
        msg = json.dumps({"seq": self._seq, "rec": rec})
        self._loop.call_soon_threadsafe(self._q.put_nowait, msg)

    async def _drain(self) -> None:
        while True:
            msg = await self._q.get()
            try:
                await self.bus.publish(self.channel, msg)
            except Exception as e:  # noqa: BLE001 — bus hiccup: keep order,
                log.error("plan publish failed", error=str(e))
                # a dropped record breaks lockstep; followers detect the
                # seq gap and fail the slice — nothing useful to do here


class PlanFollower:
    """Follower side: bus records → engine.apply_plan_op, in order, on ONE
    dedicated replay thread across all of the worker's models (total
    order matches the liaison's shared dispatch lock)."""

    def __init__(self, bus: MessageBus, channel: str,
                 engines: dict[str, InferenceEngine],
                 on_divergence: Callable[[str], Awaitable[None]]):
        self.bus = bus
        self.channel = channel
        self.engines = engines
        self.on_divergence = on_divergence
        self.applied = 0
        self._expected = 1
        self._sub: Subscription | None = None
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(
            target=self._replay, name="plan-replay", daemon=True,
        )
        self._thread.start()
        self._sub = await self.bus.subscribe(self.channel, self._on_msg)

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None
        self._stop.set()
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    async def _on_msg(self, _ch: str, raw: str) -> None:
        d = json.loads(raw)
        if d["seq"] != self._expected:
            await self.on_divergence(
                f"plan sequence gap: expected {self._expected}, "
                f"got {d['seq']} (lost record → SPMD divergence)"
            )
            return
        self._expected += 1
        self._q.put(d["rec"])

    def _replay(self) -> None:
        while not self._stop.is_set():
            rec = self._q.get()
            if rec is None:
                return
            try:
                eng = self.engines[rec["model"]]
                eng.apply_plan_op(rec)
                self.applied += 1
            except Exception as e:  # noqa: BLE001
                log.error("plan replay failed", op=rec.get("op"),
                          error=str(e))
                if self._loop is not None:
                    asyncio.run_coroutine_threadsafe(
                        self.on_divergence(f"plan replay failed: {e}"),
                        self._loop,
                    )
                return
