"""Worker capability gathering.

Reference analogue: WorkerClientService.gatherNodeCapabilities
(client/src/services/WorkerClientService.ts:129-154) — which never filled
systemResources or performanceTier (SURVEY.md §2.3 ⚠). Fix-by-design: both
are populated here, plus the TPU additions (topology, shard layouts) the
scheduler's topology-aware routing uses.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform

from gridllm_tpu.utils.types import (
    ModelInfo,
    ModelShardLayout,
    NodeCapabilities,
    SystemResources,
    TpuTopology,
    iso_now,
)


def _meminfo_mb() -> tuple[float, float]:
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                fields[k] = float(v.strip().split()[0]) / 1024.0
        return fields.get("MemTotal", 0.0), fields.get("MemAvailable", 0.0)
    except OSError:  # non-linux
        return 0.0, 0.0


def system_resources() -> SystemResources:
    total, avail = _meminfo_mb()
    try:
        load1 = os.getloadavg()[0]
        cores = os.cpu_count() or 1
        cpu_pct = min(100.0, 100.0 * load1 / cores)
    except OSError:
        cpu_pct = 0.0
    return SystemResources(
        cpuCores=os.cpu_count() or 1,
        totalMemoryMB=total,
        availableMemoryMB=avail,
        cpuUsagePercent=round(cpu_pct, 1),
        memoryUsagePercent=round(100.0 * (1 - avail / total), 1) if total else 0.0,
        platform=platform.system().lower(),
        architecture=platform.machine(),
    )


def tpu_topology() -> TpuTopology:
    import jax

    devices = jax.devices()
    kinds = {d.device_kind for d in devices}
    hosts = {getattr(d, "process_index", 0) for d in devices}
    return TpuTopology(
        platform=devices[0].platform,
        numDevices=len(devices),
        numHosts=len(hosts),
        deviceKind=", ".join(sorted(kinds)),
    )


def _param_count_estimate(mc) -> int:
    """Decoder param count from the config dims (embed + L×(attn+ffn))."""
    try:
        e, f, v = mc.hidden_size, mc.intermediate_size, mc.vocab_size
        h, kvh, d, L = mc.num_heads, mc.num_kv_heads, mc.head_dim_, mc.num_layers
        attn = e * h * d + 2 * e * kvh * d + h * d * e
        ffn = 3 * e * f
        if getattr(mc, "num_experts", 0):
            ffn *= mc.num_experts
        head = 0 if mc.tie_embeddings else e * v
        return v * e + L * (attn + ffn) + head
    except AttributeError:
        return 0


def _human_params(n: int) -> str:
    if n <= 0:
        return "Unknown"
    if n >= 1e9:
        return f"{n / 1e9:.1f}B"
    return f"{n / 1e6:.0f}M"


def total_slots(engines: dict) -> int:
    """Total concurrent slots across UNIQUE engines — /api/copy aliases
    the same engine under a second name, and counting it per name would
    over-advertise capacity (the scheduler would over-assign; jobs queue
    inside the engine instead of being NACKed to other workers). Single
    source of truth for both the worker's admission gate
    (worker/service.py) and the advertised maxConcurrentTasks here."""
    uniq = {id(e): e for e in engines.values()}
    return max(
        sum(getattr(getattr(e, "config", None), "max_slots", 1)
            for e in uniq.values()),
        1,
    )


def gather_capabilities(
    worker_id: str,
    engines: dict[str, object],
    performance_tier: str | None = None,
) -> NodeCapabilities:
    topo = tpu_topology()
    if performance_tier is None:
        performance_tier = "high" if topo.platform == "tpu" else "medium"
    models, layouts = [], []
    max_slots = total_slots(engines)
    for name, eng in engines.items():
        c = getattr(eng, "config", None)
        mc = getattr(eng, "cfg", None)
        details = None
        if mc is not None:
            family = getattr(mc, "family", "unknown")
            families = [family]
            if getattr(mc, "vision", False):
                families.append("clip")  # Ollama marks vision via families
            n_params = _param_count_estimate(mc)
            details = {
                "parent_model": "", "format": "safetensors",
                "family": family, "families": families,
                "parameter_size": _human_params(n_params),
                "quantization_level": (
                    "Q8_0" if getattr(c, "quantize", None) == "int8"
                    else str(getattr(c, "dtype", "bfloat16")).upper()
                ),
                "vision": bool(getattr(mc, "vision", False)),
                # active fleet health (ISSUE 19): the canary prober keys
                # its golden output hash on (model, engineConfigHash) —
                # two workers share a golden ONLY when every knob that
                # can legitimately change sampled bytes matches. A dtype
                # or quantization drift is then a health incident, not a
                # new golden.
                "engineConfigHash": hashlib.sha256(json.dumps({
                    "model": name,
                    "family": family,
                    "dtype": str(getattr(c, "dtype", "bfloat16")),
                    "quantize": getattr(c, "quantize", None),
                    "platform": topo.platform,
                }, sort_keys=True).encode()).hexdigest()[:16],
            }
        models.append(ModelInfo(name=name, model=name, details=details))
        mesh = getattr(eng, "mesh", None)
        layouts.append(ModelShardLayout(
            name=name,
            strategy="pipeline" if mesh is not None and mesh.shape.get("pp", 1) > 1
            else "tensor" if mesh is not None and mesh.shape.get("tp", 1) > 1
            else "expert" if mesh is not None and mesh.shape.get("ep", 1) > 1
            else "replicated",
            meshAxes=dict(mesh.shape) if mesh is not None else {},
            dtype=str(getattr(c, "dtype", "bfloat16")),
            maxSeqLen=getattr(eng, "max_context", 8192),
            maxBatchSlots=getattr(c, "max_slots", 1),
        ))
    return NodeCapabilities(
        workerId=worker_id,
        availableModels=models,
        systemResources=system_resources(),
        performanceTier=performance_tier,  # type: ignore[arg-type]
        maxConcurrentTasks=max(max_slots, 1),
        supportedFormats=["json"],
        lastUpdated=iso_now(),
        topology=topo,
        shardLayouts=layouts,
    )
