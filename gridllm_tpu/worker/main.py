"""Worker process entry (``gridllm-worker``).

Reference analogue: client/src/index.ts (WorkerApplication) — health-only
HTTP app + the worker service. Models to serve come from GRIDLLM_MODELS
(comma-separated registry names); checkpoints from GRIDLLM_CHECKPOINT_DIR
({dir}/{name-with-:-replaced-by-_}).
"""

from __future__ import annotations

import asyncio
import os
import platform

from aiohttp import web

import gridllm_tpu
from gridllm_tpu.bus import create_bus
from gridllm_tpu.engine import EngineConfig, InferenceEngine
from gridllm_tpu.parallel.mesh import MeshConfig
from gridllm_tpu.utils.config import Config, env_bool, load_config
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import iso_now
from gridllm_tpu.worker.capabilities import system_resources
from gridllm_tpu.worker.plan import (
    PlanFollower,
    PlanPublisher,
    plan_channel,
    ready_key,
)
from gridllm_tpu.worker.service import WorkerService

log = get_logger("worker.main")


def resolve_checkpoint(root: str | None, model: str) -> tuple[str | None, str | None]:
    """(checkpoint_path, tokenizer_path) for `model` under a checkpoint
    root: weights at {root}/{name-with-:-replaced-by-_}, tokenizer either
    in a tokenizer/ subdir or alongside the weights. Single source of
    truth — bench.py resolves real-checkpoint runs through this too."""
    if not root:
        return None, None
    cand = os.path.join(root, model.replace(":", "_"))
    if not os.path.isdir(cand):
        return None, None
    tok_sub = os.path.join(cand, "tokenizer")
    return cand, tok_sub if os.path.isdir(tok_sub) else cand


def _mesh_config(config: Config) -> MeshConfig | None:
    if not config.engine.mesh_shape:
        return None
    axes = dict(
        kv.split(":") for kv in config.engine.mesh_shape.split(",") if kv
    )
    return MeshConfig(**{k: int(v) for k, v in axes.items()})


def pull_engine_factory(config: Config):
    """WorkerService.engine_factory for /api/pull: like build_one_engine
    but REFUSES models whose checkpoint does not resolve — a pull that
    "succeeds" onto random weights would serve gibberish with a success
    status. GRIDLLM_ALLOW_SYNTHETIC_WEIGHTS=1 overrides (test/bench
    deployments that intentionally run synthetic weights)."""

    def factory(name: str) -> InferenceEngine:
        ckpt, _ = resolve_checkpoint(config.engine.checkpoint_dir, name)
        if ckpt is None and not env_bool(
            "GRIDLLM_ALLOW_SYNTHETIC_WEIGHTS"
        ):
            raise ValueError(
                f"no checkpoint for {name!r} under "
                f"{config.engine.checkpoint_dir or '$GRIDLLM_CHECKPOINT_DIR'}"
                " — refusing to serve random weights (set "
                "GRIDLLM_ALLOW_SYNTHETIC_WEIGHTS=1 to override)"
            )
        return build_one_engine(config, name)

    return factory


def build_one_engine(config: Config, name: str) -> InferenceEngine:
    """Engine for one model under this worker's settings — used at startup
    and by /api/pull load-on-demand (via pull_engine_factory)."""
    ckpt, tok = resolve_checkpoint(config.engine.checkpoint_dir, name)
    buckets = tuple(
        int(b) for b in config.engine.prefill_buckets.split(",") if b
    )
    eng = InferenceEngine(EngineConfig(
        model=name,
        checkpoint_path=ckpt,
        tokenizer=tok,
        dtype=config.engine.dtype,
        max_slots=config.engine.max_batch_slots,
        page_size=config.engine.kv_page_size,
        prefill_buckets=buckets,
        mesh=_mesh_config(config),
    ))
    log.info("engine ready", model=name, checkpoint=ckpt or "random-init")
    return eng


def build_engines(config: Config) -> dict[str, InferenceEngine]:
    names = [m.strip() for m in config.engine.models.split(",") if m.strip()]
    return {name: build_one_engine(config, name) for name in names}


def build_health_app(service: WorkerService) -> web.Application:
    """reference: client/src/routes/health.ts:8-59 + /worker/status
    (client/src/index.ts:75-82)."""
    # client_max_size: the /kvx/ migration route receives whole KV
    # payloads in one POST (aiohttp's 1 MB default would 413 any real
    # transfer — that is exactly the path chosen for LARGE payloads)
    app = web.Application(client_max_size=1024**3)
    started = iso_now()

    async def health(_):
        return web.json_response({
            "status": "healthy", "timestamp": iso_now(),
            "worker": service.worker_id, "version": gridllm_tpu.__version__,
        })

    async def live(_):
        return web.json_response({"status": "alive", "timestamp": iso_now()})

    async def ready(_):
        return web.json_response({"status": "ready", "timestamp": iso_now()})

    async def system(_):
        res = system_resources()
        return web.json_response({
            "status": "ok", "timestamp": iso_now(), "startedAt": started,
            "resources": res.model_dump(), "platform": platform.system().lower(),
        })

    async def status(_):
        return web.json_response({
            "workerId": service.worker_id,
            "status": service._status(),
            "currentJobs": service.current_jobs,
            "totalJobsProcessed": service.total_processed,
            "models": list(service.engines),
        })

    async def metrics(_):
        # the process-global registry carries every worker-plane series:
        # engine tokens/steps/KV pool, kernel-dispatch paths, bus, jobs
        from gridllm_tpu.obs import PROMETHEUS_CONTENT_TYPE, default_registry

        return web.Response(text=default_registry().render(),
                            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE})

    async def dump(_):
        # worker-side flight recorder artifact: this process's event rings
        # + live engine batch state. No scheduler here — the gateway's
        # /admin/dump carries the control-plane view; the worker service's
        # ACTIVE execution spans ride along so a wedged request's trace is
        # readable from the worker even before it resolves.
        from gridllm_tpu.obs import build_dump

        artifact = build_dump(reason="on_demand")
        artifact["worker"] = {
            "workerId": service.worker_id,
            "currentJobs": service.current_jobs,
            "models": list(service.engines),
        }
        artifact["activeTraces"] = {
            rid: service.tracer.export(rid)
            for rid in service.tracer.active_ids()
        }
        return web.json_response(artifact)

    async def memory(_):
        # engine-side device-memory breakdown (obs/perf.py): THIS process
        # holds the weights and KV pools, so this is the authoritative
        # weights/KV/workspace + headroom view in split deployments.
        # to_thread: the live_arrays walk is synchronous.
        from gridllm_tpu.obs import memory_snapshot

        return web.json_response(await asyncio.to_thread(memory_snapshot))

    async def profile(request):
        from gridllm_tpu.obs.perf import handle_profile_request

        # to_thread: capture start does blocking dir-prune/start_trace
        # work — the health port must keep answering liveness probes
        status, payload = await asyncio.to_thread(
            handle_profile_request, request.query.get("seconds"))
        return web.json_response(payload, status=status)

    async def drain(request):
        # graceful drain (ISSUE 9): stop accepting work, finish short
        # decodes within the budget, live-migrate the rest. The worker
        # keeps running afterward (status "draining") — process exit is
        # the SIGTERM path's job; this route is for rolling restarts
        # orchestrated from outside.
        budget = request.query.get("budget_ms")
        try:
            budget_ms = int(budget) if budget else None
        except ValueError:
            return web.json_response(
                {"error": f"budget_ms must be an integer, got {budget!r}"},
                status=400)
        report = await service.drain(budget_ms)
        return web.json_response(report)

    async def kvx(request):
        # direct worker-to-worker KV migration (ISSUE 7): the whole wire
        # payload in one POST — the large-transfer fast path that skips
        # the bus. The header arrived via the bus prepare message; an
        # unknown request id means no prepare was seen and the sender
        # falls back to bus chunks (or local serving).
        rid = request.match_info["request_id"]
        body = await request.read()
        result = await service.kvx.feed_http(rid, body)
        return web.json_response(result,
                                 status=200 if result.get("ok") else 409)

    app.add_routes([
        web.get("/health", health), web.get("/health/live", live),
        web.get("/health/ready", ready), web.get("/health/system", system),
        web.get("/worker/status", status), web.get("/metrics", metrics),
        web.get("/admin/dump", dump), web.get("/admin/memory", memory),
        web.post("/admin/profile", profile),
        web.post("/admin/drain", drain),
        web.post("/kvx/{request_id}", kvx),
    ])
    return app


async def run(config: Config | None = None) -> None:
    """Worker process entry. Single-host: bus + engines + WorkerService.

    Multi-host slice (GRIDLLM_NUM_PROCS > 1, SURVEY.md §5.8b): every
    process joins the jax group FIRST (so jax.devices() is the global
    slice and engine meshes emit cross-host collectives), then:
      - process 0 (liaison) runs the full bus worker — ONE logical worker;
      - followers hold the jax runtime open and watch slice health.
    Any member death fails the WHOLE logical worker: the liaison announces
    `worker:disconnected` (scheduler orphans its jobs, scheduler.py orphan
    path) and every process exits so the supervisor restarts the slice
    together.
    """
    from gridllm_tpu.parallel.distributed import initialize_group, shutdown_group
    from gridllm_tpu.worker.group import GroupMembership, fail_logical_worker

    config = config or load_config()
    from gridllm_tpu.obs import default_flight_recorder

    default_flight_recorder().set_capacity(config.obs.flightrec_capacity)
    group = initialize_group()
    if group.is_group and not os.environ.get("WORKER_ID"):
        # ALL slice processes must agree on the logical worker id or the
        # member heartbeat keys never match and slice-failure detection is
        # a silent no-op. Without an explicit WORKER_ID, derive a shared,
        # slice-unique id from the coordinator address.
        import hashlib

        wid = "worker-slice-" + hashlib.sha1(
            (group.coordinator or "").encode()
        ).hexdigest()[:12]
        config.worker = config.worker.model_copy(update={"worker_id": wid})
    bus = create_bus(config.bus.url, key_prefix=config.bus.key_prefix,
                     password=config.bus.password, db=config.bus.db,
                     endpoints=config.bus.endpoints)
    await bus.connect()

    # fleet timeline (ISSUE 17): the worker publishes its flight-recorder
    # lifecycle events on obs:event so gateway/shard timelines include the
    # execution side. Publisher only — incident stores live control-plane
    # side. Batched + drop-counted: the decode loop never blocks on it.
    timeline_pub = None
    tl = config.obs.timeline
    if tl.enabled:
        from gridllm_tpu.obs import TimelinePublisher

        timeline_pub = TimelinePublisher(
            config.worker.worker_id, queue_capacity=tl.queue_capacity,
            flush_ms=tl.flush_ms, batch_max=tl.batch_max)
        timeline_pub.install()
        await timeline_pub.start(bus)

    stop = asyncio.Event()
    slice_broken: list[str] = []
    if group.is_liaison:
        engines = build_engines(config)
        if not engines:
            raise SystemExit("no models configured: set GRIDLLM_MODELS")
        service = WorkerService(
            bus, engines, config.worker,
            stream_flush_ms=config.engine.stream_flush_ms,
            # model management only outside a worker group: a slice's
            # engines must be built (and torn down) in lockstep on every
            # process — plan replay has no engine-construction op
            engine_factory=(
                None if group.is_group else pull_engine_factory(config)
            ),
        )
        if group.is_group:
            service.admin_ops_enabled = False

        async def on_slice_failure(reason: str) -> None:
            await fail_logical_worker(bus, service.worker_id, reason)
            await service.stop(announce=False)
            slice_broken.append(reason)
            stop.set()

        membership = GroupMembership(
            bus, service.worker_id, group,
            heartbeat_interval_s=config.worker.heartbeat_interval_ms / 1000.0,
            on_slice_failure=on_slice_failure,
        )
        await membership.start()
        # multi-host SPMD: broadcast every device-dispatching action so
        # followers issue the same computations (worker/plan.py; VERDICT
        # r03 missing #1 — liaison-only dispatch deadlocks the collectives)
        publishers: list[PlanPublisher] = []
        if group.is_group:
            import threading

            loop = asyncio.get_running_loop()
            pub = PlanPublisher(bus, plan_channel(service.worker_id), loop)
            pub.start()
            publishers.append(pub)
            # ONE dispatch lock across every engine: the liaison's
            # cross-engine dispatch order must equal the plan order
            shared_lock = threading.RLock()
            for model, eng in engines.items():
                eng.dispatch_lock = shared_lock
                eng.plan_sink = (
                    lambda rec, m=model: pub.sink({**rec, "model": m})
                )
            # barrier: every follower's plan subscription must be LIVE
            # before the first job can be assigned — pub/sub has no replay
            for pid in range(1, group.num_processes):
                for _ in range(1200):
                    if await bus.get(ready_key(service.worker_id, pid)):
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise SystemExit(
                        f"slice follower {pid} never became plan-ready"
                    )
        await service.start()
        app = build_health_app(service)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, config.worker.host, config.worker.port)
        await site.start()
        log.info("worker http listening", port=config.worker.port)

        # Graceful drain on SIGTERM (ISSUE 9): rolling deploys and TPU
        # preemption notices deliver SIGTERM first — finish short decodes
        # within the drain budget, live-migrate the rest, then exit. The
        # service.stop() in the finally publishes the unregister, so any
        # job the drain could not hand off orphan-requeues WITH its
        # resume snapshot preserved scheduler-side.
        import signal as _signal

        # the drain task must be held somewhere that outlives the signal
        # handler: the loop keeps only a weak reference, and a collected
        # task would silently skip stop.set() — the worker would ignore
        # SIGTERM until the orchestrator escalates to SIGKILL
        drain_tasks: list[asyncio.Task] = []

        def _on_sigterm() -> None:
            async def _graceful() -> None:
                try:
                    await service.drain()
                finally:
                    stop.set()

            log.info("SIGTERM received; draining before exit")
            drain_tasks.append(asyncio.ensure_future(_graceful()))

        try:
            asyncio.get_running_loop().add_signal_handler(
                _signal.SIGTERM, _on_sigterm)
        except (NotImplementedError, RuntimeError):  # non-unix platforms
            pass
        try:
            await stop.wait()
        finally:
            await membership.stop()
            await service.stop()
            for pub in publishers:
                await pub.stop()
            await runner.cleanup()
            if timeline_pub is not None:
                await timeline_pub.stop()
            await bus.disconnect()
            if slice_broken:
                # jax.distributed teardown blocks on dead slice members —
                # fail fast so the supervisor restarts the slice together
                from gridllm_tpu.obs import default_flight_recorder

                default_flight_recorder().record(
                    "worker", "fatal_exit", worker=service.worker_id,
                    reason=slice_broken[0])
                log.error("slice broken; exiting", reason=slice_broken[0])
                os._exit(1)
            shutdown_group(group)
    else:
        # follower: build the SAME engines (identical jit programs over the
        # global mesh) and replay the liaison's step plan — every process
        # must issue the same computation or the collectives deadlock
        engines = build_engines(config)

        async def on_slice_failure(reason: str) -> None:
            slice_broken.append(reason)
            stop.set()

        membership = GroupMembership(
            bus, config.worker.worker_id, group,
            heartbeat_interval_s=config.worker.heartbeat_interval_ms / 1000.0,
            on_slice_failure=on_slice_failure,
        )
        await membership.start()
        follower = PlanFollower(
            bus, plan_channel(config.worker.worker_id), engines,
            on_divergence=on_slice_failure,
        )
        await follower.start()

        # signal the liaison this process can hear the plan (it holds
        # registration until every follower is ready). TTL + refresh, NOT
        # a plain set: a persistent key from a previous slice incarnation
        # would let a restarted liaison pass the barrier while this
        # process is still building engines — publishing to a channel
        # with no subscriber (pub/sub has no replay).
        rk = ready_key(config.worker.worker_id, group.process_id)

        async def refresh_ready() -> None:
            # transient bus errors must not kill the heartbeat: a dead
            # refresh loop lets the key expire and a later liaison restart
            # then waits out its whole barrier timeout on a live follower
            # (same per-beat guard as GroupMembership._beacon_loop)
            while True:
                try:
                    await bus.set_with_expiry(rk, "1", ttl_s=10.0)
                except Exception as e:  # noqa: BLE001
                    log.warning("ready-key refresh failed; retrying",
                                key=rk, error=str(e))
                await asyncio.sleep(3.0)

        ready_task = asyncio.create_task(refresh_ready())
        log.info("follower replaying step plan", models=list(engines))
        try:
            await stop.wait()
        finally:
            ready_task.cancel()
            await follower.stop()
            await membership.stop()
            if timeline_pub is not None:
                await timeline_pub.stop()
            await bus.disconnect()
            if slice_broken:
                log.error("slice broken; follower exiting",
                          reason=slice_broken[0])
                os._exit(1)
            shutdown_group(group)


def main() -> None:  # pragma: no cover
    # Make the JAX_PLATFORMS env var authoritative: environment plugins
    # (e.g. a TPU-relay sitecustomize) may force jax.config's platform
    # list at interpreter start, which would make an explicit
    # JAX_PLATFORMS=cpu worker still try (and possibly hang on) the
    # accelerator backend. Backend init is lazy, so pinning here — before
    # the first jax.devices() in engine build — restores the documented
    # env-var semantics.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
