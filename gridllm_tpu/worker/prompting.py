"""Prompt assembly + output post-processing for the Ollama option surface.

Round-3 VERDICT (#2 missing): `system`, `template`, `suffix`,
`format:"json"`, `think`, and `tools` were accepted by the gateway,
stored in job metadata, and never read again. The reference forwarded all
of them to Ollama which APPLIED them
(client/src/services/OllamaService.ts:197-226; option schema
server/src/routes/ollama.ts:26-56). This module is where they take
effect in the TPU worker:

- `template`: a minimal Go-template subset covering the placeholders real
  Ollama Modelfiles use: ``{{ .System }}``, ``{{ .Prompt }}``,
  ``{{ .Suffix }}``, ``{{ .Response }}`` and conditional blocks
  ``{{ if .X }}...{{ end }}`` (with ``{{- -}}`` whitespace trimming).
- `system`: folded into the chat template (generate path: as the system
  message of a two-message conversation when the tokenizer has a chat
  template; else a plain prefix block).
- `suffix`: substituted when the custom template references ``.Suffix``
  (fill-in-middle models); ignored otherwise — matching Ollama, where a
  template without suffix support simply never renders it.
- `format` ("json" or a JSON schema object): instruction injection +
  final-output extraction of the first balanced JSON value. DIVERGENCE:
  Ollama enforces JSON with grammar-constrained decoding; here the
  constraint is soft (instruction) with a hard post-extraction, and
  streaming is buffered to the final frame so streamed bytes never
  disagree with the extracted result.
- `think`: ``<think>...</think>`` blocks are split into the `thinking`
  field (Ollama: message.thinking / response.thinking). think=False asks
  chat templates that support it (qwen3) to disable thinking.
- `tools`: templated through the tokenizer's chat template (HF
  ``apply_chat_template(tools=...)``); model output is parsed back into
  structured tool calls — both the llama3 JSON form
  (``{"name": ..., "parameters": ...}``) and the qwen/hermes
  ``<tool_call>{...}</tool_call>`` form.
"""

from __future__ import annotations

import json
import re
from typing import Any

from gridllm_tpu.engine.tokenizer import Tokenizer

# ---------------------------------------------------------------------------
# Go-template subset
# ---------------------------------------------------------------------------

_IF_RE = re.compile(
    r"\{\{-?\s*if\s+\.(\w+)\s*-?\}\}(.*?)\{\{-?\s*end\s*-?\}\}", re.S
)
_VAR_RE = re.compile(r"\{\{-?\s*\.(\w+)\s*-?\}\}")


def render_template(template: str, fields: dict[str, str]) -> str:
    """Render the Go-template subset Ollama Modelfiles rely on. `fields`
    keys are capitalized placeholder names (System, Prompt, Suffix,
    Response); missing/empty fields render as empty and fail `if` blocks."""

    def do_if(m: re.Match) -> str:
        name, body = m.group(1), m.group(2)
        if fields.get(name):
            return _render(body)
        return ""

    def do_var(m: re.Match) -> str:
        return fields.get(m.group(1), "") or ""

    def _render(s: str) -> str:
        s = _IF_RE.sub(do_if, s)
        return _VAR_RE.sub(do_var, s)

    return _render(template)


# ---------------------------------------------------------------------------
# generate-path prompt assembly
# ---------------------------------------------------------------------------

def build_generate_prompt(
    prompt: str,
    tokenizer: Tokenizer,
    system: str | None = None,
    template: str | None = None,
    suffix: str | None = None,
    raw: bool = False,
) -> str:
    """Assemble the final model prompt for /api/generate.

    raw=True bypasses all templating (Ollama: raw mode sends the prompt
    verbatim). A custom `template` wins over the model's chat template.
    """
    if raw:
        return prompt
    if template:
        return render_template(template, {
            "System": system or "",
            "Prompt": prompt,
            "Suffix": suffix or "",
            "Response": "",
        })
    if system:
        inner = getattr(tokenizer, "_tok", None)
        if inner is not None and getattr(inner, "chat_template", None):
            return inner.apply_chat_template(
                [{"role": "system", "content": system},
                 {"role": "user", "content": prompt}],
                tokenize=False, add_generation_prompt=True,
            )
        return f"<|system|>\n{system}\n<|user|>\n{prompt}\n<|assistant|>\n"
    return prompt


# ---------------------------------------------------------------------------
# chat rendering with system/tools/think
# ---------------------------------------------------------------------------

def render_chat_full(
    messages: list[dict[str, Any]],
    tokenizer: Tokenizer,
    tools: list[dict[str, Any]] | None = None,
    think: Any = None,
) -> str:
    """Chat messages (+ optional tool definitions) → model prompt.

    HF chat templates receive `tools` natively (the model's own trained
    tool format — llama3.1 JSON, qwen hermes-style, etc.). think=False is
    forwarded as enable_thinking=False for templates that support it
    (qwen3); unsupported templates ignore it. The templateless fallback
    frames tools as a system block with the llama3-style JSON calling
    convention.
    """
    # normalize OpenAI-shaped history: assistant tool_calls carry
    # arguments as a JSON string; HF templates expect objects
    norm: list[dict[str, Any]] = []
    for m in messages:
        if m.get("tool_calls"):
            m = dict(m)
            fixed = []
            for tc in m["tool_calls"]:
                fn = dict(tc.get("function") or {})
                if isinstance(fn.get("arguments"), str):
                    try:
                        fn["arguments"] = json.loads(fn["arguments"])
                    except ValueError:
                        pass
                fixed.append({**tc, "function": fn})
            m["tool_calls"] = fixed
        norm.append(m)
    messages = norm

    inner = getattr(tokenizer, "_tok", None)
    if inner is not None and getattr(inner, "chat_template", None):
        kwargs: dict[str, Any] = {}
        if tools:
            kwargs["tools"] = tools
        if think is False:
            kwargs["enable_thinking"] = False
        try:
            return inner.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True, **kwargs
            )
        except TypeError:  # template without tools/enable_thinking support
            return inner.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
    parts = []
    if tools:
        parts.append(
            "<|system|>\nYou have access to these tools:\n"
            + json.dumps(tools)
            + '\nTo call a tool respond ONLY with JSON: '
              '{"name": <tool name>, "parameters": <arguments object>}\n'
        )
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        if role == "tool":
            content = f"[tool result] {content}"
        if m.get("tool_calls"):
            content = (content or "") + "".join(
                json.dumps(tc.get("function", tc)) for tc in m["tool_calls"]
            )
        parts.append(f"<|{role}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


# ---------------------------------------------------------------------------
# output post-processing: thinking, tool calls, JSON mode
# ---------------------------------------------------------------------------

_THINK_RE = re.compile(r"<think>(.*?)</think>\s*", re.S)


def split_thinking(text: str) -> tuple[str | None, str]:
    """Extract ``<think>...</think>`` into (thinking, remaining_text)."""
    blocks = _THINK_RE.findall(text)
    if not blocks:
        return None, text
    return "\n".join(b.strip() for b in blocks), _THINK_RE.sub("", text)


_TOOL_TAG_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.S)


def _normalize_call(obj: Any) -> dict[str, Any] | None:
    """Accept {"name", "parameters"|"arguments"} (llama3 / hermes) →
    Ollama tool_call shape {"function": {"name", "arguments"}}."""
    if not isinstance(obj, dict):
        return None
    fn = obj.get("function") if isinstance(obj.get("function"), dict) else obj
    name = fn.get("name")
    if not isinstance(name, str) or not name:
        return None
    args = fn.get("parameters", fn.get("arguments", {}))
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except ValueError:
            args = {"raw": args}
    if not isinstance(args, dict):
        args = {"value": args}
    return {"function": {"name": name, "arguments": args}}


def parse_tool_calls(text: str) -> tuple[list[dict[str, Any]], str]:
    """Parse model output into (tool_calls, remaining_content).

    Handles the qwen/hermes ``<tool_call>{json}</tool_call>`` form and the
    llama3.1 bare-JSON form (entire output is one JSON object with
    name+parameters, possibly wrapped in a python-tag-free list).
    """
    calls: list[dict[str, Any]] = []

    def tag_sub(m: re.Match) -> str:
        try:
            call = _normalize_call(json.loads(m.group(1)))
        except ValueError:
            return m.group(0)  # unparseable: leave in content
        if call:
            calls.append(call)
            return ""
        return m.group(0)

    rest = _TOOL_TAG_RE.sub(tag_sub, text).strip()
    if calls:
        return calls, rest

    stripped = text.strip()
    if stripped.startswith(("{", "[")):
        val, _, end = _first_json_value(stripped)
        if val is not None and not stripped[end:].strip():
            objs = val if isinstance(val, list) else [val]
            parsed = [_normalize_call(o) for o in objs]
            if parsed and all(p is not None for p in parsed) and all(
                isinstance(o, dict) and ("parameters" in o or "arguments" in o
                                         or "function" in o)
                for o in objs
            ):
                return [p for p in parsed if p], ""
    return [], text


# ---------------------------------------------------------------------------
# JSON mode
# ---------------------------------------------------------------------------

def _first_json_value(s: str) -> tuple[Any, int, int]:
    """Decode the first balanced JSON value in `s`; returns
    (value, start_index, end_index) or (None, 0, 0)."""
    dec = json.JSONDecoder()
    for i, ch in enumerate(s):
        if ch in "{[":
            try:
                val, end = dec.raw_decode(s, i)
                return val, i, end
            except ValueError:
                continue
    return None, 0, 0


def json_instruction(fmt: Any) -> str:
    """The soft constraint appended for format requests.

    NOTE: this instruction + extract_json below are the ENTIRE
    ``format:"json"`` enforcement today. engine/jsonmask.py holds an
    experimental grammar PDA for true per-step constrained decoding, but
    it is NOT wired — the sampler has no vocabulary-mask hook — so output
    that parses is best-effort, not guaranteed (see jsonmask's module
    docstring before assuming otherwise)."""
    if isinstance(fmt, dict):
        return (
            "\nRespond ONLY with JSON matching this JSON schema, with no "
            "other text:\n" + json.dumps(fmt)
        )
    return "\nRespond ONLY with valid JSON, with no other text."


def extract_json(text: str) -> str:
    """Hard post-extraction for format requests: the model's own span of
    the first balanced JSON value in the output (Ollama guarantees valid
    JSON via grammar-constrained decoding; this is the soft-constraint
    analogue's enforcement half). Falls back to the raw text when nothing
    parses."""
    val, start, end = _first_json_value(text)
    if val is None:
        return text
    return text[start:end]
