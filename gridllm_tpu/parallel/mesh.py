"""Device-mesh construction.

Axis vocabulary (fixed across the framework):
  "pp" — pipeline axis: decoder LAYERS sharded here (parallel/pipeline.py
         token-passing stages); activations hop stages via ppermute, so
         per-step traffic is one [S, E] tensor per hop — cheap enough for
         DCN, hence outermost
  "dp" — replica/data axis: independent continuous batches (slots split here)
  "tp" — tensor axis: attention heads + MLP hidden sharded here; the decode
         all-reduce rides this axis over ICI
  "ep" — expert axis (MoE): experts distributed here, tokens all-to-all'd
  "sp" — sequence axis: long-context prefill splits the time dimension here
         (ring attention via ppermute)

One logical worker = one mesh. Multi-host slices build the same mesh from
jax.devices() after jax.distributed.initialize (SURVEY.md §5.8(b)); the bus
protocol only ever sees the single logical worker.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("pp", "dp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 on at most one axis means "absorb the rest"."""

    pp: int = 1
    dp: int = 1
    ep: int = 1
    tp: int = -1
    sp: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        dims = [self.pp, self.dp, self.ep, self.tp, self.sp]
        wild = [i for i, d in enumerate(dims) if d == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(d for d in dims if d != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            dims[wild[0]] = n_devices // fixed
        if math.prod(dims) != n_devices:
            raise ValueError(
                f"mesh {dims} needs {math.prod(dims)} devices, have {n_devices}"
            )
        return tuple(dims)  # type: ignore[return-value]


def build_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the worker mesh over `devices` (default: all of jax.devices()).

    Axis order puts "sp" innermost → ring-attention ppermute neighbours are
    ICI-adjacent; "dp" outermost → replicas may span DCN without putting
    per-token collectives on it.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    shape = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def local_mesh(n: int | None = None) -> Mesh:
    """All-local-devices mesh with everything on "tp" (single-host default)."""
    devices = jax.devices()[: n or len(jax.devices())]
    return build_mesh(MeshConfig(tp=-1), devices)
