"""Multi-host worker-group runtime: jax.distributed lifecycle.

SURVEY.md §5.8(b): a multi-host TPU slice registers as ONE logical worker.
ICI/DCN-level array communication is jax's own coordination service
(`jax.distributed.initialize` → XLA collectives over the global mesh);
the bus protocol (§2.6) only ever sees the single logical worker, spoken
for by the liaison host (process 0). The reference's analogue is
process-level multi-node deployment (docs/deployment/DEPLOYMENT.md:7-33)
— it never splits a model, so this lifecycle is new capability.

Env contract (all optional; absent → single-host, no-op):
  GRIDLLM_COORD_ADDR   host:port of process 0 (jax coordinator)
  GRIDLLM_NUM_PROCS    total processes in the slice
  GRIDLLM_PROC_ID      this process's id (0 = liaison)
"""

from __future__ import annotations

import dataclasses

from gridllm_tpu.utils.config import env_int, env_raw
from gridllm_tpu.utils.logging import get_logger

log = get_logger("parallel.distributed")


@dataclasses.dataclass(frozen=True)
class GroupConfig:
    """Shape of one logical worker's process group."""

    coordinator: str | None = None   # host:port of process 0
    num_processes: int = 1
    process_id: int = 0

    @staticmethod
    def from_env() -> "GroupConfig":
        return GroupConfig(
            coordinator=env_raw("GRIDLLM_COORD_ADDR") or None,
            num_processes=env_int("GRIDLLM_NUM_PROCS"),
            process_id=env_int("GRIDLLM_PROC_ID"),
        )

    @property
    def is_group(self) -> bool:
        return self.num_processes > 1

    @property
    def is_liaison(self) -> bool:
        """Process 0 speaks the bus protocol for the whole slice."""
        return self.process_id == 0


def initialize_group(cfg: GroupConfig | None = None) -> GroupConfig:
    """Join the slice's jax process group (no-op for single-host).

    Must run before any jax backend use in this process. After this,
    jax.devices() is the GLOBAL device list across all slice hosts and
    meshes built from it emit cross-host collectives.
    """
    cfg = cfg or GroupConfig.from_env()
    if not cfg.is_group:
        return cfg
    if not cfg.coordinator:
        raise ValueError(
            "GRIDLLM_NUM_PROCS > 1 requires GRIDLLM_COORD_ADDR (host:port "
            "of process 0) — a slice cannot form without a coordinator"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    log.info("joined worker group", coordinator=cfg.coordinator,
             process=f"{cfg.process_id}/{cfg.num_processes}",
             global_devices=jax.device_count(),
             local_devices=jax.local_device_count())
    return cfg


def shutdown_group(cfg: GroupConfig) -> None:
    if not cfg.is_group:
        return
    import jax

    try:
        jax.distributed.shutdown()
    except Exception as e:  # already torn down / coordinator gone
        log.warning("distributed shutdown failed", error=str(e))
