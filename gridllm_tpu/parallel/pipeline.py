"""Pipeline parallelism over the "pp" mesh axis (SURVEY.md §2.5; the last
parallelism form the framework lacked — VERDICT r03 missing #7).

TPU-first design: stages are LAYER blocks. Every stacked [L, ...] params
leaf and the [L, ...] KV page pool are sharded on axis 0 over "pp"
(parallel/sharding.py), so stage s holds layers [s*L/pp, (s+1)*L/pp) and
their KV — the memory win that makes models beyond one slice's HBM
servable. Compute is a token-passing schedule inside ONE jitted program:

    jax.shard_map, manual over {"pp"} only (jax partial-manual mode) —
    "tp"/"ep"/"sp"/"dp" stay AUTO, so the existing GSPMD tensor layout
    (Megatron specs, psum on wo/w_down) keeps working untouched inside
    each stage.

    the live activation starts on stage 0 (every device embeds — cheap,
    replicated); each stage applies its layer block when the live value
    reaches it (lax.cond on axis_index, per-device branches are exactly
    what manual mode permits), then the value hops one stage via
    ppermute. After the last stage, a masked psum broadcasts the final
    hidden state so the (pp-replicated) unembed + sampler see it
    everywhere. Per step the wire carries (pp-1+1) tensors of [S, E] —
    tens of KB, cheap enough to ride DCN, which is why "pp" is the
    outermost mesh axis.

Two schedules share this structure. Prefill (one slot at a time by
construction) and the fallback decode use the SEQUENTIAL schedule — one
live activation, 1/pp utilization. The decode hot path is MICROBATCHED
(GPipe-style): slots split into pp groups; at tick t stage p runs
microbatch t-p. Each stage does pp ticks of work in a 2pp-1-tick step,
so utilization is pp/(2pp-1) ≈ 50% (the classic GPipe bubble; more
microbatches than stages would push it higher). Either way PP's main
buy here is MEMORY — BASELINE's serving configs are all within-slice,
where tp is the right axis; pp is for the models that do not fit one
slice.

The reference has no analogue (single-GPU Ollama nodes); the design
follows the public GPipe/shard_map pattern (PAPERS.md — pattern
reference only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.ops.kvcache import (
    PagedKVCache,
    write_decode_all,
    write_prefill_all,
)
from gridllm_tpu.ops.layers import rms_norm

Params = dict


def _pp_shard_map(mesh, in_specs, out_specs):
    """Decorator for the pp token-passing programs: manual over {"pp"}
    only, tp/ep/sp/dp stay AUTO (GSPMD). Resolves whichever shard_map
    this jax ships — the stable ``jax.shard_map`` (``axis_names`` +
    ``check_vma``) or the older experimental one (``auto`` = the
    non-manual axes, ``check_rep``)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return partial(sm, mesh=mesh, axis_names={"pp"},
                       in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    from jax.experimental.shard_map import shard_map

    return partial(shard_map, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False,
                   auto=frozenset(mesh.axis_names) - {"pp"})


def pp_size(mesh) -> int:
    return int(mesh.shape.get("pp", 1)) if mesh is not None else 1


def validate(cfg: ModelConfig, mesh) -> None:
    pp = pp_size(mesh)
    if pp <= 1:
        return
    if cfg.num_layers % pp:
        raise ValueError(
            f"{cfg.name}: num_layers {cfg.num_layers} not divisible by "
            f"pp={pp}"
        )
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError(
            "pp and sp (ring-attention prefill) cannot combine yet — "
            "nested manual collectives; shape the mesh with one of them"
        )
    if cfg.family not in ("llama", "qwen2", "qwen3", "llava"):
        raise ValueError(
            f"pp supports the llama-skeleton families, not {cfg.family}"
        )


def _ring(pp: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % pp) for i in range(pp)]


def _stage_specs(params: Params) -> Params:
    """shard_map in_specs for the params pytree: layer-stacked leaves are
    manual on "pp" axis 0, everything else pp-replicated. Only the MANUAL
    axis appears — tp/ep placement stays automatic (GSPMD)."""

    def leaf_spec(path, leaf):
        in_layers = any(
            isinstance(e, jax.tree_util.DictKey) and e.key == "layers"
            for e in path
        )
        return P("pp") if in_layers else P()

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def _bcast_from_last(x: jnp.ndarray, p: jnp.ndarray, pp: int) -> jnp.ndarray:
    """Masked psum: the fully-processed activation lives on stage pp-1;
    every stage needs it for the (replicated) final norm + unembed.
    The sum runs in fp32: exact (one nonzero term), and bf16 psum under
    partial-manual shard_map hard-crashes XLA's CPU backend ("Invalid
    binary instruction opcode copy", hlo_instruction.cc:1585 — jax 0.9)."""
    mask = (p == pp - 1).astype(jnp.float32)
    x32 = x.astype(jnp.float32) * mask
    return jax.lax.psum(x32, "pp").astype(x.dtype)


def _token_passing(pp: int, stage, x, k_pool, v_pool):
    """The shared schedule of all three entry points: the live activation
    visits each stage in turn (lax.cond on this device's stage id — only
    the owner computes), hopping stages via ppermute; the final stage's
    result is broadcast to all for the replicated norm/unembed tail.
    Returns (x broadcast everywhere, k_pool, v_pool)."""
    p = jax.lax.axis_index("pp")
    for k in range(pp):
        x, k_pool, v_pool = jax.lax.cond(
            p == k, stage, lambda args: args, (x, k_pool, v_pool)
        )
        if k < pp - 1:
            x = jax.lax.ppermute(x, "pp", _ring(pp))
    return _bcast_from_last(x, p, pp), k_pool, v_pool


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    cache: PagedKVCache,
    active: jnp.ndarray,
    mlp=llama._mlp,
    mesh=None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """PP decode step — same contract as llama.decode_step.

    Slots are split into pp MICROBATCHES and pipelined GPipe-style: at
    tick t, stage p runs its layer block on microbatch t-p — pp ticks of
    work per stage in a 2pp-1-tick step (≈50% utilization vs the
    sequential schedule's 1/pp; the fill/drain bubble is the classic
    GPipe cost of matching microbatch count to stage count). Falls back
    to the sequential schedule when S % pp != 0.
    """
    pp = pp_size(mesh)
    s = tokens.shape[0]
    positions = cache.lengths
    new_lengths = jnp.minimum(
        cache.lengths + active.astype(jnp.int32), cache.max_context
    )
    microbatched = s % pp == 0 and s >= pp

    @_pp_shard_map(
        mesh,
        in_specs=(_stage_specs(params), P(), P("pp"), P("pp"), P(), P(), P()),
        out_specs=(P(), P("pp"), P("pp")),
    )
    def run(params, tokens, k_pool, v_pool, page_table, positions, active):
        x = params["embed"][tokens]  # [S, E] — every stage embeds

        def stage(args):
            x, kp, vp = args
            x, k_new, v_new = llama.decode_layers(
                params["layers"], cfg, x, kp, vp, page_table, positions,
                cache.page_size, mlp,
            )
            # Pallas stays off here regardless of cfg.use_pallas: the auto
            # axes inside this partial-manual region (tp/ep) still go
            # through GSPMD, and pallas_call has no partitioning rule —
            # same constraint that makes the engine disable kernels under
            # any mesh (engine.py _init).
            kp, vp = write_decode_all(
                kp, vp, k_new, v_new, page_table, positions, active,
                cache.page_size, use_pallas=False,
            )
            return x, kp, vp

        x, k_pool, v_pool = _token_passing(pp, stage, x, k_pool, v_pool)
        x = rms_norm(x, params["final_norm"], cfg.rms_eps)
        logits = llama._unembed(cfg, params, x)
        return logits, k_pool, v_pool

    @_pp_shard_map(
        mesh,
        in_specs=(_stage_specs(params), P(), P("pp"), P("pp"), P(), P(), P()),
        out_specs=(P(), P("pp"), P("pp")),
    )
    def run_mb(params, tokens, k_pool, v_pool, page_table, positions,
               active):
        p = jax.lax.axis_index("pp")
        m_sz = s // pp
        e = params["embed"].shape[1]
        n_local = jax.tree.leaves(params["layers"])[0].shape[0]
        kvh, d = k_pool.shape[-2], k_pool.shape[-1]
        x_all = params["embed"][tokens]          # [S, E] — cheap, replicated
        buf = jnp.zeros((m_sz, e), x_all.dtype)  # activation arriving from p-1
        outs = jnp.zeros((pp, m_sz, e), x_all.dtype)  # last-stage results
        k_acc = jnp.zeros((pp, n_local, m_sz, kvh, d), k_pool.dtype)
        v_acc = jnp.zeros_like(k_acc)

        def stage_mb(x_in, m):
            """This stage's layer block on microbatch m's slots."""
            off = m * m_sz
            pt = jax.lax.dynamic_slice_in_dim(page_table, off, m_sz)
            pos = jax.lax.dynamic_slice_in_dim(positions, off, m_sz)
            return llama.decode_layers(
                params["layers"], cfg, x_in, k_pool, v_pool, pt, pos,
                cache.page_size, mlp,
            )

        for t in range(2 * pp - 1):  # static unroll: pipeline schedule
            m = t - p                # this tick's microbatch for this stage
            mc = jnp.clip(m, 0, pp - 1)
            busy = (m >= 0) & (m < pp)
            # stage 0 picks up fresh embeddings; later stages continue the
            # activation handed over by the previous stage last tick
            fresh = jax.lax.dynamic_slice_in_dim(x_all, mc * m_sz, m_sz)
            x_in = jnp.where(p == 0, fresh, buf)

            def work(args):
                x_in, k_acc, v_acc = args
                x_out, k_new, v_new = stage_mb(x_in, mc)
                k_acc = jax.lax.dynamic_update_slice_in_dim(
                    k_acc, k_new[None], mc, axis=0)
                v_acc = jax.lax.dynamic_update_slice_in_dim(
                    v_acc, v_new[None], mc, axis=0)
                return x_out, k_acc, v_acc

            x_out, k_acc, v_acc = jax.lax.cond(
                busy, work, lambda args: args, (x_in, k_acc, v_acc)
            )
            outs = jnp.where(
                busy & (p == pp - 1),
                jax.lax.dynamic_update_slice_in_dim(outs, x_out[None], mc,
                                                    axis=0),
                outs,
            )
            if t < 2 * pp - 2:
                buf = jax.lax.ppermute(x_out, "pp", _ring(pp))

        # every device wrote its own layer block's K/V for ALL microbatches
        # (accumulated per tick) — one deferred pool write, as elsewhere
        k_new_all = k_acc.transpose(1, 0, 2, 3, 4).reshape(
            n_local, s, kvh, d)
        v_new_all = v_acc.transpose(1, 0, 2, 3, 4).reshape(
            n_local, s, kvh, d)
        k_pool, v_pool = write_decode_all(
            k_pool, v_pool, k_new_all, v_new_all, page_table, positions,
            active, cache.page_size, use_pallas=False,
        )
        # final-stage activations → everyone, for the replicated tail
        x = _bcast_from_last(outs.reshape(s, e), p, pp)
        x = rms_norm(x, params["final_norm"], cfg.rms_eps)
        logits = llama._unembed(cfg, params, x)
        return logits, k_pool, v_pool

    fn = run_mb if microbatched else run
    logits, k_pool, v_pool = jax.jit(fn)(
        params, tokens, cache.k, cache.v, cache.page_table, positions, active
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool, page_table=cache.page_table,
        lengths=new_lengths, page_size=cache.page_size,
    )


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp=llama._mlp,
    attn=None,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """PP prefill of ONE slot — same contract as llama.prefill."""
    if attn is not None:
        raise ValueError("pp prefill has no sp/ring-attention variant")
    pp = pp_size(mesh)

    @_pp_shard_map(
        mesh,
        in_specs=(
            _stage_specs(params), P(), P(),
            P("pp"), P("pp"), P(), P(),
        ),
        out_specs=(P(), P("pp"), P("pp")),
    )
    def run(params, tokens, embeds_or_tokens, k_pool, v_pool, length,
            table_row):
        x = (
            params["embed"][tokens] if embeds is None else embeds_or_tokens
        )
        x = x.astype(params["embed"].dtype)[None]  # [1, T, E]

        def stage(args):
            x, kp, vp = args
            x, k_new, v_new = llama.prefill_layers(
                params["layers"], cfg, x, length[None], mlp,
            )
            kp, vp = write_prefill_all(
                kp, vp, k_new, v_new, table_row, jnp.int32(0), length,
                cache.page_size, use_pallas=False,  # see decode_step note
            )
            return x, kp, vp

        x, k_pool, v_pool = _token_passing(pp, stage, x, k_pool, v_pool)
        x = rms_norm(x, params["final_norm"], cfg.rms_eps)
        last = x[0, jnp.maximum(length - 1, 0)]
        logits = llama._unembed(cfg, params, last)
        return logits, k_pool, v_pool

    logits, k_pool, v_pool = jax.jit(run)(
        params, tokens, tokens if embeds is None else embeds,
        cache.k, cache.v, length, table_row,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(length),
        page_size=cache.page_size,
    )


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    start: jnp.ndarray,
    length: jnp.ndarray,
    cache: PagedKVCache,
    slot: jnp.ndarray,
    table_row: jnp.ndarray,
    mlp=llama._mlp,
    mesh=None,
    embeds: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """PP chunked prefill — same contract as llama.prefill_chunk."""
    pp = pp_size(mesh)

    @_pp_shard_map(
        mesh,
        in_specs=(
            _stage_specs(params), P(), P(), P("pp"), P("pp"), P(), P(), P(),
        ),
        out_specs=(P(), P("pp"), P("pp")),
    )
    def run(params, tokens, embeds_or_tokens, k_pool, v_pool, start,
            length, table_row):
        x = (
            params["embed"][tokens] if embeds is None else embeds_or_tokens
        )
        x = x.astype(params["embed"].dtype)[None]  # [1, C, E]

        def stage(args):
            x, kp, vp = args
            x, k_new, v_new = llama.prefill_chunk_layers(
                params["layers"], cfg, x, kp, vp, table_row, start, length,
                cache.page_size, mlp,
            )
            kp, vp = write_prefill_all(
                kp, vp, k_new, v_new, table_row, start, length,
                cache.page_size, use_pallas=False,  # see decode_step note
            )
            return x, kp, vp

        x, k_pool, v_pool = _token_passing(pp, stage, x, k_pool, v_pool)
        x = rms_norm(x, params["final_norm"], cfg.rms_eps)
        last = x[0, jnp.maximum(length - 1, 0)]
        logits = llama._unembed(cfg, params, last)
        return logits, k_pool, v_pool

    logits, k_pool, v_pool = jax.jit(run)(
        params, tokens, tokens if embeds is None else embeds,
        cache.k, cache.v, start, length, table_row,
    )
    return logits, PagedKVCache(
        k=k_pool, v=v_pool,
        page_table=cache.page_table.at[slot].set(table_row),
        lengths=cache.lengths.at[slot].set(start + length),
        page_size=cache.page_size,
    )
