"""Sharding specs for params, KV cache, and per-step data.

Megatron-style tensor layout on the "tp" axis (the JAX way: annotate leaf
shardings, let GSPMD insert the all-reduces — no hand-written collectives in
the model code):

  wq/wk/wv, w_gate/w_up   [L, E, out]   out sharded     (column parallel)
  wo, w_down              [L, in, E]    in  sharded     (row parallel → the
                                        per-layer psum XLA inserts is the
                                        decode-critical ICI all-reduce)
  embed                   [V, E]        vocab sharded
  lm_head                 [E, V]        vocab sharded (logits all-gathered
                                        once per step for the sampler)
  norms                   replicated
  kv page pools  [L, P, ps, KVH, D]     KVH sharded (GQA: each tp shard owns
                                        its kv groups; q heads shard the same
                                        way via wq's out dim)
  experts (MoE)  [L, X, ...]            X sharded over "ep"

Any dim not divisible by its mesh axis falls back to replicated for that dim
(e.g. tiny test configs with KVH=2 on tp=8) — correctness first, the memory
win only where the layout allows it.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _fit(mesh: Mesh, shape: tuple[int, ...], spec: tuple[str | None, ...]) -> NamedSharding:
    """NamedSharding with per-dim divisibility fallback to replicated."""
    dims = []
    for size, ax in zip(shape, spec):
        ok = ax is not None and size % mesh.shape[ax] == 0
        dims.append(ax if ok else None)
    return NamedSharding(mesh, P(*dims))


# leaf-name → spec template, by trailing path component. Templates are for
# the STACKED [L, ...] layout of `layers` leaves; non-layer leaves listed
# with their own rank.
# Stacked layer leaves put the [L] axis on "pp" (pipeline stages own
# contiguous layer blocks; _fit's divisibility fallback replicates when
# L % pp != 0 — but the pipeline itself requires divisibility and the
# engine validates it up front).
_LAYER_SPECS: dict[str, tuple[str | None, ...]] = {
    "wq": ("pp", None, "tp"),
    "wk": ("pp", None, "tp"),
    "wv": ("pp", None, "tp"),
    "wo": ("pp", "tp", None),
    "w_gate": ("pp", None, "tp"),
    "w_up": ("pp", None, "tp"),
    "w_down": ("pp", "tp", None),
    "attn_norm": ("pp", None),
    "mlp_norm": ("pp", None),
    # gemma2's four-norm block
    "post_attn_norm": ("pp", None),
    "pre_ffn_norm": ("pp", None),
    "post_ffn_norm": ("pp", None),
    # qwen2 qkv bias: [L, out] shards with its projection's out dim
    "bq": ("pp", "tp"),
    "bk": ("pp", "tp"),
    "bv": ("pp", "tp"),
    # qwen3 per-head qk norms: [L, D]
    "q_norm": ("pp", None),
    "k_norm": ("pp", None),
    # MoE router + experts (mixtral): experts stacked on a [L, X, ...] axis
    "router": ("pp", None, None),
    "we_gate": ("pp", "ep", None, "tp"),
    "we_up": ("pp", "ep", None, "tp"),
    "we_down": ("pp", "ep", "tp", None),
}
_TOP_SPECS: dict[str, tuple[str | None, ...]] = {
    "embed": ("tp", None),
    "lm_head": (None, "tp"),
    "final_norm": (None,),
}


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Pytree of NamedSharding congruent with a model params pytree."""

    def spec_for(path, leaf) -> NamedSharding:
        name = None
        for entry in reversed(path):
            if isinstance(entry, jax.tree_util.DictKey):
                name = entry.key
                break
        table = _LAYER_SPECS if any(
            isinstance(e, jax.tree_util.DictKey) and e.key == "layers" for e in path
        ) else _TOP_SPECS
        spec = table.get(name, (None,) * leaf.ndim)
        if len(spec) != leaf.ndim:  # unknown leaf → replicate
            spec = (None,) * leaf.ndim
        return _fit(mesh, leaf.shape, spec)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def cache_shardings(cache: Any, mesh: Mesh) -> Any:
    """PagedKVCache-shaped pytree of shardings: pools layer-sharded on pp
    and KVH-sharded on tp, tables/lengths replicated (they are tiny and
    host-authored)."""
    pool = _fit(mesh, cache.k.shape, ("pp", None, None, "tp", None))
    rep_t = NamedSharding(mesh, P(*(None,) * cache.page_table.ndim))
    rep_l = NamedSharding(mesh, P(None))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(cache),
        [pool, pool, rep_t, rep_l],
    )


def data_shardings(mesh: Mesh) -> NamedSharding:
    """Per-step scalars/vectors (tokens, lengths, active masks): replicated —
    every tp shard needs the full batch, and the arrays are bytes-sized."""
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a params pytree onto the mesh per param_shardings."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), params, param_shardings(params, mesh)
    )


def shard_cache(cache: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), cache, cache_shardings(cache, mesh)
    )
