"""Parallelism layer: device meshes + sharding specs (SURVEY.md §2.5, §7).

The reference's only parallelism is request-level DP across whole workers
(server/src/services/JobScheduler.ts:317-360). Everything here is NEW TPU
capability living inside one logical worker: tensor/expert sharding over an
ICI mesh, with XLA inserting the collectives (scaling-book recipe: pick a
mesh, annotate shardings, let pjit do the rest).
"""

from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh, local_mesh
from gridllm_tpu.parallel.sharding import (
    cache_shardings,
    data_shardings,
    param_shardings,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "local_mesh",
    "param_shardings",
    "cache_shardings",
    "data_shardings",
]
