"""Scheduler shard: one leased partition owner in the scaled control
plane (ISSUE 15).

A ``SchedulerShard`` wires together a full JobScheduler (with a
ShardContext restricting it to leased partitions), the lease manager,
and the ``ctrl:submit``/``ctrl:cancel`` subscriptions. Worker heartbeats
fan out once on the bus and this shard's registry consumes them like
any other — orphan sweeps, retry/backoff budgets, SLO accounting,
deadlines, and the hang watchdog all run here, per shard, exactly as
they ran in the single-box scheduler.

Failover is handled like worker failure already is: when another
shard's lease expires, this member's lease sweep adopts the partition
(epoch bump) and ``adopt_shard`` replays the dead shard's durable job
state from the bus — queued records rejoin the queue, live assignments
re-arm with their remaining timeout, and the jobs' workers never notice
(their streams flow straight to the gateway replicas). A deposed shard
is fenced out of every mutating path by the scheduler's lease checks,
so a partitioned shard can never double-assign a job it no longer owns.
"""

from __future__ import annotations

import asyncio
import json
import time

from gridllm_tpu.bus.base import (
    CH_CTRL_CANCEL,
    CH_CTRL_SUBMIT,
    MessageBus,
    Subscription,
)
from gridllm_tpu.controlplane.lease import ShardLeaseManager
from gridllm_tpu.controlplane.partition import ShardContext
from gridllm_tpu.scheduler.registry import WorkerRegistry
from gridllm_tpu.scheduler.scheduler import JobScheduler
from gridllm_tpu.utils.config import ControlPlaneConfig, SchedulerConfig
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest

log = get_logger("controlplane.shard")


class SchedulerShard:
    def __init__(self, bus: MessageBus, registry: WorkerRegistry,
                 scheduler_config: SchedulerConfig | None = None,
                 cp: ControlPlaneConfig | None = None,
                 member_id: str = "", settle_s: float | None = None,
                 slo_config=None, watchdog_config=None):
        from gridllm_tpu.controlplane.client import make_member_id

        cp = cp or ControlPlaneConfig()
        self.bus = bus
        self.registry = registry
        self.member_id = make_member_id(member_id or cp.member_id, "shard")
        self.lease = ShardLeaseManager(
            bus, self.member_id, cp.num_shards,
            home_shards=(cp.shard_id,),
            ttl_ms=cp.lease_ttl_ms, renew_ms=cp.renew_interval_ms,
            on_acquired=self._on_lease_acquired,
            on_lost=self._on_lease_lost,
            settle_s=settle_s)
        self.ctx = ShardContext(cp.num_shards, self.member_id, self.lease)
        self.scheduler = JobScheduler(
            bus, registry, scheduler_config, shard=self.ctx,
            slo_config=slo_config, watchdog_config=watchdog_config)
        # the lease metrics join the shard scheduler's registry so the
        # shard health port's /metrics serves them
        self.lease.attach_metrics(self.scheduler.metrics)
        self._subs: list[Subscription] = []
        self._started = False

    # -- lease callbacks -----------------------------------------------------
    async def _on_lease_acquired(self, idx: int, adopted: bool) -> None:
        if adopted and self._started:
            await self.scheduler.adopt_shard(idx)

    async def _on_lease_lost(self, idx: int, reason: str) -> None:
        self.scheduler.release_shard(idx)

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        """Order matters: the home lease first (so initialize() loads the
        partition's durable state), then the scheduler's full machinery,
        then the submission fan-in."""
        await self.lease.start()
        await self.scheduler.initialize()
        self._started = True
        self._subs.append(
            await self.bus.subscribe(CH_CTRL_SUBMIT, self._on_submit))
        self._subs.append(
            await self.bus.subscribe(CH_CTRL_CANCEL, self._on_cancel))
        log.info("scheduler shard started", member=self.member_id,
                 shards=self.lease.held_shards(),
                 num_shards=self.ctx.num_shards)

    async def stop(self) -> None:
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()
        await self.scheduler.shutdown()
        await self.lease.stop(release=True)

    async def kill(self) -> None:
        """Chaos/test hook: die the way SIGKILL dies — drop every
        subscription and timer with NO handoff, NO lease release, NO
        durable-state cleanup. The fleet only learns from the lease TTL
        running out, exactly like a killed process."""
        self.lease.kill()
        for s in self._subs:
            await s.unsubscribe()
        self._subs.clear()
        sched = self.scheduler
        sched._running = False
        await sched.watchdog.stop()
        if sched._sweep_task is not None:
            sched._sweep_task.cancel()
            sched._sweep_task = None
        for h in (*sched._timeout_handles.values(),
                  *sched._retry_handles.values()):
            h.cancel()
        sched._timeout_handles.clear()
        sched._retry_handles.clear()
        for s in sched._subs:
            await s.unsubscribe()
        sched._subs.clear()

    # -- submission fan-in ---------------------------------------------------
    async def _on_submit(self, _ch: str, raw: str) -> None:
        try:
            data = json.loads(raw)
            request = InferenceRequest.model_validate(data["request"])
        except Exception as e:  # noqa: BLE001 — bad submits are dropped loud
            log.error("bad ctrl:submit payload", error=str(e))
            return
        if not self.ctx.owns(request.id):
            if await self._park_submission(request):
                self.scheduler._ctrl_submits.inc(event="parked")
            else:
                self.scheduler._ctrl_submits.inc(event="ignored")
            return
        self.scheduler._ctrl_submits.inc(event="accepted")
        await self.scheduler.add_job(request)

    async def _park_submission(self, request: InferenceRequest) -> bool:
        """Owner-less-window recovery: a submit whose partition owner is
        dead — whether its lease has visibly expired yet or not — would
        otherwise be dropped by every shard and lost until the client
        times out. Every NON-owner therefore parks the request straight
        into the partition's durable queue record (idempotent across
        shards: same hash field, same content), so whichever member owns
        or adopts the partition replays it. The live owner's normal flow
        subsumes the parked copy: enqueue overwrites the same field and
        dispatch/cancel hdel it. A ghost record left by a park racing
        past the owner's hdel is defused at adoption by the
        actives-first/_recent_done replay checks and, last-ditch, the
        worker-side duplicate-assignment drop. The timestamp-derived seq
        sorts parked jobs after any replayed backlog."""
        from gridllm_tpu.scheduler.scheduler import shard_queue_key

        idx = self.ctx.shard_for(request.id)
        try:
            await self.bus.hset(shard_queue_key(idx), request.id,
                                json.dumps({
                                    "seq": int(time.time() * 1000),
                                    "request": request.model_dump(
                                        mode="json"),
                                }))
        except Exception as e:  # noqa: BLE001 — parking is best-effort
            log.warning("submission park failed",
                        job_id=request.id, error=str(e))
            return False
        return True

    async def _on_cancel(self, _ch: str, raw: str) -> None:
        try:
            data = json.loads(raw)
            job_id = str(data["jobId"])
        except Exception:
            return
        if not self.ctx.owns(job_id):
            return
        await self.scheduler.cancel_job(
            job_id, reason=str(data.get("reason") or "cancelled"))


async def wait_for_ownership(shards: list[SchedulerShard],
                             num_shards: int,
                             timeout_s: float = 10.0) -> bool:
    """Test/boot helper: wait until every partition is held by someone."""
    deadline = asyncio.get_running_loop().time() + timeout_s
    while asyncio.get_running_loop().time() < deadline:
        held = {i for sh in shards for i in sh.lease.held_shards()}
        if len(held) >= num_shards:
            return True
        await asyncio.sleep(0.02)
    return False
