"""Stateless gateway replica's scheduler facade (ISSUE 15).

``GatewaySubmitter`` keeps the JobScheduler's public submit surface —
``submit_and_wait`` / ``submit_streaming_job`` / ``cancel_job`` /
``get_stats`` — so every gateway route works unchanged, but owns NO
partition: ``add_job`` publishes the request on the durable
``ctrl:submit`` channel and the owning scheduler shard (shard.py)
enqueues it. Results and stream frames never touch a shard on the way
back — workers publish them on the durable per-job channels the submit
path already subscribes, which is exactly why any replica can serve any
request and why streaming state rebuilds after a replica restart: the
broker's replay rings (PR 10) re-deliver the frames the replica missed.

The waiter-side timeout still cancels remotely (``ctrl:cancel``); SLO
judgment stays here because only the submitting replica sees the
client-observed TTFT/e2e. Orphan sweeps, retries, deadlines, and the
hang watchdog all live on the shards.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any

from gridllm_tpu.bus.base import (
    CH_CTRL_CANCEL,
    CH_CTRL_SUBMIT,
    MessageBus,
)
from gridllm_tpu.obs.timeline import emit_event
from gridllm_tpu.obs.tracer import trace_pattern
from gridllm_tpu.scheduler.registry import WorkerRegistry
from gridllm_tpu.scheduler.scheduler import JobScheduler
from gridllm_tpu.utils.config import SchedulerConfig, SLOConfig
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest

log = get_logger("controlplane.client")


def make_member_id(configured: str, role: str) -> str:
    """Stable-if-configured member id (GRIDLLM_CONTROLPLANE_ID); the
    generated fallback is unique per process so status envelopes and
    lease owners never collide."""
    return configured or f"{role}-{uuid.uuid4().hex[:8]}"


class GatewaySubmitter(JobScheduler):
    """A JobScheduler that owns nothing: submissions fan out on the bus,
    and only the submit-side state (waiters, tracer, SLO) lives here."""

    def __init__(self, bus: MessageBus, registry: WorkerRegistry,
                 config: SchedulerConfig | None = None,
                 slo_config: SLOConfig | None = None,
                 member_id: str = ""):
        super().__init__(bus, registry, config, slo_config=slo_config)
        self.member_id = make_member_id(member_id, "gateway")

    # -- lifecycle -----------------------------------------------------------
    async def initialize(self) -> None:
        """Submit-side wiring only: no lifecycle-channel subscriptions,
        no dispatch/sweep loops, no watchdog — a replica has no queue to
        sweep and no assignments to watch. Worker span timelines are
        still ingested so /admin/trace stitches end to end on whichever
        replica served the request."""
        self._running = True
        self._subs.append(
            await self.bus.psubscribe(trace_pattern(), self._on_trace))
        log.info("gateway submitter initialized", member=self.member_id)

    # -- submit surface ------------------------------------------------------
    async def add_job(self, request: InferenceRequest,
                      requeue: bool = False) -> str:
        """Publish the request to the scheduler shards. The per-class
        deadline is stamped HERE (submission time is the gateway's
        clock); everything downstream — queueing, dispatch, retries —
        belongs to the owning shard."""
        md = request.metadata
        if "deadlineAt" not in md:
            deadline_ms = self._deadline_for(request)
            if deadline_ms > 0:
                md["deadlineAt"] = time.time() + deadline_ms / 1000
        await self.bus.publish(CH_CTRL_SUBMIT, json.dumps({
            "request": request.model_dump(mode="json"),
            "submitter": self.member_id,
        }))
        # accounted as ctrl published ONLY: the owning shard counts the
        # job's `queued` event (and its terminal event) — counting it
        # here too would double every job fleet-wide and break the
        # "queued balances against terminal events" invariant
        self._ctrl_submits.inc(event="published")
        # fleet timeline (ISSUE 17): the gateway-side anchor of every
        # request's causal slice — attributed to THIS replica, ordered
        # before the owning shard's events by the ctrl:submit bus edge
        emit_event("gateway.submitted", member=self.member_id,
                   request_id=request.id, model=request.model)
        log.job("job published to scheduler shards", request.id,
                model=request.model)
        self.emit("job_queued", request)
        return request.id

    async def cancel_job(self, job_id: str, reason: str = "cancelled") -> bool:
        """Relay the cancellation; the owning shard resolves whether the
        job was queued, retrying, or active and accounts it exactly once."""
        await self.bus.publish(CH_CTRL_CANCEL, json.dumps({
            "jobId": job_id, "reason": reason,
            "submitter": self.member_id,
        }))
        self._drop_resume_state(job_id)
        return True

    def identity(self) -> dict[str, Any]:
        return {"role": "gateway", "member": self.member_id,
                "shards": [], "numShards": 0}
