"""Scheduler-shard process entry: ``python -m gridllm_tpu.controlplane``.

Builds bus → registry (full liveness — shards own the death verdicts
for their partitions' jobs) → SchedulerShard → StatusPublisher, plus a
small health HTTP listener (``GRIDLLM_SHARD_HEALTH_PORT``) serving this
shard's ``/metrics``, ``/admin/slo``, ``/admin/dump``, ``/admin/trace``
and ``/health/live`` so Prometheus can scrape shards directly — the
gateway replicas' FleetView serves the aggregated fleet view either way.

Configuration: ``GRIDLLM_SHARD_COUNT`` (fleet-wide M), ``GRIDLLM_SHARD_ID``
(this process's home partition), ``GRIDLLM_SHARD_LEASE_TTL_MS`` /
``GRIDLLM_SHARD_RENEW_MS`` (failover timers), ``GRIDLLM_BUS_URL`` /
``GRIDLLM_BUS_ENDPOINTS`` (the shared bus, HA pair supported).
"""

from __future__ import annotations

import asyncio
import signal

from gridllm_tpu.utils.logging import get_logger

log = get_logger("controlplane.main")


async def run_shard() -> None:
    from aiohttp import web

    from gridllm_tpu.bus import create_bus
    from gridllm_tpu.controlplane.shard import SchedulerShard
    from gridllm_tpu.controlplane.status import StatusPublisher
    from gridllm_tpu.gateway import obs_routes
    from gridllm_tpu.scheduler import WorkerRegistry
    from gridllm_tpu.utils.config import load_config

    config = load_config()
    cp = config.controlplane
    bus = create_bus(config.bus.url, key_prefix=config.bus.key_prefix,
                     password=config.bus.password, db=config.bus.db,
                     endpoints=config.bus.endpoints)
    await bus.connect()
    registry = WorkerRegistry(bus, config.scheduler)
    shard = SchedulerShard(bus, registry, config.scheduler, cp,
                           slo_config=config.obs.slo,
                           watchdog_config=config.obs.watchdog)
    # fleet timeline (ISSUE 17): shards publish their lifecycle events and
    # keep their own store + incident collector, so a surviving shard's
    # health port answers /admin/incidents even with every gateway down
    timeline_pub = None
    timeline_store = None
    incidents = None
    tl = config.obs.timeline
    if tl.enabled:
        from gridllm_tpu.obs import (
            IncidentCollector,
            TimelinePublisher,
            TimelineStore,
        )

        timeline_pub = TimelinePublisher(
            shard.member_id, queue_capacity=tl.queue_capacity,
            flush_ms=tl.flush_ms, batch_max=tl.batch_max)
        timeline_store = TimelineStore(capacity=tl.store_capacity,
                                       max_requests=tl.store_requests)
        incidents = IncidentCollector(
            timeline_store, member=shard.member_id,
            window_ms=tl.incident_window_ms,
            max_incidents=tl.max_incidents)
        timeline_pub.install()
        await timeline_pub.start(bus)
        await timeline_store.attach(bus)
    await registry.initialize()
    await shard.start()
    status = StatusPublisher(bus, shard.scheduler, "shard",
                             shard.member_id, cp.status_interval_ms,
                             lease=shard.lease)
    await status.start()

    runner: web.AppRunner | None = None
    if cp.shard_health_port:
        app = web.Application()
        app.add_routes(obs_routes.build_routes(shard.scheduler,
                                               timeline=timeline_store,
                                               incidents=incidents))

        async def live(_request: web.Request) -> web.Response:
            return web.json_response({
                "status": "alive",
                "member": shard.member_id,
                "shards": shard.lease.held_shards(),
            })

        app.add_routes([web.get("/health/live", live)])
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", cp.shard_health_port)
        await site.start()
    log.info("scheduler shard serving", member=shard.member_id,
             home=cp.shard_id, num_shards=cp.num_shards,
             health_port=cp.shard_health_port or None)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    log.info("scheduler shard shutting down", member=shard.member_id)
    await status.stop()
    if runner is not None:
        await runner.cleanup()
    await shard.stop()
    await registry.shutdown()
    if timeline_pub is not None:
        await timeline_pub.stop()
    if timeline_store is not None:
        await timeline_store.detach()
    await bus.disconnect()


def main() -> None:  # pragma: no cover
    asyncio.run(run_shard())


if __name__ == "__main__":  # pragma: no cover
    main()
