"""Horizontally scaled control plane (ISSUE 15): N stateless gateway
replicas in front of M scheduler shards, each owning a deterministic
partition of the job-id space via bus-backed leases fenced by epoch.

Modules (import them directly — ``client``/``shard`` pull in the
scheduler, so this package ``__init__`` stays dependency-light):

- ``partition``: ``shard_of`` job-id mapping + the ``ShardContext`` the
  JobScheduler duck-types;
- ``lease``: bus-backed ownership leases (acquire/renew/adopt, epoch
  bump per transfer, self-fencing on missed renewals);
- ``client``: ``GatewaySubmitter`` — the stateless replica's scheduler
  facade (publishes on ``ctrl:submit``, awaits the durable per-job
  result/stream channels);
- ``shard``: ``SchedulerShard`` — one partition owner: full scheduler +
  lease manager + submission fan-in + failover adoption;
- ``status``: ``StatusPublisher``/``FleetView`` — the thin aggregation
  layer behind the fleet-wide ``/metrics``, ``/admin/slo``,
  ``/admin/dump``, and ``/health/workers`` views.

Run a shard process with ``python -m gridllm_tpu.controlplane``; run
gateway replicas with ``GRIDLLM_CONTROLPLANE=gateway``.
"""

from gridllm_tpu.controlplane.partition import ShardContext, shard_of

__all__ = ["ShardContext", "shard_of"]
