"""Bus-backed shard-ownership leases with epoch fencing (ISSUE 15).

Each scheduler shard owns a partition of the job-id space by holding a
lease record in the bus hash ``shard_leases``:

    {idx: {"owner": member, "epoch": N, "renewedAt": ts, "ttlMs": ttl}}

Semantics, in the PR 10 epoch-fencing shape:

- **Acquire** bumps the record's epoch: every ownership transfer is a
  strictly newer epoch, so two members can never both believe they hold
  the same (shard, epoch) pair. With no compare-and-set on the bus
  contract, acquisition is write → settle → read-back-verify: both
  racing candidates write, the broker serializes, and after the settle
  window only the LAST writer reads itself back as owner. The loser's
  next renewal sees a foreign owner/epoch and deposes itself. The settle
  window is deterministic per member (spread, not synchronized) and must
  exceed the bus round trip — the renew interval bounds any residual
  overlap, and the scheduler's fence check refuses mutations the moment
  freshness lapses.
- **Renew** re-reads before rewriting: a foreign owner OR a foreign
  epoch under our own name means we were deposed — drop ownership and
  fire ``on_lost`` (the scheduler releases the partition's local state
  without touching the durable records the new owner replays).
- **Expire locally**: if renewals stop landing (partition, dead broker)
  for longer than the TTL, the member fences ITSELF — it cannot prove
  nobody else adopted the shard, so ``fenced()`` goes False and every
  mutating scheduler path refuses. This is the "a deposed or partitioned
  shard can never double-assign" contract.
- **Sweep/adopt**: every member scans the other partitions each
  interval; an expired or missing lease is acquired (epoch bump) and
  ``on_acquired(idx, adopted=True)`` triggers the scheduler's durable-
  state replay (adopt_shard).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import time
from typing import Any, Awaitable, Callable

from gridllm_tpu.bus.base import MessageBus
from gridllm_tpu.obs import MetricsRegistry
from gridllm_tpu.utils.logging import get_logger

log = get_logger("controlplane.lease")

LEASES_KEY = "shard_leases"

# (idx, adopted) → None; adopted=False is the boot-time home acquisition
AcquiredCb = Callable[[int, bool], Awaitable[None] | None]
# (idx, reason) → None; reason is "deposed" or "expired"
LostCb = Callable[[int, str], Awaitable[None] | None]


def _settle_s(member_id: str) -> float:
    """Deterministic per-member settle window (40-80 ms): candidates that
    race an acquisition settle at different times, so the later writer's
    record is visible to the earlier one's read-back."""
    h = int.from_bytes(
        hashlib.blake2b(member_id.encode(), digest_size=2).digest(), "big")
    return 0.04 + (h % 40) / 1000.0


class ShardLeaseManager:
    def __init__(self, bus: MessageBus, member_id: str, num_shards: int,
                 home_shards: tuple[int, ...] | list[int],
                 ttl_ms: float, renew_ms: float,
                 metrics: MetricsRegistry | None = None,
                 on_acquired: AcquiredCb | None = None,
                 on_lost: LostCb | None = None,
                 settle_s: float | None = None):
        self.bus = bus
        self.member_id = member_id
        self.num_shards = num_shards
        self.home_shards = tuple(home_shards)
        self.ttl_ms = float(ttl_ms)
        self.renew_ms = float(renew_ms)
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.settle_s = (_settle_s(member_id) if settle_s is None
                         else settle_s)
        self._held: dict[int, int] = {}       # shard idx → our epoch
        self._last_ok: dict[int, float] = {}  # shard idx → monotonic renew
        self._task: asyncio.Task | None = None
        self._running = False
        self._transitions = None
        self._epoch_gauge = None
        if metrics is not None:
            self.attach_metrics(metrics)

    def attach_metrics(self, metrics: MetricsRegistry) -> None:
        """Register the lease instruments on a registry — called by the
        SchedulerShard with its scheduler's per-instance registry so the
        shard health port's /metrics serves them."""
        self._transitions = metrics.counter(
            "gridllm_shard_lease_transitions_total",
            "Shard-ownership lease transitions, by event (acquired = "
            "boot-time home partition, adopted = failover takeover "
            "with epoch bump, deposed = a newer owner appeared, "
            "expired = renewals stopped landing and the member "
            "fenced itself, released = graceful shutdown).",
            ("event",))
        self._epoch_gauge = metrics.gauge(
            "gridllm_shard_lease_epoch",
            "Lease epoch of each shard partition this member "
            "currently holds — bumps exactly once per ownership "
            "transfer (the fencing token).",
            ("shard",))
        metrics.add_collector("shard_lease", self._collect)

    # -- observability -------------------------------------------------------
    def _collect(self) -> None:
        for idx, epoch in self._held.items():
            self._epoch_gauge.set(epoch, shard=str(idx))

    def _count(self, event: str) -> None:
        if self._transitions is not None:
            self._transitions.inc(event=event)

    # -- queries -------------------------------------------------------------
    def holds(self, idx: int) -> bool:
        return idx in self._held

    def held_shards(self) -> list[int]:
        return sorted(self._held)

    def held_epochs(self) -> dict[int, int]:
        """{shard idx: our epoch} for every partition currently held."""
        return dict(self._held)

    def epochs(self) -> dict[str, int]:
        return {str(i): e for i, e in sorted(self._held.items())}

    def fenced(self, idx: int) -> bool:
        """Fresh-lease check: held AND the last successful renewal landed
        within the TTL. This is what the scheduler's mutating paths ask."""
        last = self._last_ok.get(idx)
        if idx not in self._held or last is None:
            return False
        return (time.monotonic() - last) * 1000.0 < self.ttl_ms

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        """Acquire the home partitions (waiting out a live holder — a
        misconfigured duplicate shard id idles instead of split-braining)
        and start the renew/sweep loop."""
        self._running = True
        for idx in self.home_shards:
            await self.try_acquire(idx, adopted=False)
        self._task = asyncio.create_task(self._loop())

    async def stop(self, release: bool = True) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if release:
            for idx in list(self._held):
                try:
                    await self.bus.hdel(LEASES_KEY, str(idx))
                except Exception as e:  # noqa: BLE001 — shutdown best-effort
                    log.warning("lease release failed", shard=idx,
                                error=str(e))
                self._count("released")
            self._held.clear()
            self._last_ok.clear()

    def kill(self) -> None:
        """Chaos/test hook: stop renewing WITHOUT releasing anything —
        exactly what a SIGKILLed shard process looks like to the fleet
        (its lease records age out and a survivor adopts them)."""
        self._running = False
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- protocol ------------------------------------------------------------
    def _parse(self, raw: str | None) -> dict[str, Any] | None:
        if not raw:
            return None
        try:
            rec = json.loads(raw)
            return rec if isinstance(rec, dict) else None
        except Exception:
            return None

    def _live(self, rec: dict[str, Any], now: float) -> bool:
        ttl = float(rec.get("ttlMs") or self.ttl_ms)
        return (now - float(rec.get("renewedAt") or 0)) * 1000.0 < ttl

    async def partition_orphaned(self, idx: int) -> bool:
        """True when the partition currently has NO live lease holder —
        the owner-less window between a shard death and adoption. Used by
        the submit fan-in: a job arriving for an orphaned partition is
        parked straight into the durable queue record so the eventual
        adopter replays it instead of every shard dropping it. Errs
        toward False (a degraded bus must not make everyone think the
        partition is free)."""
        try:
            cur = self._parse(await self.bus.hget(LEASES_KEY, str(idx)))
        except Exception:  # noqa: BLE001
            return False
        return cur is None or not self._live(cur, time.time())

    async def try_acquire(self, idx: int, adopted: bool) -> bool:
        """One write → settle → read-back-verify acquisition attempt.

        The read-back runs TWICE with a settle window between: a racing
        candidate whose write lands after our first verification is
        caught by the second, so both-believe-they-won requires the
        loser's write to straggle past two settle windows (per-member
        deterministic lengths, so the candidates never settle in
        lockstep). The residual overlap is bounded by one renew interval
        (the next renewal reads a foreign record and deposes) and backed
        by the worker-side duplicate-assignment drop — an overlapped
        dispatch is ignored by a worker already running the job."""
        try:
            cur = self._parse(await self.bus.hget(LEASES_KEY, str(idx)))
            now = time.time()
            if cur is not None and cur.get("owner") != self.member_id \
                    and self._live(cur, now):
                return False  # live foreign lease — not adoptable
            epoch = int((cur or {}).get("epoch") or 0) + 1
            rec = {"owner": self.member_id, "epoch": epoch,
                   "renewedAt": now, "ttlMs": self.ttl_ms}
            await self.bus.hset(LEASES_KEY, str(idx), json.dumps(rec))
            for _ in range(2):
                await asyncio.sleep(self.settle_s)
                back = self._parse(await self.bus.hget(LEASES_KEY,
                                                       str(idx)))
                if back is None or back.get("owner") != self.member_id \
                        or int(back.get("epoch") or 0) != epoch:
                    return False  # lost the settle race — later writer won
        except Exception as e:  # noqa: BLE001 — bus failure = no lease
            log.warning("lease acquisition failed", shard=idx, error=str(e))
            return False
        self._held[idx] = epoch
        self._last_ok[idx] = time.monotonic()
        self._count("adopted" if adopted else "acquired")
        log.info("shard lease acquired", shard=idx, epoch=epoch,
                 adopted=adopted, member=self.member_id)
        if self.on_acquired is not None:
            ret = self.on_acquired(idx, adopted)
            if asyncio.iscoroutine(ret):
                await ret
        return True

    async def _lose(self, idx: int, reason: str) -> None:
        self._held.pop(idx, None)
        self._last_ok.pop(idx, None)
        self._count(reason)
        log.warning("shard lease lost", shard=idx, reason=reason,
                    member=self.member_id)
        if self.on_lost is not None:
            ret = self.on_lost(idx, reason)
            if asyncio.iscoroutine(ret):
                await ret

    async def _renew(self, idx: int) -> None:
        epoch = self._held.get(idx)
        if epoch is None:
            return
        try:
            cur = self._parse(await self.bus.hget(LEASES_KEY, str(idx)))
            if cur is None or cur.get("owner") != self.member_id \
                    or int(cur.get("epoch") or 0) != epoch:
                # a newer owner (or a newer incarnation of us) holds it
                await self._lose(idx, "deposed")
                return
            cur["renewedAt"] = time.time()
            await self.bus.hset(LEASES_KEY, str(idx), json.dumps(cur))
            self._last_ok[idx] = time.monotonic()
        except Exception as e:  # noqa: BLE001 — renewal may miss a beat
            log.warning("lease renewal failed", shard=idx, error=str(e))
            last = self._last_ok.get(idx, 0.0)
            if (time.monotonic() - last) * 1000.0 >= self.ttl_ms:
                # can't prove ownership anymore — self-fence and drop
                await self._lose(idx, "expired")

    async def _sweep(self) -> None:
        """Adopt any partition whose lease is missing or expired."""
        for idx in range(self.num_shards):
            if idx in self._held:
                continue
            try:
                cur = self._parse(await self.bus.hget(LEASES_KEY, str(idx)))
            except Exception:  # noqa: BLE001 — degraded bus: no adoption
                continue
            if cur is not None and self._live(cur, time.time()) \
                    and cur.get("owner") != self.member_id:
                continue
            await self.try_acquire(idx, adopted=True)

    async def _loop(self) -> None:
        interval = self.renew_ms / 1000.0
        while self._running:
            await asyncio.sleep(interval)
            try:
                for idx in list(self._held):
                    await self._renew(idx)
                await self._sweep()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — loop must survive
                log.error("lease loop iteration failed", error=str(e))
