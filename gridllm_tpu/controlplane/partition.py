"""Deterministic job-id → scheduler-shard partitioning (ISSUE 15).

The whole scaled control plane rests on one agreement: every member —
gateway replicas, scheduler shards, adoption logic, tests — maps a job
id to the SAME shard index with no coordination. ``shard_of`` is a
stable content hash (blake2b, not Python's seeded ``hash()``) so the
mapping survives process restarts, mixed Python versions, and replays
from durable bus state. Changing M reshuffles the space; all members of
one fleet must agree on ``num_shards`` (``GRIDLLM_SHARD_COUNT``).

``ShardContext`` is the handle the JobScheduler duck-types (it is
injected, never imported, so scheduler/ stays import-free of
controlplane/): ownership = "this member holds the bus lease for the
job's partition", fencing = "and that lease is still provably fresh".
"""

from __future__ import annotations

import hashlib
from typing import Any


def shard_of(job_id: str, num_shards: int) -> int:
    """Stable partition index of a job id in [0, num_shards)."""
    if num_shards <= 1:
        return 0
    digest = hashlib.blake2b(job_id.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(digest, "big") % num_shards


class ShardContext:
    """One scheduler shard's view of the partition space: which shards
    this member currently holds leases for, and whether those leases are
    fresh enough to act on. Backed by a ShardLeaseManager (lease.py)."""

    def __init__(self, num_shards: int, member_id: str, lease: Any):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.num_shards = num_shards
        self.member_id = member_id
        self.lease = lease

    def shard_for(self, job_id: str) -> int:
        return shard_of(job_id, self.num_shards)

    def held(self) -> list[int]:
        """Partition indices this member currently holds leases for."""
        return self.lease.held_shards()

    def owns(self, job_id: str) -> bool:
        """Partition-set membership: the job's shard is leased by this
        member (possibly stale — use fenced_job before mutating)."""
        return self.lease.holds(self.shard_for(job_id))

    def fenced_job(self, job_id: str) -> bool:
        """Lease-fenced ownership: held AND renewed within the TTL. The
        JobScheduler consults this on every mutating path; a deposed or
        partitioned shard answers False and refuses the operation."""
        return self.lease.fenced(self.shard_for(job_id))

    def identity(self) -> dict[str, Any]:
        """The shard-identity block stamped into get_stats()/admin views."""
        return {
            "role": "shard",
            "member": self.member_id,
            "shards": self.held(),
            "numShards": self.num_shards,
            "epochs": self.lease.epochs(),
        }
