"""Control-plane status fan-out + fleet-wide aggregation (ISSUE 15).

Every control-plane member (gateway replica or scheduler shard)
publishes a periodic envelope on ``ctrl:status``; each gateway
replica's ``FleetView`` keeps the latest envelope per member and serves
the thin aggregation layer the admin surface reads — so ``/metrics``,
``/admin/slo``, ``/admin/dump``, and ``/health/workers`` present one
fleet-wide view regardless of which replica is asked, WITHOUT ever
summing unlabeled numbers: everything stays keyed by member/shard
identity (the PR 1 "health and scrapes agree" invariant, per shard).

A member whose envelope goes stale (no publish within the prune
window) drops out of the view; a shard partition nobody fresh claims
reads as lease-lost (``gridllm_shard_lease_held`` 0 — the
``GridLLMShardLeaseLost`` alert) until a survivor adopts it.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any

from gridllm_tpu.bus.base import (
    CH_CTRL_STATUS,
    CH_OBS_DUMP,
    MessageBus,
    Subscription,
    obs_dump_reply_channel,
)
from gridllm_tpu.obs import MetricsRegistry, build_dump, merge_capacity
from gridllm_tpu.utils.logging import get_logger

log = get_logger("controlplane.status")


class StatusPublisher:
    """Periodic ``ctrl:status`` envelope for one member. Shards attach
    their scheduler + lease state; gateway replicas publish their
    submit-side SLO view so /admin/slo aggregates the client-observed
    judgments from every replica."""

    def __init__(self, bus: MessageBus, scheduler: Any, role: str,
                 member_id: str, interval_ms: float,
                 lease: Any | None = None):
        self.bus = bus
        self.scheduler = scheduler
        self.role = role
        self.member_id = member_id
        self.interval_s = interval_ms / 1000.0
        self.lease = lease
        self._task: asyncio.Task | None = None
        self._dump_sub: Subscription | None = None

    def _per_shard_counts(self) -> dict[str, dict[str, Any]]:
        """Exact per-partition queue/active counts (a member may hold
        several partitions after adoption — attribute jobs to the one
        that owns them, not to the member as a blob)."""
        sched = self.scheduler
        if sched.shard is None or self.lease is None:
            return {}
        out = {str(i): {"epoch": e, "queued": 0, "active": 0}
               for i, e in self.lease.held_epochs().items()}
        for qj in list(sched.job_queue):
            rec = out.get(str(sched.shard.shard_for(qj.request.id)))
            if rec is not None:
                rec["queued"] += 1
        for job_id in list(sched.active_jobs):
            rec = out.get(str(sched.shard.shard_for(job_id)))
            if rec is not None:
                rec["active"] += 1
        return out

    def envelope(self) -> str:
        sched = self.scheduler
        return json.dumps({
            "member": self.member_id,
            "role": self.role,
            "ts": time.time(),
            "shards": (self.lease.held_shards()
                       if self.lease is not None else []),
            "leases": self._per_shard_counts(),
            "stats": sched.get_stats(),
            "slo": sched.slo.snapshot(),
            # fleet economics (ISSUE 16): this member's per-model
            # demand/headroom snapshot + its usage-ledger view; shards
            # carry the authoritative demand (they own the queues)
            "capacity": (sched.capacity.snapshot()
                         if getattr(sched, "capacity", None) is not None
                         else None),
            "usage": (sched.usage.snapshot()
                      if getattr(sched, "usage", None) is not None
                      else None),
            # active fleet health (ISSUE 19): this member's worker health
            # verdicts + canary summary — shards carry the authoritative
            # view (their monitors issue the verdicts)
            "health": (sched.health.snapshot()
                       if getattr(sched, "health", None) is not None
                       else None),
            "canary": (sched.prober.summary()
                       if getattr(sched, "prober", None) is not None
                       else None),
            "queued": len(sched.job_queue),
            "active": len(sched.active_jobs),
            "hangs": len(sched.watchdog.hangs),
        })

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())
        # fleet-merged dump (ISSUE 17): every member with a status
        # publisher also answers dump collection ops, so ONE
        # /admin/dump?fleet=1 call captures the whole control plane
        self._dump_sub = await self.bus.subscribe(CH_OBS_DUMP,
                                                  self._on_dump_request)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._dump_sub is not None:
            await self._dump_sub.unsubscribe()
            self._dump_sub = None

    async def _on_dump_request(self, _ch: str, raw: str) -> None:
        """Answer one fleet dump collection op with this member's local
        artifact on the per-op reply channel (keyed by member identity;
        the requester never merges artifacts silently)."""
        try:
            op_id = str(json.loads(raw).get("opId") or "")
        except (ValueError, TypeError):
            return
        if not op_id:
            return
        try:
            artifact = build_dump(self.scheduler, reason="fleet_dump")
            await self.bus.publish(
                obs_dump_reply_channel(op_id),
                json.dumps({"opId": op_id, "member": self.member_id,
                            "dump": artifact}, default=str))
        except Exception as e:  # noqa: BLE001 — dumps are best-effort
            log.warning("fleet dump reply failed", error=str(e),
                        opId=op_id)

    async def publish_once(self) -> None:
        await self.bus.publish(CH_CTRL_STATUS, self.envelope())

    async def _loop(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — status is best-effort
                log.warning("status publish failed", error=str(e))
            await asyncio.sleep(self.interval_s)


class FleetView:
    """Latest-envelope-per-member aggregation on a gateway replica."""

    def __init__(self, bus: MessageBus, metrics: MetricsRegistry,
                 stale_after_ms: float):
        self.bus = bus
        self.stale_after_s = stale_after_ms / 1000.0
        self._members: dict[str, dict[str, Any]] = {}
        # high-water shard count: when EVERY shard envelope goes stale
        # (total shard outage — exactly when GridLLMShardLeaseLost must
        # fire) the live envelopes alone would say numShards=0 and the
        # lease-held gauges would freeze at their last value instead of
        # dropping to 0; the remembered fleet size keeps driving them
        self._max_shards = 0
        self._sub: Subscription | None = None
        self._queue_gauge = metrics.gauge(
            "gridllm_shard_queue_depth",
            "Jobs queued per scheduler-shard partition, aggregated from "
            "the shards' ctrl:status envelopes by the gateway replica "
            "serving the scrape.",
            ("shard",))
        self._active_gauge = metrics.gauge(
            "gridllm_shard_active_jobs",
            "Jobs assigned per scheduler-shard partition, aggregated "
            "from the shards' ctrl:status envelopes.",
            ("shard",))
        self._held_gauge = metrics.gauge(
            "gridllm_shard_lease_held",
            "1 while some live scheduler shard holds the partition's "
            "lease (per its fresh ctrl:status envelope), 0 while the "
            "partition is orphaned awaiting adoption — the "
            "GridLLMShardLeaseLost alert watches this.",
            ("shard",))
        self._members_gauge = metrics.gauge(
            "gridllm_controlplane_members",
            "Live control-plane members by role (gateway replicas and "
            "scheduler shards with a fresh status envelope).",
            ("role",))
        metrics.add_collector("controlplane_fleet", self._collect)

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        self._sub = await self.bus.subscribe(CH_CTRL_STATUS, self._on_status)

    async def stop(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None

    async def _on_status(self, _ch: str, raw: str) -> None:
        try:
            env = json.loads(raw)
            member = str(env["member"])
        except Exception:
            return
        env["receivedAt"] = time.monotonic()
        ident = (env.get("stats") or {}).get("shard") or {}
        self._max_shards = max(self._max_shards,
                               int(ident.get("numShards") or 0))
        self._members[member] = env

    # -- aggregation ---------------------------------------------------------
    def _live_members(self) -> dict[str, dict[str, Any]]:
        now = time.monotonic()
        self._members = {
            m: env for m, env in self._members.items()
            if now - env.get("receivedAt", 0) < self.stale_after_s}
        return dict(self._members)

    def num_shards(self) -> int:
        """Fleet shard count: the high-water mark over every shard
        identity ever seen, so a total shard outage (no live envelopes)
        still reports the real fleet size and the per-shard gauges keep
        being driven (to 0 — the alert condition)."""
        for env in self._members.values():
            ident = (env.get("stats") or {}).get("shard") or {}
            self._max_shards = max(self._max_shards,
                                   int(ident.get("numShards") or 0))
        return self._max_shards

    def _collect(self) -> None:
        members = self._live_members()
        roles: dict[str, int] = {"gateway": 0, "shard": 0}
        claimed: dict[int, dict[str, Any]] = {}
        for env in members.values():
            roles[env.get("role", "?")] = roles.get(env.get("role", "?"),
                                                    0) + 1
            for idx_s, rec in (env.get("leases") or {}).items():
                try:
                    claimed[int(idx_s)] = rec
                except (TypeError, ValueError):
                    continue
        for role, n in roles.items():
            self._members_gauge.set(n, role=role)
        for idx in range(self.num_shards()):
            rec = claimed.get(idx)
            self._held_gauge.set(1 if rec is not None else 0,
                                 shard=str(idx))
            if rec is not None:
                self._queue_gauge.set(int(rec.get("queued") or 0),
                                      shard=str(idx))
                self._active_gauge.set(int(rec.get("active") or 0),
                                      shard=str(idx))

    def members(self) -> dict[str, dict[str, Any]]:
        """Envelope summaries for /health and /admin/dump — keyed by
        member id, shard identity preserved."""
        out = {}
        for member, env in self._live_members().items():
            out[member] = {
                "role": env.get("role"),
                "shards": env.get("shards"),
                "queued": env.get("queued"),
                "active": env.get("active"),
                "hangs": env.get("hangs"),
                "ageS": round(time.monotonic()
                              - env.get("receivedAt", 0), 3),
            }
        return out

    def merged_stats(self) -> dict[str, Any]:
        """Fleet job stats: per-member blocks (shard identity attached)
        plus shard-only totals — gateway replicas' submit counters are
        reported but never summed into the shard totals (they count the
        same jobs from the other side)."""
        members = self._live_members()
        per_member: dict[str, Any] = {}
        totals: dict[str, float] = {}
        for member, env in members.items():
            stats = env.get("stats") or {}
            per_member[member] = stats
            if env.get("role") != "shard":
                continue
            for key, val in stats.items():
                if isinstance(val, (int, float)) and not isinstance(
                        val, bool):
                    totals[key] = totals.get(key, 0) + val
        return {"perMember": per_member, "shardTotals": totals,
                "numShards": self.num_shards()}

    def merged_slo(self) -> dict[str, Any]:
        """Every member's SLO snapshot, keyed by member id with its role
        — attainment ratios from different members are never averaged
        into one unlabeled number."""
        return {
            member: {"role": env.get("role"), "slo": env.get("slo")}
            for member, env in self._live_members().items()}

    def merged_health(self) -> dict[str, Any]:
        """Fleet health (ISSUE 19): every member's worker-health verdicts
        and canary summary, keyed by member id with its role — verdicts
        from different monitors are presented side by side, never merged
        into one unlabeled state."""
        return {
            member: {"role": env.get("role"),
                     "health": env.get("health"),
                     "canary": env.get("canary")}
            for member, env in self._live_members().items()}

    def merged_capacity(self) -> dict[str, Any]:
        """Fleet capacity (ISSUE 16): per-member snapshots (identity
        preserved) plus the cross-shard merge — demand sums across shards
        (they partition the job-id space), worker headroom does not
        (every shard's registry observes the same workers), so the merge
        rules live in obs.capacity.merge_capacity."""
        members = self._live_members()
        per_member = {
            member: {"role": env.get("role"),
                     "capacity": env.get("capacity")}
            for member, env in members.items()}
        fleet = merge_capacity(
            env.get("capacity") or {}
            for env in members.values() if env.get("role") == "shard")
        return {"perMember": per_member, "fleet": fleet,
                "numShards": self.num_shards()}
