"""Numerics-sanitizer tests (gridcheck v3, ISSUE 14).

The sanitizer must (1) pass kernel outputs inside the registry
tolerance, (2) fail ones outside it — including a real dispatcher whose
kernel is deliberately skewed, the exit-3 acceptance fixture — (3) trip
on NaN/Inf, (4) sample deterministically under seeding, and (5) cost
nothing when disabled. Tests that deliberately trip the sanitizer reset
it afterwards so a GRIDLLM_SANITIZE=1 session's end-of-run verdict
(tests/conftest.py) stays clean.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.analysis import numcheck


@pytest.fixture
def armed():
    numcheck.reset()
    numcheck.configure(sample=1.0, seed=0, armed=True)
    yield
    numcheck.reset()
    numcheck.reload_from_env()  # exact restore of the session's policy


def test_shadow_within_tolerance_is_clean(armed):
    @jax.jit
    def f(x):
        return numcheck.shadow(
            "attention_prefill", x, lambda: x + 1e-4)

    jax.block_until_ready(f(jnp.ones((4, 4))))
    assert numcheck.violations() == []
    assert numcheck.report()["shadowed_dispatches"] == 1


def test_shadow_past_tolerance_records_violation(armed):
    @jax.jit
    def f(x):
        return numcheck.shadow("attention_decode", x, lambda: x + 0.5)

    jax.block_until_ready(f(jnp.ones((4,))))
    v = numcheck.violations()
    assert len(v) == 1 and v[0]["kind"] == "tolerance"
    assert v[0]["op"] == "attention_decode"
    assert v[0]["excess"] > 0 and v[0]["max_err"] == pytest.approx(0.5)
    with pytest.raises(numcheck.NumericsError):
        numcheck.assert_clean()


def test_shadow_honors_validity_mask(armed):
    # the mismatch sits entirely in the masked (unspecified) region
    @jax.jit
    def f(x):
        bad_ref = x.at[2].add(100.0)
        return numcheck.shadow(
            "attention_prefill", x, lambda: bad_ref,
            valid=jnp.array([True, True, False]))

    jax.block_until_ready(f(jnp.zeros((3,))))
    assert numcheck.violations() == []


def test_shadow_tuple_output_with_none_members(armed):
    # the ragged dispatcher's shape: (chunk, group), either may be None
    @jax.jit
    def f(x):
        out = (None, x)
        return numcheck.shadow(
            "attention_ragged", out, lambda: (None, x + 0.2),
            valid=(None, None))[1]

    jax.block_until_ready(f(jnp.ones((2, 2))))
    v = numcheck.violations()
    assert len(v) == 1 and v[0]["op"] == "attention_ragged"


def test_shadow_flags_nan_in_valid_region(armed):
    # NaN excess must COUNT as a violation (`x > 0` is False for NaN):
    # a kernel going non-finite where the reference is finite is the
    # exact failure mode the shadow exists to catch
    @jax.jit
    def f(x):
        return numcheck.shadow(
            "attention_prefill", x.at[0].set(jnp.nan), lambda: x)

    jax.block_until_ready(f(jnp.ones((3,))))
    v = numcheck.violations()
    assert len(v) == 1 and v[0]["kind"] == "tolerance", v


def test_nan_tripwire(armed):
    @jax.jit
    def f(x):
        numcheck.check_finite("sampler.logits", x)
        return x * 2

    jax.block_until_ready(f(jnp.ones((3,))))
    assert numcheck.violations() == []
    jax.block_until_ready(f(jnp.array([1.0, jnp.nan, jnp.inf])))
    v = numcheck.violations()
    assert len(v) == 1 and v[0]["kind"] == "nonfinite"
    assert v[0]["op"] == "sampler.logits" and v[0]["bad_elements"] == 2


def test_finite_tripwire_skips_integer_arrays(armed):
    numcheck.check_finite("kv.write", jnp.ones((2,), jnp.int32))
    assert numcheck.report()["finite_checks"] == 0


def test_sampling_determinism_under_seeding():
    try:
        numcheck.configure(sample=0.3, seed=1234, armed=True)
        first = [numcheck._decide("attention_ragged") for _ in range(64)]
        numcheck.configure(sample=0.3, seed=1234)
        again = [numcheck._decide("attention_ragged") for _ in range(64)]
        assert first == again
        # a different op draws an independent stream from the same seed,
        # and a different seed changes the sequence
        numcheck.configure(sample=0.3, seed=1234)
        other_op = [numcheck._decide("attention_decode") for _ in range(64)]
        numcheck.configure(sample=0.3, seed=4321)
        other_seed = [numcheck._decide("attention_ragged") for _ in range(64)]
        assert first != other_op
        assert first != other_seed
    finally:
        # a mid-test failure must not leak the armed/sample override into
        # later tests (conftest judges the session on numcheck state)
        numcheck.reset()
        numcheck.reload_from_env()


def test_disabled_is_a_noop(armed):
    numcheck.configure(armed=False)

    def exploding_ref():
        raise AssertionError("reference must not be traced when disabled")

    x = jnp.ones((2,))
    out = numcheck.shadow("attention_prefill", x, exploding_ref)
    assert out is x
    numcheck.check_finite("kv.write", jnp.array([jnp.nan]))
    rep = numcheck.report()
    assert rep["violations"] == []
    assert rep["shadowed_dispatches"] == 0 and rep["finite_checks"] == 0


def test_skewed_kernel_trips_through_real_dispatcher(armed, monkeypatch):
    """The acceptance fixture: a kernel deliberately skewed past the
    registry tolerance is caught by the shadow on the REAL dispatch
    path (ops.attention.attention_prefill, kernels on)."""
    from gridllm_tpu.ops import attention, kvcache, pallas_kernels

    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kvcache._env_mode.cache_clear()

    def skewed(q, k, v, seq_lens, **kw):
        return attention.attention_prefill_ref(q, k, v, seq_lens) + 1.0

    monkeypatch.setattr(pallas_kernels, "flash_prefill", skewed)
    try:
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (1, 8, 4, 16), jnp.float32)
        k = jax.random.normal(key, (1, 8, 2, 16), jnp.float32)
        v = jax.random.normal(key, (1, 8, 2, 16), jnp.float32)
        out = attention.attention_prefill(q, k, v,
                                          jnp.asarray([8], jnp.int32))
        jax.block_until_ready(out)
    finally:
        kvcache._env_mode.cache_clear()
    v_ = numcheck.violations()
    assert any(x["kind"] == "tolerance" and x["op"] == "attention_prefill"
               for x in v_), v_


def test_unskewed_kernel_is_clean_through_real_dispatcher(armed,
                                                         monkeypatch):
    from gridllm_tpu.ops import attention, kvcache

    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kvcache._env_mode.cache_clear()
    try:
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (1, 8, 4, 16), jnp.float32)
        k = jax.random.normal(key, (1, 8, 2, 16), jnp.float32)
        v = jax.random.normal(key, (1, 8, 2, 16), jnp.float32)
        out = attention.attention_prefill(q, k, v,
                                          jnp.asarray([6], jnp.int32))
        jax.block_until_ready(out)
    finally:
        kvcache._env_mode.cache_clear()
    assert numcheck.violations() == []
    assert numcheck.report()["shadowed_dispatches"] >= 1


def test_engine_serving_path_is_shadow_covered(armed, monkeypatch):
    """Coverage gate for the numcheck-smoke CI job: a REAL engine serving
    greedy tokens with interpret-mode kernels must shadow-execute a
    nonzero number of kernel dispatches (sampling 1.0) and come out
    clean — without this assertion the gate could go green with zero
    shadow coverage (kernels silently off, suites bypassing the
    dispatchers)."""
    from gridllm_tpu.engine import (EngineConfig, GenerationRequest,
                                    InferenceEngine)
    from gridllm_tpu.ops import kvcache

    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kvcache._env_mode.cache_clear()
    try:
        eng = InferenceEngine(EngineConfig(
            model="tiny-llama", max_slots=2, num_pages=64, page_size=8,
            max_pages_per_slot=8, prefill_buckets=(16, 32)))
        res = eng.generate(GenerationRequest(
            id="numcheck-cover", prompt="hello world",
            options={"temperature": 0.0, "num_predict": 8}))
        assert len(res.token_ids) == 8
    finally:
        kvcache._env_mode.cache_clear()
    rep = numcheck.report()
    assert rep["shadowed_dispatches"] > 0, rep
    assert rep["finite_checks"] > 0, rep
    assert rep["ok"], rep["violations"]


def test_tolerance_lookup_matches_registry():
    from gridllm_tpu.ops.kernels import KERNELS, tolerance

    for spec in KERNELS:
        rtol, atol = tolerance(spec.dispatch)
        assert rtol >= spec.rtol and atol >= spec.atol
    with pytest.raises(KeyError):
        tolerance("no_such_op")


def test_violation_reaches_flight_recorder(armed):
    from gridllm_tpu.obs.flightrec import default_flight_recorder

    numcheck.check_finite("kv.write", jnp.array([np.nan], jnp.float32))
    rings = default_flight_recorder().snapshot()["rings"]
    events = [e for e in rings.get("numcheck", [])
              if e.get("event") == "nonfinite"]
    assert events, "numcheck violation should land in the flight recorder"
