"""Unit tests for the compute primitives (CPU, fp32 where it matters).

SURVEY.md §4: the reference has zero unit tests; the rebuild adds numerics
tests the reference never could (its compute lived in Ollama).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.ops import (
    PagedKVCache,
    RopeScaling,
    SamplingParams,
    apply_rope,
    attention_prefill,
    paged_attention_decode,
    precompute_rope,
    rms_norm,
    sample_tokens,
)
from gridllm_tpu.ops.kvcache import PageAllocator, write_decode, write_prefill


def ref_attention(q, k, v, causal=True):
    """Dense fp32 oracle, GQA via explicit repeat."""
    t, h, d = q.shape
    kvh = k.shape[1]
    k = np.repeat(k, h // kvh, axis=1)
    v = np.repeat(v, h // kvh, axis=1)
    logits = np.einsum("thd,shd->hts", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((t, t), bool))
        logits = np.where(mask[None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("hts,shd->thd", p, v)


class TestLayers:
    def test_rms_norm_matches_formula(self):
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        w = np.random.RandomState(1).rand(16).astype(np.float32)
        got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-6)
        want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_rope_rotation_preserves_norm(self):
        inv = precompute_rope(64)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 4, 64).astype(np.float32))
        pos = jnp.broadcast_to(jnp.arange(5), (2, 5))
        y = apply_rope(x, pos, inv)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            rtol=1e-4,
        )

    def test_rope_position_zero_is_identity(self):
        inv = precompute_rope(32)
        x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 2, 32).astype(np.float32))
        y = apply_rope(x, jnp.zeros((1, 1), jnp.int32), inv)
        np.testing.assert_allclose(y, x, atol=1e-6)

    def test_rope_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n
        inv = precompute_rope(64)
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(1, 1, 1, 64).astype(np.float32))
        k = jnp.asarray(rs.randn(1, 1, 1, 64).astype(np.float32))

        def dot(m, n):
            qm = apply_rope(q, jnp.full((1, 1), m, jnp.int32), inv)
            kn = apply_rope(k, jnp.full((1, 1), n, jnp.int32), inv)
            return float(jnp.sum(qm * kn))

        assert dot(5, 3) == pytest.approx(dot(12, 10), rel=1e-4)

    def test_llama3_scaling_changes_low_freqs_only(self):
        base = precompute_rope(128, theta=500000.0)
        scaled = precompute_rope(128, theta=500000.0, scaling=RopeScaling())
        base, scaled = np.asarray(base), np.asarray(scaled)
        assert np.allclose(base[:8], scaled[:8])  # high-freq band untouched
        assert np.allclose(base[-4:] / scaled[-4:], 8.0, rtol=1e-3)  # low-freq /factor


class TestAttention:
    def test_prefill_matches_dense_oracle(self):
        rs = np.random.RandomState(0)
        t, h, kvh, d = 7, 8, 2, 16
        q = rs.randn(1, t, h, d).astype(np.float32)
        k = rs.randn(1, t, kvh, d).astype(np.float32)
        v = rs.randn(1, t, kvh, d).astype(np.float32)
        got = attention_prefill(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.array([t])
        )
        want = ref_attention(q[0], k[0], v[0])
        np.testing.assert_allclose(np.asarray(got)[0], want, rtol=1e-4, atol=1e-5)

    def test_prefill_padding_ignored(self):
        rs = np.random.RandomState(1)
        t, real = 8, 5
        q = rs.randn(1, t, 4, 8).astype(np.float32)
        k = rs.randn(1, t, 4, 8).astype(np.float32)
        v = rs.randn(1, t, 4, 8).astype(np.float32)
        full = attention_prefill(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.array([real]))
        # zero-out padding kv → same result for the first `real` queries
        k2, v2 = k.copy(), v.copy()
        k2[:, real:] = 99.0
        v2[:, real:] = 99.0
        poisoned = attention_prefill(jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), jnp.array([real]))
        np.testing.assert_allclose(
            np.asarray(full)[:, :real], np.asarray(poisoned)[:, :real], rtol=1e-5
        )

    def test_paged_decode_matches_prefill_last_token(self):
        """Prefill T-1 tokens into the cache, decode token T → must equal
        row T-1 of full-prefill attention."""
        rs = np.random.RandomState(2)
        t, h, kvh, d, ps = 10, 4, 2, 16, 4
        k_all = rs.randn(t, kvh, d).astype(np.float32)
        v_all = rs.randn(t, kvh, d).astype(np.float32)
        q_all = rs.randn(t, h, d).astype(np.float32)

        cache = PagedKVCache.create(1, 8, ps, kvh, d, max_slots=2, max_pages_per_slot=4, dtype=jnp.float32)
        alloc = PageAllocator(8, ps, 4)
        alloc.alloc(0, t)
        row = jnp.asarray(alloc.table_row(0), jnp.int32)

        kp, vp = write_prefill(
            cache.k[0], cache.v[0],
            jnp.asarray(k_all), jnp.asarray(v_all),
            row, jnp.int32(0), jnp.int32(t), ps,
        )
        table = cache.page_table.at[0].set(row)
        q_last = jnp.asarray(q_all[t - 1 : t])  # [1, H, D] → use as slot 0
        q_batch = jnp.concatenate([q_last, jnp.zeros_like(q_last)], axis=0)
        out = paged_attention_decode(
            q_batch, kp, vp, table, jnp.array([t, 0], jnp.int32), ps
        )
        want = ref_attention(q_all, k_all, v_all)[t - 1]
        np.testing.assert_allclose(np.asarray(out)[0], want, rtol=1e-4, atol=1e-5)

    def test_write_decode_then_attend(self):
        rs = np.random.RandomState(3)
        kvh, d, ps = 2, 8, 4
        cache = PagedKVCache.create(1, 4, ps, kvh, d, max_slots=1, max_pages_per_slot=2, dtype=jnp.float32)
        alloc = PageAllocator(4, ps, 2)
        ks, vs = [], []
        kp, vp = cache.k[0], cache.v[0]
        table = cache.page_table
        for i in range(6):
            alloc.alloc(0, i + 1)
            table = table.at[0].set(jnp.asarray(alloc.table_row(0), jnp.int32))
            kn = rs.randn(1, kvh, d).astype(np.float32)
            vn = rs.randn(1, kvh, d).astype(np.float32)
            ks.append(kn[0]); vs.append(vn[0])
            kp, vp = write_decode(
                kp, vp, jnp.asarray(kn), jnp.asarray(vn), table,
                jnp.array([i], jnp.int32), jnp.array([True]), ps,
            )
        q = rs.randn(1, 4, d).astype(np.float32)
        out = paged_attention_decode(jnp.asarray(q), kp, vp, table, jnp.array([6], jnp.int32), ps)
        want = ref_attention(
            q, np.stack(ks), np.stack(vs), causal=False
        )  # single query attends all 6
        np.testing.assert_allclose(np.asarray(out)[0], want[0], rtol=1e-4, atol=1e-5)


class TestPageAllocator:
    def test_alloc_grow_free_cycle(self):
        a = PageAllocator(num_pages=4, page_size=8, max_pages_per_slot=3)
        assert a.alloc(0, 8) is not None and a.free_pages == 3
        assert a.alloc(0, 9) is not None and a.free_pages == 2  # grew by one page
        assert a.alloc(1, 17) is None  # needs 3, only 2 free
        a.free(0)
        assert a.free_pages == 4
        assert a.alloc(1, 17) is not None

    def test_per_slot_cap(self):
        a = PageAllocator(num_pages=10, page_size=4, max_pages_per_slot=2)
        assert a.alloc(0, 9) is None  # 3 pages > per-slot cap
        assert a.alloc(0, 8) is not None

    def test_table_row_padded(self):
        a = PageAllocator(num_pages=4, page_size=8, max_pages_per_slot=3)
        a.alloc(0, 10)
        row = a.table_row(0)
        assert len(row) == 3 and row.count(-1) == 1


class TestSampling:
    def _params(self, **kw):
        p = SamplingParams.defaults(2)
        for k, v in kw.items():
            setattr(p, k, jnp.asarray(v))
        return p

    def test_greedy_when_temperature_zero(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(2, 100).astype(np.float32))
        p = self._params(temperature=[0.0, 0.0], repeat_penalty=[1.0, 1.0])
        tok = sample_tokens(logits, p)
        np.testing.assert_array_equal(np.asarray(tok), np.argmax(np.asarray(logits), -1))

    def test_seed_determinism_and_step_variation(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(2, 50).astype(np.float32))
        p1 = self._params(temperature=[1.5, 1.5], seed=[7, 7], step=[0, 0])
        a = sample_tokens(logits, p1)
        b = sample_tokens(logits, p1)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same (seed, step)
        # same params, advancing step → the rng chain must eventually differ
        many_same = all(
            np.array_equal(
                np.asarray(sample_tokens(logits, self._params(temperature=[1.5, 1.5], seed=[7, 7], step=[s, s]))),
                np.asarray(a),
            )
            for s in range(1, 8)
        )
        assert not many_same  # steps advance the rng chain

    def test_top_k_one_is_greedy(self):
        logits = jnp.asarray(np.random.RandomState(2).randn(2, 64).astype(np.float32))
        p = self._params(temperature=[2.0, 2.0], top_k=[1, 1], repeat_penalty=[1.0, 1.0])
        tok = sample_tokens(logits, p)
        np.testing.assert_array_equal(np.asarray(tok), np.argmax(np.asarray(logits), -1))

    def test_top_p_tiny_is_greedy(self):
        logits = jnp.asarray(np.random.RandomState(3).randn(2, 64).astype(np.float32))
        p = self._params(temperature=[2.0, 2.0], top_p=[1e-6, 1e-6], repeat_penalty=[1.0, 1.0])
        tok = sample_tokens(logits, p)
        np.testing.assert_array_equal(np.asarray(tok), np.argmax(np.asarray(logits), -1))

    def test_repeat_penalty_suppresses_seen_token(self):
        # token 0 hugely preferred but heavily penalized and already seen
        logits = np.full((1, 10), -5.0, np.float32)
        logits[0, 0] = 2.0
        logits[0, 1] = 1.9
        counts = np.zeros((1, 10), np.int32)
        counts[0, 0] = 3
        p = SamplingParams.defaults(1)
        p.temperature = jnp.asarray([0.0])
        p.repeat_penalty = jnp.asarray([50.0])
        tok = sample_tokens(jnp.asarray(logits), p, jnp.asarray(counts))
        assert int(tok[0]) == 1

    def test_sampling_respects_distribution(self):
        # two-token distribution ~[0.88, 0.12] at temp 1 — frequencies should track
        logits = jnp.asarray([[2.0, 0.0] + [-30.0] * 62], jnp.float32)
        sampler = jax.jit(sample_tokens)
        n = 200
        hits = 0
        for s in range(n):
            p = SamplingParams.defaults(1)
            p.temperature = jnp.asarray([1.0])
            p.top_k = jnp.asarray([0])
            p.top_p = jnp.asarray([1.0])
            p.repeat_penalty = jnp.asarray([1.0])
            p.step = jnp.asarray([s])
            hits += int(sampler(logits, p)[0] == 0)
        assert 0.75 * n < hits < 0.99 * n


# ---------------------------------------------------------------------------
# Lane-padded pool (d=64 kernel decode path — VERDICT r04 #5)
# ---------------------------------------------------------------------------

def test_decode_dispatch_on_lane_padded_pool_matches_unpadded_ref():
    """The engine allocates D=128 pages for d=64 models; the dispatch pads
    q/k_cur/v_cur and slices out — results must equal attention over the
    unpadded pool."""
    import numpy as np

    from gridllm_tpu.ops.attention import (
        paged_attention_decode,
        paged_attention_decode_ref,
    )

    S, H, KVH, d, dpool = 3, 8, 4, 64, 128
    P_, ps, MPS = 16, 8, 4
    key = jax.random.PRNGKey(0)
    kp = jax.random.normal(key, (P_, ps, KVH, d), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(1), (P_, ps, KVH, d), jnp.float32)
    pad = [(0, 0)] * 3 + [(0, dpool - d)]
    kp_pad, vp_pad = jnp.pad(kp, pad), jnp.pad(vp, pad)
    pt = jnp.tile(jnp.arange(MPS, dtype=jnp.int32)[None], (S, 1))
    lens = jnp.array([9, 0, 25], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (S, H, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(3), (S, KVH, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(4), (S, KVH, d), jnp.float32)

    # padded-pool dispatch, jnp path
    got = paged_attention_decode(
        q, kp_pad, vp_pad, pt, lens, ps, k_cur=kc, v_cur=vc,
        use_pallas=False,
    )
    want = paged_attention_decode_ref(q, kp, vp, pt, lens, ps, k_cur=kc, v_cur=vc)
    np.testing.assert_allclose(got, want, atol=2e-5)

    # padded-pool dispatch, interpret-kernel path
    import os

    os.environ["GRIDLLM_PALLAS"] = "interpret"
    from gridllm_tpu.ops import kvcache

    kvcache._env_mode.cache_clear()
    try:
        got_k = paged_attention_decode(
            q, kp_pad, vp_pad, pt, lens, ps, k_cur=kc, v_cur=vc,
        )
    finally:
        os.environ.pop("GRIDLLM_PALLAS", None)
        kvcache._env_mode.cache_clear()
    np.testing.assert_allclose(got_k, want, atol=2e-5)


def test_prefix_chunk_on_lane_padded_pool_matches_unpadded():
    import numpy as np

    from gridllm_tpu.ops.attention import attention_prefix_chunk

    T, H, KVH, d, dpool = 8, 8, 4, 64, 128
    P_, ps, MPS = 16, 8, 4
    kp = jax.random.normal(jax.random.PRNGKey(0), (P_, ps, KVH, d), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(1), (P_, ps, KVH, d), jnp.float32)
    pad = [(0, 0)] * 3 + [(0, dpool - d)]
    row = jnp.arange(MPS, dtype=jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (1, T, H, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(3), (T, KVH, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(4), (T, KVH, d), jnp.float32)
    start, total = jnp.int32(8), jnp.int32(8 + 6)

    got = attention_prefix_chunk(
        q, jnp.pad(kp, pad), jnp.pad(vp, pad), row, start, total, ps,
        k_cur=kc, v_cur=vc,
    )
    want = attention_prefix_chunk(
        q, kp, vp, row, start, total, ps, k_cur=kc, v_cur=vc,
    )
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_writes_pad_new_rows_to_pool_lanes():
    import numpy as np

    from gridllm_tpu.ops.kvcache import write_decode_all

    L, P_, ps, KVH, d, dpool = 2, 8, 8, 4, 64, 128
    S = 3
    kp = jnp.zeros((L, P_, ps, KVH, dpool), jnp.float32)
    vp = jnp.zeros((L, P_, ps, KVH, dpool), jnp.float32)
    pt = jnp.tile(jnp.arange(4, dtype=jnp.int32)[None], (S, 1))
    positions = jnp.array([0, 9, 17], jnp.int32)
    active = jnp.array([True, True, True])
    kn = jax.random.normal(jax.random.PRNGKey(5), (L, S, KVH, d), jnp.float32)
    vn = jax.random.normal(jax.random.PRNGKey(6), (L, S, KVH, d), jnp.float32)

    out_k, _ = write_decode_all(kp, vp, kn, vn, pt, positions, active, ps,
                                use_pallas=False)
    # row 0 of slot 0 landed in page 0 offset 0, first d lanes = kn, rest 0
    np.testing.assert_allclose(out_k[:, 0, 0, :, :d], kn[:, 0])
    assert float(jnp.abs(out_k[..., d:]).max()) == 0.0


def test_engine_pool_lane_padding_policy(monkeypatch):
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.ops import kvcache

    # CPU auto: kernels off -> no padding
    monkeypatch.delenv("GRIDLLM_PALLAS", raising=False)
    kvcache._env_mode.cache_clear()
    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=2, page_size=8, num_pages=16,
        max_pages_per_slot=4, prefill_buckets=(16,),
    ))
    assert eng.cache.k.shape[-1] == eng.cfg.head_dim_

    # forced padded layout (what real TPU gets): pool at 128 lanes, and
    # generation still works through the pad/slice dispatch
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    monkeypatch.setenv("GRIDLLM_POOL_PAD", "1")
    kvcache._env_mode.cache_clear()
    try:
        eng2 = InferenceEngine(EngineConfig(
            model="tiny-llama", max_slots=2, page_size=8, num_pages=16,
            max_pages_per_slot=4, prefill_buckets=(16,),
        ))
        assert eng2.cache.k.shape[-1] == 128
        from gridllm_tpu.engine import GenerationRequest

        res = eng2.generate(GenerationRequest(
            id="pad", prompt="ab", options={"temperature": 0.0, "num_predict": 4},
        ))
        assert len(res.token_ids) == 4
    finally:
        kvcache._env_mode.cache_clear()
