"""Pallas kernels under meshes (VERDICT r04 #2).

pallas_call has no GSPMD partitioning rule, so the dispatch layers wrap
the kernels in a FULL-manual shard_map at the kernel boundary with
kv-heads split over "tp" (ops.kvcache.kernel_mesh_axis). These tests run
that meshed path on the virtual 8-device CPU mesh with interpret-mode
kernels and assert exact parity with the jnp references — the same
wrapper code runs compiled kernels on real TPU.

Reference behavior being reproduced: the serving engine of the reference
runs whatever Ollama does on one GPU (client/src/services/OllamaService.ts);
sharded serving with kernel-grade attention is where this framework has no
reference analogue and must self-verify (SURVEY.md §4, §7 step 5-6).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.ops.attention import (
    attention_prefill,
    attention_prefill_ref,
    paged_attention_decode,
    paged_attention_decode_ref,
)
from gridllm_tpu.ops.kvcache import (
    kernel_mesh_axis,
    write_decode_all,
    write_prefill_all,
)
from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh"
)


@pytest.fixture(autouse=True)
def _interpret_kernels(monkeypatch):
    from gridllm_tpu.ops import kvcache

    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kvcache._env_mode.cache_clear()
    yield
    kvcache._env_mode.cache_clear()


def _mesh(tp=4, dp=2, sp=1, ep=1):
    return build_mesh(MeshConfig(tp=tp, dp=dp, sp=sp, ep=ep))


L, NP, PS, MPS = 3, 24, 16, 6
S, H, KVH, D = 4, 16, 8, 64


def _decode_operands(kvh=KVH, h=H, d=D):
    key = jax.random.PRNGKey(0)
    kp = jax.random.normal(key, (L, NP, PS, kvh, d), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(1), (L, NP, PS, kvh, d),
                           jnp.float32)
    pt = jnp.tile(jnp.arange(MPS, dtype=jnp.int32)[None], (S, 1))
    lens = jnp.array([37, 0, 90, 5], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (S, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(3), (S, kvh, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(4), (S, kvh, d), jnp.float32)
    return kp, vp, pt, lens, q, kc, vc


def test_kernel_mesh_axis_modes():
    mesh = _mesh(tp=4, dp=2)
    assert kernel_mesh_axis(None, 8, 16) == ("direct", None)
    assert kernel_mesh_axis(mesh, 8, 16) == ("wrap", "tp")
    assert kernel_mesh_axis(mesh, 2, 16) == ("wrap", None)  # kvh % tp != 0
    pp = build_mesh(MeshConfig(pp=2, tp=4, dp=1))
    assert kernel_mesh_axis(pp, 8, 16) == ("ref", None)


def test_meshed_decode_matches_ref():
    mesh = _mesh()
    kp, vp, pt, lens, q, kc, vc = _decode_operands()

    def f(q, kp, vp, pt, lens, kc, vc):
        return paged_attention_decode(
            q, kp, vp, pt, lens, PS, k_cur=kc, v_cur=vc,
            layer=jnp.int32(1), use_pallas=True, mesh=mesh,
        )

    out = jax.jit(f)(q, kp, vp, pt, lens, kc, vc)
    ref = paged_attention_decode_ref(
        q, kp[1], vp[1], pt, lens, PS, k_cur=kc, v_cur=vc
    )
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_meshed_decode_indivisible_heads_replicates():
    """KVH=2 on tp=4: wrapper engages with heads replicated (matches
    sharding._fit's fallback) and stays correct."""
    mesh = _mesh()
    kp, vp, pt, lens, q, kc, vc = _decode_operands(kvh=2, h=4)

    def f(q, kp, vp, pt, lens, kc, vc):
        return paged_attention_decode(
            q, kp, vp, pt, lens, PS, k_cur=kc, v_cur=vc,
            layer=jnp.int32(2), use_pallas=True, mesh=mesh,
        )

    out = jax.jit(f)(q, kp, vp, pt, lens, kc, vc)
    ref = paged_attention_decode_ref(
        q, kp[2], vp[2], pt, lens, PS, k_cur=kc, v_cur=vc
    )
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_meshed_decode_traced_window_softcap():
    """gemma2-style: traced per-layer window + static softcap through the
    meshed wrapper."""
    mesh = _mesh()
    kp, vp, pt, lens, q, kc, vc = _decode_operands()

    def f(q, kp, vp, pt, lens, kc, vc, win):
        return paged_attention_decode(
            q, kp, vp, pt, lens, PS, k_cur=kc, v_cur=vc,
            layer=jnp.int32(0), use_pallas=True, mesh=mesh,
            logit_softcap=50.0, window=win,
        )

    win = jnp.int32(32)
    out = jax.jit(f)(q, kp, vp, pt, lens, kc, vc, win)
    ref = paged_attention_decode_ref(
        q, kp[0], vp[0], pt, lens, PS, k_cur=kc, v_cur=vc,
        logit_softcap=50.0, window=win,
    )
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_meshed_prefill_matches_ref():
    mesh = _mesh()
    B, T = 1, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, KVH, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, T, KVH, D), jnp.float32)
    sl = jnp.array([200], jnp.int32)

    out = jax.jit(
        lambda q, k, v, sl: attention_prefill(
            q, k, v, sl, use_pallas=True, mesh=mesh
        )
    )(q, k, v, sl)
    ref = attention_prefill_ref(q, k, v, sl)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_meshed_write_decode_matches_scatter():
    mesh = _mesh()
    kvh, d = KVH, D
    kp = jnp.zeros((L, NP, PS, kvh, d), jnp.float32)
    vp = jnp.zeros((L, NP, PS, kvh, d), jnp.float32)
    pt = jnp.tile(jnp.arange(MPS, dtype=jnp.int32)[None], (S, 1))
    positions = jnp.array([3, 17, 0, 95], jnp.int32)
    active = jnp.array([True, True, False, True])
    kn = jax.random.normal(jax.random.PRNGKey(5), (L, S, kvh, d), jnp.float32)
    vn = jax.random.normal(jax.random.PRNGKey(6), (L, S, kvh, d), jnp.float32)

    out_k, out_v = jax.jit(
        lambda kp, vp, kn, vn, pt, pos, act: write_decode_all(
            kp, vp, kn, vn, pt, pos, act, PS, use_pallas=True, mesh=mesh
        )
    )(kp, vp, kn, vn, pt, positions, active)
    ref_k, ref_v = write_decode_all(
        kp, vp, kn, vn, pt, positions, active, PS, use_pallas=False
    )
    np.testing.assert_array_equal(out_k, ref_k)
    np.testing.assert_array_equal(out_v, ref_v)


def test_meshed_write_prefill_matches_scatter():
    mesh = _mesh()
    kvh, d = KVH, D
    T = 2 * PS  # kernel path needs T % page_size == 0
    kp = jnp.zeros((L, NP, PS, kvh, d), jnp.float32)
    vp = jnp.zeros((L, NP, PS, kvh, d), jnp.float32)
    row = jnp.arange(MPS, dtype=jnp.int32)
    kn = jax.random.normal(jax.random.PRNGKey(7), (L, T, kvh, d), jnp.float32)
    vn = jax.random.normal(jax.random.PRNGKey(8), (L, T, kvh, d), jnp.float32)
    start, length = jnp.int32(PS), jnp.int32(PS + 5)

    out_k, out_v = jax.jit(
        lambda kp, vp, kn, vn, row, start, length: write_prefill_all(
            kp, vp, kn, vn, row, start, length, PS, use_pallas=True,
            mesh=mesh,
        )
    )(kp, vp, kn, vn, row, start, length)
    ref_k, ref_v = write_prefill_all(
        kp, vp, kn, vn, row, start, length, PS, use_pallas=False
    )
    # the chunk kernel writes whole pages while the scatter drops padded
    # rows (tests/test_pallas.py) — only positions < start+length are part
    # of the contract (attention masks by length, padding is never read)
    for t in range(int(length)):
        pos = int(start) + t
        p, o = int(row[pos // PS]), pos % PS
        np.testing.assert_array_equal(out_k[:, p, o], ref_k[:, p, o])
        np.testing.assert_array_equal(out_v[:, p, o], ref_v[:, p, o])


def test_meshed_engine_keeps_kernels_on():
    """A tp mesh no longer flips cfg.use_pallas off (engine/engine.py);
    only pp > 1 does (the pipeline region pins jnp paths itself)."""
    from gridllm_tpu.engine import EngineConfig, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", mesh=MeshConfig(tp=8), max_slots=2,
        num_pages=16, page_size=8, max_pages_per_slot=8,
        prefill_buckets=(16,),
    ))
    assert eng.cfg.use_pallas is not False  # auto/env policy preserved

    eng_pp = InferenceEngine(EngineConfig(
        model="tiny-llama", mesh=MeshConfig(pp=2, tp=4), max_slots=2,
        num_pages=16, page_size=8, max_pages_per_slot=8,
        prefill_buckets=(16,),
    ))
    assert eng_pp.cfg.use_pallas is False


def test_meshed_engine_generates_with_kernels():
    """End-to-end: a tp:8-meshed engine serving with interpret-mode
    kernels produces the same tokens as an unmeshed jnp engine (greedy,
    same random weights)."""
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    from gridllm_tpu.ops import kvcache

    opts = {"temperature": 0.0, "num_predict": 8}
    results = {}
    try:
        for tag, mesh, env in (
            ("meshed-kernels", MeshConfig(tp=8), "interpret"),
            ("unmeshed-jnp", None, "0"),
        ):
            os.environ["GRIDLLM_PALLAS"] = env
            kvcache._env_mode.cache_clear()
            eng = InferenceEngine(EngineConfig(
                model="tiny-llama", mesh=mesh, max_slots=2, num_pages=64,
                page_size=8, max_pages_per_slot=8, prefill_buckets=(16, 32),
            ))
            res = eng.generate(GenerationRequest(
                id=tag, prompt="hello", options=opts,
            ))
            results[tag] = res.token_ids
    finally:
        os.environ["GRIDLLM_PALLAS"] = "interpret"
        kvcache._env_mode.cache_clear()
    assert results["meshed-kernels"] == results["unmeshed-jnp"]
    assert len(results["meshed-kernels"]) == 8


def test_meshed_prefix_chunk_matches_ref():
    """The chunk-prefill kernel through the full-manual tp shard_map."""
    mesh = _mesh()
    t, ps, maxp = 16, 8, 6
    kp = jax.random.normal(jax.random.PRNGKey(0), (L, NP, PS, KVH, D),
                           jnp.float32)
    vp = kp * 0.9
    row = jnp.arange(maxp, dtype=jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(1), (1, t, H, D), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(2), (t, KVH, D), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(3), (t, KVH, D), jnp.float32)
    start, total = jnp.int32(PS), jnp.int32(PS + 12)

    from gridllm_tpu.ops.attention import attention_prefix_chunk

    got = jax.jit(
        lambda q, kp, vp, row, start, total, kc, vc: attention_prefix_chunk(
            q, kp, vp, row, start, total, PS, k_cur=kc, v_cur=vc,
            layer=jnp.int32(1), use_pallas=True, mesh=mesh,
        )
    )(q, kp, vp, row, start, total, kc, vc)
    want = attention_prefix_chunk(
        q, kp, vp, row, start, total, PS, k_cur=kc, v_cur=vc,
        layer=jnp.int32(1), use_pallas=False,
    )
    np.testing.assert_allclose(got[:, :12], want[:, :12], atol=2e-5)
