"""Pallas kernels vs the jnp oracles, in interpret mode on CPU
(SURVEY.md §4: engine numerics get golden coverage; the kernels must be
bit-for-bit-close to the reference implementations they replace)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.ops import attention
from gridllm_tpu.ops.attention import (
    attention_prefill_ref,
    paged_attention_decode_ref,
)
from gridllm_tpu.ops.kvcache import PageAllocator, PagedKVCache, write_prefill
from gridllm_tpu.ops.pallas_kernels import flash_prefill, paged_decode


@pytest.mark.parametrize("t,h,kvh,d,lens", [
    (64, 4, 2, 16, [64]),          # full block, GQA
    (128, 4, 4, 32, [100]),        # ragged length, MHA
    (256, 8, 2, 64, [256, 17]),    # batch of 2, very ragged
    (64, 2, 1, 128, [1]),          # single valid token
])
def test_flash_prefill_matches_ref(t, h, kvh, d, lens):
    b = len(lens)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)

    want = attention_prefill_ref(q, k, v, seq_lens)
    got = flash_prefill(q, k, v, seq_lens, interpret=True)
    # padding rows (pos >= len) are unspecified; compare valid region only
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(
            np.asarray(got[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5,
        )


def test_flash_prefill_bf16():
    t, h, kvh, d = 128, 4, 2, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (1, t, h, d), jnp.float32).astype(jnp.bfloat16)
    k = jax.random.normal(kk, (1, t, kvh, d), jnp.float32).astype(jnp.bfloat16)
    v = jax.random.normal(kv, (1, t, kvh, d), jnp.float32).astype(jnp.bfloat16)
    seq_lens = jnp.asarray([90], jnp.int32)
    want = attention_prefill_ref(q, k, v, seq_lens)
    got = flash_prefill(q, k, v, seq_lens, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got[0, :90], np.float32), np.asarray(want[0, :90], np.float32),
        rtol=3e-2, atol=3e-2,
    )


def _fill_pool(key, lens, page_size=8, kvh=2, d=16, maxp=8, num_pages=32):
    """Build a pool with len(lens) slots holding random K/V of given lengths."""
    s = len(lens)
    cache = PagedKVCache.create(1, num_pages, page_size, kvh, d, s, maxp,
                                dtype=jnp.float32)
    alloc = PageAllocator(num_pages, page_size, maxp)
    k_pool, v_pool = cache.k[0], cache.v[0]
    table = np.full((s, maxp), -1, np.int32)
    for i, ln in enumerate(lens):
        if ln == 0:
            continue
        alloc.alloc(i, ln)
        row = np.asarray(alloc.table_row(i), np.int32)
        table[i] = row
        key, ka, kb = jax.random.split(key, 3)
        # bucket-pad to a multiple of page_size for write_prefill
        t_pad = -(-ln // page_size) * page_size
        k_new = jax.random.normal(ka, (t_pad, kvh, d), jnp.float32)
        v_new = jax.random.normal(kb, (t_pad, kvh, d), jnp.float32)
        k_pool, v_pool = write_prefill(
            k_pool, v_pool, k_new, v_new, jnp.asarray(row), jnp.int32(0),
            jnp.int32(ln), page_size,
        )
    return k_pool, v_pool, jnp.asarray(table), page_size


@pytest.mark.parametrize("lens,h", [
    ([5], 4),              # single slot, partial page
    ([8, 17, 1, 30], 4),   # ragged multi-slot
    ([0, 12], 2),          # inactive slot present
])
def test_paged_decode_matches_ref(lens, h):
    kvh, d = 2, 16
    k_pool, v_pool, table, ps = _fill_pool(jax.random.PRNGKey(2), lens)
    s = len(lens)
    q = jax.random.normal(jax.random.PRNGKey(3), (s, h, d), jnp.float32)
    lengths = jnp.asarray(lens, jnp.int32)

    want = paged_attention_decode_ref(q, k_pool, v_pool, table, lengths, ps)
    got = paged_decode(q, k_pool, v_pool, table, lengths, ps, interpret=True)
    for i, ln in enumerate(lens):
        if ln == 0:
            continue  # inactive slots are unspecified in both impls
        np.testing.assert_allclose(
            np.asarray(got[i]), np.asarray(want[i]), rtol=2e-5, atol=2e-5,
        )


def test_dispatch_env(monkeypatch):
    """GRIDLLM_PALLAS resolves the documented modes; the per-call
    use_pallas override beats the env policy."""
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    assert attention._pallas_mode(None) == (True, True)
    assert attention._pallas_mode(False) == (False, True)
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "0")
    assert attention._pallas_mode(None) == (False, False)
    assert attention._pallas_mode(True) == (True, False)
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "auto")
    use, interp = attention._pallas_mode(None)
    assert use == (jax.default_backend() == "tpu") and interp is False
    attention._env_mode.cache_clear()


def test_model_end_to_end_with_kernels(monkeypatch):
    """tiny-llama greedy decode via the public dispatch (interpret kernels)
    reproduces the pure-jnp path token-for-token."""
    from gridllm_tpu.models import llama
    from gridllm_tpu.models.configs import get_config

    cfg = get_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    prompt = [5, 17, 99, 3, 42]

    def greedy(n=4):
        cache = PagedKVCache.create(
            cfg.num_layers, 16, 8, cfg.num_kv_heads, cfg.head_dim_, 2, 8,
            dtype=jnp.float32,
        )
        alloc = PageAllocator(16, 8, 8)
        alloc.alloc(0, 16)
        row = jnp.asarray(alloc.table_row(0), jnp.int32)
        padded = jnp.asarray(prompt + [0] * 3, jnp.int32)
        logits, cache = llama.prefill(
            params, cfg, padded, jnp.int32(len(prompt)), cache, jnp.int32(0), row
        )
        out = [int(jnp.argmax(logits))]
        tok = jnp.zeros((2,), jnp.int32).at[0].set(out[0])
        active = jnp.zeros((2,), bool).at[0].set(True)
        for _ in range(n - 1):
            logits, cache = llama.decode_step(params, cfg, tok, cache, active)
            nxt = int(jnp.argmax(logits[0]))
            out.append(nxt)
            tok = tok.at[0].set(nxt)
        return out

    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "0")
    want = greedy()
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    got = greedy()
    attention._env_mode.cache_clear()
    assert got == want


# ---------------------------------------------------------------------------
# paged KV write kernels vs the scatter oracle (interpret mode)
# ---------------------------------------------------------------------------

def test_paged_write_decode_matches_scatter():
    from gridllm_tpu.ops.pallas_kernels import paged_write_decode
    from gridllm_tpu.ops.kvcache import _safe_page_idx, write_decode_all

    L, s, maxp, ps, kvh, d, num_pages = 3, 4, 4, 8, 2, 16, 16
    key = jax.random.PRNGKey(7)
    kp = jax.random.normal(key, (L, num_pages, ps, kvh, d), jnp.float32)
    vp = kp * 2.0
    kn = jax.random.normal(jax.random.PRNGKey(8), (L, s, kvh, d), jnp.float32)
    vn = kn + 1.0
    table = jnp.asarray([
        [3, 1, -1, -1],   # slot 0: 2 pages mapped
        [5, -1, -1, -1],  # slot 1: 1 page
        [7, 8, 9, 10],    # slot 2: full
        [-1, -1, -1, -1], # slot 3: unmapped
    ], jnp.int32)
    pos = jnp.asarray([9, 3, 31, 0], jnp.int32)
    act = jnp.asarray([True, True, True, False])

    want_k, want_v = write_decode_all(
        kp, vp, kn, vn, table, pos, act, ps, use_pallas=False
    )

    srange = jnp.arange(s, dtype=jnp.int32)
    page_idx = _safe_page_idx(
        lambda p: table[srange, p], pos, act, ps, maxp, num_pages
    )
    got_k, got_v = paged_write_decode(
        kp, vp, kn, vn, page_idx, pos % ps, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))
    np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))


@pytest.mark.parametrize("start,length", [
    (0, 32),    # fresh prefill, full pages
    (0, 19),    # fresh prefill, ragged tail (padding rows land in owned page)
    (16, 32),   # chunk continuation, page-aligned start
    (16, 5),    # continuation, ragged
])
def test_paged_write_chunk_matches_scatter_valid_region(start, length):
    """The kernel writes whole pages (incl. padding tails the scatter path
    drops), so compare only positions < start+length — the contract is that
    padded positions are never read (attention masks by length)."""
    from gridllm_tpu.ops.pallas_kernels import paged_write_chunk
    from gridllm_tpu.ops.kvcache import write_prefill_all

    L, t, ps, kvh, d, num_pages, maxp = 2, 32, 8, 2, 16, 16, 8
    kn = jax.random.normal(jax.random.PRNGKey(3), (L, t, kvh, d), jnp.float32)
    vn = kn * 3.0
    kp = jnp.zeros((L, num_pages, ps, kvh, d), jnp.float32)
    vp = jnp.zeros_like(kp)
    row = jnp.asarray([4, 9, 2, 11, 6, 1, 13, 3], jnp.int32)[:maxp]

    want_k, want_v = write_prefill_all(
        kp, vp, kn, vn, row, jnp.int32(start), jnp.int32(length), ps,
        use_pallas=False,
    )
    got_k, got_v = paged_write_chunk(
        kp, vp, kn, vn, row, jnp.int32(start), jnp.int32(length), ps,
        interpret=True,
    )

    # compare per valid absolute position through the table, every layer
    for i in range(length):
        p_abs = start + i
        page = int(row[p_abs // ps])
        off = p_abs % ps
        np.testing.assert_array_equal(
            np.asarray(got_k[:, page, off]), np.asarray(want_k[:, page, off]),
            err_msg=f"k mismatch at abs pos {p_abs}",
        )
        np.testing.assert_array_equal(
            np.asarray(got_v[:, page, off]), np.asarray(want_v[:, page, off]),
        )
    # pages not in this chunk's span must be untouched
    touched = {int(row[(start + i) // ps]) for i in range(max(length, 1))}
    for page in range(num_pages):
        if page not in touched:
            np.testing.assert_array_equal(
                np.asarray(got_k[:, page]), np.asarray(want_k[:, page]),
                err_msg=f"page {page} modified unexpectedly",
            )


def test_paged_decode_current_token_merge_matches_overlay():
    """Kernel merge_cur mode == ref overlay mode == old written-pool mode."""
    from gridllm_tpu.ops.pallas_kernels import paged_decode
    from gridllm_tpu.ops.kvcache import write_decode_all

    s, maxp, ps, kvh, d, num_pages, h = 3, 4, 8, 2, 16, 16, 4
    kq = jax.random.PRNGKey(11)
    q = jax.random.normal(kq, (s, h, d), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(12), (num_pages, ps, kvh, d), jnp.float32)
    vp = kp * 0.5
    kc = jax.random.normal(jax.random.PRNGKey(13), (s, kvh, d), jnp.float32)
    vc = kc - 0.25
    table = jnp.asarray([[3, 1, -1, -1], [5, 6, -1, -1], [7, -1, -1, -1]], jnp.int32)
    prefix = jnp.asarray([9, 13, 0], jnp.int32)  # slot 2: fresh (empty prefix)
    act = jnp.asarray([True, True, True])

    # oracle: write the current token, then attend with lengths incl. it
    kp_w, vp_w = write_decode_all(
        kp[None], vp[None], kc[None], vc[None], table, prefix, act, ps,
        use_pallas=False,
    )
    want = paged_attention_decode_ref(
        q, kp_w[0], vp_w[0], table, prefix + 1, ps
    )

    got_ref = paged_attention_decode_ref(
        q, kp, vp, table, prefix, ps, k_cur=kc, v_cur=vc
    )
    got_kernel = paged_decode(
        q, kp, vp, table, prefix, ps, k_cur=kc, v_cur=vc, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got_ref), np.asarray(want), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_kernel), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_paged_decode_layer_indexed_pool():
    """5D pool + layer index reads the right layer (kernel and ref)."""
    from gridllm_tpu.ops.pallas_kernels import paged_decode
    from gridllm_tpu.ops import attention

    L, s, maxp, ps, kvh, d, num_pages, h = 3, 2, 2, 8, 2, 16, 8, 4
    q = jax.random.normal(jax.random.PRNGKey(1), (s, h, d), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(2), (L, num_pages, ps, kvh, d), jnp.float32)
    vp = kp + 1.0
    table = jnp.asarray([[1, 2], [4, -1]], jnp.int32)
    lens = jnp.asarray([12, 6], jnp.int32)
    for li in range(L):
        want = paged_attention_decode_ref(q, kp[li], vp[li], table, lens, ps)
        got = paged_decode(q, kp, vp, table, lens, ps,
                           layer=jnp.int32(li), interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
        got2 = attention.paged_attention_decode(
            q, kp, vp, table, lens, ps, layer=jnp.int32(li), use_pallas=False
        )
        np.testing.assert_allclose(np.asarray(got2), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_prefix_chunk_overlay_matches_written_pool():
    """attention_prefix_chunk with k_cur overlay == chunk already written."""
    from gridllm_tpu.ops.kvcache import write_prefill_all
    from gridllm_tpu.ops.attention import attention_prefix_chunk

    t, ps, kvh, d, num_pages, maxp, h = 16, 8, 2, 16, 16, 8, 4
    start, chunk_len = 8, 10
    q = jax.random.normal(jax.random.PRNGKey(5), (1, t, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(6), (t, kvh, d), jnp.float32)
    vc = kc * 2.0
    row = jnp.asarray([4, 9, 2, 11, 6, 1, 13, 3], jnp.int32)
    # prefix: positions 0..start-1 already in the pool
    kp = jax.random.normal(jax.random.PRNGKey(9), (num_pages, ps, kvh, d), jnp.float32)
    vp = kp - 0.5
    total = jnp.int32(start + chunk_len)

    kp_w, vp_w = write_prefill_all(
        kp[None], vp[None], kc[None], vc[None], row,
        jnp.int32(start), jnp.int32(chunk_len), ps, use_pallas=False,
    )
    want = attention_prefix_chunk(
        q, kp_w[0], vp_w[0], row, jnp.int32(start), total, ps,
        use_pallas=False,
    )
    got = attention_prefix_chunk(
        q, kp, vp, row, jnp.int32(start), total, ps,
        k_cur=kc, v_cur=vc, use_pallas=False,
    )
    np.testing.assert_allclose(
        np.asarray(got[:, :chunk_len]), np.asarray(want[:, :chunk_len]),
        rtol=2e-5, atol=2e-5,
    )


# ---------------------------------------------------------------------------
# kernel coverage: streamed flash prefill + d=64 padding (VERDICT #9)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("t,lens", [(256, [256]), (512, [300, 512])])
def test_flash_prefill_streamed_matches_ref(t, lens):
    from gridllm_tpu.ops.pallas_kernels import flash_prefill_streamed

    h, kvh, d = 4, 2, 32
    b = len(lens)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(21), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)
    want = attention_prefill_ref(q, k, v, seq_lens)
    got = flash_prefill_streamed(q, k, v, seq_lens, interpret=True)
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(
            np.asarray(got[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5,
        )


def test_attention_prefill_routes_streamed_past_vmem_cap(monkeypatch):
    """Past the VMEM budget the dispatch must pick the streaming kernel,
    not fall back to the quadratic-memory jnp path."""
    from unittest import mock
    from gridllm_tpu.ops import attention, pallas_kernels

    monkeypatch.setattr(attention, "_FLASH_KV_VMEM_CAP", 1024)  # force
    t, h, kvh, d = 256, 4, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(1), (1, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(2), (1, t, kvh, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(3), (1, t, kvh, d), jnp.float32)
    lens = jnp.asarray([200], jnp.int32)
    want = attention_prefill_ref(q, k, v, lens)
    with mock.patch.object(
        pallas_kernels, "flash_prefill_streamed",
        wraps=pallas_kernels.flash_prefill_streamed,
    ) as spy:
        monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
        attention._env_mode.cache_clear()
        got = attention.attention_prefill(q, k, v, lens)
        attention._env_mode.cache_clear()
        assert spy.called
    np.testing.assert_allclose(
        np.asarray(got[0, :200]), np.asarray(want[0, :200]),
        rtol=2e-5, atol=2e-5,
    )


def test_attention_prefill_d64_pads_to_lane_tile(monkeypatch):
    """qwen2.5-class head_dim 64: the dispatch zero-pads to the 128-lane
    tile, corrects the softmax scale, and slices back — exact vs ref."""
    from gridllm_tpu.ops import attention

    t, h, kvh, d = 128, 4, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(4), (1, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(5), (1, t, kvh, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(6), (1, t, kvh, d), jnp.float32)
    lens = jnp.asarray([100], jnp.int32)
    want = attention_prefill_ref(q, k, v, lens)
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    attention._env_mode.cache_clear()
    got = attention.attention_prefill(q, k, v, lens)
    attention._env_mode.cache_clear()
    assert got.shape == want.shape  # padding sliced back off
    np.testing.assert_allclose(
        np.asarray(got[0, :100]), np.asarray(want[0, :100]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("window,softcap", [
    (8, 0.0),       # window only
    (0, 30.0),      # softcap only
    (24, 50.0),     # both (gemma2 shape)
    (1, 50.0),      # degenerate window: self-attention only
])
def test_flash_prefill_softcap_window_matches_ref(window, softcap):
    t, h, kvh, d = 128, 4, 2, 32
    lens = [128, 70]
    b = len(lens)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)

    want = attention_prefill_ref(
        q, k, v, seq_lens, logit_softcap=softcap, window=window)
    got = flash_prefill(q, k, v, seq_lens, interpret=True,
                        softcap=softcap, window=window)
    from gridllm_tpu.ops.pallas_kernels import flash_prefill_streamed

    got_s = flash_prefill_streamed(q, k, v, seq_lens, interpret=True,
                                   softcap=softcap, window=window)
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(
            np.asarray(got[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(got_s[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window,softcap,merge", [
    (16, 0.0, False),
    (0, 50.0, True),
    (16, 50.0, True),
    (1, 0.0, True),      # window 1: only the merged current token attends
])
def test_paged_decode_softcap_window_matches_ref(window, softcap, merge):
    lens = [5, 30, 17]
    kvh, d, h = 2, 16, 4
    k_pool, v_pool, table, ps = _fill_pool(jax.random.PRNGKey(11), lens)
    s = len(lens)
    q = jax.random.normal(jax.random.PRNGKey(12), (s, h, d), jnp.float32)
    lengths = jnp.asarray(lens, jnp.int32)
    kc = vc = None
    if merge:
        kc = jax.random.normal(jax.random.PRNGKey(13), (s, kvh, d), jnp.float32)
        vc = jax.random.normal(jax.random.PRNGKey(14), (s, kvh, d), jnp.float32)

    want = paged_attention_decode_ref(
        q, k_pool, v_pool, table, lengths, ps, k_cur=kc, v_cur=vc,
        logit_softcap=softcap, window=window)
    got = paged_decode(q, k_pool, v_pool, table, lengths, ps,
                       k_cur=kc, v_cur=vc, interpret=True,
                       softcap=softcap, window=window)
    for i in range(s):
        np.testing.assert_allclose(
            np.asarray(got[i]), np.asarray(want[i]), rtol=2e-5, atol=2e-5)


def test_gemma2_engine_uses_kernels_in_interpret_mode(monkeypatch):
    """The softcap+window model family must keep the Pallas path: force
    interpret-mode kernels and check gemma2 generation matches the
    jnp-path output token-for-token."""
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine.engine import GenerationRequest

    kw = dict(model="tiny-gemma2", max_slots=2, page_size=8, num_pages=32,
              max_pages_per_slot=8, prefill_buckets=(16, 32))
    req = dict(prompt="kernel parity check", options={
        "temperature": 0, "num_predict": 6, "seed": 9})

    monkeypatch.setenv("GRIDLLM_PALLAS", "0")
    plain = InferenceEngine(EngineConfig(**kw)).generate(
        GenerationRequest(id="a", **req))
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kernels = InferenceEngine(EngineConfig(**kw)).generate(
        GenerationRequest(id="b", **req))
    assert plain.token_ids == kernels.token_ids


@pytest.mark.parametrize("window", [32, 129, 200])
def test_flash_prefill_window_multiblock(window):
    """t=256 = two 128-wide k blocks: the below-window block-skip bounds
    (kb0 in the resident kernel, the pl.when skip in the streamed one)
    actually fire with kb0 > 0 — a single-block case can't regress them.
    window=129 straddles a block boundary."""
    t, h, kvh, d = 256, 4, 2, 32
    lens = [256, 180]
    b = len(lens)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(21), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)

    want = attention_prefill_ref(q, k, v, seq_lens, window=window)
    got = flash_prefill(q, k, v, seq_lens, interpret=True, window=window)
    from gridllm_tpu.ops.pallas_kernels import flash_prefill_streamed

    got_s = flash_prefill_streamed(q, k, v, seq_lens, interpret=True,
                                   window=window)
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(
            np.asarray(got[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(got_s[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5)


def test_paged_decode_window_skips_pages_multipage():
    """Slot long enough (60 tokens, 8/page) that a 16-token window makes
    p0 > 0 — the below-window pages are skipped entirely and the result
    still matches the full-gather oracle."""
    lens = [60]
    kvh, d, h = 2, 16, 4
    k_pool, v_pool, table, ps = _fill_pool(jax.random.PRNGKey(31), lens)
    q = jax.random.normal(jax.random.PRNGKey(32), (1, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(33), (1, kvh, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(34), (1, kvh, d), jnp.float32)
    lengths = jnp.asarray(lens, jnp.int32)
    for window in (16, 17, 8, 3):
        want = paged_attention_decode_ref(
            q, k_pool, v_pool, table, lengths, ps, k_cur=kc, v_cur=vc,
            window=window)
        got = paged_decode(q, k_pool, v_pool, table, lengths, ps,
                           k_cur=kc, v_cur=vc, interpret=True, window=window)
        np.testing.assert_allclose(
            np.asarray(got[0]), np.asarray(want[0]), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# prefix_chunk kernel (chunked prefill against the paged prefix)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("start,chunk_valid", [
    (0, 16),       # first chunk, full
    (16, 10),      # second chunk, ragged tail
    (32, 1),       # deep prefix, single valid row
])
def test_prefix_chunk_kernel_matches_jnp(start, chunk_valid):
    """pallas_kernels.prefix_chunk (interpret) == the jnp prefix-chunk
    path, over a multi-page prefix + in-register chunk overlay."""
    from gridllm_tpu.ops.attention import attention_prefix_chunk
    from gridllm_tpu.ops.pallas_kernels import prefix_chunk

    t, ps, kvh, d, num_pages, maxp, h = 16, 8, 2, 16, 16, 8, 4
    q = jax.random.normal(jax.random.PRNGKey(5), (1, t, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(6), (t, kvh, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(7), (t, kvh, d), jnp.float32)
    row = jnp.asarray([4, 9, 2, 11, 6, 1, 13, 3], jnp.int32)
    kp = jax.random.normal(jax.random.PRNGKey(9), (num_pages, ps, kvh, d),
                           jnp.float32)
    vp = kp - 0.5
    total = jnp.int32(start + chunk_valid)

    want = attention_prefix_chunk(
        q, kp, vp, row, jnp.int32(start), total, ps, k_cur=kc, v_cur=vc,
        use_pallas=False,
    )
    got = prefix_chunk(
        q, kp, vp, row, jnp.int32(start), total, ps, k_cur=kc, v_cur=vc,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[:, :chunk_valid]), np.asarray(want[:, :chunk_valid]),
        rtol=2e-5, atol=2e-5,
    )


def test_prefix_chunk_kernel_full_pool_layer_select():
    """5D pool + traced layer index, matching the in-scan usage."""
    from gridllm_tpu.ops.attention import attention_prefix_chunk
    from gridllm_tpu.ops.pallas_kernels import prefix_chunk

    L, t, ps, kvh, d, num_pages, maxp, h = 3, 16, 8, 2, 16, 16, 8, 4
    q = jax.random.normal(jax.random.PRNGKey(1), (1, t, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(2), (t, kvh, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(3), (t, kvh, d), jnp.float32)
    row = jnp.arange(maxp, dtype=jnp.int32)
    kp = jax.random.normal(jax.random.PRNGKey(4), (L, num_pages, ps, kvh, d),
                           jnp.float32)
    vp = kp * 0.7
    start, total = jnp.int32(16), jnp.int32(16 + 16)

    want = attention_prefix_chunk(
        q, kp, vp, row, start, total, ps, k_cur=kc, v_cur=vc,
        layer=jnp.int32(2), use_pallas=False,
    )
    got = prefix_chunk(
        q, kp, vp, row, start, total, ps, k_cur=kc, v_cur=vc,
        layer=jnp.int32(2), interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefix_chunk_kernel_window_softcap():
    """Sliding window (mistral/gemma2) + softcap through the chunk kernel:
    windows that reach back into the paged prefix must match the jnp
    mask."""
    from gridllm_tpu.ops.attention import attention_prefix_chunk
    from gridllm_tpu.ops.pallas_kernels import prefix_chunk

    t, ps, kvh, d, num_pages, maxp, h = 16, 8, 2, 16, 16, 8, 4
    q = jax.random.normal(jax.random.PRNGKey(11), (1, t, h, d), jnp.float32)
    kc = jax.random.normal(jax.random.PRNGKey(12), (t, kvh, d), jnp.float32)
    vc = jax.random.normal(jax.random.PRNGKey(13), (t, kvh, d), jnp.float32)
    row = jnp.arange(maxp, dtype=jnp.int32)
    kp = jax.random.normal(jax.random.PRNGKey(14), (num_pages, ps, kvh, d),
                           jnp.float32)
    vp = kp + 0.3
    start, total = jnp.int32(24), jnp.int32(24 + 16)

    for win in (6, 20):
        want = attention_prefix_chunk(
            q, kp, vp, row, start, total, ps, k_cur=kc, v_cur=vc,
            use_pallas=False, logit_softcap=30.0, window=jnp.int32(win),
        )
        got = prefix_chunk(
            q, kp, vp, row, start, total, ps, k_cur=kc, v_cur=vc,
            interpret=True, softcap=30.0, window=jnp.int32(win),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_prefix_chunk_dispatch_routes_to_kernel(monkeypatch):
    """attention_prefix_chunk takes the kernel when interpret kernels are
    on and the chunk fits VMEM; long prompts keep kernel-path prefill
    (VERDICT r04 #5 'done' condition)."""
    from unittest import mock

    from gridllm_tpu.ops import attention, kvcache, pallas_kernels

    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    kvcache._env_mode.cache_clear()
    try:
        t, ps, kvh, d, num_pages, maxp, h = 16, 8, 2, 16, 16, 8, 4
        q = jax.random.normal(jax.random.PRNGKey(0), (1, t, h, d), jnp.float32)
        kc = jax.random.normal(jax.random.PRNGKey(1), (t, kvh, d), jnp.float32)
        vc = jax.random.normal(jax.random.PRNGKey(2), (t, kvh, d), jnp.float32)
        row = jnp.arange(maxp, dtype=jnp.int32)
        kp = jax.random.normal(jax.random.PRNGKey(3), (num_pages, ps, kvh, d),
                               jnp.float32)
        with mock.patch.object(
            pallas_kernels, "prefix_chunk", wraps=pallas_kernels.prefix_chunk
        ) as spy:
            attention.attention_prefix_chunk(
                q, kp, kp, row, jnp.int32(8), jnp.int32(8 + 16), ps,
                k_cur=kc, v_cur=vc,
            )
            assert spy.called
    finally:
        kvcache._env_mode.cache_clear()
