"""Pallas kernels vs the jnp oracles, in interpret mode on CPU
(SURVEY.md §4: engine numerics get golden coverage; the kernels must be
bit-for-bit-close to the reference implementations they replace)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.ops import attention
from gridllm_tpu.ops.attention import (
    attention_prefill_ref,
    paged_attention_decode_ref,
)
from gridllm_tpu.ops.kvcache import PageAllocator, PagedKVCache, write_prefill
from gridllm_tpu.ops.pallas_kernels import flash_prefill, paged_decode


@pytest.mark.parametrize("t,h,kvh,d,lens", [
    (64, 4, 2, 16, [64]),          # full block, GQA
    (128, 4, 4, 32, [100]),        # ragged length, MHA
    (256, 8, 2, 64, [256, 17]),    # batch of 2, very ragged
    (64, 2, 1, 128, [1]),          # single valid token
])
def test_flash_prefill_matches_ref(t, h, kvh, d, lens):
    b = len(lens)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kvh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kvh, d), jnp.float32)
    seq_lens = jnp.asarray(lens, jnp.int32)

    want = attention_prefill_ref(q, k, v, seq_lens)
    got = flash_prefill(q, k, v, seq_lens, interpret=True)
    # padding rows (pos >= len) are unspecified; compare valid region only
    for i, ln in enumerate(lens):
        np.testing.assert_allclose(
            np.asarray(got[i, :ln]), np.asarray(want[i, :ln]),
            rtol=2e-5, atol=2e-5,
        )


def test_flash_prefill_bf16():
    t, h, kvh, d = 128, 4, 2, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (1, t, h, d), jnp.float32).astype(jnp.bfloat16)
    k = jax.random.normal(kk, (1, t, kvh, d), jnp.float32).astype(jnp.bfloat16)
    v = jax.random.normal(kv, (1, t, kvh, d), jnp.float32).astype(jnp.bfloat16)
    seq_lens = jnp.asarray([90], jnp.int32)
    want = attention_prefill_ref(q, k, v, seq_lens)
    got = flash_prefill(q, k, v, seq_lens, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got[0, :90], np.float32), np.asarray(want[0, :90], np.float32),
        rtol=3e-2, atol=3e-2,
    )


def _fill_pool(key, lens, page_size=8, kvh=2, d=16, maxp=8, num_pages=32):
    """Build a pool with len(lens) slots holding random K/V of given lengths."""
    s = len(lens)
    cache = PagedKVCache.create(1, num_pages, page_size, kvh, d, s, maxp,
                                dtype=jnp.float32)
    alloc = PageAllocator(num_pages, page_size, maxp)
    k_pool, v_pool = cache.k[0], cache.v[0]
    table = np.full((s, maxp), -1, np.int32)
    for i, ln in enumerate(lens):
        if ln == 0:
            continue
        alloc.alloc(i, ln)
        row = np.asarray(alloc.table_row(i), np.int32)
        table[i] = row
        key, ka, kb = jax.random.split(key, 3)
        # bucket-pad to a multiple of page_size for write_prefill
        t_pad = -(-ln // page_size) * page_size
        k_new = jax.random.normal(ka, (t_pad, kvh, d), jnp.float32)
        v_new = jax.random.normal(kb, (t_pad, kvh, d), jnp.float32)
        k_pool, v_pool = write_prefill(
            k_pool, v_pool, k_new, v_new, jnp.asarray(row), jnp.int32(0),
            jnp.int32(ln), page_size,
        )
    return k_pool, v_pool, jnp.asarray(table), page_size


@pytest.mark.parametrize("lens,h", [
    ([5], 4),              # single slot, partial page
    ([8, 17, 1, 30], 4),   # ragged multi-slot
    ([0, 12], 2),          # inactive slot present
])
def test_paged_decode_matches_ref(lens, h):
    kvh, d = 2, 16
    k_pool, v_pool, table, ps = _fill_pool(jax.random.PRNGKey(2), lens)
    s = len(lens)
    q = jax.random.normal(jax.random.PRNGKey(3), (s, h, d), jnp.float32)
    lengths = jnp.asarray(lens, jnp.int32)

    want = paged_attention_decode_ref(q, k_pool, v_pool, table, lengths, ps)
    got = paged_decode(q, k_pool, v_pool, table, lengths, ps, interpret=True)
    for i, ln in enumerate(lens):
        if ln == 0:
            continue  # inactive slots are unspecified in both impls
        np.testing.assert_allclose(
            np.asarray(got[i]), np.asarray(want[i]), rtol=2e-5, atol=2e-5,
        )


def test_dispatch_env(monkeypatch):
    """GRIDLLM_PALLAS resolves the documented modes; the per-call
    use_pallas override beats the env policy."""
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    assert attention._pallas_mode(None) == (True, True)
    assert attention._pallas_mode(False) == (False, True)
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "0")
    assert attention._pallas_mode(None) == (False, False)
    assert attention._pallas_mode(True) == (True, False)
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "auto")
    use, interp = attention._pallas_mode(None)
    assert use == (jax.default_backend() == "tpu") and interp is False
    attention._env_mode.cache_clear()


def test_model_end_to_end_with_kernels(monkeypatch):
    """tiny-llama greedy decode via the public dispatch (interpret kernels)
    reproduces the pure-jnp path token-for-token."""
    from gridllm_tpu.models import llama
    from gridllm_tpu.models.configs import get_config

    cfg = get_config("tiny-llama")
    params = llama.init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    prompt = [5, 17, 99, 3, 42]

    def greedy(n=4):
        cache = PagedKVCache.create(
            cfg.num_layers, 16, 8, cfg.num_kv_heads, cfg.head_dim_, 2, 8,
            dtype=jnp.float32,
        )
        alloc = PageAllocator(16, 8, 8)
        alloc.alloc(0, 16)
        row = jnp.asarray(alloc.table_row(0), jnp.int32)
        padded = jnp.asarray(prompt + [0] * 3, jnp.int32)
        logits, cache = llama.prefill(
            params, cfg, padded, jnp.int32(len(prompt)), cache, jnp.int32(0), row
        )
        out = [int(jnp.argmax(logits))]
        tok = jnp.zeros((2,), jnp.int32).at[0].set(out[0])
        active = jnp.zeros((2,), bool).at[0].set(True)
        for _ in range(n - 1):
            logits, cache = llama.decode_step(params, cfg, tok, cache, active)
            nxt = int(jnp.argmax(logits[0]))
            out.append(nxt)
            tok = tok.at[0].set(nxt)
        return out

    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "0")
    want = greedy()
    attention._env_mode.cache_clear()
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    got = greedy()
    attention._env_mode.cache_clear()
    assert got == want
