"""RespBus ↔ gridbus broker wire tests: the same contract as test_bus.py,
exercised over a real TCP socket speaking RESP2."""

import asyncio

from gridllm_tpu.bus.broker import GridBusBroker
from gridllm_tpu.bus.resp import RespBus


async def _make():
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    return broker, bus


async def _teardown(broker, *buses):
    for b in buses:
        await b.disconnect()
    await broker.stop()


async def test_wire_kv_hash_ttl():
    broker, bus = await _make()
    try:
        assert await bus.is_healthy()
        await bus.set("k", "v")
        assert await bus.get("k") == "v"
        assert await bus.ttl("k") == -1
        await bus.set_with_expiry("hb", "1", ttl_s=10)
        assert 0 <= await bus.ttl("hb") <= 10
        assert await bus.ttl("nope") == -2
        await bus.hset("workers", "w1", '{"a":1}')
        assert await bus.hget("workers", "w1") == '{"a":1}'
        assert await bus.hgetall("workers") == {"w1": '{"a":1}'}
        await bus.hdel("workers", "w1")
        assert await bus.hgetall("workers") == {}
        await bus.delete("k")
        assert await bus.get("k") is None
    finally:
        await _teardown(broker, bus)


async def test_wire_pubsub_between_two_clients():
    broker, server_bus = await _make()
    worker_bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await worker_bus.connect()
    try:
        got = []
        done = asyncio.Event()

        async def on_msg(ch, m):
            got.append((ch, m))
            done.set()

        sub = await server_bus.subscribe("worker:registered", on_msg)
        await asyncio.sleep(0.05)  # let SUBSCRIBE reach the broker
        n = await worker_bus.publish("worker:registered", '{"workerId":"w1"}')
        await asyncio.wait_for(done.wait(), 2)
        assert n == 1
        assert got == [("worker:registered", '{"workerId":"w1"}')]

        await sub.unsubscribe()
        await asyncio.sleep(0.05)
        assert await worker_bus.publish("worker:registered", "x") == 0
    finally:
        await _teardown(broker, server_bus, worker_bus)


async def test_wire_psubscribe():
    broker, bus = await _make()
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await pub.connect()
    try:
        got = []
        done = asyncio.Event()

        async def on_msg(ch, m):
            got.append((ch, m))
            done.set()

        await bus.psubscribe("job:stream:*", on_msg)
        await asyncio.sleep(0.05)
        await pub.publish("job:stream:abc", "tok")
        await asyncio.wait_for(done.wait(), 2)
        assert got == [("job:stream:abc", "tok")]
    finally:
        await _teardown(broker, bus, pub)


async def test_wire_main_conn_survives_broker_restart():
    """KV/publish must recover after the broker restarts (lazy reconnect)."""
    broker, bus = await _make()
    port = broker.port
    await bus.set("k", "v1")
    await broker.stop()
    broker2 = GridBusBroker()
    await broker2.start("127.0.0.1", port)
    try:
        await bus.set("k2", "v2")  # lazy reconnect inside command()
        assert await bus.get("k2") == "v2"
        assert await bus.is_healthy()
    finally:
        await _teardown(broker2, bus)


async def test_wire_ordering():
    broker, bus = await _make()
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await pub.connect()
    try:
        got = []
        done = asyncio.Event()

        async def h(ch, m):
            await asyncio.sleep(0.001)
            got.append(m)
            if len(got) == 10:
                done.set()

        await bus.subscribe("s", h)
        await asyncio.sleep(0.05)
        for i in range(10):
            await pub.publish("s", str(i))
        await asyncio.wait_for(done.wait(), 3)
        assert got == [str(i) for i in range(10)]
    finally:
        await _teardown(broker, bus, pub)


async def test_aof_state_survives_broker_restart(tmp_path):
    """SURVEY §5.4: the reference's Redis ran --appendonly yes so scheduler
    state (workers hash, active_jobs, queue keys) survives broker
    restarts; gridbus --aof must give the same guarantee. Expired keys
    must NOT resurrect."""
    aof = str(tmp_path / "bus.aof")

    broker = GridBusBroker(aof_path=aof)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await bus.set("plain", "v1")
    await bus.set_with_expiry("short", "gone", ttl_s=0.2)
    await bus.set_with_expiry("long", "kept", ttl_s=60.0)
    await bus.hset("h", "f1", "a")
    await bus.hset("h", "f2", "b")
    await bus.hdel("h", "f2")
    await bus.set("deleted", "x")
    await bus.delete("deleted")
    await bus.disconnect()
    await broker.stop()

    await asyncio.sleep(0.25)  # "short" expires while the broker is down

    broker2 = GridBusBroker(aof_path=aof)
    await broker2.start("127.0.0.1", 0)
    bus2 = RespBus(host="127.0.0.1", port=broker2.port, key_prefix="T:")
    await bus2.connect()
    try:
        assert await bus2.get("plain") == "v1"
        assert await bus2.get("short") is None
        assert await bus2.get("long") == "kept"
        assert await bus2.hgetall("h") == {"f1": "a"}
        assert await bus2.get("deleted") is None
    finally:
        await bus2.disconnect()
        await broker2.stop()


async def test_aof_tolerates_torn_tail(tmp_path):
    """A crash mid-append leaves a torn last line; replay must stop there
    and keep everything before it."""
    aof = str(tmp_path / "bus.aof")
    broker = GridBusBroker(aof_path=aof)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await bus.set("a", "1")
    await bus.set("b", "2")
    await bus.disconnect()
    await broker.stop()

    with open(aof, "a") as f:
        f.write('{"op":"set","k":"T:c","v":"tor')  # torn write

    broker2 = GridBusBroker(aof_path=aof)
    await broker2.start("127.0.0.1", 0)
    bus2 = RespBus(host="127.0.0.1", port=broker2.port, key_prefix="T:")
    await bus2.connect()
    try:
        assert await bus2.get("a") == "1"
        assert await bus2.get("b") == "2"
        assert await bus2.get("c") is None
    finally:
        await bus2.disconnect()
        await broker2.stop()


async def test_aof_refuses_midfile_corruption(tmp_path):
    """Corruption NOT at the tail means the file is damaged; replaying a
    prefix and compacting over the original would silently destroy every
    good record after the corruption — the broker must refuse to start."""
    import json

    import pytest

    aof = str(tmp_path / "bus.aof")
    with open(aof, "w") as f:
        f.write(json.dumps({"op": "set", "k": "T:a", "v": "1"}) + "\n")
        f.write("GARBAGE-NOT-JSON\n")
        f.write(json.dumps({"op": "set", "k": "T:b", "v": "2"}) + "\n")
    broker = GridBusBroker(aof_path=aof)
    with pytest.raises(RuntimeError, match="corrupt record 2/3"):
        await broker.start("127.0.0.1", 0)


async def test_aof_keeps_bak_of_previous_log(tmp_path):
    """The pre-compaction log survives as .bak — the snapshot must never
    be the only copy of the state it was derived from."""
    import os

    aof = str(tmp_path / "bus.aof")
    broker = GridBusBroker(aof_path=aof)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await bus.set("x", "1")
    await bus.disconnect()
    await broker.stop()

    broker2 = GridBusBroker(aof_path=aof)
    await broker2.start("127.0.0.1", 0)
    await broker2.stop()
    assert os.path.exists(aof + ".bak")


async def test_aof_recovers_from_bak_when_log_missing(tmp_path):
    """Crash window in compaction: if the process dies after the log was
    renamed to .bak but before the compacted snapshot landed at the log
    path, the next start must replay .bak — NOT silently begin empty."""
    import os

    aof = str(tmp_path / "bus.aof")
    broker = GridBusBroker(aof_path=aof)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await bus.set("x", "survives")
    await bus.hset("h", "f", "v")
    await bus.disconnect()
    await broker.stop()

    # simulate the crash window: log renamed aside, snapshot never landed
    os.replace(aof, aof + ".bak")
    assert not os.path.exists(aof)

    broker2 = GridBusBroker(aof_path=aof)
    await broker2.start("127.0.0.1", 0)
    bus2 = RespBus(host="127.0.0.1", port=broker2.port, key_prefix="T:")
    await bus2.connect()
    try:
        assert await bus2.get("x") == "survives"
        assert await bus2.hgetall("h") == {"f": "v"}
    finally:
        await bus2.disconnect()
        await broker2.stop()
    assert os.path.exists(aof)  # compaction re-published the log
