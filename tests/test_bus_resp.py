"""RespBus ↔ gridbus broker wire tests: the same contract as test_bus.py,
exercised over a real TCP socket speaking RESP2."""

import asyncio

from gridllm_tpu.bus.broker import GridBusBroker
from gridllm_tpu.bus.resp import RespBus


async def _make():
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    return broker, bus


async def _teardown(broker, *buses):
    for b in buses:
        await b.disconnect()
    await broker.stop()


async def test_wire_kv_hash_ttl():
    broker, bus = await _make()
    try:
        assert await bus.is_healthy()
        await bus.set("k", "v")
        assert await bus.get("k") == "v"
        assert await bus.ttl("k") == -1
        await bus.set_with_expiry("hb", "1", ttl_s=10)
        assert 0 <= await bus.ttl("hb") <= 10
        assert await bus.ttl("nope") == -2
        await bus.hset("workers", "w1", '{"a":1}')
        assert await bus.hget("workers", "w1") == '{"a":1}'
        assert await bus.hgetall("workers") == {"w1": '{"a":1}'}
        await bus.hdel("workers", "w1")
        assert await bus.hgetall("workers") == {}
        await bus.delete("k")
        assert await bus.get("k") is None
    finally:
        await _teardown(broker, bus)


async def test_wire_pubsub_between_two_clients():
    broker, server_bus = await _make()
    worker_bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await worker_bus.connect()
    try:
        got = []
        done = asyncio.Event()

        async def on_msg(ch, m):
            got.append((ch, m))
            done.set()

        sub = await server_bus.subscribe("worker:registered", on_msg)
        await asyncio.sleep(0.05)  # let SUBSCRIBE reach the broker
        n = await worker_bus.publish("worker:registered", '{"workerId":"w1"}')
        await asyncio.wait_for(done.wait(), 2)
        assert n == 1
        assert got == [("worker:registered", '{"workerId":"w1"}')]

        await sub.unsubscribe()
        await asyncio.sleep(0.05)
        assert await worker_bus.publish("worker:registered", "x") == 0
    finally:
        await _teardown(broker, server_bus, worker_bus)


async def test_wire_psubscribe():
    broker, bus = await _make()
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await pub.connect()
    try:
        got = []
        done = asyncio.Event()

        async def on_msg(ch, m):
            got.append((ch, m))
            done.set()

        await bus.psubscribe("job:stream:*", on_msg)
        await asyncio.sleep(0.05)
        await pub.publish("job:stream:abc", "tok")
        await asyncio.wait_for(done.wait(), 2)
        assert got == [("job:stream:abc", "tok")]
    finally:
        await _teardown(broker, bus, pub)


async def test_wire_main_conn_survives_broker_restart():
    """KV/publish must recover after the broker restarts (lazy reconnect)."""
    broker, bus = await _make()
    port = broker.port
    await bus.set("k", "v1")
    await broker.stop()
    broker2 = GridBusBroker()
    await broker2.start("127.0.0.1", port)
    try:
        await bus.set("k2", "v2")  # lazy reconnect inside command()
        assert await bus.get("k2") == "v2"
        assert await bus.is_healthy()
    finally:
        await _teardown(broker2, bus)


async def test_wire_ordering():
    broker, bus = await _make()
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await pub.connect()
    try:
        got = []
        done = asyncio.Event()

        async def h(ch, m):
            await asyncio.sleep(0.001)
            got.append(m)
            if len(got) == 10:
                done.set()

        await bus.subscribe("s", h)
        await asyncio.sleep(0.05)
        for i in range(10):
            await pub.publish("s", str(i))
        await asyncio.wait_for(done.wait(), 3)
        assert got == [str(i) for i in range(10)]
    finally:
        await _teardown(broker, bus, pub)
