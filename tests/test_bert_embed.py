"""BERT embedding family goldens vs HF BertModel + engine embed path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.models import bert_embed
from gridllm_tpu.models.configs import get_config

CFG = get_config("tiny-bert")


def _hf_pair():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)
    model = transformers.BertModel(CFG.hf_config()).eval()
    params = bert_embed.convert_hf_state_dict(
        CFG, model.state_dict(), dtype=jnp.float32
    )
    return model, torch, params


def test_hidden_states_match_hf():
    model, torch, params = _hf_pair()
    tokens = np.array([[5, 17, 99, 3, 42, 7, 250, 1]], np.int32)
    ours = np.asarray(bert_embed.hidden_states(params, CFG, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_padding_masked_like_hf_attention_mask():
    """Our seq_lens masking == HF attention_mask for the valid region."""
    model, torch, params = _hf_pair()
    tokens = np.array([[5, 17, 99, 0, 0, 0, 0, 0]], np.int32)
    ours = np.asarray(bert_embed.hidden_states(
        params, CFG, jnp.asarray(tokens), seq_lens=jnp.asarray([3], jnp.int32)
    ))
    with torch.no_grad():
        theirs = model(
            torch.from_numpy(tokens).long(),
            attention_mask=torch.tensor([[1, 1, 1, 0, 0, 0, 0, 0]]),
        ).last_hidden_state.numpy()
    np.testing.assert_allclose(ours[0, :3], theirs[0, :3], rtol=2e-4, atol=2e-4)


def test_pool_modes():
    h = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 8)), jnp.float32)
    lens = jnp.asarray([2, 4], jnp.int32)
    mean = np.asarray(bert_embed.pool(h, lens, "mean"))
    cls = np.asarray(bert_embed.pool(h, lens, "cls"))
    np.testing.assert_allclose(np.linalg.norm(mean, axis=-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(cls, axis=-1), 1.0, rtol=1e-5)
    # mean must ignore padding: recompute row 0 by hand over 2 tokens
    manual = np.asarray(h[0, :2]).mean(0)
    manual /= np.linalg.norm(manual)
    np.testing.assert_allclose(mean[0], manual, rtol=1e-5)


def test_engine_embeds_and_rejects_generation():
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-bert", prefill_buckets=(32,), seed=0,
    ))
    vecs = eng.embed(["hello world", "another text"])
    assert len(vecs) == 2 and len(vecs[0]) == CFG.hidden_size
    np.testing.assert_allclose(np.linalg.norm(vecs[0]), 1.0, rtol=1e-2)  # bf16
    # same text twice -> identical embedding; different -> different
    again = eng.embed(["hello world"])[0]
    np.testing.assert_allclose(again, vecs[0], rtol=1e-5, atol=1e-6)
    assert not np.allclose(vecs[0], vecs[1])

    done = []
    eng.submit(GenerationRequest(
        id="g1", prompt="hi",
        on_chunk=lambda d, fin, res: done.append(res) if fin else None,
    ))
    assert done and done[0].done_reason == "error"


def test_checkpoint_roundtrip(tmp_path):
    from gridllm_tpu.engine.loader import load_checkpoint, save_checkpoint

    params = bert_embed.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    save_checkpoint(params, CFG, str(tmp_path))
    back = load_checkpoint(CFG, str(tmp_path), dtype=jnp.float32)
    tokens = jnp.asarray([[9, 8, 7, 6]], jnp.int32)
    a = bert_embed.hidden_states(params, CFG, tokens)
    b = bert_embed.hidden_states(back, CFG, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
