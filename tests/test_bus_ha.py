"""Bus high availability (ISSUE 10): resumable channels, warm-standby
broker failover, epoch fencing, and partition-aware liveness.

The headline invariant extends PR 9's: however the BROKER dies mid-stream
— accept-drop, torn reply, SIGKILL-equivalent stop with a warm standby
tailing it — the client-observed token stream is exactly-once and
byte-identical to the undisturbed run, and no healthy job is
orphan-requeued just because the control plane blinked.

Units drive the broker/RespBus pair directly (replay rings, seq dedupe,
FENCE/FAILOVER); the liveness units pin the registry/scheduler holds;
the slow two-broker chaos test reuses the PR 9 differential harness with
the scheduler AND workers on real RESP connections, killing the primary
mid-decode.
"""

from __future__ import annotations

import asyncio
import time
import types
import uuid

import pytest

from gridllm_tpu import faults
from gridllm_tpu.bus import InMemoryBus, create_bus
from gridllm_tpu.bus.base import (
    durable_channel,
    encode_seq,
    liveness_suspended,
    split_seq,
)
from gridllm_tpu.bus.broker import GridBusBroker
from gridllm_tpu.bus.resp import (
    RespBus,
    RespProtocolError,
    encode_command,
    read_reply,
)
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
from gridllm_tpu.utils.types import InferenceRequest, JobAssignment
from gridllm_tpu.worker.service import WorkerService

from .test_fault_tolerance import (
    CHAOS_TOKENS,
    MODEL,
    N_PREDICT,
    PROMPT,
    ft_config,
    make_engine,
    reference_run,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


async def _wait(predicate, timeout_s: float = 10.0, interval: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


# ----------------------------------------------------------------- units


def test_durable_channel_classification():
    for ch in ("job:result:abc", "job:stream:abc", "job:snapshot",
               "job:handoff", "job:drain", "job:completed", "job:failed",
               "kvx:req-1", "admin:result:op1", "worker:w1:job"):
        assert durable_channel(ch), ch
    for ch in ("worker:heartbeat", "worker:registered", "trace:abc",
               "slice:w1:plan", "worker:admin",
               "worker:reregister:w1", "worker:status_update"):
        assert not durable_channel(ch), ch


def test_durable_classification_matches_legacy_patterns():
    """ISSUE 13 satellite: durable_channel now DERIVES from the typed
    channel registry — prove the derived classification agrees with the
    PR 10 hardcoded pattern list on every registered channel family
    (instantiated with representative ids). The one deliberate
    divergence: job:timeout, which the legacy list called durable but
    which turned out to be subscribed-and-never-published drift — it is
    no longer a registered channel at all."""
    import re

    from gridllm_tpu.bus.base import CHANNELS

    legacy_prefixes = ("job:result:", "job:stream:", "admin:result:",
                      "kvx:", "obs:dump:reply:")
    legacy_fixed = {"job:completed", "job:failed", "job:timeout",
                    "job:snapshot", "job:handoff", "job:drain",
                    "job:preempted",
                    # ISSUE 15: the control-plane submit/cancel channels
                    # postdate the PR 10 list and are durable by design —
                    # a submission published while a scheduler shard's
                    # subscriber is mid-reconnect must replay, not vanish
                    # (ctrl:status stays best-effort fire-and-forget)
                    "ctrl:submit", "ctrl:cancel",
                    # ISSUE 17: timeline event batches and fleet-dump
                    # replies replay across a subscriber reconnect —
                    # the incident window / dump op must not vanish
                    # into the exact outage it exists to record
                    "obs:event",
                    # ISSUE 19: health-state verdicts replay across a
                    # subscriber reconnect — a missed quarantine would
                    # leave a replica routing at a bad worker
                    "health:state"}

    def legacy(ch: str) -> bool:
        if ch in legacy_fixed or ch.startswith(legacy_prefixes):
            return True
        return ch.startswith("worker:") and ch.endswith(":job")

    assert len(CHANNELS) >= 20
    for spec in CHANNELS.values():
        ch = re.sub(r"\{[^{}]+\}", "w1-abc123", spec.pattern)
        assert durable_channel(ch) == spec.durable == legacy(ch), \
            (spec.family, ch)


def test_seq_framing_roundtrip():
    framed = encode_seq(42, '{"x": 1}')
    assert split_seq(framed) == (42, '{"x": 1}')
    # unframed payloads (real Redis, in-memory bus) pass through whole
    assert split_seq('{"x": 1}') == (None, '{"x": 1}')
    assert split_seq("") == (None, "")


async def test_replay_ring_resumes_outage_gap():
    """Messages published on a durable channel while the subscriber's
    connection is down are REPLAYED on reconnect — in order, no gap, no
    duplicate — and the replay counts in the replayed-messages counter."""
    from gridllm_tpu.bus.resp import _REPLAYED

    broker = GridBusBroker(ring_cap=16)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await pub.connect()
    got: list[str] = []

    async def handler(_ch, m):
        got.append(m)

    try:
        await bus.subscribe("job:stream:x", handler)
        await asyncio.sleep(0.05)
        await pub.publish("job:stream:x", "a")
        await pub.publish("job:stream:x", "b")
        assert await _wait(lambda: got == ["a", "b"])
        replayed0 = int(_REPLAYED.value(channel="job:stream"))
        # tear the subscriber transport; the gap lands while it is down
        bus._sub.writer.close()
        await asyncio.sleep(0.05)
        await pub.publish("job:stream:x", "c")
        await pub.publish("job:stream:x", "d")
        assert await _wait(lambda: len(got) >= 4, timeout_s=15)
        assert got == ["a", "b", "c", "d"]
        assert int(_REPLAYED.value(channel="job:stream")) - replayed0 == 2
        assert bus.partition_state()["degraded"] is False
        assert bus.partition_state()["lastRejoin"] is not None
    finally:
        await bus.disconnect()
        await pub.disconnect()
        await broker.stop()


async def test_seq_dedupe_drops_replay_overlap():
    """A RESUME from an OLDER watermark than the client's replays frames
    the client already delivered — the per-channel seq dedupe must drop
    every one of them (consumer-observed exactly-once)."""
    broker = GridBusBroker(ring_cap=16)
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    pub = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    await pub.connect()
    got: list[str] = []

    async def handler(_ch, m):
        got.append(m)

    try:
        await bus.subscribe("job:result:j1", handler)
        await asyncio.sleep(0.05)
        for m in ("r1", "r2", "r3"):
            await pub.publish("job:result:j1", m)
        assert await _wait(lambda: got == ["r1", "r2", "r3"])
        assert bus._last_seq["job:result:j1"] == 3
        # force an overlapping replay: everything after seq 1 again
        await bus._sub.send_only("RESUME", "job:result:j1", 1)
        await pub.publish("job:result:j1", "r4")  # proves the pump is live
        assert await _wait(lambda: "r4" in got)
        assert got == ["r1", "r2", "r3", "r4"]  # r2/r3 replays deduped
    finally:
        await bus.disconnect()
        await pub.disconnect()
        await broker.stop()


async def test_resume_reports_ring_outrun_as_lost():
    """A gap bigger than the replay ring is reported in the resume ack's
    ``lost`` field instead of silently replaying a hole."""
    broker = GridBusBroker(ring_cap=4)
    await broker.start("127.0.0.1", 0)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       broker.port)
        for i in range(10):  # seqs 1..10; ring keeps 7..10
            broker._publish("job:stream:z", f"m{i + 1}")
        writer.write(encode_command("RESUME", "job:stream:z", 2))
        await writer.drain()
        frames = [await read_reply(reader) for _ in range(5)]
        ack = frames[-1]
        assert ack[0] == "resume" and ack[1] == "job:stream:z"
        assert int(ack[2]) == 4          # replayed 7..10
        assert int(ack[3]) == 4          # lost 3..6
        replayed = [split_seq(f[2]) for f in frames[:-1]]
        assert replayed == [(7, "m7"), (8, "m8"), (9, "m9"), (10, "m10")]
        writer.close()
    finally:
        await broker.stop()


async def test_broker_seq_reset_voids_watermark_instead_of_muting():
    """A broker restart with no standby loses its seq history. The
    reconnecting subscriber is then AHEAD of the broker — its RESUME
    must void the stale watermark (lost=-1 ack) so fresh low-seq
    messages are delivered, not silently dropped as duplicates until
    the new counter overtakes the old one."""
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    port = broker.port
    bus = RespBus(host="127.0.0.1", port=port, key_prefix="T:")
    await bus.connect()
    got: list[str] = []

    async def handler(_ch, m):
        got.append(m)

    broker2 = None
    pub = None
    try:
        await bus.subscribe("job:stream:r", handler)
        await asyncio.sleep(0.05)
        pub = RespBus(host="127.0.0.1", port=port, key_prefix="T:")
        await pub.connect()
        for m in ("a", "b", "c"):
            await pub.publish("job:stream:r", m)
        assert await _wait(lambda: got == ["a", "b", "c"])
        assert bus._last_seq["job:stream:r"] == 3
        await broker.stop()
        broker2 = GridBusBroker()  # fresh seq counters (no AOF, no standby)
        await broker2.start("127.0.0.1", port)
        # subscriber reconnects, RESUMEs at 3, broker acks lost=-1
        assert await _wait(
            lambda: "job:stream:r" not in bus._last_seq, timeout_s=30)
        await pub.publish("job:stream:r", "d")  # fresh seq 1
        assert await _wait(lambda: got == ["a", "b", "c", "d"],
                           timeout_s=10), \
            "post-reset messages muted by the stale watermark"
    finally:
        await bus.disconnect()
        if pub is not None:
            await pub.disconnect()
        await (broker2 or broker).stop()


async def test_ring_eviction_keeps_seq_counter():
    """Evicting an idle channel's replay ring must NOT reset its seq
    counter: a later publish would restart at seq 1 and long-lived
    subscribers would drop it as a stale duplicate."""
    broker = GridBusBroker(ring_cap=4)
    broker.MAX_RING_CHANNELS = 2
    broker._publish("job:drain", "d1")
    broker._publish("job:stream:a", "x")
    broker._publish("job:stream:b", "x")  # evicts job:drain's ring
    assert "job:drain" not in broker._rings
    assert broker._seq["job:drain"] == 1  # counter survives the eviction
    broker._publish("job:drain", "d2")
    assert broker._seq["job:drain"] == 2  # monotonic, not restarted


async def test_stale_demotion_survives_broker_restart(tmp_path):
    """A fenced-off primary stays stale across a supervisor restart: the
    demotion is persisted in the AOF, so the resurrected process cannot
    come back as a willing write target at its pre-failover epoch."""
    aof = str(tmp_path / "bus.aof")
    broker = GridBusBroker(aof_path=aof)
    await broker.start("127.0.0.1", 0)
    reader, writer = await asyncio.open_connection("127.0.0.1", broker.port)
    writer.write(encode_command("FENCE", 7))
    await writer.drain()
    with pytest.raises(RespProtocolError, match="STALE"):
        await read_reply(reader)
    writer.close()
    await broker.stop()

    broker2 = GridBusBroker(aof_path=aof)
    await broker2.start("127.0.0.1", 0)
    try:
        assert broker2.stale
        r2, w2 = await asyncio.open_connection("127.0.0.1", broker2.port)
        w2.write(encode_command("SET", "k", "v"))
        await w2.drain()
        with pytest.raises(RespProtocolError, match="STALE"):
            await read_reply(r2)
        w2.close()
    finally:
        await broker2.stop()


async def test_epoch_fencing_rejects_stale_primary():
    """FENCE carrying a newer epoch demotes a primary to stale; every
    subsequent mutation and publish is refused — the split-brain gate."""
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       broker.port)

        async def ask(*args):
            writer.write(encode_command(*args))
            await writer.drain()
            return await read_reply(reader)

        assert await ask("EPOCH") == ["primary", 1]
        with pytest.raises(RespProtocolError, match="STALE"):
            await ask("FENCE", 5)
        assert broker.stale
        for cmd in (("SET", "k", "v"), ("HSET", "h", "f", "v"),
                    ("DEL", "k"), ("PUBLISH", "job:snapshot", "{}")):
            with pytest.raises(RespProtocolError, match="STALE"):
                await ask(*cmd)
        assert broker._kv == {}
        # reads still answer (diagnosis stays possible on a fenced broker)
        assert await ask("GET", "k") is None
        assert await ask("EPOCH") == ["stale", 1]
        writer.close()
    finally:
        await broker.stop()


async def test_fenced_connection_epoch_must_match_broker():
    """A connection fenced at epoch N is refused once the broker moved to
    N+1 — the laggard-client half of the fencing story."""
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    try:
        r1, w1 = await asyncio.open_connection("127.0.0.1", broker.port)

        async def ask(r, w, *args):
            w.write(encode_command(*args))
            await w.drain()
            return await read_reply(r)

        assert await ask(r1, w1, "FENCE", 1) == "OK"
        assert await ask(r1, w1, "SET", "k", "v") == "OK"
        broker.epoch = 2  # a failover elsewhere moved the epoch on
        with pytest.raises(RespProtocolError, match="FENCED"):
            await ask(r1, w1, "SET", "k", "v2")
        assert broker._kv["k"] == "v"
        w1.close()
    finally:
        await broker.stop()


async def test_warm_standby_failover_end_to_end():
    """Primary dies mid-session: the endpoint-listed client fails over,
    promotes the standby (epoch bump), finds the replicated KV state
    there, and the subscriber RESUMEs the replicated ring so frames
    published around the failover arrive exactly-once."""
    from gridllm_tpu.bus.resp import _FAILOVERS

    primary = GridBusBroker()
    await primary.start("127.0.0.1", 0)
    standby = GridBusBroker(replica_of=("127.0.0.1", primary.port))
    await standby.start("127.0.0.1", 0)
    assert await _wait(lambda: standby.repl_synced, timeout_s=5)
    eps = [("127.0.0.1", primary.port), ("127.0.0.1", standby.port)]
    bus = RespBus(host=eps[0][0], port=eps[0][1], key_prefix="T:",
                  endpoints=eps)
    await bus.connect()
    got: list[str] = []

    async def handler(_ch, m):
        got.append(m)

    try:
        failovers0 = int(_FAILOVERS.value())
        await bus.subscribe("job:stream:f", handler)
        await asyncio.sleep(0.05)
        await bus.set("jobrec", "state-1")
        await bus.publish("job:stream:f", "before")
        assert await _wait(lambda: got == ["before"])
        await primary.stop()
        # first command after the kill fails over and promotes
        await bus.set("jobrec", "state-2")
        assert standby.role == "primary"
        assert standby.epoch >= 2
        assert await bus.get("jobrec") == "state-2"
        await bus.publish("job:stream:f", "after")
        assert await _wait(lambda: got == ["before", "after"], timeout_s=15)
        assert int(_FAILOVERS.value()) > failovers0
    finally:
        await bus.disconnect()
        await standby.stop()


async def test_resurrected_stale_primary_is_fenced_not_split_brained():
    """The old primary comes back (same port, pre-failover epoch) while
    clients are on the promoted standby: a client reconnecting through
    the endpoint list fences the resurrection off and lands its write on
    the real primary — the KV state never forks."""
    primary = GridBusBroker()
    await primary.start("127.0.0.1", 0)
    p0 = primary.port
    standby = GridBusBroker(replica_of=("127.0.0.1", p0))
    await standby.start("127.0.0.1", 0)
    assert await _wait(lambda: standby.repl_synced, timeout_s=5)
    eps = [("127.0.0.1", p0), ("127.0.0.1", standby.port)]
    bus = RespBus(host=eps[0][0], port=eps[0][1], key_prefix="T:",
                  endpoints=eps)
    await bus.connect()
    old = None
    try:
        await bus.set("k", "v0")
        await primary.stop()
        await bus.set("k", "v1")  # fails over; standby promoted to epoch 2
        assert standby.role == "primary" and standby.epoch >= 2
        old = GridBusBroker()
        await old.start("127.0.0.1", p0)
        # force the main connection to re-walk the endpoint list
        bus._main.writer.close()
        await asyncio.sleep(0.05)
        await bus.set("k", "v2")
        assert old.stale            # demoted by the FENCE handshake
        assert old._kv == {}        # the write never landed there
        assert standby._kv.get("T:k") == "v2"
    finally:
        await bus.disconnect()
        await standby.stop()
        if old is not None:
            await old.stop()


async def test_unsynced_standby_refuses_promotion():
    """Bring-up race guard: a standby that never reached its primary
    holds no state — FAILOVER must refuse (-NOTSYNCED) so a client that
    boots before the primary cannot promote an empty broker into a
    split brain."""
    # replica_of points at a port nobody listens on: never syncs
    standby = GridBusBroker(replica_of=("127.0.0.1", 1))
    await standby.start("127.0.0.1", 0)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       standby.port)
        writer.write(encode_command("FAILOVER", 2))
        await writer.drain()
        with pytest.raises(RespProtocolError, match="NOTSYNCED"):
            await read_reply(reader)
        assert standby.role == "replica"
        writer.close()
    finally:
        await standby.stop()


async def test_subscriber_never_gives_up(monkeypatch):
    """Satellite 1: an outage longer than reconnect_max_attempts used to
    kill the push loop permanently. Now the loop retries forever with
    capped full-jitter backoff and recovers when the broker returns."""
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    port = broker.port
    bus = RespBus(host="127.0.0.1", port=port, key_prefix="T:",
                  reconnect_max_attempts=2)
    await bus.connect()
    got: list[str] = []

    async def handler(_ch, m):
        got.append(m)

    broker2 = None
    try:
        await bus.subscribe("job:stream:n", handler)
        await asyncio.sleep(0.05)
        await broker.stop()
        # let the reconnect loop burn well past the old give-up limit
        assert await _wait(
            lambda: bus.partition_state()["degraded"], timeout_s=5)
        await asyncio.sleep(1.5)
        broker2 = GridBusBroker()
        await broker2.start("127.0.0.1", port)
        assert await _wait(
            lambda: not bus.partition_state()["degraded"], timeout_s=30)
        # subscriptions were re-issued: a fresh publish arrives
        pub = RespBus(host="127.0.0.1", port=port, key_prefix="T:")
        await pub.connect()
        await pub.publish("job:stream:n", "alive")
        assert await _wait(lambda: got == ["alive"], timeout_s=10)
        await pub.disconnect()
    finally:
        await bus.disconnect()
        if broker2 is not None:
            await broker2.stop()


# -------------------------------------------- broker-side fault injection


async def test_broker_accept_drop_site():
    """broker.accept: the TCP connect succeeds but the broker hangs up
    before reading a byte; the client's bring-up retry absorbs it."""
    faults.configure("broker.accept=@1", seed=0)
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    try:
        await bus.connect()  # first connection injected away, retried
        assert await bus.is_healthy()
        from gridllm_tpu.faults import _INJECTED

        assert int(_INJECTED.value(site="broker.accept")) >= 1
    finally:
        await bus.disconnect()
        await broker.stop()


async def test_broker_reply_reset_site():
    """broker.reply: half a reply lands, then the connection resets. The
    client must abandon the torn reply stream and recover on a fresh
    connection — never resync into the stale bytes."""
    broker = GridBusBroker()
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    try:
        await bus.set("k", "v" * 64)
        faults.configure("broker.reply=@1", seed=0)
        assert await bus.get("k") == "v" * 64  # torn reply → retry wins
        faults.reset()
        assert await bus.get("k") == "v" * 64
    finally:
        await bus.disconnect()
        await broker.stop()


async def test_broker_fsync_stall_site(tmp_path):
    """broker.fsync: the AOF fsync stalls the broker's event loop — every
    client round-trip freezes for the stall window, then completes."""
    faults.configure("broker.fsync=@1", seed=0)
    broker = GridBusBroker(aof_path=str(tmp_path / "bus.aof"))
    await broker.start("127.0.0.1", 0)
    bus = RespBus(host="127.0.0.1", port=broker.port, key_prefix="T:")
    await bus.connect()
    try:
        t0 = time.monotonic()
        await bus.set("k", "v")  # first logged write fsyncs → stalls
        assert time.monotonic() - t0 >= 0.35
        assert await bus.get("k") == "v"
    finally:
        await bus.disconnect()
        await broker.stop()


# --------------------------------------------- partition-aware liveness


class _PartitionStateBus(InMemoryBus):
    """In-memory bus with an injectable partition_state (the registry/
    scheduler holds only read this dict — no wire needed to unit them)."""

    def __init__(self):
        super().__init__()
        self.state = {"degraded": False, "since": None, "lastRejoin": None}

    def partition_state(self):
        return dict(self.state)


def test_liveness_suspended_helper():
    bus = _PartitionStateBus()
    assert not liveness_suspended(bus, 1000)
    bus.state["degraded"] = True
    bus.state["since"] = time.monotonic()
    assert liveness_suspended(bus, 1000)
    bus.state["degraded"] = False
    bus.state["lastRejoin"] = time.monotonic()
    assert liveness_suspended(bus, 1000)      # inside the rejoin grace
    bus.state["lastRejoin"] = time.monotonic() - 2.0
    assert not liveness_suspended(bus, 1000)  # grace expired


async def test_registry_suspends_death_verdicts_during_partition():
    """A worker silent through a bus partition is NOT removed; once the
    session is healthy and the grace expires, organic staleness is swept
    exactly as before."""
    bus = _PartitionStateBus()
    await bus.connect()
    cfg = SchedulerConfig(
        worker_heartbeat_timeout_ms=200,
        worker_cleanup_interval_ms=50,
        connection_monitor_interval_ms=50,
        quick_disconnect_window_ms=150,
        bus_rejoin_grace_ms=400,
    )
    registry = WorkerRegistry(bus, cfg)
    await registry.initialize()
    try:
        from gridllm_tpu.utils.types import NodeCapabilities, WorkerInfo

        info = WorkerInfo(
            workerId="part-w1",
            capabilities=NodeCapabilities(workerId="part-w1"),
            status="online", currentJobs=0)
        info.lastHeartbeat = time.time()
        registry.workers["part-w1"] = info
        # partition starts; the worker goes silent WAY past the timeout
        bus.state["degraded"] = True
        bus.state["since"] = time.monotonic()
        await asyncio.sleep(0.6)
        assert "part-w1" in registry.workers, \
            "worker pronounced dead during a bus partition"
        # session rejoins: verdicts stay held for the grace window
        bus.state["degraded"] = False
        bus.state["lastRejoin"] = time.monotonic()
        await asyncio.sleep(0.2)
        assert "part-w1" in registry.workers
        # grace expires with the worker still silent → organic removal
        assert await _wait(lambda: "part-w1" not in registry.workers,
                           timeout_s=5)
    finally:
        await registry.shutdown()
        await bus.disconnect()


async def test_orphan_sweep_deferred_during_partition():
    """An active job whose worker looks gone is NOT orphan-requeued while
    the scheduler's own bus session is degraded — and IS once the rejoin
    grace expires."""
    bus = _PartitionStateBus()
    await bus.connect()
    cfg = SchedulerConfig(
        worker_heartbeat_timeout_ms=300,
        worker_cleanup_interval_ms=10_000,   # registry stays out of it
        connection_monitor_interval_ms=10_000,
        quick_disconnect_window_ms=150,
        orphan_assign_threshold_ms=50,
        sweep_interval_ms=50,
        bus_rejoin_grace_ms=300,
    )
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    try:
        req = InferenceRequest(id="part-j1", model=MODEL, prompt="x")
        assignment = JobAssignment(jobId="part-j1", workerId="gone-w",
                                   request=req, timeout=60_000)
        scheduler.active_jobs["part-j1"] = assignment
        bus.state["degraded"] = True
        bus.state["since"] = time.monotonic()
        await asyncio.sleep(0.4)
        assert "part-j1" in scheduler.active_jobs, \
            "job orphaned during a bus partition"
        assert int(scheduler._jobs_total.value(event="orphaned")) == 0
        bus.state["degraded"] = False
        bus.state["lastRejoin"] = time.monotonic()
        assert await _wait(
            lambda: int(scheduler._jobs_total.value(event="orphaned")) == 1,
            timeout_s=5)
    finally:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


# --------------------------------------------- worker-side frame buffer


class _FlakyPublishBus(InMemoryBus):
    def __init__(self):
        super().__init__()
        self.fail_publish = False
        self.published: list[tuple[str, str]] = []

    async def publish(self, channel: str, message: str) -> int:
        if self.fail_publish:
            raise ConnectionError("bus blip (injected)")
        self.published.append((channel, message))
        return await super().publish(channel, message)


async def test_worker_buffers_stream_frames_through_bus_blip():
    """Tentpole part 3: stream-frame publishes that fail are coalesced
    (contiguous text, original absolute offset) and drained as ONE frame
    when the bus returns — the decode itself never pauses and the
    gateway's offset trim sees a seamless byte stream."""
    import json

    bus = _FlakyPublishBus()
    await bus.connect()
    svc = WorkerService(bus, {}, WorkerConfig(worker_id="buf-w"))
    req = types.SimpleNamespace(id="buf-j1", model=MODEL,
                                request_type="generate")
    try:
        await svc._flush_stream(req, "hello ", 1, 0)
        bus.fail_publish = True
        await svc._flush_stream(req, "cruel ", 2, 6)
        await svc._flush_stream(req, "dark ", 3, 12)
        assert svc._frame_buf["buf-j1"] == (6, "cruel dark ", 3)
        assert len(bus.published) == 1
        bus.fail_publish = False
        await svc._flush_stream(req, "world", 4, 17)
        assert "buf-j1" not in svc._frame_buf
        assert len(bus.published) == 2
        frame = json.loads(bus.published[1][1])
        assert frame["response"] == "cruel dark world"
        assert frame["offset"] == 6
        total = "".join(json.loads(m)["response"]
                        for _, m in bus.published)
        assert total == "hello cruel dark world"
    finally:
        await bus.disconnect()


# ------------------------------------------------- create_bus endpoints


def test_create_bus_parses_endpoint_lists():
    bus = create_bus("resp://h1:6001,h2:6002")
    assert isinstance(bus, RespBus)
    assert bus.endpoints == [("h1", 6001), ("h2", 6002)]
    bus2 = create_bus("resp://h1:6001",
                      endpoints=["resp://h1:6001", "h3:6003"])
    assert bus2.endpoints == [("h1", 6001), ("h3", 6003)]
    bus3 = create_bus("", endpoints=["resp://h9:6009"])
    assert isinstance(bus3, RespBus)
    assert bus3.endpoints == [("h9", 6009)]
    assert isinstance(create_bus(""), InMemoryBus)


# ------------------------------------------------ two-broker chaos (slow)


@pytest.mark.slow
async def test_kill_primary_broker_mid_decode_exactly_once():
    """THE acceptance criterion (ISSUE 10): the scheduler and two workers
    all speak RESP to a primary broker with a warm standby tailing it.
    The primary is killed mid-decode. Clients fail over and promote the
    standby, the gateway's subscriber RESUMEs the replicated rings, the
    worker drains its buffered frames — and the client stream is
    byte-identical to the undisturbed greedy run with ZERO healthy jobs
    orphan-requeued by the blip."""
    n = N_PREDICT
    text_ref, evals_ref = await reference_run(n=n)

    primary = GridBusBroker()
    await primary.start("127.0.0.1", 0)
    standby = GridBusBroker(replica_of=("127.0.0.1", primary.port))
    await standby.start("127.0.0.1", 0)
    assert await _wait(lambda: standby.repl_synced, timeout_s=5)
    eps = [f"resp://127.0.0.1:{primary.port}",
           f"resp://127.0.0.1:{standby.port}"]

    def ha_bus():
        return create_bus(eps[0], endpoints=eps)

    # generous worker liveness (first-compile GIL pressure over a real
    # broker starves heartbeats) but a SHORT rejoin grace so the test's
    # post-recovery assertions run quickly; orphan detection stays armed
    # so the zero-orphans assertion is meaningful
    cfg = ft_config(worker_heartbeat_timeout_ms=60_000,
                    worker_cleanup_interval_ms=500,
                    connection_monitor_interval_ms=500,
                    quick_disconnect_window_ms=30_000,
                    orphan_assign_threshold_ms=1_000,
                    bus_rejoin_grace_ms=3_000)
    bus = ha_bus()
    await bus.connect()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    workers: list[WorkerService] = []
    worker_buses = []
    try:
        for i in range(2):
            wbus = ha_bus()
            await wbus.connect()
            worker_buses.append(wbus)
            svc = WorkerService(
                wbus, {MODEL: make_engine()},
                WorkerConfig(worker_id=f"ha-w{i}",
                             heartbeat_interval_ms=150),
                stream_flush_ms=5)
            svc._snap_every = 2
            await svc.start()
            workers.append(svc)
        assert await _wait(
            lambda: len(registry.get_online_workers()) == 2, timeout_s=60)

        chunks: list[str] = []

        async def on_chunk(c) -> None:
            chunks.append(c.response)

        req = InferenceRequest(
            id=f"ha-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=PROMPT,
            stream=True,
            options={"temperature": 0, "num_predict": n},
            metadata={"requestType": "inference"})
        task = asyncio.create_task(scheduler.submit_streaming_job(
            req, on_chunk, timeout_ms=150_000))
        # deterministic mid-decode point: the snapshot watermark
        assert await _wait(
            lambda: len((scheduler._resume_snap.get(req.id) or
                         {"tokens": []})["tokens"]) >= CHAOS_TOKENS,
            timeout_s=120)
        await primary.stop()  # SIGKILL-equivalent: every client loses it

        result = await task
        assert result.success, result.error
        text = "".join(chunks)
        assert text == (result.response.response or ""), \
            "client stream diverged from the final response text"
        assert text == text_ref
        assert int(result.response.eval_count or 0) == evals_ref
        # the standby took over as primary
        assert standby.role == "primary"
        assert standby.epoch >= 2
        # zero healthy jobs orphan-requeued by the broker bounce
        assert int(scheduler._jobs_total.value(event="orphaned")) == 0
        assert int(scheduler._jobs_total.value(event="retried")) == 0
        # a second request over the promoted standby works end to end
        text2, res2 = "", None
        chunks2: list[str] = []

        async def on_chunk2(c) -> None:
            chunks2.append(c.response)

        req2 = InferenceRequest(
            id=f"ha2-{uuid.uuid4().hex[:8]}", model=MODEL, prompt=PROMPT,
            stream=True,
            options={"temperature": 0, "num_predict": n},
            metadata={"requestType": "inference"})
        res2 = await scheduler.submit_streaming_job(req2, on_chunk2,
                                                    timeout_ms=150_000)
        text2 = "".join(chunks2)
        assert res2.success, res2.error
        assert text2 == text_ref
    finally:
        for svc in workers:
            await svc.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
        for wbus in worker_buses:
            await wbus.disconnect()
        await standby.stop()
        await primary.stop()
