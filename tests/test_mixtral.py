"""Mixtral MoE numerics goldens (same two-oracle scheme as test_models.py):
HF MixtralForCausalLM on identical tiny weights, then paged prefill/decode
vs the cache-free forward."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.models import mixtral
from gridllm_tpu.models.configs import get_config
from gridllm_tpu.ops.kvcache import PagedKVCache, PageAllocator

CFG = get_config("tiny-mixtral")


@pytest.fixture(scope="module")
def params_fp32():
    return mixtral.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)


def _hf_model(params):
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import MixtralForCausalLM

    model = MixtralForCausalLM(CFG.hf_config()).eval()
    sd = {}

    def put(name, arr, transpose):
        a = np.asarray(arr, np.float32)
        sd[name] = torch.from_numpy(a.T.copy() if transpose else a.copy())

    put("model.embed_tokens.weight", params["embed"], False)
    lp = params["layers"]
    for i in range(CFG.num_layers):
        pre = f"model.layers.{i}."
        put(pre + "input_layernorm.weight", lp["attn_norm"][i], False)
        put(pre + "self_attn.q_proj.weight", lp["wq"][i], True)
        put(pre + "self_attn.k_proj.weight", lp["wk"][i], True)
        put(pre + "self_attn.v_proj.weight", lp["wv"][i], True)
        put(pre + "self_attn.o_proj.weight", lp["wo"][i], True)
        put(pre + "post_attention_layernorm.weight", lp["mlp_norm"][i], False)
        put(pre + "block_sparse_moe.gate.weight", lp["router"][i], True)
        for x in range(CFG.num_experts):
            epre = pre + f"block_sparse_moe.experts.{x}."
            put(epre + "w1.weight", lp["we_gate"][i, x], True)
            put(epre + "w2.weight", lp["we_down"][i, x], True)
            put(epre + "w3.weight", lp["we_up"][i, x], True)
    put("model.norm.weight", params["final_norm"], False)
    put("lm_head.weight", params["lm_head"], True)
    model.load_state_dict(sd)
    return model, torch


def test_forward_matches_hf(params_fp32):
    model, torch = _hf_model(params_fp32)
    tokens = np.array([[5, 17, 99, 3, 42, 7, 250, 1]], np.int32)
    ours = np.asarray(mixtral.forward(params_fp32, CFG, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_convert_hf_state_dict_roundtrip(params_fp32):
    model, _torch = _hf_model(params_fp32)
    back = mixtral.convert_hf_state_dict(CFG, model.state_dict(), dtype=jnp.float32)
    tokens = jnp.asarray([[9, 8, 7, 6, 5]], jnp.int32)
    a = mixtral.forward(params_fp32, CFG, tokens)
    b = mixtral.forward(back, CFG, tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_moe_mlp_matches_per_token_brute_force(params_fp32):
    """_moe_mlp (the production dense-weighted einsum) == an independent
    per-token loop that runs only the top-k selected experts — catches
    gating bugs (dropped renormalization, wrong combine) without torch."""
    x = jax.random.normal(jax.random.PRNGKey(2), (5, CFG.hidden_size), jnp.float32)
    lp = jax.tree.map(lambda a: a[0], params_fp32["layers"])
    got = np.asarray(mixtral._moe_mlp(CFG, None, lp, x))

    def silu(a):
        return a / (1.0 + np.exp(-a))

    xs = np.asarray(x)
    router = np.asarray(lp["router"])
    want = np.zeros_like(xs)
    for t in range(xs.shape[0]):
        logits = xs[t] @ router
        p = np.exp(logits - logits.max())
        p /= p.sum()
        top = np.argsort(-p)[: CFG.experts_per_token]
        w = p[top] / p[top].sum()
        for wi, xp in zip(w, top):
            g = xs[t] @ np.asarray(lp["we_gate"][xp])
            u = xs[t] @ np.asarray(lp["we_up"][xp])
            want[t] += wi * (silu(g) * u) @ np.asarray(lp["we_down"][xp])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_prefill_decode_match_forward(params_fp32):
    prompt = [5, 17, 99, 3, 42]
    n_gen = 5
    seq = list(prompt)
    oracle = []
    for _ in range(n_gen):
        logits = mixtral.forward(params_fp32, CFG, jnp.asarray([seq], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        oracle.append(nxt)
        seq.append(nxt)

    cache = PagedKVCache.create(
        CFG.num_layers, 16, 8, CFG.num_kv_heads, CFG.head_dim_, 4, 8,
        dtype=jnp.float32,
    )
    alloc = PageAllocator(16, 8, 8)
    slot = 1
    alloc.alloc(slot, len(prompt) + n_gen)
    row = jnp.asarray(alloc.table_row(slot), jnp.int32)
    padded = jnp.asarray(prompt + [0] * (8 - len(prompt)), jnp.int32)
    logits, cache = mixtral.prefill(
        params_fp32, CFG, padded, jnp.int32(len(prompt)), cache,
        jnp.int32(slot), row,
    )
    got = [int(jnp.argmax(logits))]
    tokens = jnp.zeros((cache.max_slots,), jnp.int32).at[slot].set(got[0])
    active = jnp.zeros((cache.max_slots,), bool).at[slot].set(True)
    for _ in range(n_gen - 1):
        logits, cache = mixtral.decode_step(params_fp32, CFG, tokens, cache, active)
        nxt = int(jnp.argmax(logits[slot]))
        got.append(nxt)
        tokens = tokens.at[slot].set(nxt)
    assert got == oracle


def test_engine_generates_with_mixtral():
    """The engine's family dispatch + fused decode works end-to-end on the
    MoE model (byte tokenizer, greedy)."""
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-mixtral", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=8, prefill_buckets=(16,), seed=0,
    ))
    res = eng.generate(GenerationRequest(
        id="m1", prompt="hello", options={"temperature": 0.0, "num_predict": 8},
    ))
    assert res.done_reason in ("length", "stop")
    assert res.eval_count > 0


def test_ragged_dispatch_matches_dense():
    """VERDICT #7: the sorted ragged-dispatch MoE form (prefill) must be
    numerically equivalent to the dense all-experts form — exact routing,
    no capacity drops — across token counts around the dispatch threshold."""
    import numpy as np

    from gridllm_tpu.models.mixtral import (
        _moe_mlp_dense,
        _moe_mlp_ragged,
        init_params,
    )

    cfg = get_config("tiny-mixtral")
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    lp = jax.tree.map(lambda a: a[0], params["layers"])  # layer 0 slice
    for t in (16, 33, 128):
        x = jax.random.normal(jax.random.PRNGKey(t), (1, t, cfg.hidden_size))
        dense = _moe_mlp_dense(cfg, lp, x)
        ragged = _moe_mlp_ragged(cfg, lp, x)
        np.testing.assert_allclose(
            np.asarray(ragged), np.asarray(dense), rtol=2e-5, atol=2e-5,
        )


def test_ragged_dispatch_through_full_model(monkeypatch):
    """Force the ragged MoE form on CPU and check the full prefill+decode
    engine path matches the dense form token-for-token (greedy)."""
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    opts = {"temperature": 0.0, "num_predict": 6}
    kw = dict(model="tiny-mixtral", max_slots=2, page_size=8, num_pages=32,
              max_pages_per_slot=8, prefill_buckets=(32,), seed=0)
    monkeypatch.setenv("GRIDLLM_MOE_RAGGED", "1")
    ragged = InferenceEngine(EngineConfig(**kw)).generate(
        GenerationRequest(id="r", prompt="hello world test", options=opts))
    monkeypatch.setenv("GRIDLLM_MOE_RAGGED", "0")
    dense = InferenceEngine(EngineConfig(**kw)).generate(
        GenerationRequest(id="d", prompt="hello world test", options=opts))
    assert ragged.token_ids == dense.token_ids


def test_meshed_ep_ragged_matches_dense(monkeypatch):
    """VERDICT r03 #7: under a mesh the MoE must not pay the 4× dense tax.
    The shard_map EP ragged dispatch must match the dense all-experts form
    numerically (fp32, 8-device CPU mesh with ep=2 × tp=2)."""
    import numpy as np
    from gridllm_tpu.models import mixtral
    from gridllm_tpu.models.configs import get_config
    from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh
    from gridllm_tpu.parallel.sharding import shard_params

    cfg = get_config("tiny-mixtral")
    mesh = build_mesh(MeshConfig(dp=2, tp=2, ep=2))
    params = mixtral.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()
          if k in ("router", "we_gate", "we_up", "we_down")}
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 32, cfg.hidden_size),
                          jnp.float32)

    dense = mixtral._moe_mlp_dense(cfg, lp, x)
    monkeypatch.setenv("GRIDLLM_MOE_RAGGED", "1")
    with mesh:
        ragged = mixtral._moe_mlp(cfg, mesh, lp, x)
    np.testing.assert_allclose(
        np.asarray(ragged), np.asarray(dense), rtol=2e-4, atol=2e-4,
    )


def test_meshed_moe_selects_ragged_for_prefill(monkeypatch):
    """Gate logic: meshed + prefill-sized tokens + divisible layout +
    ragged enabled → the EP shard_map path (not dense)."""
    from unittest import mock
    from gridllm_tpu.models import mixtral
    from gridllm_tpu.models.configs import get_config
    from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh

    cfg = get_config("tiny-mixtral")
    mesh = build_mesh(MeshConfig(dp=2, tp=2, ep=2))
    params = mixtral.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()
          if k in ("router", "we_gate", "we_up", "we_down")}
    x = jnp.zeros((1, 32, cfg.hidden_size), jnp.float32)
    monkeypatch.setenv("GRIDLLM_MOE_RAGGED", "1")
    with mock.patch.object(
        mixtral, "_moe_mlp_ragged_ep", wraps=mixtral._moe_mlp_ragged_ep
    ) as spy:
        with mesh:
            mixtral._moe_mlp(cfg, mesh, lp, x)
        assert spy.called
    # decode-sized batch stays dense under the mesh
    xs = jnp.zeros((4, cfg.hidden_size), jnp.float32)
    with mock.patch.object(mixtral, "_moe_mlp_ragged_ep") as spy2:
        with mesh:
            mixtral._moe_mlp(cfg, mesh, lp, xs)
        assert not spy2.called


def test_delegation_threads_mesh_to_llama():
    """The engine passes mesh=self.mesh to the family module; mixtral's
    delegation wrappers must forward it to llama or the meshed-kernel
    dispatch (ops.kvcache.kernel_mesh_axis) silently degrades to bare
    pallas_call under GSPMD (review finding, round 5)."""
    from unittest import mock

    from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh

    cfg = get_config("tiny-mixtral")
    mesh = build_mesh(MeshConfig(tp=2, dp=4))
    seen = {}

    def spy_decode(params, c, tokens, cache, active, mlp=None, mesh=None):
        seen["decode"] = mesh
        raise RuntimeError("stop")

    def spy_chunk(params, c, tokens, start, length, cache, slot, row,
                  mlp=None, mesh=None, embeds=None):
        seen["chunk"] = mesh
        raise RuntimeError("stop")

    with mock.patch.object(mixtral.llama, "decode_step", spy_decode):
        try:
            mixtral.decode_step(None, cfg, None, None, None, mesh=mesh)
        except RuntimeError:
            pass
    with mock.patch.object(mixtral.llama, "prefill_chunk", spy_chunk):
        try:
            mixtral.prefill_chunk(None, cfg, None, None, None, None, None,
                                  None, mesh=mesh)
        except RuntimeError:
            pass
    assert seen["decode"] is mesh
    assert seen["chunk"] is mesh
