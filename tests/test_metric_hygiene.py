"""Metric-hygiene lint (ISSUE 2 satellite): every instrument the framework
registers must (a) carry a ``gridllm_``-prefixed lowercase snake_case name
and (b) never use an unbounded-cardinality label (per-request/job/trace
ids) — one bad label turns a scrape into a memory leak and kills the TSDB.

The check is runtime, not grep: it builds a full gateway stack (which
registers every scheduler/gateway/SLO/watchdog instrument on the instance
registry) and imports the engine/worker/bus modules (which register the
process-global instruments), then lints BOTH registries' actual metrics.
New instruments are covered automatically; the suite fails on violation.
"""

import re

from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.obs import default_registry
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config

from .helpers import fast_config

NAME_RE = re.compile(r"^gridllm_[a-z][a-z0-9_]*$")

# labels whose value space grows with traffic — forbidden on any instrument
FORBIDDEN_LABELS = {
    "request_id", "requestid", "job_id", "jobid", "id", "trace_id",
    "traceid", "span_id", "prompt", "text", "user", "session",
}


def _lint(registry, origin: str) -> list[str]:
    problems = []
    with registry._lock:
        metrics = list(registry._metrics.values())
    assert metrics, f"{origin}: no metrics registered — lint is vacuous"
    for m in metrics:
        if not NAME_RE.match(m.name):
            problems.append(f"{origin}: {m.name!r} violates "
                            "gridllm_[a-z0-9_]+ naming")
        for label in m.labelnames:
            if label.lower() in FORBIDDEN_LABELS:
                problems.append(f"{origin}: {m.name!r} carries unbounded-"
                                f"cardinality label {label!r}")
        if not m.help:
            problems.append(f"{origin}: {m.name!r} has no help text")
    return problems


async def test_all_registered_metrics_are_hygienic():
    # imports register the process-global (engine/worker/bus/ops) series
    import gridllm_tpu.engine.engine  # noqa: F401
    import gridllm_tpu.ops.kvcache  # noqa: F401
    import gridllm_tpu.worker.service  # noqa: F401

    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    create_app(bus, registry, scheduler, Config(scheduler=cfg))
    try:
        problems = _lint(scheduler.metrics, "scheduler-registry")
        problems += _lint(default_registry(), "default-registry")
        assert not problems, "\n".join(problems)
    finally:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


def test_lint_catches_violations():
    """The lint itself must fail on a bad name and a bad label — otherwise
    a regression in the checker silently waives the whole policy."""
    from gridllm_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("gridllm_good_total", "Fine.", ("model",))
    reg.counter("BadName_total", "Bad name.")
    reg.counter("gridllm_leaky_total", "Bad label.", ("job_id",))
    problems = _lint(reg, "t")
    assert len(problems) == 2
    assert any("BadName_total" in p for p in problems)
    assert any("job_id" in p for p in problems)
