"""Metric-hygiene lint, runtime half (ISSUE 2 satellite; folded into the
analysis rule registry by ISSUE 8): the POLICY — naming regex, forbidden
labels, help text — lives in ``gridllm_tpu/analysis/rules/metric_hygiene``
and is shared with the static ``python -m gridllm_tpu.analysis`` rule.
This suite applies it at runtime: build a full gateway stack (registering
every scheduler/gateway/SLO/watchdog instrument on the instance registry),
import the engine/worker/bus modules (process-global instruments), then
lint BOTH registries' actual metrics — dynamically constructed
instruments included, which the static rule cannot see.
"""

from gridllm_tpu.analysis.rules.metric_hygiene import (
    FORBIDDEN_LABELS,
    NAME_RE,
    lint_registry,
)
from gridllm_tpu.bus.memory import InMemoryBus
from gridllm_tpu.gateway.app import create_app
from gridllm_tpu.obs import default_registry
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config

from .helpers import fast_config


async def test_all_registered_metrics_are_hygienic():
    # imports register the process-global (engine/worker/bus/ops) series
    import gridllm_tpu.engine.engine  # noqa: F401
    import gridllm_tpu.ops.kvcache  # noqa: F401
    import gridllm_tpu.worker.service  # noqa: F401

    bus = InMemoryBus(key_prefix="G:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    create_app(bus, registry, scheduler, Config(scheduler=cfg))
    try:
        problems = lint_registry(scheduler.metrics, "scheduler-registry")
        problems += lint_registry(default_registry(), "default-registry")
        assert not problems, "\n".join(problems)
    finally:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


def test_lint_catches_violations():
    """The shared lint must fail on a bad name and a bad label — otherwise
    a regression in the checker silently waives the whole policy (static
    AND runtime, now that both halves import it from the rule module)."""
    from gridllm_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("gridllm_good_total", "Fine.", ("model",))
    reg.counter("BadName_total", "Bad name.")
    reg.counter("gridllm_leaky_total", "Bad label.", ("job_id",))
    problems = lint_registry(reg, "t")
    assert len(problems) == 2
    assert any("BadName_total" in p for p in problems)
    assert any("job_id" in p for p in problems)
    # the policy constants are importable and sane (used by both halves)
    assert NAME_RE.match("gridllm_good_total")
    assert "job_id" in FORBIDDEN_LABELS


def test_empty_registry_is_vacuous_not_clean():
    from gridllm_tpu.obs import MetricsRegistry

    problems = lint_registry(MetricsRegistry(), "empty")
    assert problems and "vacuous" in problems[0]
