"""Speculative decoding tests (ISSUE 5): greedy spec-on vs spec-off
token-stream parity, mid-span stop-sequence truncation, KV
rollback-to-length units (page-boundary crossing + ref-counted cached
pages), n-gram drafter units, and zero steady-state recompiles with
speculation armed (reusing the PR-4 tripwire harness)."""

import jax.numpy as jnp
import numpy as np
import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.obs.perf import recompile_totals
from gridllm_tpu.ops.kvcache import (
    PagedKVCache,
    PageAllocator,
    gather_kv,
    rollback_to_length,
    write_decode_all,
    write_multi_all,
)
from gridllm_tpu.ops.spec import NgramDrafter, make_drafter

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
)

# repetitive prompt + penalty off: greedy output settles into a cycle the
# n-gram drafter can extend, so parity tests exercise REAL acceptance
REP_PROMPT = "ab ab ab ab ab ab"
REP_OPTS = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 24}


@pytest.fixture(scope="module")
def spec_on():
    return InferenceEngine(EngineConfig(**TINY, spec_decode=True, spec_k=4))


@pytest.fixture(scope="module")
def spec_off():
    return InferenceEngine(EngineConfig(**TINY, spec_decode=False))


# ---------------------------------------------------------------------------
# drafter units
# ---------------------------------------------------------------------------


def test_drafter_matches_most_recent_occurrence():
    d = NgramDrafter(max_n=3, min_n=1)
    #        0  1  2  3  4  5  6  7
    ids = [1, 2, 3, 9, 1, 2, 3, 5, 1, 2, 3]
    # suffix [1,2,3] matched at its MOST RECENT earlier occurrence (idx 4)
    assert d.draft(ids, 4) == [5, 1, 2, 3]


def test_drafter_prefers_longest_suffix():
    d = NgramDrafter(max_n=3, min_n=1)
    # last-2 [7, 8] occurs earlier (→ 9); last-1 [8] also occurs (→ 1);
    # the longer match wins
    ids = [7, 8, 9, 8, 1, 7, 8]
    assert d.draft(ids, 2) == [9, 8]


def test_drafter_no_match_and_bounds():
    d = NgramDrafter(max_n=3, min_n=1)
    assert d.draft([1, 2, 3, 4], 4) == []      # no recurring suffix
    assert d.draft([5], 4) == []               # too short
    assert d.draft([1, 2, 1, 2], 0) == []      # k = 0
    assert d.draft([1, 2, 1], 2) == [2, 1]     # continuation truncated at end


def test_drafter_lookback_bounds_scan():
    far = [1, 2, 3] + [9] * 50 + [1, 2]
    assert NgramDrafter(max_n=2, min_n=2).draft(far, 1) == [3]
    assert NgramDrafter(max_n=2, min_n=2, lookback=10).draft(far, 1) == []


def test_drafter_factory_env(monkeypatch):
    monkeypatch.setenv("GRIDLLM_SPEC_NGRAM_MAX", "7")
    d = make_drafter()
    assert isinstance(d, NgramDrafter) and d.max_n == 7
    with pytest.raises(ValueError):
        make_drafter("nope")


# ---------------------------------------------------------------------------
# greedy parity: spec-on streams are byte-identical to spec-off
# ---------------------------------------------------------------------------


def test_greedy_parity_repetitive_with_real_acceptance(spec_on, spec_off):
    r_off = spec_off.generate(
        GenerationRequest(id="p0", prompt=REP_PROMPT, options=dict(REP_OPTS)))
    r_on = spec_on.generate(
        GenerationRequest(id="p1", prompt=REP_PROMPT, options=dict(REP_OPTS)))
    assert r_on.token_ids == r_off.token_ids
    assert r_on.text == r_off.text
    # the parity must not be vacuous: the repetitive stream really
    # speculated and really had drafts accepted
    assert r_on.spec_proposed > 0
    assert r_on.spec_accepted > 0
    assert r_off.spec_proposed == 0  # spec off truly off


def test_greedy_parity_with_repeat_penalty(spec_on, spec_off):
    # default repeat_penalty 1.1: the accept path's in-scan window/counts
    # bookkeeping must track the sequential path's exactly
    opts = {"temperature": 0.0, "num_predict": 16}
    for prompt in ("hello world hello world", "xyzzy", REP_PROMPT):
        r_off = spec_off.generate(
            GenerationRequest(id="q0", prompt=prompt, options=dict(opts)))
        r_on = spec_on.generate(
            GenerationRequest(id="q1", prompt=prompt, options=dict(opts)))
        assert r_on.token_ids == r_off.token_ids, prompt


def test_greedy_parity_concurrent_batch(spec_on, spec_off):
    """Batched spec streams (ragged per-slot accept lengths) still equal
    their solo spec-off outputs."""
    opts = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 10}
    prompts = ("aa aa aa aa", "bc bc bc bc", "hello")
    solo = {
        p: spec_off.generate(
            GenerationRequest(id=p, prompt=p, options=dict(opts))).token_ids
        for p in prompts
    }
    results = {}

    def mk(p):
        def cb(d, done, res):
            if done:
                results[p] = res.token_ids
        return cb

    for p in prompts:
        spec_on.submit(GenerationRequest(
            id=p, prompt=p, options=dict(opts), on_chunk=mk(p)))
    while len(results) < len(prompts):
        spec_on.step()
    assert results == solo


def test_sampled_seeded_deterministic(spec_on):
    """Sampled spec streams are not byte-equal to spec-off (documented:
    the DISTRIBUTION is preserved via rejection sampling) but must stay
    deterministic per (seed, prompt)."""
    opts = {"temperature": 0.9, "seed": 7, "num_predict": 12}
    r1 = spec_on.generate(
        GenerationRequest(id="s1", prompt=REP_PROMPT, options=dict(opts)))
    r2 = spec_on.generate(
        GenerationRequest(id="s2", prompt=REP_PROMPT, options=dict(opts)))
    assert r1.token_ids == r2.token_ids


# ---------------------------------------------------------------------------
# stop sequences / EOS inside an accepted span
# ---------------------------------------------------------------------------


def test_stop_sequence_mid_span_truncates(spec_on, spec_off):
    base = spec_off.generate(GenerationRequest(
        id="b0", prompt=REP_PROMPT, options=dict(REP_OPTS)))
    if len(base.text) < 8:
        pytest.skip("greedy output too short to carve a stop from")
    # a stop buried deep in the stream: by then the spec engine is inside
    # accepted spans, so the stop must truncate MID-span
    stop = base.text[5:8]
    expect = spec_off.generate(GenerationRequest(
        id="b1", prompt=REP_PROMPT,
        options={**REP_OPTS, "stop": [stop]}))
    chunks = []
    got = spec_on.generate(GenerationRequest(
        id="b2", prompt=REP_PROMPT, options={**REP_OPTS, "stop": [stop]},
        on_chunk=lambda d, done, r: chunks.append(d)))
    assert got.text == expect.text
    assert got.token_ids == expect.token_ids
    assert got.done_reason == "stop"
    assert stop not in got.text
    assert "".join(chunks) == got.text  # nothing past the stop ever emitted


def test_num_predict_exact_under_spec(spec_on):
    res = spec_on.generate(GenerationRequest(
        id="np", prompt=REP_PROMPT,
        options={**REP_OPTS, "num_predict": 7}))
    assert res.eval_count == 7
    assert res.done_reason == "length"


# ---------------------------------------------------------------------------
# KV multi-token append + rollback-to-length units
# ---------------------------------------------------------------------------


def _mk_cache(num_pages=8, page_size=4, slots=2, max_pages=4, kvh=2, d=4):
    return PagedKVCache.create(1, num_pages, page_size, kvh, d, slots,
                               max_pages, dtype=jnp.float32)


def _rows(t, kvh=2, d=4, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(1, 1, t, kvh, d), jnp.float32)


def test_write_multi_matches_sequential_decode_writes():
    """write_multi_all(T tokens at once) == T write_decode_all calls."""
    cache_a, cache_b = _mk_cache(), _mk_cache()
    table = jnp.asarray([[0, 1, 2, -1], [3, 4, -1, -1]], jnp.int32)
    active = jnp.asarray([True, True])
    t = 3
    k_new = jnp.concatenate([_rows(t, seed=1), _rows(t, seed=2)], axis=1)
    v_new = jnp.concatenate([_rows(t, seed=3), _rows(t, seed=4)], axis=1)
    base = jnp.asarray([2, 5], jnp.int32)  # slot 1 crosses its page boundary
    positions = base[:, None] + jnp.arange(t)[None]
    ka, va = write_multi_all(cache_a.k, cache_a.v, k_new, v_new, table,
                             positions, active, cache_a.page_size)
    kb, vb = cache_b.k, cache_b.v
    for i in range(t):
        kb, vb = write_decode_all(kb, vb, k_new[:, :, i], v_new[:, :, i],
                                  table, positions[:, i], active,
                                  cache_b.page_size)
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_write_multi_drops_inactive_and_past_capacity():
    cache = _mk_cache()
    table = jnp.asarray([[0, 1, 2, 3], [4, 5, -1, -1]], jnp.int32)
    t = 4
    k_new = jnp.concatenate([_rows(t, seed=1), _rows(t, seed=2)], axis=1)
    # slot 0 inactive; slot 1 writes 6..9 but owns 2 pages (capacity 8):
    # positions 8, 9 must drop
    positions = jnp.asarray([[0, 1, 2, 3], [6, 7, 8, 9]], jnp.int32)
    k, v = write_multi_all(cache.k, cache.v, k_new, k_new, table, positions,
                           jnp.asarray([False, True]), cache.page_size)
    np.testing.assert_array_equal(np.asarray(k[0, 0]), 0.0)  # inactive slot
    row1, _ = gather_kv(k[0], v[0], table[1], cache.page_size)
    np.testing.assert_array_equal(np.asarray(row1[:6]), 0.0)  # untouched
    assert np.any(np.asarray(row1[6:8]) != 0)                 # written
    # past-capacity positions dropped, page 0 (another slot's!) untouched
    np.testing.assert_array_equal(np.asarray(k[0, 0]), 0.0)


def test_rollback_across_page_boundary_restores_contract():
    """Optimistic K+1 write crossing a page boundary, rollback to the
    accepted length, then the 'true' continuation overwrites the junk —
    the surviving rows must equal a cache that never saw the junk."""
    cache_a, cache_b = _mk_cache(), _mk_cache()
    table = jnp.asarray([[0, 1, 2, -1], [-1, -1, -1, -1]], jnp.int32)
    active = jnp.asarray([True, False])
    ps = cache_a.page_size  # 4
    base = 2  # span 2..6 crosses the page-0 → page-1 boundary
    cache_a = PagedKVCache(k=cache_a.k, v=cache_a.v,
                           page_table=cache_a.page_table,
                           lengths=jnp.asarray([base, 0], jnp.int32),
                           page_size=ps)
    t = 5
    junk_k = jnp.concatenate([_rows(t, seed=10), _rows(t, seed=11)], axis=1)
    positions = cache_a.lengths[:, None] + jnp.arange(t)[None]
    ka, va = write_multi_all(cache_a.k, cache_a.v, junk_k, junk_k, table,
                             positions, active, ps)
    cache_a = PagedKVCache(k=ka, v=va, page_table=cache_a.page_table,
                           lengths=cache_a.lengths, page_size=ps)
    accepted = 2  # keep rows at 2, 3; rows 4..6 are rejected junk
    cache_a = rollback_to_length(
        cache_a, jnp.asarray([base + accepted, 0], jnp.int32))
    assert int(cache_a.lengths[0]) == base + accepted
    # true continuation overwrites the junk region (positions 4..6)
    cont_k = jnp.concatenate([_rows(3, seed=20), _rows(3, seed=21)], axis=1)
    cont_pos = cache_a.lengths[:, None] + jnp.arange(3)[None]
    ka, va = write_multi_all(cache_a.k, cache_a.v, cont_k, cont_k, table,
                             cont_pos, active, ps)
    # reference cache: the accepted rows + continuation, junk never written
    kb, vb = write_multi_all(cache_b.k, cache_b.v, junk_k[:, :, :accepted],
                             junk_k[:, :, :accepted], table,
                             positions[:, :accepted], active, ps)
    kb, vb = write_multi_all(kb, vb, cont_k, cont_k, table, cont_pos,
                             active, ps)
    n_valid = base + accepted + 3
    rows_a, _ = gather_kv(ka[0], va[0], table[0], ps)
    rows_b, _ = gather_kv(kb[0], vb[0], table[0], ps)
    np.testing.assert_array_equal(np.asarray(rows_a[:n_valid]),
                                  np.asarray(rows_b[:n_valid]))


def test_rollback_never_touches_refcount_shared_pages():
    """A warm slot sharing ref-counted prefix-cache pages (PR 3): verify
    writes + rollback live strictly past the prompt, so the shared pages'
    bytes are identical before and after."""
    ps = 4
    alloc = PageAllocator(8, ps, 4, cache_pages=-1)
    prompt = list(range(10))  # 2 full pages (8 tokens) registrable
    alloc.alloc(0, len(prompt) + 2)
    alloc.free(0, prompt)  # registers pages for tokens 0..7
    cached = alloc.match_prefix(1, prompt)
    assert cached == 8
    row = alloc.table_row(1)
    shared = row[:2]
    assert all(alloc._refs[p] == 1 for p in shared)  # pinned by slot 1
    alloc.alloc(1, len(prompt) + 2)

    cache = _mk_cache()
    table = jnp.asarray([row, [-1] * 4], jnp.int32)
    # pretend the shared pages hold real prefix KV
    marker = jnp.ones_like(cache.k[:, 0]) * 7.5
    k = cache.k.at[:, shared[0]].set(marker).at[:, shared[1]].set(marker * 2)
    cache = PagedKVCache(k=k, v=k, page_table=cache.page_table,
                         lengths=jnp.asarray([len(prompt), 0], jnp.int32),
                         page_size=ps)
    before_k = np.asarray(cache.k[:, shared])
    # speculative span at positions >= prompt_len, then rollback
    t = 3
    spec_k = jnp.concatenate([_rows(t, seed=30), _rows(t, seed=31)], axis=1)
    positions = cache.lengths[:, None] + jnp.arange(t)[None]
    ka, va = write_multi_all(cache.k, cache.v, spec_k, spec_k, table,
                             positions, jnp.asarray([True, False]), ps)
    cache = rollback_to_length(
        PagedKVCache(k=ka, v=va, page_table=cache.page_table,
                     lengths=cache.lengths, page_size=ps),
        jnp.asarray([len(prompt) + 1, 0], jnp.int32))
    np.testing.assert_array_equal(np.asarray(cache.k[:, shared]), before_k)


# ---------------------------------------------------------------------------
# recompile tripwire: speculation armed = zero steady recompiles
# ---------------------------------------------------------------------------


def test_zero_steady_recompiles_with_spec_armed(spec_on):
    """Varying batch fill, draft counts, and ragged accept lengths all run
    through ONE compiled verify program — no steady-state recompiles once
    the tripwire is armed (the PR-4 harness contract, now for spec)."""
    assert spec_on.perf.armed  # fixtures above completed requests
    before = recompile_totals()["steady"]
    opts = {"temperature": 0.0, "repeat_penalty": 1.0, "num_predict": 6}
    done = []
    for n in (1, 2, 3):
        for i in range(n):
            spec_on.submit(GenerationRequest(
                id=f"fill{n}-{i}", prompt=REP_PROMPT if i % 2 else "hello",
                options=dict(opts),
                on_chunk=lambda d, fin, res: fin and done.append(res)))
        target = sum((1, 2, 3)[: (1, 2, 3).index(n) + 1])
        while len(done) < target:
            spec_on.step()
    assert recompile_totals()["steady"] == before


def test_spec_stats_flow_to_result_and_state(spec_on):
    res = spec_on.generate(GenerationRequest(
        id="st", prompt=REP_PROMPT, options=dict(REP_OPTS)))
    assert res.spec_proposed >= res.spec_accepted >= 0
    state = spec_on.batch_state()
    assert state["specDecode"]["k"] == 4
    assert state["specDecode"]["steps"] > 0
    assert state["specDecode"]["emitted"] >= state["specDecode"]["accepted"]


def test_spec_env_defaults(monkeypatch):
    """GRIDLLM_SPEC_DECODE defaults on; =0 disables; GRIDLLM_SPEC_K sets
    the depth; EngineConfig overrides env."""
    eng = InferenceEngine(EngineConfig(**TINY))
    assert eng._spec_k == 4  # default-on, default depth
    monkeypatch.setenv("GRIDLLM_SPEC_DECODE", "0")
    assert InferenceEngine(EngineConfig(**TINY))._spec_k == 0
    monkeypatch.setenv("GRIDLLM_SPEC_DECODE", "1")
    monkeypatch.setenv("GRIDLLM_SPEC_K", "2")
    assert InferenceEngine(EngineConfig(**TINY))._spec_k == 2
    assert InferenceEngine(
        EngineConfig(**TINY, spec_decode=False))._spec_k == 0
