"""Tiered KV cache (ISSUE 11): host-RAM spill, int8 KV, suspend-to-host.

Covers the spill codec round trip (page-boundary straddles, fp/int8),
allocator spill/restore hooks, the engine-level spill→restore path under
eviction pressure (tier-on vs tier-off greedy streams byte-identical on
the raw spill path), refcount pinning (a shared page never leaves HBM
mid-decode), int8 KV greedy-parity-within-tolerance, fault-injected
restore failure degrading to a cold prefill, suspend-to-host parking,
and the scheduler preemption round trip.
"""

import asyncio
import time
import uuid

import numpy as np
import pytest

from gridllm_tpu import faults
from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.ops.kvcache import PageAllocator, QuantPages, quantize_kv_rows
from gridllm_tpu.ops.kvtier import (
    HostKVTier,
    dequantize_page,
    quantize_page,
    quantize_rows_np,
)
from gridllm_tpu.transfer.wire import (
    Assembler,
    build_spill_header,
    iter_chunks,
    spill_arrays,
)

TINY = dict(
    model="tiny-llama",
    max_slots=2,
    page_size=16,
    num_pages=16,
    max_pages_per_slot=12,
    prefill_buckets=(32, 64),
    prefill_chunk=16,
    seed=7,
)

SHARED = "Policy clause: the quick brown fox jumps over the lazy dog. " * 3
LONG = ("X" * 150) + " overflow tail"


def _gen(prompt, rid=None, n=8, **opts):
    return GenerationRequest(
        id=rid or uuid.uuid4().hex,
        prompt=prompt,
        options={"temperature": 0, "num_predict": n, **opts},
    )


def _engine(**kw):
    cfg = dict(TINY)
    cfg.update(kw)
    return InferenceEngine(EngineConfig(**cfg))


# ---------------------------------------------------------------------------
# spill codec (wire)
# ---------------------------------------------------------------------------

def _page(seed=0, L=2, ps=8, kvh=2, d=16, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(L, 1, ps, kvh, d)).astype(dtype)


def test_spill_codec_raw_round_trip():
    k, v = _page(0), _page(1)
    header, payload = build_spill_header("ab" * 16, "m", k, v)
    assert header["kind"] == "kv-spill" and header["quant"] is None
    # chunk framing: reassemble through the SAME Assembler the migration
    # wire uses, chunk-by-chunk with crc checks
    asm = Assembler(dict(header))
    for _seq, frame in iter_chunks(header, payload):
        asm.feed(frame)
    k2, v2, ks, vs = spill_arrays(header, asm.payload())
    assert np.array_equal(k2, k) and np.array_equal(v2, v)
    assert ks is None and vs is None


def test_spill_codec_int8_page_bound():
    k, v = _page(2), _page(3)
    kq, ksc = quantize_page(k)
    vq, vsc = quantize_page(v)
    header, payload = build_spill_header(
        "cd" * 16, "m", kq, vq, k_scale=ksc, v_scale=vsc, quant="int8-page")
    asm = Assembler(dict(header))
    asm.feed_raw(payload)
    k2, v2, ks2, vs2 = spill_arrays(header, asm.payload())
    kd = dequantize_page(k2, ks2)
    # symmetric per-(layer, page) scale: worst case half a quant step
    step = ks2.max()
    assert np.abs(kd - k).max() <= step * 0.5 + 1e-6
    vd = dequantize_page(v2, vs2)
    assert np.abs(vd - v).max() <= vs2.max() * 0.5 + 1e-6


def test_spill_codec_rejects_corruption():
    k, v = _page(4), _page(5)
    header, payload = build_spill_header("ee" * 16, "m", k, v)
    asm = Assembler(dict(header))
    asm.feed_raw(payload[:-4] + b"\x00\x00\x00\x01")
    from gridllm_tpu.transfer.wire import WireError

    with pytest.raises(WireError):
        asm.payload()


def test_tier_lru_eviction_and_promotion():
    k, v = _page(6), _page(7)
    # capacity for ~2 raw pages
    one = len(build_spill_header("00" * 16, "m", k, v)[1])
    t = HostKVTier(one * 2 + 10, model="m", spill_int8=False)
    assert t.put(b"a" * 16, k, v)
    assert t.put(b"b" * 16, k, v)
    assert t.get(b"a" * 16) is not None  # promote a to MRU
    assert t.put(b"c" * 16, k, v)       # evicts b (LRU)
    assert b"b" * 16 not in t and b"a" * 16 in t and b"c" * 16 in t
    assert t.evictions == 1
    # a page larger than the whole tier is refused, not wedged
    small = HostKVTier(16, model="m")
    assert not small.put(b"d" * 16, k, v)


# ---------------------------------------------------------------------------
# int8 quantization primitives
# ---------------------------------------------------------------------------

def test_quantize_kv_rows_bound():
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 2, 16)),
                    jnp.float32)
    q, s = quantize_kv_rows(x)
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None, None]
    # per-row symmetric quant: error bounded by half a step per element
    assert np.abs(deq - np.asarray(x)).max() <= float(np.asarray(s).max()) / 2 + 1e-6
    qn, sn = quantize_rows_np(np.asarray(x))
    assert np.array_equal(np.asarray(q), qn)
    assert np.allclose(np.asarray(s), sn)


# ---------------------------------------------------------------------------
# allocator hooks
# ---------------------------------------------------------------------------

def test_allocator_spill_and_restore_hooks():
    a = PageAllocator(4, 4, 4, cache_pages=-1)
    spilled: dict[bytes, int] = {}
    a.spill_sink = lambda page, key: spilled.__setitem__(key, page)

    ids = list(range(12))  # 3 full pages
    a.alloc(0, 12)
    a.free(0, ids)
    assert a.cached_pages == 3
    # a fresh allocation bigger than free evicts from the LRU → spills
    a.alloc(1, 16)
    assert len(spilled) >= 3  # every registered eviction offered to the sink
    a.free(1)

    # restore_source: a chain miss consults it; returning a registered
    # page id lets the match keep walking
    b = PageAllocator(8, 4, 4, cache_pages=-1)
    store: dict[bytes, bool] = {}

    def restore(key):
        store[key] = True
        page = b.claim_page()
        if page is None:
            return None
        b.register_claimed(page, key)
        b.unpin_pages([page])
        return b.peek_key(key)

    b.restore_source = restore
    matched = b.match_prefix(0, ids)
    assert matched == 8  # 2 full pages (the last token is never matched)
    assert len(store) == 2
    b.free(0)


def test_pinned_shared_page_never_evicts():
    """A page pinned by a live request is not in the LRU: eviction (and
    therefore spill-then-free) can never touch it — allocation fails
    instead."""
    a = PageAllocator(4, 4, 4, cache_pages=-1)
    spilled = []
    a.spill_sink = lambda page, key: spilled.append(page)
    a.alloc(0, 16)  # all 4 pages
    a.free(0, list(range(16)))
    # slot 1 matches + pins 3 cached pages (the last full page stays
    # unpinned — the match always stops short of the final token)
    matched = a.match_prefix(1, list(range(16)))
    assert matched == 12
    owned = a.alloc(1, 16)
    assert owned is not None
    pinned = owned[:3]
    # the fresh 4th page legitimately evicted (and spilled) the UNPINNED
    # cached page; the pinned shares must never appear in the spill log
    assert set(spilled).isdisjoint(pinned)
    # slot 2 wants pages: nothing reclaimable (all pinned) → None, and
    # still no pinned page ever spilled
    assert a.alloc(2, 8) is None
    assert set(spilled).isdisjoint(pinned)


# ---------------------------------------------------------------------------
# engine: spill → restore under eviction pressure
# ---------------------------------------------------------------------------

def _drive_pressure(engine):
    """Warm request, long-request eviction storm, same request again.
    Returns (warm result, post-eviction result)."""
    warm = engine.generate(_gen(SHARED + "Q:", rid="warm"))
    engine.generate(_gen(LONG, rid="long"))
    post = engine.generate(_gen(SHARED + "Q:", rid="post"))
    return warm, post


def test_spill_restore_round_trip_byte_identical():
    """Raw-spill tier on vs tier off: the long request evicts the warm
    prefix either way; with the tier the post request restores it (warm,
    byte-identical), without it the prefill is cold — and the STREAMS
    are byte-identical across all four runs (greedy fp16 path)."""
    on = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False)
    warm_on, post_on = _drive_pressure(on)
    st = on.host_tier.stats()
    assert on.alloc.evictions > 0
    assert st["spills"] > 0
    assert st["restores"] > 0, st
    assert post_on.cached_tokens > 0  # warm again after the storm
    on.stop()

    off = _engine(kv_host_bytes=0)
    warm_off, post_off = _drive_pressure(off)
    assert off.host_tier is None
    assert post_off.cached_tokens == 0  # the regression the tier fixes
    off.stop()

    assert post_on.text == post_off.text == warm_on.text == warm_off.text
    assert post_on.token_ids == post_off.token_ids


def test_int8_spill_restore_completes():
    """int8 spill (default): restored streams complete and stay warm;
    exact bytes are only promised by the raw spill path."""
    e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=True)
    _warm, post = _drive_pressure(e)
    assert e.host_tier.stats()["restores"] > 0
    assert post.cached_tokens > 0
    assert post.done_reason in ("stop", "length")
    e.stop()


def test_restore_page_boundary_straddle():
    """A prompt whose cached prefix ends mid-page restores only the full
    pages (the straddling tail is recomputed), and the restored prefix
    still yields a byte-identical stream."""
    e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False)
    # 40-token prompt: 2 full pages (page_size 16) + 8-token straddle
    prompt = "S" * 40
    r1 = e.generate(_gen(prompt, rid="s1", n=6))
    e.generate(_gen(LONG, rid="evict", n=4))
    r2 = e.generate(_gen(prompt, rid="s2", n=6))
    assert r2.cached_tokens == 32  # full pages only
    assert r2.text == r1.text and r2.token_ids == r1.token_ids
    e.stop()


def test_injected_restore_failure_degrades_to_cold():
    """kvtier.restore fault: the admission falls back to a cold prefill —
    correct stream, counted failure, never a wedged request."""
    e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False)
    try:
        warm, _post = _drive_pressure(e)
        # arm the fault AFTER the pressure run so the next restore fails
        e.generate(_gen(LONG + " again", rid="evict2"))
        faults.configure("kvtier.restore=1.0")
        r = e.generate(_gen(SHARED + "Q:", rid="cold"))
        assert r.cached_tokens == 0            # cold prefill, counted miss
        assert r.text == warm.text             # stream still correct
        assert e.host_tier.stats()["restoreFailures"] > 0
    finally:
        faults.reset()
        e.stop()


def test_injected_spill_failure_loses_page_quietly():
    """kvtier.spill fault: the evicted page is simply absent from the
    tier — the later match is a tier miss, not an error."""
    faults.configure("kvtier.spill=1.0")
    try:
        e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False)
        _warm, post = _drive_pressure(e)
        st = e.host_tier.stats()
        assert st["spills"] == 0 and st["restores"] == 0
        assert st["misses"] > 0
        assert post.cached_tokens == 0
        assert post.done_reason in ("stop", "length")
        e.stop()
    finally:
        faults.reset()


def test_lane_padded_pool_spill_restore(monkeypatch):
    """Lane-padded pools (interpret mode + GRIDLLM_POOL_PAD) spill the
    UNPADDED model head dim and re-pad on restore — same contract as the
    migration wire."""
    monkeypatch.setenv("GRIDLLM_PALLAS", "interpret")
    monkeypatch.setenv("GRIDLLM_POOL_PAD", "1")
    monkeypatch.setenv("GRIDLLM_RAGGED_ATTN", "0")
    from gridllm_tpu.ops.kvcache import _env_mode

    _env_mode.cache_clear()
    try:
        e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False,
                    num_pages=12, max_slots=1)
        assert e.cache.k.shape[-1] == 128  # padded pool (d=16 model)
        prompt = "P" * 48
        r1 = e.generate(_gen(prompt, rid="lp1", n=4))
        e.generate(_gen("Y" * 150, rid="lpe", n=2))
        r2 = e.generate(_gen(prompt, rid="lp2", n=4))
        assert e.host_tier.stats()["restores"] > 0
        assert r2.cached_tokens > 0
        assert r2.text == r1.text
        e.stop()
    finally:
        _env_mode.cache_clear()


# ---------------------------------------------------------------------------
# int8 resident KV pool
# ---------------------------------------------------------------------------

def test_int8_pool_layout_and_accounting():
    e = _engine(kv_int8=True, num_pages=32)
    assert isinstance(e.cache.k, QuantPages)
    alloc = e.memory_arrays()["alloc"]
    assert alloc["kvInt8"] is True
    # int8 + f32-per-row scales: well under half the bf16 pool bytes
    fp = _engine(num_pages=32)
    assert (e.cache.k.nbytes + e.cache.v.nbytes) < (
        fp.cache.k.nbytes + fp.cache.v.nbytes)
    fp.stop()
    e.stop()


def test_int8_attention_close_to_fp():
    """ops-level tolerance contract: decode attention over an int8 pool
    holding (the quantization of) the same content as an fp pool stays
    within the per-row quant error's reach of the fp output."""
    import jax.numpy as jnp

    from gridllm_tpu.ops.attention import paged_attention_decode

    L, P, ps, kvh, d, s = 2, 6, 8, 2, 16, 3
    rng = np.random.default_rng(0)
    kf = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(L, P, ps, kvh, d)), jnp.float32)

    def to_quant(x):
        q, sc = quantize_kv_rows(x.reshape(L, P * ps, kvh, d))
        return QuantPages(q.reshape(L, P, ps, kvh, d),
                          sc.reshape(L, P, ps))

    kq, vq = to_quant(kf), to_quant(vf)
    pt = jnp.asarray(np.arange(P).reshape(s, 2), jnp.int32)
    lengths = jnp.asarray([10, 13, 5], jnp.int32)
    q = jnp.asarray(rng.normal(size=(s, 4, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(s, kvh, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(s, kvh, d)), jnp.float32)
    li = jnp.int32(1)
    of = paged_attention_decode(q, kf, vf, pt, lengths, ps, k_cur=kc,
                                v_cur=vc, layer=li, use_pallas=False)
    oq = paged_attention_decode(q, kq, vq, pt, lengths, ps, k_cur=kc,
                                v_cur=vc, layer=li, use_pallas=False)
    assert float(jnp.abs(of - oq).max()) < 0.05


def test_int8_greedy_parity_within_tolerance():
    """Greedy streams on the tiny model: int8 KV must agree with the fp
    pool on a substantial shared PREFIX — after the first divergent
    sample the streams legitimately fork, so positional overlap past it
    proves nothing."""
    fp = _engine(num_pages=32)
    r_fp = fp.generate(_gen(SHARED + "Go:", rid="fp", n=12))
    fp.stop()
    q8 = _engine(kv_int8=True, num_pages=32)
    r_q8 = q8.generate(_gen(SHARED + "Go:", rid="q8", n=12))
    q8.stop()
    prefix = 0
    for a, b in zip(r_fp.token_ids, r_q8.token_ids):
        if a != b:
            break
        prefix += 1
    assert prefix >= 4, (r_fp.token_ids, r_q8.token_ids)
    assert r_q8.done_reason in ("stop", "length")


def test_int8_pool_spill_restore_and_prefix_cache():
    """int8 pool + host tier: spills carry the int8 rows + per-row
    scales verbatim, restores land them back exactly (the restored
    stream is byte-identical to the warm one on the SAME int8 engine)."""
    e = _engine(kv_int8=True, kv_host_bytes=1 << 22)
    warm, post = _drive_pressure(e)
    assert e.host_tier.stats()["restores"] > 0
    assert post.cached_tokens > 0
    assert post.text == warm.text and post.token_ids == warm.token_ids
    e.stop()


def test_int8_migration_export_import_round_trip():
    """KV migration between int8 pools rides the fp wire: export
    dequantizes, import requantizes per row — decode-side match warm."""
    src = _engine(kv_int8=True, num_pages=32)
    res = src.generate(_gen(SHARED + "M:", rid="m1", n=6))
    export = src.export_prefix_pages(res.context[:-1])
    assert export is not None
    src.stop()
    from gridllm_tpu.transfer.wire import build_header

    header, payload = build_header(
        "m1", "tiny-llama", export["tokens"], export["k"], export["v"],
        kv_layout=export["kvLayout"], quant=export["quant"])
    asm = Assembler(dict(header))
    asm.feed_raw(payload)
    tokens, k, v = asm.arrays()
    dst = _engine(kv_int8=True, num_pages=32)
    installed = dst.import_prefix_pages(tokens, k, v, header)
    assert installed == len(tokens)
    r2 = dst.generate(_gen(SHARED + "M:", rid="m2", n=6))
    assert r2.cached_tokens > 0
    dst.stop()


# ---------------------------------------------------------------------------
# suspend-to-host
# ---------------------------------------------------------------------------

def test_park_to_host_frees_hbm_and_resumes_exactly():
    e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False, num_pages=32)
    r1 = e.generate(_gen(SHARED + "Park:", rid="p1", n=10))
    cached = e.alloc.cached_pages
    assert cached > 0
    parked = e.park_to_host(r1.context[:-1])
    assert parked > 0
    assert e.alloc.cached_pages == 0           # HBM actually freed
    assert e.host_tier.stats()["pages"] >= parked // e.config.page_size
    r2 = e.generate(_gen(SHARED + "Park:", rid="p2", n=10))
    assert r2.cached_tokens > 0                # restored from host
    assert r2.text == r1.text and r2.token_ids == r1.token_ids
    e.stop()


def test_park_never_frees_shared_pinned_pages():
    """park_to_host while another request still shares the prefix: the
    shared pages are copied to host but STAY resident (refcount-pinned),
    and the live decode is unaffected."""
    e = _engine(kv_host_bytes=1 << 22, kv_spill_int8=False, num_pages=32,
                max_slots=2)
    r1 = e.generate(_gen(SHARED + "A:", rid="sh1", n=6))
    # a second request pins the shared prefix pages and stays "live":
    # drive it manually so it holds the slot while we park
    e.start()
    import threading

    done = threading.Event()
    box = []

    def cb(_d, d, res):
        if d:
            box.append(res)
            done.set()

    e.submit(GenerationRequest(id="sh2", prompt=SHARED + "A:",
                               options={"temperature": 0, "num_predict": 200},
                               on_chunk=cb))
    t0 = time.time()
    while not e.active_requests and time.time() - t0 < 20:
        time.sleep(0.01)
    pinned_before = e.alloc.cached_pages
    e.park_to_host(r1.context[:-1])
    # shared pages were pinned by sh2's admission → not freed
    assert e.alloc.cached_pages <= pinned_before
    done.wait(60)
    assert box and box[0].done_reason in ("stop", "length")
    # the parked copy never corrupted the live stream's shared prefix:
    # same prompt, greedy → sh2's stream extends r1's exactly
    common = min(len(box[0].text), len(r1.text))
    assert box[0].text[:common] == r1.text[:common]
    e.stop()


def test_tier_disabled_without_prefix_cache():
    e = _engine(kv_host_bytes=1 << 22, prefix_cache=False)
    assert e.host_tier is None
    e.stop()


# ---------------------------------------------------------------------------
# scheduler preemption (suspend-to-host priority)
# ---------------------------------------------------------------------------

async def test_preemption_round_trip():
    """A queued high-priority generation preempts a running low-priority
    one: the victim suspends to the host tier, the interactive job runs,
    the victim resumes exactly-once and completes."""
    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.utils.types import InferenceRequest, Priority
    from gridllm_tpu.worker.service import WorkerService

    eng = InferenceEngine(EngineConfig(
        model="tiny-llama", max_slots=1, page_size=16, num_pages=48,
        max_pages_per_slot=16, prefill_buckets=(32, 64), prefill_chunk=16,
        kv_host_bytes=1 << 22, kv_spill_int8=False, seed=3))
    bus = InMemoryBus()
    await bus.connect()
    cfg = Config()
    # fast sweep so the preempt trigger fires well before the tiny
    # batch decode (≈2 s warm) drains on its own
    sched_cfg = cfg.scheduler.model_copy(
        update={"preempt_after_ms": 100, "sweep_interval_ms": 200})
    registry = WorkerRegistry(bus, sched_cfg)
    scheduler = JobScheduler(bus, registry, sched_cfg)
    await registry.initialize()
    await scheduler.initialize()
    worker = WorkerService(bus, {"tiny-llama": eng}, WorkerConfig(),
                           stream_flush_ms=5)
    await worker.start()
    await asyncio.sleep(0.2)

    def req(prompt, prio, n):
        return InferenceRequest(
            id=uuid.uuid4().hex, model="tiny-llama", prompt=prompt,
            request_type="generate", priority=prio,
            options={"temperature": 0, "num_predict": n}, stream=False)

    try:
        # warm compiles so the batch job is decoding when preempted
        await scheduler.submit_and_wait(req("warmup", Priority.medium, 4),
                                        timeout_ms=180_000)
        batch = req("count: one two three four", Priority.low, 400)
        t_batch = asyncio.ensure_future(
            scheduler.submit_and_wait(batch, timeout_ms=180_000))
        await asyncio.sleep(0.4)
        r_inter = await asyncio.wait_for(
            scheduler.submit_and_wait(
                req("hello there", Priority.high, 8), timeout_ms=120_000),
            120)
        r_batch = await asyncio.wait_for(t_batch, 240)
        jt = scheduler._jobs_total
        assert r_inter.success
        assert r_batch.success
        assert int(jt.value(event="preempt_requested")) >= 1
        assert int(jt.value(event="preempted")) >= 1
        # exactly-once: the resumed batch stream reports its FULL token
        # count (resume folded prior tokens into generated state)
        assert r_batch.response.eval_count > 50
        # the victim's KV really took the host round trip
        st = eng.host_tier.stats()
        assert st["spills"] >= 1 and st["restores"] >= 1
    finally:
        await worker.stop()
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
