"""Qwen2/Qwen3 family goldens — the reference's own CI uses qwen models
(tests/integration/integration.ts:4 default qwen3:0.6b, CI qwen2.5:0.5b),
so these families matter for drop-in parity. Direction: random-init the HF
twin, convert its state dict into our pytree, compare logits — exercises
convert_hf_state_dict on the bias/qk_norm leaves too."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import get_config


def _golden(tiny_name: str, hf_cls_name: str):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = get_config(tiny_name)
    hf_cls = getattr(transformers, hf_cls_name)
    torch.manual_seed(0)
    model = hf_cls(cfg.hf_config()).eval()
    params = llama.convert_hf_state_dict(cfg, model.state_dict(), dtype=jnp.float32)

    tokens = np.array([[5, 17, 99, 3, 42, 7, 250, 1]], np.int32)
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    return cfg, params


def test_qwen2_forward_matches_hf():
    cfg, params = _golden("tiny-qwen2", "Qwen2ForCausalLM")
    assert "bq" in params["layers"] and "q_norm" not in params["layers"]


def test_qwen3_forward_matches_hf():
    cfg, params = _golden("tiny-qwen3", "Qwen3ForCausalLM")
    assert "q_norm" in params["layers"] and "bq" not in params["layers"]


def test_qwen_prefill_decode_match_forward():
    """Paged path parity for a knobbed family (qk_norm must flow through
    prefill and decode identically)."""
    from gridllm_tpu.ops.kvcache import PageAllocator, PagedKVCache

    cfg = get_config("tiny-qwen3")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    # give the norms non-trivial weights so a missing qk_norm would show
    params["layers"]["q_norm"] = params["layers"]["q_norm"] * 1.5
    params["layers"]["k_norm"] = params["layers"]["k_norm"] * 0.7
    prompt = [5, 17, 99, 3, 42]
    n_gen = 5

    seq = list(prompt)
    oracle = []
    for _ in range(n_gen):
        logits = llama.forward(params, cfg, jnp.asarray([seq], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        oracle.append(nxt)
        seq.append(nxt)

    cache = PagedKVCache.create(
        cfg.num_layers, 16, 8, cfg.num_kv_heads, cfg.head_dim_, 4, 8,
        dtype=jnp.float32,
    )
    alloc = PageAllocator(16, 8, 8)
    alloc.alloc(0, len(prompt) + n_gen)
    row = jnp.asarray(alloc.table_row(0), jnp.int32)
    padded = jnp.asarray(prompt + [0] * (8 - len(prompt)), jnp.int32)
    logits, cache = llama.prefill(
        params, cfg, padded, jnp.int32(len(prompt)), cache, jnp.int32(0), row
    )
    got = [int(jnp.argmax(logits))]
    tokens = jnp.zeros((cache.max_slots,), jnp.int32).at[0].set(got[0])
    active = jnp.zeros((cache.max_slots,), bool).at[0].set(True)
    for _ in range(n_gen - 1):
        logits, cache = llama.decode_step(params, cfg, tokens, cache, active)
        nxt = int(jnp.argmax(logits[0]))
        got.append(nxt)
        tokens = tokens.at[0].set(nxt)
    assert got == oracle


def test_qwen_engine_serves():
    from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine

    eng = InferenceEngine(EngineConfig(
        model="tiny-qwen2", max_slots=2, page_size=8, num_pages=32,
        max_pages_per_slot=8, prefill_buckets=(16,), seed=0,
    ))
    res = eng.generate(GenerationRequest(
        id="q1", prompt="hi", options={"temperature": 0.0, "num_predict": 6},
    ))
    assert res.eval_count > 0
