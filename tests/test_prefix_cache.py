"""Automatic prefix caching (ISSUE 3).

Allocator: ref-counted content-addressed pages, reuse LRU, eviction, COW
accounting. Engine: identical prompts run cold vs warm produce byte-identical
token streams (the warm run provably hitting the cache), eviction pressure
mid-decode never touches pinned pages, and GRIDLLM_PREFIX_CACHE=0 restores
the pre-cache allocator behavior exactly. Scheduler: prefix-affinity routing
breaks ties and weighs, but never overrides load caps.
"""

import json
import uuid

import pytest

from gridllm_tpu.engine import EngineConfig, GenerationRequest, InferenceEngine
from gridllm_tpu.ops.kvcache import PageAllocator

TINY = dict(
    model="tiny-llama",
    max_slots=4,
    page_size=8,
    num_pages=64,
    max_pages_per_slot=8,
    prefill_buckets=(16, 32),
    prefill_chunk=16,
)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocator_match_refcount_and_registration():
    a = PageAllocator(8, 4, 8, cache_pages=-1)
    ids = list(range(10))  # 2 full pages + a partial tail
    pages = a.alloc(0, 10)
    assert len(pages) == 3
    a.free(0, ids)
    # the 2 full pages register and park in the LRU; the tail page frees
    assert a.cached_pages == 2
    assert a.free_pages == 6
    # warm: the same prefix matches both full pages (capped below the last
    # token — (10-1)//4 = 2 pages = 8 tokens), pinning them out of the LRU
    cached = a.match_prefix(1, ids)
    assert cached == 8
    assert a.cached_pages == 0
    owned = a.alloc(1, 10)
    assert owned[:2] == pages[:2]  # shared copy-free
    assert a.hits == 2 and a.misses == 1  # 3 prompt pages, 2 hit
    a.free(1, ids)
    assert a.cached_pages == 2  # released back into the LRU


def test_allocator_divergent_prefix_does_not_match():
    a = PageAllocator(8, 4, 8, cache_pages=-1)
    ids = list(range(10))
    a.alloc(0, 10)
    a.free(0, ids)
    other = [99] + ids[1:]  # first page differs → chain breaks at page 0
    assert a.match_prefix(1, other) == 0


def test_allocator_eviction_spares_pinned_pages_and_counts_cow():
    a = PageAllocator(4, 4, 4, cache_pages=-1)
    ids = [1, 2, 3, 4, 5, 6, 7, 8]  # exactly 2 full pages
    first = a.alloc(0, 8)
    a.free(0, ids)
    assert a.cached_pages == 2
    # warm match caps at (8-1)//4 = 1 page; the second page IS cached but
    # must be privately rebuilt (the last token lives in it) → a COW copy
    cached = a.match_prefix(1, ids)
    assert cached == 4
    assert a.alloc(1, 8) is not None  # admission succeeds → stats commit
    assert a.cow_copies == 1
    # pool pressure: 2 fresh pages wanted, 1 free + 1 evictable; the pinned
    # page must survive, the unpinned cached page is evicted
    assert a.alloc(2, 8) is not None
    assert a.evictions == 1
    assert first[0] in a.table_row(1)  # pinned page still backs slot 1
    a.free(1)
    a.free(2)
    assert a.cached_pages == 1  # the still-registered pinned page returns


def test_allocator_lru_cap_bounds_cached_pages():
    a = PageAllocator(16, 4, 8, cache_pages=2)
    for slot in range(3):
        ids = [slot * 100 + i for i in range(8)]
        a.alloc(slot, 8)
        a.free(slot, ids)
    assert a.cached_pages == 2
    assert a.evictions == 4
    assert a.free_pages == 14


def test_allocator_match_stats_count_once_across_retries():
    """A pool-exhausted admission bounces: match → alloc fails → free →
    requeue → match again. The prompt's pages must be counted ONCE, at the
    admission that actually succeeds — not once per retry."""
    a = PageAllocator(4, 4, 8, cache_pages=-1)
    ids = list(range(8))
    a.alloc(0, 8)
    a.free(0, ids)  # 2 cached pages
    assert a.match_prefix(1, ids) == 4
    assert a.alloc(1, 40) is None  # 10 pages wanted, pool has 4
    a.free(1)  # engine unpins and requeues
    assert a.hits == 0 and a.misses == 0  # nothing committed
    assert a.match_prefix(1, ids) == 4
    assert a.alloc(1, 8) is not None
    assert a.hits == 1 and a.misses == 1  # counted exactly once


def test_allocator_disabled_is_legacy_behavior():
    a = PageAllocator(8, 4, 8)  # cache_pages=0 → prefix caching off
    a.alloc(0, 10)
    a.free(0, list(range(10)))
    assert a.cached_pages == 0 and a.free_pages == 8
    assert a.match_prefix(1, list(range(10))) == 0
    assert a.hits == 0 and a.misses == 0


# ---------------------------------------------------------------------------
# engine: cold vs warm differential
# ---------------------------------------------------------------------------

def _gen(eng, rid, prompt, opts, sink=None):
    return eng.generate(GenerationRequest(
        id=rid, prompt=prompt, options=opts,
        on_chunk=(lambda d, done, r: sink.append(d)) if sink is not None
        else None,
    ))


def test_cold_vs_warm_identical_token_stream():
    eng = InferenceEngine(EngineConfig(**TINY))
    prompt = "abcdefgh" * 5  # 41 ids + BOS → 5 full pages of cached prefix
    opts = {"temperature": 0.0, "num_predict": 8}
    cold_chunks: list = []
    warm_chunks: list = []
    cold = _gen(eng, "cold", prompt, opts, cold_chunks)
    assert eng.alloc.hits == 0 and cold.cached_tokens == 0
    warm = _gen(eng, "warm", prompt, opts, warm_chunks)
    assert eng.alloc.hits > 0, "warm run did not hit the prefix cache"
    assert warm.cached_tokens > 0 and warm.cached_tokens % TINY["page_size"] == 0
    assert warm.token_ids == cold.token_ids
    assert warm.text == cold.text
    assert "".join(warm_chunks) == "".join(cold_chunks)
    # Ollama surface unchanged: prompt_eval_count stays the FULL prompt
    assert warm.prompt_eval_count == cold.prompt_eval_count


def test_cold_bucket_vs_warm_chunk_short_prompt_identical():
    """Prompts shorter than prefill_chunk run cold through the bucketed
    whole-prompt program but warm through the chunk program; greedy
    outputs must still agree (the same numerical equivalence the existing
    chunked-vs-single-shot prefill test relies on)."""
    eng = InferenceEngine(EngineConfig(**{**TINY, "prefill_chunk": 64}))
    prompt = "abcdefgh" * 5  # 42 ids ≤ chunk 64 → cold takes the bucket path
    opts = {"temperature": 0.0, "num_predict": 8}
    cold = _gen(eng, "c", prompt, opts)
    warm = _gen(eng, "w", prompt, opts)
    assert warm.cached_tokens > 0
    assert warm.token_ids == cold.token_ids
    assert warm.text == cold.text


def test_warm_sampler_state_matches_cold_seeded_with_penalty():
    """The repeat-penalty window spans the cached region (repeat_last_n >
    uncached tail): warm must replay the cached tokens through the window
    bookkeeping or seeded sampling would diverge from the cold path."""
    eng = InferenceEngine(EngineConfig(**TINY))
    prompt = "abcabcab" * 5  # repetitive → the penalty actually bites
    opts = {"temperature": 0.9, "seed": 123, "num_predict": 10,
            "repeat_penalty": 1.5, "repeat_last_n": 64}
    cold = _gen(eng, "c", prompt, opts)
    warm = _gen(eng, "w", prompt, opts)
    assert warm.cached_tokens > 0
    assert warm.token_ids == cold.token_ids


def test_multiturn_context_reuses_previous_generation():
    """Turn 2's prompt = turn 1's full context (Ollama multi-turn shape):
    the cached pages cover prompt AND generated tokens of turn 1."""
    eng = InferenceEngine(EngineConfig(**TINY))
    opts = {"temperature": 0.0, "num_predict": 12}
    t1 = _gen(eng, "t1", "abcdefgh" * 4, opts)
    follow = GenerationRequest(id="t2", prompt_ids=list(t1.context) + [65, 66],
                               options=opts)
    t2 = eng.generate(follow)
    # turn 1's context is 44+ tokens → at least 4 full pages reusable
    assert t2.cached_tokens >= 4 * TINY["page_size"]
    assert t2.done_reason in ("stop", "length")


def test_evicted_cache_mid_decode_completes_correctly():
    """Eviction pressure while a warm request decodes: refcounts pin its
    matched pages, only unpinned cached pages are reclaimed, and the warm
    output stays identical to the cold run."""
    cfg = EngineConfig(**{**TINY, "num_pages": 20})
    eng = InferenceEngine(cfg)
    prompt_a = "abcdefgh" * 5
    prompt_b = "hgfedcba" * 5
    opts = {"temperature": 0.0, "num_predict": 8}
    cold_a = _gen(eng, "cold-a", prompt_a, opts)
    _gen(eng, "cold-b", prompt_b, opts)  # second cached chain (evictable)
    results: dict = {}

    def mk(name):
        def cb(d, done, res):
            if done:
                results[name] = res
        return cb

    eng.submit(GenerationRequest(id="warm", prompt=prompt_a, options=opts,
                                 on_chunk=mk("warm")))
    for _ in range(3):  # admit + a few decode steps
        eng.step()
    evictions_before = eng.alloc.evictions
    # pool-hungry stranger (no shared prefix) forces evictions mid-decode
    eng.submit(GenerationRequest(id="filler", prompt="qrstuvwx" * 6,
                                 options={"temperature": 0.0,
                                          "num_predict": 12},
                                 on_chunk=mk("filler")))
    while len(results) < 2:
        eng.step()
    assert eng.alloc.evictions > evictions_before, (
        "setup failed to exert eviction pressure")
    assert results["warm"].cached_tokens > 0
    assert results["warm"].token_ids == cold_a.token_ids


def test_prefix_cache_disabled_is_pre_cache_behavior(monkeypatch):
    eng = InferenceEngine(EngineConfig(**TINY, prefix_cache=False))
    prompt = "abcdefgh" * 5
    opts = {"temperature": 0.7, "seed": 9, "num_predict": 8}
    r1 = _gen(eng, "a", prompt, opts)
    r2 = _gen(eng, "b", prompt, opts)
    assert r1.token_ids == r2.token_ids  # deterministic, both cold
    assert r2.cached_tokens == 0
    assert eng.alloc.hits == 0 and eng.alloc.misses == 0
    assert eng.alloc.cached_pages == 0
    assert eng.alloc.free_pages == TINY["num_pages"]  # all pages returned
    # the env knob resolves the same way
    monkeypatch.setenv("GRIDLLM_PREFIX_CACHE", "0")
    env_off = InferenceEngine(EngineConfig(**TINY))
    assert env_off._prefix_cache_cap == 0
    monkeypatch.setenv("GRIDLLM_PREFIX_CACHE", "1")
    monkeypatch.setenv("GRIDLLM_PREFIX_CACHE_PAGES", "7")
    env_capped = InferenceEngine(EngineConfig(**TINY))
    assert env_capped._prefix_cache_cap == 7
    # a 0-page LRU means "no cache" at every layer, not "unbounded"
    monkeypatch.setenv("GRIDLLM_PREFIX_CACHE_PAGES", "0")
    env_zero = InferenceEngine(EngineConfig(**TINY))
    assert env_zero._prefix_cache_cap == 0


def test_prefill_metrics_split_cached_vs_computed():
    from gridllm_tpu.obs import default_registry

    eng = InferenceEngine(EngineConfig(**TINY))
    counter = default_registry().get("gridllm_engine_tokens_total")
    prompt = "abcdefgh" * 5
    opts = {"temperature": 0.0, "num_predict": 4}
    _gen(eng, "a", prompt, opts)
    before = counter.value(model="tiny-llama", kind="prefill_cached")
    warm = _gen(eng, "b", prompt, opts)
    after = counter.value(model="tiny-llama", kind="prefill_cached")
    assert after - before == warm.cached_tokens > 0


# ---------------------------------------------------------------------------
# scheduler: prefix-affinity routing
# ---------------------------------------------------------------------------

async def test_prefix_affinity_breaks_ties_not_load_caps():
    from gridllm_tpu.bus import InMemoryBus
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.types import InferenceRequest

    from .helpers import FakeWorker, fast_config

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    cfg = fast_config()
    registry = WorkerRegistry(bus, cfg)
    scheduler = JobScheduler(bus, registry, cfg)
    await registry.initialize()
    await scheduler.initialize()
    w1 = FakeWorker(bus, "w1", ["m1"], max_concurrent=4)
    w2 = FakeWorker(bus, "w2", ["m1"], max_concurrent=4)
    await w1.start()
    await w2.start()
    await bus.flush()
    # w2 heartbeats a prefix digest (the real WorkerService ships this from
    # its completed-jobs LRU)
    await bus.publish("worker:heartbeat", json.dumps({
        "workerId": "w2", "status": "online", "currentJobs": 0,
        "prefixKeys": ["k1", "k2"]}))
    await bus.flush()
    assert registry.get_worker("w2").cachedPrefixes == ["k1", "k2"]

    def request(**md):
        return InferenceRequest(id=f"j-{uuid.uuid4().hex[:6]}", model="m1",
                                prompt="hi", metadata=md)

    try:
        # tie on load → affinity wins (without it, insertion order gives w1)
        assert scheduler._select_worker(request()).workerId == "w1"
        picked = scheduler._select_worker(request(prefixKey="k1"))
        assert picked.workerId == "w2"
        # load gap beyond the affinity weight → the hot worker sheds
        registry.get_worker("w2").currentJobs = 3  # load 0.75 vs 0.0
        assert scheduler._select_worker(
            request(prefixKey="k1")).workerId == "w1"
        # at capacity the worker is not even a candidate
        registry.get_worker("w2").currentJobs = 4
        registry.get_worker("w1").currentJobs = 0
        assert scheduler._select_worker(
            request(prefixKey="k1")).workerId == "w1"
    finally:
        await w1.stop(announce=False)
        await w2.stop(announce=False)
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()


async def test_worker_prefix_digest_gated_on_cache_enabled():
    """With the engine's prefix cache off there are no pages to route
    toward: the worker must not advertise prefix keys (the scheduler's
    affinity term would otherwise skew routing with zero prefill saved)."""
    from gridllm_tpu.bus import InMemoryBus
    from gridllm_tpu.utils.config import WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    class Req:
        model = "tiny-llama"
        metadata = {"prefixKey": "k1"}

    bus = InMemoryBus(key_prefix="T:")
    await bus.connect()
    try:
        off = WorkerService(
            bus, {"tiny-llama": InferenceEngine(
                EngineConfig(**TINY, prefix_cache=False))}, WorkerConfig())
        off._note_prefix_key(Req())
        assert not off._prefix_keys
        on = WorkerService(
            bus, {"tiny-llama": InferenceEngine(EngineConfig(**TINY))},
            WorkerConfig())
        on._note_prefix_key(Req())
        assert list(on._prefix_keys) == ["k1"]
    finally:
        await bus.disconnect()


def test_gateway_prefix_key_stable_and_distinct():
    from gridllm_tpu.gateway.common import prefix_key

    a = prefix_key("m", "sys", "prompt text")
    assert a == prefix_key("m", "sys", "prompt text")
    assert a != prefix_key("m", "other sys", "prompt text")
    assert a != prefix_key("m2", "sys", "prompt text")
    assert a != prefix_key("m", None, "prompt text")
    # structured parts (chat messages) hash stably too
    msgs = [{"role": "system", "content": "s"}, {"role": "user", "content": "u"}]
    assert prefix_key("m", msgs) == prefix_key("m", list(msgs))
